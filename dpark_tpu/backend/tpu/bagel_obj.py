"""Device adapter for OBJECT Bagel programs — general edition.

Replaces the r4 template-in-state columnarizer (VERDICT r4 #4: its
device subset required degree <= 8, <= 8 degree classes, scalar values,
and messages only to the vertex's own out-edges).  The lifted design:

* **Class-sliced tracing.**  Vertices are sharded by hash(id) and, per
  device, grouped into contiguous slices by out-degree.  The user's
  per-vertex ``compute`` is jax.vmap'd over each class slice with a
  REAL Python list of that degree's Edge proxies — ``len(outEdges)``
  stays exact at trace time — so per-class work is proportional to the
  class size, not the whole graph, and the degree cap rises from 8 to
  MAX_DEGREE (the number of DISTINCT degrees still bounds compile
  count; see bagel.MAX_DEGREE_CLASSES).
* **Messages are data (CSR-style send).**  ``Message.target_id`` may be
  any integer — a traced edge target, a computed id, a constant —
  because emitted messages leave compute as (dst, value) ARRAYS,
  flatten across classes into one per-device buffer sized by the total
  message count (not n x max_degree), and route by hash(dst) through
  the same bucketize-combine + all_to_all exchange the shuffle plane
  uses.  Messages to non-neighbors and variable message counts
  (halt-and-send, notify-one) all work; unknown targets drop at
  delivery exactly like the object loop.
* **Structured vertex values.**  ``Vertex.value`` may be any pytree of
  numeric scalars/vectors (tuple, dict, nested, np arrays); leaves ride
  as separate columns.  Message values stay scalar (they feed the
  monoid combine).

Semantics parity with Bagel._run_fast (the host golden model): inactive
vertices with no mail pass through untouched; only compute-invoked
vertices may send; the halting counters see EMITTED messages (unknown
targets included, dropped at next delivery); superstep is a static
Python int per compiled step (object programs branch on it).

Reference: dpark/bagel.py superstep loop (SURVEY.md 3.2); the hash-dst
exchange is the survey's [H] mapping, shared with backend/tpu/bagel.py.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from dpark_tpu import conf
from dpark_tpu.backend.tpu import collectives, layout
from dpark_tpu.backend.tpu.executor import _shard_map
from dpark_tpu.utils.log import get_logger
from dpark_tpu.utils.phash import phash_np

logger = get_logger("tpu.bagel_obj")

AXIS = conf.MESH_AXIS
_SENT = np.iinfo(np.int64).max


def _not_columnar(msg):
    from dpark_tpu.bagel import _NotColumnarizable
    return _NotColumnarizable(msg)


class DeviceObjectPregel:
    """One columnarized object-Bagel run over the executor's mesh.

    Inputs are already validated/flattened by Bagel._run_columnar:
      ids (n,) int64 unique; vleaves: list of (n, ...) numeric columns
      (the flattened Vertex.value pytree); act (n,) bool; degs (n,)
      int64; tgt_flat (E,) int64 edge targets in per-vertex emission
      order (CSR with offsets = cumsum(degs)); ev_flat: None or (E,)
      numeric edge values; pend: None or (dst (m,), val (m,)) initial
      messages; compute: the user's object compute; monoid: the
      provable BasicCombiner op.
    """

    def __init__(self, executor, compute, monoid, vdef, ids, vleaves,
                 act, degs, tgt_flat, ev_flat, pend, max_superstep):
        from dpark_tpu.bagel import PregelInputError
        self.ex = executor
        self.ndev = executor.ndev
        self.mesh = executor.mesh
        self.compute = compute
        self.monoid = monoid
        self.vdef = vdef
        self.max_superstep = max_superstep
        self._compiled = {}
        n = ids.shape[0]
        if np.unique(ids).shape[0] != n:
            raise PregelInputError("vertex ids must be unique")
        if n and int(ids.max()) == _SENT:
            raise PregelInputError("vertex id equals the padding sentinel")
        self.vdtypes = [np.dtype(l.dtype) for l in vleaves]
        self.vshapes = [tuple(l.shape[1:]) for l in vleaves]
        self.nvl = len(vleaves)
        self.has_ev = ev_flat is not None
        self.edt = np.dtype(ev_flat.dtype) if self.has_ev else None

        self.classes = sorted(set(degs.tolist())) or [0]
        self.mdt = self._discover_mdt(pend)

        # -- per-(class, device) tables ---------------------------------
        ndev = self.ndev
        vdev = (phash_np(ids) % np.uint32(ndev)).astype(np.int64)
        offs = np.concatenate([[0], np.cumsum(degs)]).astype(np.int64)
        sh = self._sharding()
        put = lambda a: jax.device_put(a, sh)           # noqa: E731
        self.tables = []
        for d in self.classes:
            sel = np.nonzero(degs == d)[0]
            cdev = vdev[sel]
            order = np.argsort(cdev, kind="stable")
            sel = sel[order]
            bounds = np.searchsorted(cdev[order], np.arange(ndev + 1))
            cnt = np.diff(bounds).astype(np.int32)
            cap = layout.round_capacity(int(cnt.max()) if sel.size else 1)
            vid = np.full((ndev, cap), _SENT, np.int64)
            hact = np.zeros((ndev, cap), bool)
            hvl = [np.zeros((ndev, cap) + shp, dt)
                   for dt, shp in zip(self.vdtypes, self.vshapes)]
            htg = np.full((ndev, cap, d), _SENT, np.int64)
            hev = (np.zeros((ndev, cap, d), self.edt)
                   if self.has_ev else None)
            for dev in range(ndev):
                lo, hi = int(bounds[dev]), int(bounds[dev + 1])
                c = hi - lo
                if not c:
                    continue
                s = sel[lo:hi]
                vid[dev, :c] = ids[s]
                hact[dev, :c] = act[s]
                for h, l in zip(hvl, vleaves):
                    h[dev, :c] = l[s]
                if d:
                    eidx = offs[s][:, None] + np.arange(d)[None, :]
                    htg[dev, :c] = tgt_flat[eidx]
                    if self.has_ev:
                        hev[dev, :c] = ev_flat[eidx]
            self.tables.append({
                "d": d, "cap": cap,
                "vid": put(vid), "act": put(hact),
                "vals": [put(h) for h in hvl],
                "tgts": put(htg),
                "evals": put(hev) if self.has_ev else None,
            })

        # -- initial messages, bucketized by hash(dst) -------------------
        self.init = None
        if pend is not None and pend[0].size:
            idst, ivals = pend
            mdev = (phash_np(idst) % np.uint32(ndev)).astype(np.int64)
            mc = np.bincount(mdev, minlength=ndev)
            cap_m = layout.round_capacity(int(mc.max() or 1))
            hm_d = np.full((ndev, cap_m), _SENT, np.int64)
            hm_v = np.zeros((ndev, cap_m), self.mdt)
            mcnt = np.zeros(ndev, np.int32)
            for dev in range(ndev):
                m = mdev == dev
                c = int(m.sum())
                mcnt[dev] = c
                if c:
                    hm_d[dev, :c] = idst[m]
                    hm_v[dev, :c] = ivals[m].astype(self.mdt)
            self.init = (put(mcnt), put(hm_d), put(hm_v))
            self.init_count = int(idst.size)
        else:
            self.init_count = 0

        # _discover_mdt's traces double as the early probe: every
        # unsupported construct in the user compute surfaced there,
        # before any device state was built

    def _sharding(self):
        return NamedSharding(self.mesh, P(AXIS))

    # ------------------------------------------------------------------
    # the per-(class, superstep, mail) traced body
    # ------------------------------------------------------------------
    def _class_body(self, d, s, mail, cell, mdt=None):
        """Per-vertex fn for jax.vmap over one class slice.  mail=False
        is the object contract's no-mail call (msg is the LITERAL None,
        so ``msg is not None`` branches exactly as on the host paths).
        ``cell["m"]`` reports the static emitted-message count of this
        trace.  mdt=None puts the body in DISCOVERY mode: emitted
        dtypes collect into cell["mdt"] instead of being checked."""
        from dpark_tpu.bagel import Edge, Message, Vertex
        import jax.tree_util as jtu
        nvl = self.nvl
        vdef = self.vdef
        discovery = mdt is None
        check_mdt = self.mdt if not discovery else None

        def body(*args):
            i = nvl
            vls = args[:i]
            vid = args[i]; i += 1
            tgts = args[i]; i += 1
            evs = None
            if self.has_ev:
                evs = args[i]; i += 1
            m = None
            if mail:
                m = args[i]; i += 1
            a = args[i]
            value = jtu.tree_unflatten(vdef, list(vls))
            edges = [Edge(tgts[j], evs[j] if evs is not None else None)
                     for j in range(d)]
            vert = Vertex(vid, value, edges, a)
            out = self.compute(vert, m, None, s)
            if not (isinstance(out, tuple) and len(out) == 2):
                raise _not_columnar("compute must return "
                                    "(vertex, messages)")
            nv, out_msgs = out
            if not isinstance(nv, Vertex):
                raise _not_columnar("compute returned non-Vertex")
            if nv.id is not vert.id:
                raise _not_columnar("compute rebound vertex id")
            new_leaves, ndef = jtu.tree_flatten(nv.value)
            if ndef != vdef:
                raise _not_columnar(
                    "compute changed the vertex value structure")
            outs = []
            for leaf, dt, shp in zip(new_leaves, self.vdtypes,
                                     self.vshapes):
                arr = jnp.asarray(leaf)
                if np.result_type(arr.dtype, dt) != np.dtype(dt):
                    raise _not_columnar(
                        "superstep %d produces %s vertex values, wider "
                        "than the initial %s" % (s, arr.dtype, dt))
                arr = jnp.asarray(arr, dt)
                if arr.shape != shp:
                    raise _not_columnar("vertex value leaf shape "
                                        "changed at superstep %d" % s)
                outs.append(arr)
            dsts, vals = [], []
            for msg_obj in (out_msgs or []):
                if not isinstance(msg_obj, Message):
                    raise _not_columnar("non-Message output")
                t = msg_obj.target_id
                if isinstance(t, bool):
                    raise _not_columnar("non-integer message target")
                td = jnp.asarray(t)
                if td.shape != () or td.dtype.kind not in "iu":
                    raise _not_columnar(
                        "message target must be an integer scalar")
                mv = jnp.asarray(msg_obj.value)
                if mv.shape != ():
                    raise _not_columnar("message values must be scalars")
                if mv.dtype.kind not in "if":
                    raise _not_columnar("non-numeric message value")
                if discovery:
                    cell["mdt"] = (np.result_type(cell["mdt"], mv.dtype)
                                   if "mdt" in cell else
                                   np.dtype(mv.dtype))
                elif np.result_type(mv.dtype, check_mdt) \
                        != np.dtype(check_mdt):
                    raise _not_columnar(
                        "superstep %d emits %s messages, wider than "
                        "the discovered %s" % (s, mv.dtype, check_mdt))
                dsts.append(jnp.asarray(td, jnp.int64))
                vals.append(jnp.asarray(
                    mv, check_mdt if not discovery else mv.dtype))
            cell["m"] = len(dsts)
            na = jnp.asarray(nv.active, bool)
            if na.shape != ():
                raise _not_columnar("Vertex.active must be a scalar")
            md = (jnp.stack(dsts) if dsts
                  else jnp.zeros((0,), jnp.int64))
            mv_ = (jnp.stack(vals) if vals
                   else jnp.zeros((0,), check_mdt or jnp.float64))
            return tuple(outs) + (na, md, mv_)
        return body

    def _body_structs(self, d, mdt, mail):
        vs = [jax.ShapeDtypeStruct((4,) + shp, dt)
              for dt, shp in zip(self.vdtypes, self.vshapes)]
        args = vs + [jax.ShapeDtypeStruct((4,), np.int64),
                     jax.ShapeDtypeStruct((4, d), np.int64)]
        if self.has_ev:
            args.append(jax.ShapeDtypeStruct((4, d), self.edt))
        if mail:
            args.append(jax.ShapeDtypeStruct((4,), mdt))
        args.append(jax.ShapeDtypeStruct((4,), np.bool_))
        return args

    def _discover_mdt(self, pend):
        """Fixed-point message-dtype discovery across ALL classes and
        both mail variants — a guess would silently truncate (e.g. int
        state emitting float shares).  Initial messages seed the guess:
        they feed the same combine and delivery as emitted ones."""
        guess = np.result_type(
            *( [dt for dt in self.vdtypes if dt.kind in "if"]
               or [np.dtype(np.float64)] ))
        if pend is not None and pend[0].size:
            pdt = np.asarray(pend[1]).dtype
            if pdt.kind not in "if":
                raise _not_columnar("non-numeric initial message values")
            guess = np.result_type(guess, pdt)
        guess = np.dtype(guess)
        for _ in range(3):
            found = guess
            for d in self.classes:
                for mail in (True, False):
                    cell = {}
                    body = self._class_body(d, 0, mail, cell, mdt=None)
                    try:
                        jax.eval_shape(jax.vmap(body),
                                       *self._body_structs(d, guess,
                                                           mail))
                    except Exception as e:
                        from dpark_tpu.bagel import _NotColumnarizable
                        if isinstance(e, _NotColumnarizable):
                            raise
                        raise _not_columnar(
                            "compute does not trace (%s)" % str(e)[:200])
                    if "mdt" in cell:
                        found = np.result_type(found, cell["mdt"])
            found = np.dtype(found)
            if found == guess:
                return found
            guess = found
        raise _not_columnar("message dtype does not stabilize")

    # ------------------------------------------------------------------
    # programs
    # ------------------------------------------------------------------
    def _p_init(self):
        """Bucketize the user's initial messages by hash(dst)."""
        ndev = self.ndev
        monoid = self.monoid

        def per_device(mcnt, mdst, mval):
            kk, vv, counts, offsets = collectives.bucketize_combine(
                mdst[0], [mval[0]], mcnt[0], ndev, None, monoid=monoid)
            out = (counts, offsets, kk, vv[0])
            return tuple(jnp.expand_dims(o, 0) for o in out)

        key = ("init",)
        if key not in self._compiled:
            fn = _shard_map(per_device, self.mesh,
                            in_specs=(P(AXIS),) * 3,
                            out_specs=(P(AXIS),) * 4)
            self._compiled[key] = jax.jit(fn)
        return self._compiled[key]

    def _p_step(self, s, rounds, slot):
        """One superstep: deliver combined messages to every class
        slice, run the class-sliced compute, flatten emitted (dst, val)
        pairs across classes, pre-combine + bucketize them by hash(dst)
        for the next exchange, and count active vertices and emitted
        messages."""
        key = ("step", s, rounds, slot)
        if key in self._compiled:
            return self._compiled[key]
        ndev = self.ndev
        monoid = self.monoid
        mdt = self.mdt
        nvl = self.nvl
        ncls = len(self.classes)
        caps = [t["cap"] for t in self.tables]
        degs = [t["d"] for t in self.tables]
        has_ev = self.has_ev
        per_cls_in = 3 + nvl + (1 if has_ev else 0)
        from dpark_tpu.bagel import monoid_identity
        ident = monoid_identity(monoid, mdt)

        def per_device(*args):
            # unpack: per class [vid, act, tgts, (evals,) vals...],
            # then rounds x cnt, rounds x (dst, val) buffers
            cls_args = []
            i = 0
            for c in range(ncls):
                cls_args.append(args[i:i + per_cls_in])
                i += per_cls_in
            cnts = [a[0] for a in args[i:i + rounds]]
            i += rounds
            bufs = args[i:]

            if rounds:
                recvs = []
                for r in range(rounds):
                    recvs.append([bufs[r * 2][0], bufs[r * 2 + 1][0]])
                flat, mask = collectives.flatten_received(recvs, cnts)
                uk, uv, _ = collectives.segment_reduce(
                    flat[0], flat[1:], mask, None, monoid=monoid)
                uval = uv[0]
            else:
                uk = uval = None

            outs = []
            msg_dsts, msg_vals = [], []
            n_active = jnp.int64(0)
            emitted = jnp.int64(0)
            for c in range(ncls):
                a = cls_args[c]
                vid, act, tgts = a[0][0], a[1][0], a[2][0]
                j = 3
                evals = None
                if has_ev:
                    evals = a[3][0]
                    j = 4
                vals = [x[0] for x in a[j:]]
                cap, d = caps[c], degs[c]
                valid = vid != _SENT
                if uk is not None:
                    pos = jnp.clip(jnp.searchsorted(uk, vid), 0,
                                   uk.shape[0] - 1)
                    has = (uk[pos] == vid) & valid
                    msg = jnp.where(has, uval[pos], ident)
                else:
                    has = jnp.zeros(cap, bool)
                    msg = jnp.full(cap, ident, mdt)
                invoked = (act | has) & valid

                cm, cn = {}, {}
                margs = vals + [vid, tgts] \
                    + ([evals] if has_ev else [])
                om = jax.vmap(self._class_body(d, s, True, cm,
                                               mdt=mdt))(
                    *(margs + [msg, act]))
                on = jax.vmap(self._class_body(d, s, False, cn,
                                               mdt=mdt))(
                    *(margs + [act]))
                new_vals = []
                for li in range(nvl):
                    pick = jnp.where(
                        collectives._bcast(has, om[li]), om[li], on[li])
                    new_vals.append(jnp.where(
                        collectives._bcast(invoked, pick), pick,
                        vals[li]))
                new_act = invoked & jnp.where(has, om[nvl], on[nvl])
                n_active = n_active + jnp.sum(new_act)
                # emitted (dst, val) blocks: the mail trace's messages
                # from invoked+has rows, the no-mail trace's from
                # invoked+~has rows; ungated rows get the sentinel dst
                # and compact away before the bucketize
                for blk, gate, cell in ((om, invoked & has, cm),
                                        (on, invoked & ~has, cn)):
                    m = cell["m"]
                    if not m:
                        continue
                    dst_b = jnp.where(gate[:, None], blk[nvl + 1],
                                      _SENT)
                    val_b = blk[nvl + 2]
                    msg_dsts.append(dst_b.reshape(-1))
                    msg_vals.append(val_b.reshape(-1).astype(mdt))
                    emitted = emitted + jnp.sum(gate) * m
                outs.extend(new_vals)
                outs.append(new_act)

            if msg_dsts:
                dst_flat = jnp.concatenate(msg_dsts)
                val_flat = jnp.concatenate(msg_vals)
                smask = dst_flat != _SENT
                packed, cnt = collectives.compact(
                    [dst_flat, val_flat], smask)
                kk, vv, counts, offsets = collectives.bucketize_combine(
                    packed[0], packed[1:], cnt, ndev, None,
                    monoid=monoid)
                mv = vv[0]
            else:
                kk = jnp.full((1,), _SENT, jnp.int64)
                mv = jnp.full((1,), ident, mdt)
                counts = jnp.zeros((ndev,), jnp.int32)
                offsets = jnp.zeros((ndev,), jnp.int32)
            outs += [counts, offsets, kk, mv,
                     jnp.reshape(n_active, (1,)),
                     jnp.reshape(emitted, (1,))]
            return tuple(jnp.expand_dims(o, 0) for o in outs)

        n_in = ncls * per_cls_in + rounds + rounds * 2
        n_out = ncls * (nvl + 1) + 6
        fn = _shard_map(per_device, self.mesh,
                        in_specs=(P(AXIS),) * n_in,
                        out_specs=(P(AXIS),) * n_out)
        self._compiled[key] = jax.jit(fn)
        return self._compiled[key]

    # ------------------------------------------------------------------
    def run(self):
        nvl = self.nvl
        ncls = len(self.classes)
        pending = None
        total_msgs = 0
        if self.init is not None:
            outs = self._p_init()(*self.init)
            pending = (outs[0], outs[1], outs[2], outs[3])
            total_msgs = self.init_count

        s = 0
        n_active = None
        while s < self.max_superstep:
            args = []
            for t in self.tables:
                args.extend([t["vid"], t["act"], t["tgts"]]
                            + ([t["evals"]] if self.has_ev else [])
                            + t["vals"])
            if pending is not None and total_msgs > 0:
                counts, offsets, kk, vv = pending
                recv_rounds, cnt_rounds, slot = self.ex._exchange_all(
                    [kk, vv], counts, offsets)
                rounds = len(recv_rounds)
                step = self._p_step(s, rounds, slot)
                args.extend(cnt_rounds)
                for r in range(rounds):
                    args.extend(recv_rounds[r])
            else:
                step = self._p_step(s, 0, 0)
            outs = step(*args)
            i = 0
            for t in self.tables:
                t["vals"] = list(outs[i:i + nvl])
                t["act"] = outs[i + nvl]
                i += nvl + 1
            counts, offsets, kk, mv = outs[i:i + 4]
            pending = (counts, offsets, kk, mv)
            n_active = int(np.asarray(
                jax.device_get(outs[i + 4])).sum())
            total_msgs = int(np.asarray(
                jax.device_get(outs[i + 5])).sum())
            s += 1
            logger.debug("obj superstep %d: active=%d msgs=%d",
                         s, n_active, total_msgs)
            if n_active == 0 and total_msgs == 0:
                break
        return self._collect()

    def _collect(self):
        """Final (ids, value leaf columns, active), unpadded and sorted
        by id."""
        ids, leaves, actv = [], [[] for _ in range(self.nvl)], []
        for t in self.tables:
            vid = np.asarray(jax.device_get(t["vid"]))
            act = np.asarray(jax.device_get(t["act"]))
            vls = [np.asarray(jax.device_get(l)) for l in t["vals"]]
            m = vid != _SENT
            ids.append(vid[m])
            actv.append(act[m])
            for i, l in enumerate(vls):
                leaves[i].append(l[m])
        ids = np.concatenate(ids) if ids else np.zeros(0, np.int64)
        order = np.argsort(ids)
        leaves = [np.concatenate(ls)[order] for ls in leaves]
        act = (np.concatenate(actv)[order] if actv
               else np.zeros(0, bool))
        return ids[order], leaves, act
