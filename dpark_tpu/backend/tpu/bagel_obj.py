"""Device adapter for OBJECT Bagel programs — general edition.

Replaces the r4 template-in-state columnarizer (VERDICT r4 #4: its
device subset required degree <= 8, <= 8 degree classes, scalar values,
and messages only to the vertex's own out-edges).  The lifted design:

* **Bucket-sliced tracing.**  Vertices are sharded by hash(id) and, per
  device, grouped into contiguous slices by out-degree CLASS.  With
  ``bagel.DEGREE_BUCKETS`` on (the default) a class is a POWER OF TWO:
  each vertex's edge list pads to the next power of two with masked
  dummy edges (target = the padding sentinel, value 0), so an arbitrary
  degree distribution costs at most ``1 + log2(MAX_DEGREE)`` traces
  (11 at the default cap) instead of one per distinct degree — the
  power-law class cap is gone.  Soundness is verified per (class,
  superstep) by an EXACT-VS-BUCKET CANARY: the user compute runs
  eagerly on small synthetic slices at exact degrees and at the padded
  width, and any divergence of vertex values, active flags, or
  non-dummy messages (plus any ``len(outEdges)`` call, recorded by the
  traced edge list) falls back to exact degree classes — the r4
  behavior, still capped by MAX_DEGREE_CLASSES — and from there to the
  host paths.  The canary is an empirical check on synthetic inputs
  (the same verification contract as the text tokenizer's sample
  check): the canonical per-edge message pattern passes because dummy
  targets carry the sentinel and drop at delivery; computes that fold
  edge values into vertex state or read the tail diverge on the canary
  and are rejected.
* **Messages are data (CSR-style send).**  ``Message.target_id`` may be
  any integer — a traced edge target, a computed id, a constant —
  because emitted messages leave compute as (dst, value-leaf) ARRAYS,
  flatten across classes into one per-device buffer sized by the total
  message count (not n x max_degree), and route by hash(dst) through
  the same bucketize-combine + all_to_all exchange the shuffle plane
  uses.  Messages to non-neighbors and variable message counts
  (halt-and-send, notify-one) all work; unknown targets drop at
  delivery exactly like the object loop.
* **Structured vertex AND message values.**  ``Vertex.value`` may be
  any pytree of numeric scalars/vectors; ``Message.value`` may be a
  small numeric pytree too (ISSUE 4 satellite — e.g. a
  ``(count, sum_vector)`` pair): each leaf rides as one extra exchange
  column (scalars or small fixed-shape vectors), and the combiner is
  either a per-leaf monoid (a classified BasicCombiner op over a
  SINGLE leaf, e.g. ``np.add`` over a vector) or the user's op TRACED
  as a structure-preserving merge over the leaf tuple (verified at
  discovery; an op that changes the value structure — tuple ``+`` is
  host concatenation — stays on the host paths).

Semantics parity with Bagel._run_fast (the host golden model): inactive
vertices with no mail pass through untouched; only compute-invoked
vertices may send; the halting counters see EMITTED messages (unknown
targets included, dropped at next delivery); superstep is a static
Python int per compiled step (object programs branch on it).

Reference: dpark/bagel.py superstep loop (SURVEY.md 3.2); the hash-dst
exchange is the survey's [H] mapping, shared with backend/tpu/bagel.py.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from dpark_tpu import conf
from dpark_tpu.backend.tpu import collectives, layout
from dpark_tpu.backend.tpu.executor import _shard_map
from dpark_tpu.utils.log import get_logger
from dpark_tpu.utils.phash import phash_np

logger = get_logger("tpu.bagel_obj")

AXIS = conf.MESH_AXIS
_SENT = np.iinfo(np.int64).max

# observability for the degree-bucketing tests: how the LAST
# DeviceObjectPregel construction classified the graph
LAST_RUN_STATS = {}


def _not_columnar(msg):
    from dpark_tpu.bagel import _NotColumnarizable
    return _NotColumnarizable(msg)


class _DegreeDependent(Exception):
    """Internal: the user compute consults the degree (len(outEdges))
    or diverges on the exact-vs-bucket canary — buckets are unsound for
    it; fall back to exact degree classes."""


class _EdgeList(list):
    """The outEdges list handed to compute under BUCKETED tracing: a
    bucket width is not the true degree, so any len() consultation is
    recorded and rejects bucketing for this program (exact classes,
    where len is exact, take over)."""

    def __init__(self, items, cell):
        super().__init__(items)
        self._dpark_cell = cell

    def __len__(self):
        self._dpark_cell["len_used"] = True
        return super().__len__()

    def __bool__(self):
        # truthiness is only "any edges?" — every member of a padded
        # class has >= 1 REAL edge (0-degree vertices sit in the exact
        # class 0), so emptiness is degree-safe and must NOT flag the
        # compute as degree-dependent (Vertex.__init__'s `outEdges or
        # []` would otherwise reject every bucketed program)
        return list.__len__(self) > 0


def _class_width(d, bucketed):
    """Degree class of a vertex: the exact degree, or the next power of
    two under bucketing (0 stays 0 — no edges, nothing to pad)."""
    if not bucketed or d <= 1:
        return int(d)
    return 1 << int(d - 1).bit_length()


class DeviceObjectPregel:
    """One columnarized object-Bagel run over the executor's mesh.

    Inputs are already validated/flattened by Bagel._run_columnar:
      ids (n,) int64 unique; vleaves: list of (n, ...) numeric columns
      (the flattened Vertex.value pytree); act (n,) bool; degs (n,)
      int64; tgt_flat (E,) int64 edge targets in per-vertex emission
      order (CSR with offsets = cumsum(degs)); ev_flat: None or (E,)
      numeric edge values; pend: None or (dst (m,), leaf columns,
      treedef) initial messages; compute: the user's object compute;
      monoid: the provable BasicCombiner op classification (None when
      the op must ride as a traced merge); combine_op: the raw op.
    """

    def __init__(self, executor, compute, monoid, vdef, ids, vleaves,
                 act, degs, tgt_flat, ev_flat, pend, max_superstep,
                 combine_op=None):
        from dpark_tpu.bagel import PregelInputError
        self.ex = executor
        self.ndev = executor.ndev
        self.mesh = executor.mesh
        self.compute = compute
        self.monoid = monoid
        self.combine_op = combine_op
        self.vdef = vdef
        self.max_superstep = max_superstep
        self._compiled = {}
        self._canaried = set()
        n = ids.shape[0]
        if np.unique(ids).shape[0] != n:
            raise PregelInputError("vertex ids must be unique")
        if n and int(ids.max()) == _SENT:
            raise PregelInputError("vertex id equals the padding sentinel")
        self.vdtypes = [np.dtype(l.dtype) for l in vleaves]
        self.vshapes = [tuple(l.shape[1:]) for l in vleaves]
        self.nvl = len(vleaves)
        self.has_ev = ev_flat is not None
        self.edt = np.dtype(ev_flat.dtype) if self.has_ev else None

        from dpark_tpu import bagel as _bagel
        degs_list = degs.tolist()
        want_buckets = _bagel.DEGREE_BUCKETS \
            and len(set(degs_list)) > 1
        # class selection + message-spec discovery + (bucketed only)
        # the superstep-0 canary; a degree-dependent compute falls back
        # to exact classes, re-checking the r4 class-count cap
        try:
            self._setup_classes(degs_list, bucketed=want_buckets,
                                pend=pend)
        except _DegreeDependent as e:
            if not want_buckets:
                raise _not_columnar(str(e))
            logger.info("degree buckets unsound for this compute "
                        "(%s); exact degree classes", e)
            self._setup_classes(degs_list, bucketed=False, pend=pend)
        LAST_RUN_STATS.clear()
        LAST_RUN_STATS.update({
            "bucketed": self.bucketed,
            "classes": len(self.classes),
            "widths": list(self.classes),
            "distinct_degrees": len(set(degs_list)),
            "msg_leaves": self.nm,
            "msg_merge": "monoid" if self._mmerge is None else "traced",
        })

        # -- per-(class, device) tables ---------------------------------
        ndev = self.ndev
        vdev = (phash_np(ids) % np.uint32(ndev)).astype(np.int64)
        offs = np.concatenate([[0], np.cumsum(degs)]).astype(np.int64)
        widths = np.asarray([_class_width(d, self.bucketed)
                             for d in degs_list], np.int64)
        sh = self._sharding()
        put = lambda a: jax.device_put(a, sh)           # noqa: E731
        ecap = max(int(tgt_flat.shape[0]) - 1, 0)
        self.tables = []
        for d in self.classes:
            sel = np.nonzero(widths == d)[0]
            cdev = vdev[sel]
            order = np.argsort(cdev, kind="stable")
            sel = sel[order]
            bounds = np.searchsorted(cdev[order], np.arange(ndev + 1))
            cnt = np.diff(bounds).astype(np.int32)
            cap = layout.round_capacity(int(cnt.max()) if sel.size else 1)
            vid = np.full((ndev, cap), _SENT, np.int64)
            hact = np.zeros((ndev, cap), bool)
            hvl = [np.zeros((ndev, cap) + shp, dt)
                   for dt, shp in zip(self.vdtypes, self.vshapes)]
            htg = np.full((ndev, cap, d), _SENT, np.int64)
            hev = (np.zeros((ndev, cap, d), self.edt)
                   if self.has_ev else None)
            for dev in range(ndev):
                lo, hi = int(bounds[dev]), int(bounds[dev + 1])
                c = hi - lo
                if not c:
                    continue
                s = sel[lo:hi]
                vid[dev, :c] = ids[s]
                hact[dev, :c] = act[s]
                for h, l in zip(hvl, vleaves):
                    h[dev, :c] = l[s]
                if d:
                    # bucketed classes: each row fills its TRUE degree,
                    # the tail keeps the sentinel target / zero value
                    dtrue = degs[s]
                    col = np.arange(d)[None, :]
                    eidx = offs[s][:, None] + np.minimum(
                        col, np.maximum(dtrue[:, None] - 1, 0))
                    eidx = np.clip(eidx, 0, ecap)
                    m = col < dtrue[:, None]
                    htg[dev, :c] = np.where(m, tgt_flat[eidx], _SENT)
                    if self.has_ev:
                        hev[dev, :c] = np.where(m, ev_flat[eidx],
                                                np.zeros((), self.edt))
            self.tables.append({
                "d": d, "cap": cap,
                "vid": put(vid), "act": put(hact),
                "vals": [put(h) for h in hvl],
                "tgts": put(htg),
                "evals": put(hev) if self.has_ev else None,
            })

        # -- initial messages, bucketized by hash(dst) -------------------
        self.init = None
        self.init_count = 0
        if pend is not None and pend[0].size:
            idst, ivls, imdef = pend
            if imdef != self.mdef:
                raise _not_columnar(
                    "initial message value structure differs from the "
                    "structure compute emits")
            for l, dt, shp in zip(ivls, self.mdts, self.mshapes):
                if tuple(np.asarray(l).shape[1:]) != shp:
                    raise _not_columnar(
                        "initial message leaf shape mismatch")
            mdev = (phash_np(idst) % np.uint32(ndev)).astype(np.int64)
            mc = np.bincount(mdev, minlength=ndev)
            cap_m = layout.round_capacity(int(mc.max() or 1))
            hm_d = np.full((ndev, cap_m), _SENT, np.int64)
            hm_v = [np.zeros((ndev, cap_m) + shp, dt)
                    for dt, shp in zip(self.mdts, self.mshapes)]
            mcnt = np.zeros(ndev, np.int32)
            for dev in range(ndev):
                m = mdev == dev
                c = int(m.sum())
                mcnt[dev] = c
                if c:
                    hm_d[dev, :c] = idst[m]
                    for hl, l in zip(hm_v, ivls):
                        hl[dev, :c] = np.asarray(l)[m].astype(hl.dtype)
            self.init = (put(mcnt), put(hm_d), [put(l) for l in hm_v])
            self.init_count = int(idst.size)

    # ------------------------------------------------------------------
    # class selection + message-spec discovery
    # ------------------------------------------------------------------
    def _setup_classes(self, degs_list, bucketed, pend):
        from dpark_tpu import bagel as _bagel
        self.bucketed = bucketed
        classes = sorted({_class_width(d, bucketed)
                          for d in degs_list}) or [0]
        if not bucketed and len(classes) > _bagel.MAX_DEGREE_CLASSES:
            raise _not_columnar(
                "%d degree classes > %d (each distinct degree is a "
                "separate trace)" % (len(classes),
                                     _bagel.MAX_DEGREE_CLASSES))
        self.classes = classes
        # min true degree per class: a class whose members all sit at
        # the class width has no padding — the canary can skip it
        self._class_min_deg = {}
        for d in degs_list:
            w = _class_width(d, bucketed)
            cur = self._class_min_deg.get(w)
            self._class_min_deg[w] = d if cur is None else min(cur, d)
        self._discover_mspec(pend)
        self._setup_merge()
        if bucketed:
            self._bucket_canary(0)

    def _mail_structs(self, batch=4):
        return [jax.ShapeDtypeStruct((batch,) + shp, dt)
                for dt, shp in zip(self.mdts, self.mshapes)]

    def _body_structs(self, d, mail, batch=4, mdts=None, mshapes=None):
        vs = [jax.ShapeDtypeStruct((batch,) + shp, dt)
              for dt, shp in zip(self.vdtypes, self.vshapes)]
        args = vs + [jax.ShapeDtypeStruct((batch,), np.int64),
                     jax.ShapeDtypeStruct((batch, d), np.int64)]
        if self.has_ev:
            args.append(jax.ShapeDtypeStruct((batch, d), self.edt))
        if mail:
            mdts = self.mdts if mdts is None else mdts
            mshapes = self.mshapes if mshapes is None else mshapes
            args.extend(jax.ShapeDtypeStruct((batch,) + shp, dt)
                        for dt, shp in zip(mdts, mshapes))
        args.append(jax.ShapeDtypeStruct((batch,), np.bool_))
        return args

    def _discover_mspec(self, pend):
        """Fixed-point discovery of the MESSAGE VALUE SPEC — pytree
        structure + per-leaf dtype/shape — across ALL classes and both
        mail variants (a guess would silently truncate, e.g. int state
        emitting float shares).  Initial messages seed the spec: they
        feed the same combine and delivery as emitted ones."""
        import jax.tree_util as jtu
        if pend is not None and pend[0].size:
            _, ivls, imdef = pend
            for l in ivls:
                if np.asarray(l).dtype.kind not in "if":
                    raise _not_columnar(
                        "non-numeric initial message values")
            spec = (imdef,
                    [np.asarray(l).dtype for l in ivls],
                    [tuple(np.asarray(l).shape[1:]) for l in ivls])
            pure_guess = False
        else:
            guess = np.result_type(
                *([dt for dt in self.vdtypes if dt.kind in "if"]
                  or [np.dtype(np.float64)]))
            spec = (jtu.tree_structure(0), [np.dtype(guess)], [()])
            pure_guess = True
        for rnd in range(4):
            # only the round-0 PURE GUESS may be replaced wholesale by
            # the first emission; a pend-seeded or settled spec is a
            # contract emissions must match
            found = [spec[0], list(spec[1]), list(spec[2]),
                     not (pure_guess and rnd == 0)]
            mail_err = None
            for mail in (False, True):
                for d in self.classes:
                    cell = {}
                    self.mdef, self.mdts, self.mshapes = \
                        spec[0], list(spec[1]), list(spec[2])
                    self.nm = len(spec[1])
                    body = self._class_body(d, 0, mail, cell,
                                            discovery=True)
                    try:
                        jax.eval_shape(jax.vmap(body),
                                       *self._body_structs(d, mail))
                    except Exception as e:
                        from dpark_tpu.bagel import _NotColumnarizable
                        if isinstance(e, (_NotColumnarizable,
                                          _DegreeDependent)):
                            raise
                        if mail:
                            # the mail STRUCT may simply be the wrong
                            # guess this round (compute indexes a tuple
                            # message while the seed is scalar): retry
                            # once the no-mail emissions correct it
                            mail_err = e
                            continue
                        raise _not_columnar(
                            "compute does not trace (%s)" % str(e)[:200])
                    if self.bucketed and cell.get("len_used"):
                        raise _DegreeDependent(
                            "compute consults len(outEdges)")
                    if "mdef" in cell:
                        if not found[3]:
                            # first emission this round: adopt its spec
                            # wholesale (the seed was only a guess)
                            found = [cell["mdef"], list(cell["mdts"]),
                                     list(cell["mshapes"]), True]
                        elif cell["mdef"] != found[0]:
                            raise _not_columnar(
                                "message value structure varies "
                                "across classes/supersteps")
                        elif cell["mshapes"] != found[2]:
                            raise _not_columnar(
                                "message leaf shapes vary")
                        else:
                            found[1] = [np.result_type(a, b)
                                        for a, b in zip(found[1],
                                                        cell["mdts"])]
            found_spec = (found[0], [np.dtype(t) for t in found[1]],
                          found[2])
            if found_spec == spec:
                if mail_err is not None:
                    raise _not_columnar(
                        "compute does not trace (%s)"
                        % str(mail_err)[:200])
                break
            spec = found_spec
        else:
            raise _not_columnar("message spec does not stabilize")
        self.mdef, self.mdts, self.mshapes = \
            spec[0], list(spec[1]), list(spec[2])
        self.nm = len(self.mdts)
        for shp in self.mshapes:
            if len(shp) > 1:
                raise _not_columnar(
                    "message leaves must be scalars or 1-D vectors")

    def _setup_merge(self):
        """Choose the message combine: a classified monoid applies
        PER LEAF only when the value is a single leaf (a bytecode
        ``a + b`` over a tuple is host concatenation, not elementwise);
        everything else traces the user's op as a structure-preserving
        merge over the leaf tuple, used by the same bucketize-combine /
        segment-reduce call sites."""
        import jax.tree_util as jtu
        from dpark_tpu.bagel import PREGEL_MONOIDS
        if self.nm == 1 and self.monoid in PREGEL_MONOIDS \
                and not self.mshapes[0]:
            self._mmerge = None
            return
        if self.nm == 1 and self.monoid in PREGEL_MONOIDS \
                and self.mshapes[0]:
            # single VECTOR leaf: classified ops (np.add & co) are
            # elementwise over arrays — the per-leaf monoid is sound
            self._mmerge = None
            return
        op = self.combine_op
        if op is None:
            raise _not_columnar("combiner op not a provable monoid")
        mdef = self.mdef
        nm = self.nm

        def leaf_merge(*flat):
            a = jtu.tree_unflatten(mdef, list(flat[:nm]))
            b = jtu.tree_unflatten(mdef, list(flat[nm:]))
            out = op(a, b)
            leaves, odef = jtu.tree_flatten(out)
            if odef != mdef:
                raise _not_columnar(
                    "combiner op does not preserve the message value "
                    "structure (host semantics would differ)")
            return tuple(leaves)

        vfn = jax.vmap(leaf_merge)

        def merged(va_leaves, vb_leaves):
            return [l.astype(dt) for l, dt in
                    zip(vfn(*(list(va_leaves) + list(vb_leaves))),
                        self.mdts)]

        try:
            structs = self._mail_structs()
            outs = jax.eval_shape(lambda *v: merged(
                list(v[:nm]), list(v[nm:])), *(structs + structs))
        except Exception as e:
            from dpark_tpu.bagel import _NotColumnarizable
            if isinstance(e, _NotColumnarizable):
                raise
            raise _not_columnar(
                "combiner op does not trace over the message leaves "
                "(%s)" % str(e)[:160])
        for o, dt, shp in zip(outs, self.mdts, self.mshapes):
            if tuple(o.shape[1:]) != shp:
                raise _not_columnar("combiner changes a message leaf "
                                    "shape")
        self.monoid = None
        self._mmerge = merged

    def _ident(self, li):
        """Filler for 'no message' rows of leaf li: the monoid identity
        when a monoid combines (absent mail then behaves as the
        identity at every call site), zeros otherwise (rows without
        mail take the no-mail trace; the filler value is never read)."""
        from dpark_tpu.bagel import monoid_identity
        if self.monoid is not None:
            return monoid_identity(self.monoid, self.mdts[li])
        return np.dtype(self.mdts[li]).type(0)

    # ------------------------------------------------------------------
    # the per-(class, superstep, mail) traced body
    # ------------------------------------------------------------------
    def _class_body(self, d, s, mail, cell, discovery=False):
        """Per-vertex fn for jax.vmap over one class slice.  mail=False
        is the object contract's no-mail call (msg is the LITERAL None,
        so ``msg is not None`` branches exactly as on the host paths).
        ``cell["m"]`` reports the static emitted-message count of this
        trace.  discovery=True collects emitted message specs into the
        cell instead of checking them."""
        from dpark_tpu.bagel import Edge, Message, Vertex
        import jax.tree_util as jtu
        nvl = self.nvl
        nm = self.nm
        vdef = self.vdef
        mdef = self.mdef
        bucketed = self.bucketed

        def body(*args):
            i = nvl
            vls = args[:i]
            vid = args[i]; i += 1
            tgts = args[i]; i += 1
            evs = None
            if self.has_ev:
                evs = args[i]; i += 1
            m = None
            if mail:
                mleaves = args[i:i + nm]; i += nm
                m = jtu.tree_unflatten(mdef, list(mleaves))
            a = args[i]
            value = jtu.tree_unflatten(vdef, list(vls))
            edge_items = [Edge(tgts[j], evs[j] if evs is not None
                               else None) for j in range(d)]
            edges = (_EdgeList(edge_items, cell) if bucketed
                     else edge_items)
            vert = Vertex(vid, value, edges, a)
            out = self.compute(vert, m, None, s)
            if not (isinstance(out, tuple) and len(out) == 2):
                raise _not_columnar("compute must return "
                                    "(vertex, messages)")
            nv, out_msgs = out
            if not isinstance(nv, Vertex):
                raise _not_columnar("compute returned non-Vertex")
            if nv.id is not vert.id:
                raise _not_columnar("compute rebound vertex id")
            new_leaves, ndef = jtu.tree_flatten(nv.value)
            if ndef != vdef:
                raise _not_columnar(
                    "compute changed the vertex value structure")
            outs = []
            for leaf, dt, shp in zip(new_leaves, self.vdtypes,
                                     self.vshapes):
                arr = jnp.asarray(leaf)
                if np.result_type(arr.dtype, dt) != np.dtype(dt):
                    raise _not_columnar(
                        "superstep %d produces %s vertex values, wider "
                        "than the initial %s" % (s, arr.dtype, dt))
                arr = jnp.asarray(arr, dt)
                if arr.shape != shp:
                    raise _not_columnar("vertex value leaf shape "
                                        "changed at superstep %d" % s)
                outs.append(arr)
            dsts, vals = [], []
            for msg_obj in (out_msgs or []):
                if not isinstance(msg_obj, Message):
                    raise _not_columnar("non-Message output")
                t = msg_obj.target_id
                if isinstance(t, bool):
                    raise _not_columnar("non-integer message target")
                td = jnp.asarray(t)
                if td.shape != () or td.dtype.kind not in "iu":
                    raise _not_columnar(
                        "message target must be an integer scalar")
                mleaves, odef = jtu.tree_flatten(msg_obj.value)
                if not mleaves:
                    raise _not_columnar(
                        "message value has no numeric leaves")
                marrs = [jnp.asarray(l) for l in mleaves]
                for arr in marrs:
                    if arr.dtype.kind not in "if":
                        raise _not_columnar("non-numeric message value")
                if discovery:
                    shapes = [tuple(arr.shape) for arr in marrs]
                    if "mdef" in cell:
                        if odef != cell["mdef"] \
                                or shapes != cell["mshapes"]:
                            raise _not_columnar(
                                "message value structure varies "
                                "within one superstep")
                        cell["mdts"] = [np.result_type(a, arr.dtype)
                                        for a, arr in zip(cell["mdts"],
                                                          marrs)]
                    else:
                        cell["mdef"] = odef
                        cell["mdts"] = [np.dtype(arr.dtype)
                                        for arr in marrs]
                        cell["mshapes"] = shapes
                else:
                    if odef != mdef:
                        raise _not_columnar(
                            "superstep %d emits a different message "
                            "value structure than discovered" % s)
                    casted = []
                    for arr, dt, shp in zip(marrs, self.mdts,
                                            self.mshapes):
                        if tuple(arr.shape) != shp:
                            raise _not_columnar(
                                "message leaf shape changed at "
                                "superstep %d" % s)
                        if np.result_type(arr.dtype, dt) != np.dtype(dt):
                            raise _not_columnar(
                                "superstep %d emits %s message leaves, "
                                "wider than the discovered %s"
                                % (s, arr.dtype, dt))
                        casted.append(jnp.asarray(arr, dt))
                    marrs = casted
                dsts.append(jnp.asarray(td, jnp.int64))
                vals.append(marrs)
            cell["m"] = len(dsts)
            na = jnp.asarray(nv.active, bool)
            if na.shape != ():
                raise _not_columnar("Vertex.active must be a scalar")
            md = (jnp.stack(dsts) if dsts
                  else jnp.zeros((0,), jnp.int64))
            mv_leaves = []
            for li in range(nm):
                dt = self.mdts[li]
                shp = self.mshapes[li]
                if vals:
                    mv_leaves.append(jnp.stack(
                        [v[li] if li < len(v) else jnp.zeros(shp, dt)
                         for v in vals]))
                else:
                    mv_leaves.append(jnp.zeros((0,) + shp, dt))
            return tuple(outs) + (na, md) + tuple(mv_leaves)
        return body

    # ------------------------------------------------------------------
    # exact-vs-bucket canary
    # ------------------------------------------------------------------
    @staticmethod
    def _canary_draw(rng, dt, shape):
        """Mixed-sign sample values: a dummy tail's zeros/sentinels are
        only provably visible when real values can sit on EITHER side
        of them (max over all-positive edge values equals max with a
        zero pad — all-positive draws would admit zero-pad-unsound
        computes; review finding, mirroring fuse._seg_pad_cases)."""
        if np.dtype(dt).kind == "f":
            return rng.uniform(-5.0, 5.0, size=shape).astype(dt)
        return rng.randint(-4, 5, size=shape).astype(dt)

    def _canary_rows(self, rng, n, d_true, width):
        """Synthetic per-vertex inputs at exact degree d_true, plus the
        same rows padded to `width` with dummy edges (sentinel targets,
        zero values)."""
        vids = np.arange(1, n + 1, dtype=np.int64)
        vals = [self._canary_draw(rng, dt, (n,) + shp)
                for dt, shp in zip(self.vdtypes, self.vshapes)]
        tgt_e = rng.randint(1, n + 1,
                            size=(n, d_true)).astype(np.int64)
        tgt_b = np.concatenate(
            [tgt_e, np.full((n, width - d_true), _SENT, np.int64)],
            axis=1)
        ev_e = ev_b = None
        if self.has_ev:
            ev_e = self._canary_draw(rng, self.edt, (n, d_true))
            ev_b = np.concatenate(
                [ev_e, np.zeros((n, width - d_true), self.edt)], axis=1)
        act = np.ones(n, bool)
        mleaves = [self._canary_draw(rng, dt, (n,) + shp)
                   for dt, shp in zip(self.mdts, self.mshapes)]
        return vids, vals, act, (tgt_e, ev_e), (tgt_b, ev_b), mleaves

    @staticmethod
    def _same_values(a, b):
        """Exact equality with NaN == NaN (mixed-sign canary draws can
        legitimately produce NaN/inf on BOTH sides — e.g. sqrt of a
        negative — and that must not read as divergence)."""
        a = np.asarray(a)
        b = np.asarray(b)
        if a.shape != b.shape:
            return False
        if a.dtype.kind == "f" or b.dtype.kind == "f":
            return bool(np.array_equal(a.astype(np.float64),
                                       b.astype(np.float64),
                                       equal_nan=True))
        return bool(np.array_equal(a, b))

    @staticmethod
    def _canary_msgs(outs, nvl, nm):
        """Per-vertex ordered (dst, leaves...) lists with sentinel
        (dummy-edge) messages dropped — dropped at delivery in real
        runs, so they carry no semantics."""
        md = np.asarray(outs[nvl + 1])
        leaves = [np.asarray(outs[nvl + 2 + li]) for li in range(nm)]
        per_vertex = []
        for i in range(md.shape[0]):
            row = []
            for j in range(md.shape[1]):
                if int(md[i, j]) == _SENT:
                    continue
                row.append((int(md[i, j]),
                            tuple(np.asarray(l[i, j]) for l in leaves)))
            per_vertex.append(row)
        return per_vertex

    @classmethod
    def _canary_msgs_equal(cls, me, mb):
        if len(me) != len(mb):
            return False
        for ra, rb in zip(me, mb):
            if len(ra) != len(rb):
                return False
            for (da, la), (db, lb) in zip(ra, rb):
                if da != db or len(la) != len(lb):
                    return False
                if not all(cls._same_values(x, y)
                           for x, y in zip(la, lb)):
                    return False
        return True

    def _bucket_canary(self, s):
        """Empirical soundness check of the padded classes at superstep
        `s`: the user compute, evaluated EAGERLY on small synthetic
        slices, must produce identical vertex values / active flags and
        identical non-dummy messages at the exact degree and at the
        bucket width.  Divergence means the compute reads the dummy
        tail (or otherwise depends on the padded width) — bucketing is
        unsound for it."""
        if not self.bucketed or s in self._canaried:
            return
        self._canaried.add(s)
        for width in self.classes:
            lb = self._class_min_deg.get(width, width)
            if width == 0 or lb >= width:
                continue             # no padded vertex in this class
            degrees = sorted({lb, (lb + width) // 2, width - 1})
            rng = np.random.RandomState(0xBA6E1 + 31 * s)
            for d_true in degrees:
                if d_true < 1:
                    continue
                n = 3
                (vids, vals, act, (tgt_e, ev_e), (tgt_b, ev_b),
                 mleaves) = self._canary_rows(rng, n, d_true, width)
                for mail in (True, False):
                    def run(width_, tgt, ev):
                        cell = {}
                        body = self._class_body(width_, s, mail, cell)
                        args = list(vals) + [vids, tgt]
                        if self.has_ev:
                            args.append(ev)
                        if mail:
                            args.extend(mleaves)
                        args.append(act)
                        return jax.vmap(body)(*args), cell
                    try:
                        oe, ce = run(d_true, tgt_e, ev_e)
                    except Exception as e:
                        # the exact-degree trace fails (e.g. compute
                        # indexes past a small true degree): exact
                        # classes would fail identically — surface
                        # through the normal fallback
                        raise _DegreeDependent(
                            "compute fails at exact degree %d (%s)"
                            % (d_true, str(e)[:120]))
                    ob, cb = run(width, tgt_b, ev_b)
                    if ce.get("len_used") or cb.get("len_used"):
                        raise _DegreeDependent(
                            "compute consults len(outEdges)")
                    for li in range(self.nvl):
                        if not self._same_values(oe[li], ob[li]):
                            raise _DegreeDependent(
                                "vertex values diverge between exact "
                                "degree %d and bucket %d at superstep "
                                "%d" % (d_true, width, s))
                    if not np.array_equal(np.asarray(oe[self.nvl]),
                                          np.asarray(ob[self.nvl])):
                        raise _DegreeDependent(
                            "active flags diverge under bucketing")
                    me = self._canary_msgs(oe, self.nvl, self.nm)
                    mb = self._canary_msgs(ob, self.nvl, self.nm)
                    if not self._canary_msgs_equal(me, mb):
                        raise _DegreeDependent(
                            "non-dummy messages diverge between exact "
                            "degree %d and bucket %d" % (d_true, width))

    # ------------------------------------------------------------------
    # programs
    # ------------------------------------------------------------------
    def _p_init(self):
        """Bucketize the user's initial messages by hash(dst)."""
        ndev = self.ndev
        monoid = self.monoid
        mmerge = self._mmerge
        nm = self.nm

        def per_device(mcnt, mdst, *mvals):
            vs = [v[0] for v in mvals]
            kk, vv, counts, offsets = collectives.bucketize_combine(
                mdst[0], vs, mcnt[0], ndev, mmerge, monoid=monoid)
            out = (counts, offsets, kk) + tuple(vv)
            return tuple(jnp.expand_dims(o, 0) for o in out)

        key = ("init",)
        if key not in self._compiled:
            fn = _shard_map(per_device, self.mesh,
                            in_specs=(P(AXIS),) * (2 + nm),
                            out_specs=(P(AXIS),) * (3 + nm))
            self._compiled[key] = jax.jit(fn)
        return self._compiled[key]

    def _p_step(self, s, rounds, slot):
        """One superstep: deliver combined messages to every class
        slice, run the class-sliced compute, flatten emitted (dst,
        value-leaves) blocks across classes, pre-combine + bucketize
        them by hash(dst) for the next exchange, and count active
        vertices and emitted messages."""
        key = ("step", s, rounds, slot)
        if key in self._compiled:
            return self._compiled[key]
        self._bucket_canary(s)
        ndev = self.ndev
        monoid = self.monoid
        mmerge = self._mmerge
        nm = self.nm
        nvl = self.nvl
        ncls = len(self.classes)
        caps = [t["cap"] for t in self.tables]
        degs = [t["d"] for t in self.tables]
        has_ev = self.has_ev
        per_cls_in = 3 + nvl + (1 if has_ev else 0)
        idents = [self._ident(li) for li in range(nm)]
        nleaves = 1 + nm

        def per_device(*args):
            # unpack: per class [vid, act, tgts, (evals,) vals...],
            # then rounds x cnt, rounds x (dst, leaf...) buffers
            cls_args = []
            i = 0
            for c in range(ncls):
                cls_args.append(args[i:i + per_cls_in])
                i += per_cls_in
            cnts = [a[0] for a in args[i:i + rounds]]
            i += rounds
            bufs = args[i:]

            if rounds:
                recvs = []
                for r in range(rounds):
                    recvs.append([bufs[r * nleaves + li][0]
                                  for li in range(nleaves)])
                flat, mask = collectives.flatten_received(recvs, cnts)
                uk, uv, _ = collectives.segment_reduce(
                    flat[0], flat[1:], mask, mmerge, monoid=monoid)
            else:
                uk = uv = None

            outs = []
            msg_dsts = []
            msg_vals = [[] for _ in range(nm)]
            n_active = jnp.int64(0)
            emitted = jnp.int64(0)
            for c in range(ncls):
                a = cls_args[c]
                vid, act, tgts = a[0][0], a[1][0], a[2][0]
                j = 3
                evals = None
                if has_ev:
                    evals = a[3][0]
                    j = 4
                vals = [x[0] for x in a[j:]]
                cap, d = caps[c], degs[c]
                valid = vid != _SENT
                if uk is not None:
                    pos = jnp.clip(jnp.searchsorted(uk, vid), 0,
                                   uk.shape[0] - 1)
                    has = (uk[pos] == vid) & valid
                    msg = [jnp.where(
                        collectives._bcast(has, u[pos]), u[pos], ident)
                        for u, ident in zip(uv, idents)]
                else:
                    has = jnp.zeros(cap, bool)
                    msg = [jnp.full((cap,) + shp, ident, dt)
                           for dt, shp, ident in zip(self.mdts,
                                                     self.mshapes,
                                                     idents)]
                invoked = (act | has) & valid

                cm, cn = {}, {}
                margs = vals + [vid, tgts] \
                    + ([evals] if has_ev else [])
                om = jax.vmap(self._class_body(d, s, True, cm))(
                    *(margs + msg + [act]))
                on = jax.vmap(self._class_body(d, s, False, cn))(
                    *(margs + [act]))
                new_vals = []
                for li in range(nvl):
                    pick = jnp.where(
                        collectives._bcast(has, om[li]), om[li], on[li])
                    new_vals.append(jnp.where(
                        collectives._bcast(invoked, pick), pick,
                        vals[li]))
                new_act = invoked & jnp.where(has, om[nvl], on[nvl])
                n_active = n_active + jnp.sum(new_act)
                # emitted (dst, leaves) blocks: the mail trace's
                # messages from invoked+has rows, the no-mail trace's
                # from invoked+~has rows; ungated rows get the sentinel
                # dst and compact away before the bucketize
                for blk, gate, cell in ((om, invoked & has, cm),
                                        (on, invoked & ~has, cn)):
                    m = cell["m"]
                    if not m:
                        continue
                    dst_b = jnp.where(gate[:, None], blk[nvl + 1],
                                      _SENT)
                    msg_dsts.append(dst_b.reshape(-1))
                    for li in range(nm):
                        leaf = blk[nvl + 2 + li]
                        msg_vals[li].append(leaf.reshape(
                            (-1,) + tuple(self.mshapes[li])))
                    emitted = emitted + jnp.sum(gate) * m
                outs.extend(new_vals)
                outs.append(new_act)

            if msg_dsts:
                dst_flat = jnp.concatenate(msg_dsts)
                val_flats = [jnp.concatenate(vl) for vl in msg_vals]
                smask = dst_flat != _SENT
                packed, cnt = collectives.compact(
                    [dst_flat] + val_flats, smask)
                kk, vv, counts, offsets = collectives.bucketize_combine(
                    packed[0], packed[1:], cnt, ndev, mmerge,
                    monoid=monoid)
            else:
                kk = jnp.full((1,), _SENT, jnp.int64)
                vv = [jnp.full((1,) + shp, ident, dt)
                      for dt, shp, ident in zip(self.mdts, self.mshapes,
                                                idents)]
                counts = jnp.zeros((ndev,), jnp.int32)
                offsets = jnp.zeros((ndev,), jnp.int32)
            outs += [counts, offsets, kk] + list(vv) + [
                jnp.reshape(n_active, (1,)),
                jnp.reshape(emitted, (1,))]
            return tuple(jnp.expand_dims(o, 0) for o in outs)

        n_in = ncls * per_cls_in + rounds + rounds * nleaves
        n_out = ncls * (nvl + 1) + 5 + nm
        fn = _shard_map(per_device, self.mesh,
                        in_specs=(P(AXIS),) * n_in,
                        out_specs=(P(AXIS),) * n_out)
        self._compiled[key] = jax.jit(fn)
        return self._compiled[key]

    def _sharding(self):
        return NamedSharding(self.mesh, P(AXIS))

    # ------------------------------------------------------------------
    def run(self):
        nvl = self.nvl
        nm = self.nm
        pending = None
        total_msgs = 0
        if self.init is not None:
            mcnt, mdst, mvals = self.init
            outs = self._p_init()(mcnt, mdst, *mvals)
            pending = (outs[0], outs[1], outs[2], list(outs[3:3 + nm]))
            total_msgs = self.init_count

        s = 0
        n_active = None
        while s < self.max_superstep:
            args = []
            for t in self.tables:
                args.extend([t["vid"], t["act"], t["tgts"]]
                            + ([t["evals"]] if self.has_ev else [])
                            + t["vals"])
            if pending is not None and total_msgs > 0:
                counts, offsets, kk, vv = pending
                recv_rounds, cnt_rounds, slot = self.ex._exchange_all(
                    [kk] + vv, counts, offsets)
                rounds = len(recv_rounds)
                step = self._p_step(s, rounds, slot)
                args.extend(cnt_rounds)
                for r in range(rounds):
                    args.extend(recv_rounds[r])
            else:
                step = self._p_step(s, 0, 0)
            outs = step(*args)
            i = 0
            for t in self.tables:
                t["vals"] = list(outs[i:i + nvl])
                t["act"] = outs[i + nvl]
                i += nvl + 1
            counts, offsets, kk = outs[i:i + 3]
            vv = list(outs[i + 3:i + 3 + nm])
            pending = (counts, offsets, kk, vv)
            n_active = int(np.asarray(
                jax.device_get(outs[i + 3 + nm])).sum())
            total_msgs = int(np.asarray(
                jax.device_get(outs[i + 4 + nm])).sum())
            s += 1
            logger.debug("obj superstep %d: active=%d msgs=%d",
                         s, n_active, total_msgs)
            if n_active == 0 and total_msgs == 0:
                break
        return self._collect()

    def _collect(self):
        """Final (ids, value leaf columns, active), unpadded and sorted
        by id."""
        ids, leaves, actv = [], [[] for _ in range(self.nvl)], []
        for t in self.tables:
            vid = np.asarray(jax.device_get(t["vid"]))
            act = np.asarray(jax.device_get(t["act"]))
            vls = [np.asarray(jax.device_get(l)) for l in t["vals"]]
            m = vid != _SENT
            ids.append(vid[m])
            actv.append(act[m])
            for i, l in enumerate(vls):
                leaves[i].append(l[m])
        ids = np.concatenate(ids) if ids else np.zeros(0, np.int64)
        order = np.argsort(ids)
        leaves = [np.concatenate(ls)[order] for ls in leaves]
        act = (np.concatenate(actv)[order] if actv
               else np.zeros(0, bool))
        return ids[order], leaves, act
