"""Stage fusion: narrow RDD chains -> one traceable per-device program.

The reference pipelines narrow dependencies as nested Python generators
(dpark/rdd.py MappedRDD.compute etc., SURVEY.md 3.1 hot loop #1).  Here the
same chain is *recorded* as a list of array ops and fused into a single
function: user record-level lambdas become columnar code via jax.vmap, so
the whole stage runs as one XLA program per device.

Graceful degradation (SURVEY.md 7.2 item 1): `analyze_stage` probes every
user function with jax.eval_shape on the record spec; anything untraceable
(strings, data-dependent control flow, side effects) returns None and the
scheduler falls back to the object path for that stage.
"""

import numpy as np

import jax
import jax.numpy as jnp

from dpark_tpu.backend.tpu import layout
from dpark_tpu.dependency import HashPartitioner, RangePartitioner
from dpark_tpu.rdd import (
    CoGroupedRDD, CSVFileRDD, CSVReaderRDD, DerivedRDD, FilteredRDD,
    FlatMappedRDD, FlatMappedValuesRDD, GZipFileRDD, KeyedRDD,
    MapPartitionsRDD, MappedRDD, MappedValuesRDD, ParallelCollection,
    ShuffledRDD, TextFileRDD, UnionRDD, _SortPartFn, _append, _extend,
    _identity, _join_values, _mk_list)
from dpark_tpu.utils.log import get_logger

logger = get_logger("tpu.fuse")

# why the LAST analyze_stage call declined the array path (set at the
# key-shape decline sites, cleared per call): the scheduler surfaces it
# in the per-stage job record and the host-fallback-key lint rule gives
# the same answer pre-flight.  Best-effort observability — never
# consulted for control flow.
_last_fallback = [None]


def _fallback(reason):
    _last_fallback[0] = reason
    return None


def last_fallback_reason():
    return _last_fallback[0]


def is_list_agg(agg):
    """The identity list-aggregator trio used by groupByKey/partitionBy:
    values need repartitioning but no combining (no-combine shuffle)."""
    return (agg.create_combiner is _mk_list
            and agg.merge_value is _append
            and agg.merge_combiners is _extend)


def partitioner_spec(part):
    """Device destination function spec for a partitioner, or None."""
    if isinstance(part, HashPartitioner):
        return ("hash",)
    if isinstance(part, RangePartitioner):
        try:
            bounds = np.asarray(part.bounds)
        except Exception:
            return None
        if bounds.dtype == object or bounds.dtype.kind in "USO":
            return None
        return ("range", bool(part.ascending))
    return None


def _spec_struct(specs):
    return [jax.ShapeDtypeStruct(shape, dt) for dt, shape in specs]


def _batched_spec_struct(specs, n=4):
    return [jax.ShapeDtypeStruct((n,) + shape, dt) for dt, shape in specs]


# exact monoid identification lives in the SHARED jax-free core
# (utils/monoid.py) so the pre-flight linter classifies identically;
# this backend contributes its jnp identities to the by-identity table
from dpark_tpu.utils import monoid as _monoid

_monoid.register_direct({jnp.add: "add", jnp.multiply: "mul",
                         jnp.minimum: "min", jnp.maximum: "max"})


def classify_merge(merge):
    """EXACT algebraic classification of a user merge function —
    "add" | "min" | "max" | "mul" | None.  See utils/monoid.py for the
    proof obligations (only provable matches qualify; everything else
    returns None and runs through the traced user function)."""
    return _monoid.classify_merge(merge)


from dpark_tpu.utils import builtin_globals_ok as _builtin_globals_ok


def classify_segagg(f):
    """EXACT classification of a mapValues function applied to a
    groupByKey value LIST as a per-group aggregate (VERDICT r4 #3:
    group->aggregate chains ride the mesh as segment reductions, no
    (k, [v]) lists ever materialize).  Delegates to the shared
    jax-free core (utils/monoid.py) — same proof obligations as
    classify_merge; only provable matches qualify."""
    return _monoid.classify_segagg(f)


def _subscript_const_index(f):
    """The integer I when f is exactly ``lambda x: x[I]`` (closure-free,
    any spelling with the same bytecode, e.g. rdd._snd) — the provable
    select-one-leaf top() key.  None otherwise."""
    code = getattr(f, "__code__", None)
    if code is None or getattr(f, "__closure__", None):
        return None
    if code.co_argcount != 1 or code.co_flags & 0x0C:
        return None
    t = (lambda x: x[99]).__code__
    if not (code.co_code == t.co_code and code.co_names == t.co_names):
        return None
    ints = [c for c in code.co_consts
            if isinstance(c, int) and not isinstance(c, bool)]
    t_other = [c for c in t.co_consts
               if not isinstance(c, int) or isinstance(c, bool)]
    other = [c for c in code.co_consts
             if not isinstance(c, int) or isinstance(c, bool)]
    if len(ints) != 1 or other != t_other:
        return None
    return ints[0]


class _IntInterval:
    """Exact integer interval for the ranged-int top-k key probe: the
    user's key expression is EXECUTED once over per-column [min, max]
    intervals (Python big ints — no wrap), and every intermediate
    operation checks its bounds against int64.  If the whole expression
    stays in range, device i64 arithmetic provably never wraps and the
    device-computed key equals the host's exact Python int for every
    record — sound, unlike a corner check of the output alone (which
    misses interior extremes like x*(K-x) and overflowing
    intermediates).  Any operation outside +, -, *, // (positive
    divisor), and unary +/- raises and keeps the host path."""

    _LIMIT = 2 ** 63 - 1

    __slots__ = ("lo", "hi")

    def __init__(self, lo, hi):
        if abs(lo) > self._LIMIT or abs(hi) > self._LIMIT:
            raise OverflowError("interval exceeds int64")
        self.lo, self.hi = lo, hi

    @classmethod
    def _of(cls, other):
        if isinstance(other, _IntInterval):
            return other
        if isinstance(other, bool) or not isinstance(other, int):
            raise TypeError("non-int operand")
        return cls(other, other)

    def __add__(self, o):
        o = self._of(o)
        return _IntInterval(self.lo + o.lo, self.hi + o.hi)
    __radd__ = __add__

    def __sub__(self, o):
        o = self._of(o)
        return _IntInterval(self.lo - o.hi, self.hi - o.lo)

    def __rsub__(self, o):
        return self._of(o).__sub__(self)

    def __mul__(self, o):
        o = self._of(o)
        corners = [self.lo * o.lo, self.lo * o.hi,
                   self.hi * o.lo, self.hi * o.hi]
        return _IntInterval(min(corners), max(corners))
    __rmul__ = __mul__

    def __floordiv__(self, o):
        o = self._of(o)
        if o.lo <= 0:
            raise ValueError("floordiv needs a provably positive "
                             "divisor")
        return _IntInterval(min(self.lo // o.lo, self.lo // o.hi),
                            max(self.hi // o.lo, self.hi // o.hi))

    def __neg__(self):
        return _IntInterval(-self.hi, -self.lo)

    def __pos__(self):
        return self


def _ranged_int_key_ok(key, treedef, specs, col_ranges):
    """True when the user's int key expression provably stays inside
    int64 over the batch's actual per-column value ranges (the
    ranged-int probe: `col_ranges[i]` = exact (lo, hi) ints of leaf i,
    None for non-int leaves — any read of an unranged leaf aborts)."""
    import jax.tree_util as jtu
    if col_ranges is None or len(col_ranges) != len(specs):
        return False
    leaves = []
    for rng, (dt, shape) in zip(col_ranges, specs):
        if rng is None or shape != () or dt.kind != "i":
            return False
        leaves.append(_IntInterval(int(rng[0]), int(rng[1])))
    try:
        out = key(jtu.tree_unflatten(treedef, leaves))
        return isinstance(out, _IntInterval)
    except Exception:
        return False


def classify_top_key(key, treedef, specs, encoded, col_ranges=None):
    """Device top-k eligibility for one result batch: how to compute
    the ordering key of each record on device.

    Returns ("leaf", i) to order by leaf column i, ("fn", key) to
    order by the traced user key (scalar numeric output), or None
    (host path).  With dictionary-ENCODED string keys in leaf 0, only
    a provable value-leaf subscript (index >= 1) qualifies — anything
    that could read leaf 0 would order by the raw ids.

    Traced INT key expressions qualify only with `col_ranges` (exact
    per-column min/max of the batch): the interval probe re-executes
    the expression over those ranges in exact Python ints and admits it
    only when no intermediate can leave int64 — the device then
    computes the same exact value the host would (overflow-risk keys
    keep the host path, pinned by test_top_int_key_expression_falls_
    back)."""
    import jax.tree_util as jtu
    nl = len(specs)
    if key is None:
        if encoded or nl != 1:
            return None
        dt, shape = specs[0]
        if shape == () and dt.kind in "if":
            return ("leaf", 0)
        return None
    idx = _subscript_const_index(key)
    if idx is not None:
        if not (0 <= idx < nl):
            return None
        if treedef != jtu.tree_structure(tuple(range(nl))):
            return None          # nested records: subscript != leaf
        dt, shape = specs[idx]
        if shape != () or dt.kind not in "if":
            return None
        if encoded and idx == 0:
            return None
        return ("leaf", idx)
    if encoded:
        return None
    try:
        fn = _row_fn(key, treedef)
        out = jax.eval_shape(fn, *_spec_struct(specs))
        if len(out) != 1 or out[0].shape != ():
            return None
        kind = np.dtype(out[0].dtype).kind
        # FLOAT outputs ride unconditionally: float arithmetic is
        # IEEE-identical per record on both sides.  INT outputs ride
        # only past the ranged probe: the host computes exact Python
        # ints while the device wraps at i64 — an integer key that
        # overflows would silently reorder (review finding).
        if kind == "f":
            return ("fn", key)
        if kind == "i" and _ranged_int_key_ok(key, treedef, specs,
                                              col_ranges):
            return ("fn", key)
    except Exception:
        pass
    return None


def fn_key(f):
    """Structural identity of a user function: same code + same captured
    cell values => same compiled program.  Unhashable captures fall back to
    object identity (no cross-run sharing, still correct)."""
    try:
        cells = tuple(c.cell_contents for c in (f.__closure__ or ()))
        hash(cells)
        return (f.__code__, cells)
    except Exception:
        return ("id", id(f))


def _row_fn(f, in_treedef):
    """Wrap a record-level user fn as leaves -> leaves with output treedef
    discovered at trace time."""
    def fn(*leaves):
        rec = jax.tree_util.tree_unflatten(in_treedef, list(leaves))
        out = f(rec)
        out_leaves, out_treedef = jax.tree_util.tree_flatten(out)
        fn.out_treedef = out_treedef
        return tuple(out_leaves)
    return fn


class MapOp:
    """map / mapValue / keyBy — all are record->record functions."""

    def __init__(self, f, key=None):
        self.f = f
        self.key = ("map", key if key is not None else fn_key(f))

    def probe(self, treedef, specs):
        fn = _row_fn(self.f, treedef)
        out_structs = jax.eval_shape(fn, *_spec_struct(specs))
        out_specs = [(np.dtype(s.dtype), tuple(s.shape))
                     for s in out_structs]
        for dt, shape in out_specs:
            if dt == np.dtype(object):
                raise TypeError("object dtype")
        self._vfn = jax.vmap(fn)
        self._out_treedef = fn.out_treedef
        return self._out_treedef, out_specs

    def apply(self, leaves, n):
        out = self._vfn(*leaves)
        return list(out), n


class SortOp:
    """Per-partition sort by the key — one scalar leaf, or every column
    of a flat tuple key, compared lexicographically like the host's
    tuple sort (backs sortByKey's final mapPartitions(_SortPartFn) on
    device)."""

    def __init__(self, ascending):
        self.ascending = ascending
        self.nk = 1
        self.key = ("sort", ascending)

    def probe(self, treedef, specs):
        nk = layout.key_width(treedef, specs, kinds="if")
        if nk is None:
            raise TypeError("sort needs a numeric scalar (or flat "
                            "numeric tuple) key")
        self.nk = nk
        self.key = ("sort", self.ascending, nk)
        return treedef, specs

    def apply(self, leaves, n):
        from dpark_tpu.backend.tpu import collectives
        cap = leaves[0].shape[0]
        valid = jnp.arange(cap) < n
        # only key column 0 needs the sentinel: padding sorts last on
        # it alone, and no valid row can carry it (ingest guard)
        k = jnp.where(valid, leaves[0],
                      collectives._sentinel(leaves[0].dtype))
        packed = collectives._lex_sort((k,) + tuple(leaves[1:]),
                                       self.nk)
        out = [packed[0]] + list(packed[1:])
        if not self.ascending:
            # reverse the valid prefix, keep padding in place
            idx = jnp.arange(cap)
            ridx = jnp.where(idx < n, n - 1 - idx, idx)
            out = [l[ridx] for l in out]
        return out, n


class FilterOp:
    def __init__(self, f, key=None):
        self.f = f
        self.key = ("filter", key if key is not None else fn_key(f))

    def probe(self, treedef, specs):
        fn = _row_fn(self.f, treedef)
        out_structs = jax.eval_shape(fn, *_spec_struct(specs))
        if (len(out_structs) != 1 or out_structs[0].shape != ()):
            raise TypeError("filter predicate must return a scalar")
        self._vfn = jax.vmap(fn)
        return treedef, specs          # unchanged record type

    def apply(self, leaves, n):
        from dpark_tpu.backend.tpu import collectives
        cap = leaves[0].shape[0]
        (pred,) = self._vfn(*leaves)
        mask = pred.astype(bool) & (jnp.arange(cap) < n)
        return collectives.compact(leaves, mask)


class SegAggOp:
    """groupByKey().mapValues(provable aggregate) consumed ON DEVICE:
    the no-combine reduce leaves each device's rows key-sorted with the
    valid prefix first, so one boundary scan + segment scatter yields
    one (k, agg) row per key — ragged (k, [v]) groups never materialize
    and no host bridge runs (reference: dpark/rdd.py groupByKey +
    mapValue; SURVEY.md 2.2 CoGroupedRDD row, 7.1 step 6).

    REQUIRES key-sorted valid-prefix input: analyze_stage only installs
    this as ops[0] of a no-combine "hbm"-source plan, whose reduce
    program (_compile_reduce's no-combine branch) sorts rows by key
    before applying ops — any new install site must preserve that.

    Float NaN caveat: NaN values are treated as absent for min/max
    (the host fold's result for a NaN-bearing group depends on shuffle
    arrival order — it ignores NaNs unless one arrives first — so no
    vectorized form can reproduce it exactly; masking NaN to the
    identity matches the host in every NaN-not-first case)."""

    def __init__(self, kind):
        self.kind = kind
        self.nk = 1
        self.key = ("segagg", kind)

    def probe(self, treedef, specs):
        nk = layout.key_width(treedef, specs, kinds="if")
        if nk is None or len(specs) != nk + 1:
            raise TypeError("segagg needs flat (k, v) records (scalar "
                            "or flat-tuple key, one scalar value)")
        self.nk = nk
        self.key = ("segagg", self.kind, nk)
        vdt, vshape = specs[nk]
        if vshape != ():
            raise TypeError("segagg needs a scalar value")
        if vdt.kind not in "if" or any(dt.kind not in "if"
                                       for dt, _ in specs[:nk]):
            raise TypeError("segagg needs numeric key and value")
        if self.kind == "count":
            odt = np.dtype(np.int64)
        elif self.kind == "mean":
            # host semantics: int values true-divide to float; float
            # values keep their width (np.float32 sum / int is f32)
            odt = np.dtype(np.float64) if vdt.kind == "i" else vdt
        elif self.kind == "sum" and vdt.kind == "i":
            # device sums are 64-bit (the executor's x64 contract:
            # counting must not wrap at 2**31)
            odt = np.dtype(np.int64)
        else:
            odt = vdt
        return treedef, list(specs[:nk]) + [(odt, ())]

    def apply(self, leaves, n):
        from dpark_tpu.backend.tpu import collectives
        nk = self.nk
        k, v = leaves[0], leaves[nk]
        cap = k.shape[0]
        idx = jnp.arange(cap)
        valid = idx < n
        ks = jnp.where(valid, k, collectives._sentinel(k.dtype))
        # segment ids from sorted-key boundaries (ANY key column
        # changing starts a group); invalid rows land in segment cap-1,
        # past the n_out valid prefix (when every row is its own
        # segment there are no invalid rows to misplace)
        changed = ks != jnp.roll(ks, 1)
        for kc in leaves[1:nk]:
            changed = changed | (kc != jnp.roll(kc, 1))
        starts = valid & ((idx == 0) | changed)
        seg = jnp.where(valid, jnp.cumsum(starts.astype(jnp.int32)) - 1,
                        cap - 1)
        n_out = jnp.sum(starts).astype(jnp.int32)
        kind = self.kind
        op_kind = {"sum": "add", "count": "add", "mean": "add",
                   "min": "min", "max": "max"}[kind]
        if kind == "count":
            vals = jnp.ones((cap,), jnp.int64)
        elif v.dtype.kind == "i" and kind in ("sum", "mean"):
            vals = v.astype(jnp.int64)   # exact int sums, like the host
        else:
            vals = v
        from dpark_tpu.bagel import monoid_identity
        ident_v = monoid_identity(op_kind, vals.dtype)
        mask_v = valid
        if kind in ("min", "max") and vals.dtype.kind == "f":
            mask_v = valid & ~jnp.isnan(vals)   # NaN caveat: see class
        vals = jnp.where(mask_v, vals, ident_v)
        op = collectives._segment_op(op_kind)
        agg = op(vals, seg, num_segments=cap)
        if kind == "mean":
            cnt = collectives._segment_op("add")(
                jnp.where(valid, jnp.ones((cap,), jnp.int64),
                          jnp.zeros((), jnp.int64)),
                seg, num_segments=cap)
            # int sums true-divide to f64; float sums keep their width
            # (jax promotion: f32 / i64 -> f32) — both match the host
            agg = agg / jnp.maximum(cnt, 1)
        # per-segment keys: min over the segment (all equal within a
        # segment, for every key column); empty segments keep the
        # sentinel in column 0 and sit past the valid prefix
        seg_min = collectives._segment_op("min")
        out_ks = [seg_min(ks, seg, num_segments=cap)]
        out_ks += [seg_min(kc, seg, num_segments=cap)
                   for kc in leaves[1:nk]]
        return out_ks + [agg], n_out


class StagePlan:
    """Everything needed to run one stage on the array path."""

    def __init__(self, source, ops, epilogue, in_treedef, in_specs,
                 out_treedef, out_specs, stage):
        self.source = source        # ("ingest", pc) | ("hbm", dep)
        self.ops = ops
        self.epilogue = epilogue    # None | ("shuffle_write", dep)
        self.in_treedef = in_treedef
        self.in_specs = in_specs
        self.out_treedef = out_treedef
        self.out_specs = out_specs
        self.stage = stage
        self.program_key = self._make_key()

    def _make_key(self):
        """Structural program identity: same ops/specs/aggregators compile
        to the same XLA program regardless of RDD/stage ids — repeated jobs
        (benchmark loops, DStream batches) reuse the jit cache.  The
        record TREEDEFS are part of the identity: ((k1, k2), v) and
        (k, (v1, v2)) flatten to the same leaf specs but compile
        different programs (key width drives the epilogue's hash/sort
        operand count; the value structure drives the lifted merge)."""
        spec_key = (tuple((str(dt), shape) for dt, shape in self.in_specs),
                    str(self.in_treedef), str(self.out_treedef))
        op_keys = tuple(op.key for op in self.ops)
        if self.epilogue is None:
            epi_key = None
        else:
            dep = self.epilogue[1]
            agg = dep.aggregator
            epi_key = ("shuffle", dep.partitioner.num_partitions,
                       fn_key(agg.create_combiner),
                       fn_key(agg.merge_combiners))
        src_key = self.source[0]
        if src_key == "hbm":
            src_key = ("hbm",
                       fn_key(self.source[1].aggregator.merge_combiners))
        return (src_key, spec_key, op_keys, epi_key)


def _mapvalue_as_record_fn(f):
    def fn(rec):
        return (rec[0], f(rec[1]))
    return fn


def _keyby_as_record_fn(f):
    def fn(rec):
        return (f(rec), rec)
    return fn


def extract_chain(top, cached_ids=()):
    """Walk narrow one-parent links from the stage's top RDD to its source.
    Returns (source_rdd, ops list root->top, passthrough) or None.
    `passthrough` is True when the chain unwrapped partitionBy's
    FlatMappedValues(identity) over a no-combine shuffle (rows stay flat
    (k, v) on device; no lists ever exist).  A chain node whose batch is
    HBM-cached terminates the walk (source = that node)."""
    ops = []
    cur = top
    passthrough = False
    while True:
        if getattr(cur, "_snapshot_path", None) is not None \
                or cur._checkpoint_path is not None \
                or cur._checkpoint_rdd is not None:
            # snapshot()/checkpoint(): the user asked for disk
            # materialization — the object path honors the read/write
            # (and the lazy checkpoint's promotion); fusing past it
            # would silently skip both
            return None
        if cur.id in cached_ids:
            ops.reverse()
            return cur, ops, passthrough
        if isinstance(cur, FlatMappedValuesRDD) \
                and cur.f is _join_values \
                and isinstance(cur.prev, CoGroupedRDD) \
                and len(cur.prev.rdds) == 2:
            # a.join(b): terminates the chain — analyze_stage checks
            # both cogroup inputs are HBM-resident and makes this a
            # device "join" source (expand on device, no host rows)
            ops.reverse()
            return cur, ops, passthrough
        if isinstance(cur, FlatMappedValuesRDD) and cur.f is _identity \
                and isinstance(cur.prev, ShuffledRDD) \
                and is_list_agg(cur.prev.aggregator):
            passthrough = True
            cur = cur.prev
        elif isinstance(cur, MappedValuesRDD):
            op = MapOp(_mapvalue_as_record_fn(cur.f),
                       ("mapvalue", fn_key(cur.f)))
            op.mapvalue_f = cur.f    # analyze may consume f as a segagg
            ops.append(op)
            cur = cur.prev
        elif isinstance(cur, KeyedRDD):
            ops.append(MapOp(_keyby_as_record_fn(cur.f),
                             ("keyby", fn_key(cur.f))))
            cur = cur.prev
        elif isinstance(cur, MappedRDD):
            ops.append(MapOp(cur.f))
            cur = cur.prev
        elif isinstance(cur, FilteredRDD):
            ops.append(FilterOp(cur.f))
            cur = cur.prev
        elif isinstance(cur, MapPartitionsRDD) \
                and isinstance(cur.f, _SortPartFn) and not cur.with_index:
            ops.append(SortOp(cur.f.ascending))
            cur = cur.prev
        elif isinstance(cur, (ParallelCollection, ShuffledRDD,
                              UnionRDD)):
            ops.reverse()
            return cur, ops, passthrough
        else:
            return None


def _sample_record(pc):
    """First record of a ParallelCollection (driver-side only)."""
    for s in pc._slices:
        if s:
            return s[0]
    return None


# ----------------------------------------------------------------------
# text-source stages (SURVEY.md 3.1 hot loop #1): the narrow chain over a
# file source is string-typed and untraceable, so it runs as a HOST
# PROLOGUE per split (the user's own generators), records are
# dictionary-encoded to int64 columns, and the shuffle write + combine
# ride the device.  The canonical wordcount shape additionally replaces
# the Python per-record loop with the C++ tokenizer (verified per run
# against the user's functions on a sample prefix).
# ----------------------------------------------------------------------

def _text_sources():
    """File-backed record sources whose narrow chains run as a host
    prologue feeding the device shuffle (lazy: tabular imports rdd)."""
    from dpark_tpu.tabular import TabularRDD
    return (TextFileRDD, GZipFileRDD, CSVReaderRDD, CSVFileRDD,
            TabularRDD)


def extract_text_chain(top):
    """Walk one-parent narrow links to a file source.  Returns
    (source_rdd, chain root->top) or None."""
    sources = _text_sources()
    chain = []
    cur = top
    while True:
        if getattr(cur, "_snapshot_path", None) is not None \
                or cur._checkpoint_path is not None \
                or cur._checkpoint_rdd is not None:
            return None          # disk materialization: object path
        if isinstance(cur, sources):
            chain.reverse()
            return cur, chain
        if isinstance(cur, DerivedRDD):
            chain.append(cur)
            cur = cur.prev
        else:
            return None


def _code_matches(f, template):
    """f is a closure-free function with the template's bytecode."""
    code = getattr(f, "__code__", None)
    if code is None or getattr(f, "__closure__", None):
        return False
    t = template.__code__
    return (code.co_code == t.co_code
            and code.co_consts == t.co_consts
            and code.co_names == t.co_names
            and code.co_argcount == t.co_argcount)


def _is_whitespace_split(f):
    # 'split' in the template is an attribute load on the argument, not
    # a global — bytecode equality is sufficient
    return f is str.split or _code_matches(f, lambda line: line.split())


def _const_split_sep(f):
    """The separator when f is exactly `lambda line: line.split(SEP)`
    with a single-byte ASCII constant (not \\n or \\r), else None.
    Bytecode must equal the template's; only the string const (the
    separator itself) may differ — it is extracted, not assumed."""
    code = getattr(f, "__code__", None)
    if code is None or getattr(f, "__closure__", None):
        return None
    t = (lambda line: line.split("\x00")).__code__
    if not (code.co_code == t.co_code
            and code.co_names == t.co_names
            and code.co_argcount == t.co_argcount):
        return None
    strs = [c for c in code.co_consts if isinstance(c, str)]
    others = [c for c in code.co_consts if not isinstance(c, str)]
    t_others = [c for c in t.co_consts if not isinstance(c, str)]
    if len(strs) != 1 or others != t_others:
        return None
    sep = strs[0]
    if len(sep) == 1 and ord(sep) < 0x80 and sep not in "\n\r":
        return sep
    return None


def _is_pair_one(f):
    return _code_matches(f, lambda w: (w, 1))


def canonical_wordcount(chain):
    """The separator string when chain is exactly
    flatMap(split) -> map(w -> (w, 1)): "" for whitespace split,
    a 1-char string for a constant-separator split, None otherwise."""
    if len(chain) != 2:
        return None
    fm, mp = chain
    if not (isinstance(fm, FlatMappedRDD) and isinstance(mp, MappedRDD)
            and _is_pair_one(mp.f)):
        return None
    if _is_whitespace_split(fm.f):
        return ""
    return _const_split_sep(fm.f)


def _sample_text_record(top):
    """First record of the narrow chain, read from the first non-empty
    split (driver-side; cached per RDD — a tabular source decompresses
    a whole chunk to produce it, so once is enough)."""
    if hasattr(top, "_tpu_sample_record"):
        return top._tpu_sample_record
    sample = None
    for sp in top.splits[:8]:
        it = top.iterator(sp)
        try:
            for rec in it:
                sample = rec
                break
        finally:
            close = getattr(it, "close", None)
            if close:
                close()
        if sample is not None:
            break
    top._tpu_sample_record = sample
    return sample


def analyze_text_stage(stage, ndev, executor_or_store):
    """Shuffle-map stage rooted at a file source: build a text StagePlan
    (host-prologue ingest + device shuffle write) or return None."""
    if not getattr(stage, "is_shuffle_map", False):
        return None
    top = stage.rdd
    extracted = extract_text_chain(top)
    if extracted is None:
        return None
    text_rdd, chain = extracted
    dep = stage.shuffle_dep
    logical_spill = False
    epi_spec = partitioner_spec(dep.partitioner)
    if epi_spec is None:
        return None

    sample = _sample_text_record(top)
    if not (isinstance(sample, tuple) and len(sample) == 2):
        return None
    k, v = sample
    key_is_str = isinstance(k, (str, bytes))
    if not key_is_str and not isinstance(k, (int, np.integer)):
        return None
    if key_is_str and epi_spec[0] != "hash":
        return None                      # str keys have no range bounds
    try:
        treedef, specs = layout.record_spec((0, v))
    except (TypeError, ValueError):
        return None
    for dt, _ in specs:
        if dt == np.dtype(object) or dt.kind in "USO":
            return None
    epi_bounds = None
    if epi_spec[0] == "range":
        epi_bounds = np.asarray(dep.partitioner.bounds,
                                dtype=np.int64)

    ops = []
    cur_treedef, cur_specs = treedef, specs
    if not is_list_agg(dep.aggregator):
        create = dep.aggregator.create_combiner
        try:
            op = MapOp(lambda rec: (rec[0], create(rec[1])))
            cur_treedef, cur_specs = op.probe(cur_treedef, cur_specs)
            ops.append(op)
        except Exception as e:
            logger.debug("create_combiner not traceable: %s", e)
            return None
        if layout.key_leaf_index(cur_treedef, cur_specs) is None:
            return None

    if dep.partitioner.num_partitions > ndev:
        # more logical partitions than devices: only the spilled-run
        # stream supports this (the rid rides the exchange, runs land
        # per logical partition) — list aggregators, untraceable
        # merges (combiner folded host-side at export), and TRACEABLE
        # merges (waves pre-reduce per (rid, key) on device before
        # spilling) all ride it.  Small inputs go to the object path.
        if not _big_text(stage):
            return None
        logical_spill = True

    plan = StagePlan(("text", None), ops, ("shuffle_write", dep),
                     treedef, specs, cur_treedef, cur_specs, stage)
    plan.src_combine = False
    plan.group_output = False
    plan.epi_spec = epi_spec
    plan.epi_bounds = epi_bounds
    plan.epi_nk = 1
    plan.src_nk = 1
    plan.text_rdd = text_rdd
    plan.text_chain = chain
    plan.encoded_keys = key_is_str
    plan.logical_spill = logical_spill
    sep = (canonical_wordcount(chain)
           if key_is_str and type(text_rdd) is TextFileRDD else None)
    plan.canonical = sep is not None
    plan.canonical_sep = sep or None      # "" (whitespace) -> None
    plan.program_key = plan.program_key + (False, False, epi_spec)
    return plan


def _leaves_merge_fn(merge, record_treedef):
    """User merge_combiners (value, value) -> value lifted to leaf
    lists, vmapped for use inside segment scans.  The value's REAL
    pytree structure is rebuilt from the record treedef before calling
    the user function — a nested combiner like avg's (sum, (s, c))
    must see its own shape, not a flat leaf tuple (flattening broke
    every nested-accumulator aggregate, e.g. Table avg)."""
    import jax.tree_util as jtu
    children = jtu.treedef_children(record_treedef)
    if len(children) == 2:
        vdef = children[1]               # records are (k, value)
        nleaves = vdef.num_leaves

        def _unwrap(leaves):
            return jtu.tree_unflatten(vdef, list(leaves))
    else:                                # flat (k, v1, v2, ...) record
        nleaves = record_treedef.num_leaves - 1

        def _unwrap(leaves):
            return leaves[0] if nleaves == 1 else tuple(leaves)

    def leaf_merge(*flat):
        va = flat[:nleaves]
        vb = flat[nleaves:]
        out = merge(_unwrap(va), _unwrap(vb))
        out_leaves = jax.tree_util.tree_leaves(out)
        return tuple(out_leaves)

    vfn = jax.vmap(leaf_merge)

    def merged(va_leaves, vb_leaves):
        return list(vfn(*(list(va_leaves) + list(vb_leaves))))
    return merged


def _columnar_row_bytes(slices):
    """Bytes per record across a slice's columns (for HBM wave sizing)."""
    for s in slices:
        cols = getattr(s, "columns", None)
        if cols is not None and len(s):
            import numpy as np
            return sum(np.asarray(c).dtype.itemsize
                       * int(np.prod(np.asarray(c).shape[1:] or (1,)))
                       for c in cols)
    return 16


def _big_columnar(pc):
    """ParallelCollection big enough for the wave stream (the r > ndev
    spill requires streaming).  The threshold is the EFFECTIVE chunk
    (HBM-sized on a real device) so data that fits one wave keeps the
    lower-overhead in-core path."""
    from dpark_tpu import conf
    from dpark_tpu.rdd import _ColumnarSlice
    slices = pc._slices
    return (all(isinstance(s, _ColumnarSlice) for s in slices)
            and max((len(s) for s in slices), default=0)
            > conf.stream_chunk_rows(_columnar_row_bytes(slices)))


def _split_bytes(sp):
    """Best-effort on-disk size of one file split: byte range when the
    split carries one (TextSplit), whole-file size otherwise (tabular /
    whole-file splits)."""
    end = getattr(sp, "end", None)
    if end is not None:
        return max(0, end - getattr(sp, "begin", 0))
    path = getattr(sp, "path", None)
    if path and "://" not in path:
        try:
            import os
            return os.path.getsize(path)
        except OSError:
            return 0
    return 0


def _big_text(stage):
    """Text source big enough for the wave stream."""
    from dpark_tpu import conf
    return (sum(_split_bytes(sp) for sp in stage.rdd.splits)
            > conf.STREAM_TEXT_BYTES)


def _range_bounds_array(bounds, specs, nk):
    """The RangePartitioner bounds as the device array the range
    epilogue compares against: 1D cast to the key spec dtype for a
    scalar key, (len(bounds), nk) for a flat tuple key — requiring one
    SHARED spec dtype across the key columns (mixed int/float tuple
    bounds have host bisect semantics no single-dtype device compare
    reproduces).  None = host fallback."""
    dt = np.dtype(specs[0][0])
    if nk == 1:
        return np.asarray(bounds, dtype=dt)
    if any(np.dtype(s[0]) != dt for s in specs[1:nk]):
        return _fallback("range partitioner over a tuple key with "
                         "mixed column dtypes")
    if not bounds:
        return np.zeros((0, nk), dtype=dt)
    arr = np.asarray(bounds, dtype=dt)
    if arr.ndim != 2 or arr.shape[1] != nk:
        return _fallback("range bounds do not match the key width")
    return arr


# a union stage materializes every branch before concatenating on
# device; bound the fan-in so one stage cannot pin arbitrarily many
# parent batches in HBM at once
MAX_UNION_SOURCES = 12


def _analyze_union_parent(parent, ndev, executor_or_store, cached_ids,
                          stage):
    """Sub-plan (epilogue=None) turning ONE UnionRDD branch into a
    device Batch of its post-ops rows, or None.  The windowed-stream
    shape — union of per-batch reduceByKey outputs feeding another
    reduceByKey — is all hbm branches (BASELINE config #4)."""
    hbm_sids = getattr(executor_or_store, "shuffle_store",
                       executor_or_store)
    extracted = extract_chain(parent, cached_ids)
    if extracted is None:
        return None
    src_rdd, ops, passthrough = extracted
    src_combine = False
    reslice = False
    if src_rdd.id in cached_ids:
        meta = executor_or_store.result_cache_meta(src_rdd.id)
        treedef, specs = meta["treedef"], meta["specs"]
        source = ("cached", src_rdd)
    elif isinstance(src_rdd, ParallelCollection):
        if src_rdd._slices is None:
            return None
        reslice = len(src_rdd._slices) != ndev
        if _big_columnar(src_rdd):
            # over-chunk inputs must ride the bounded wave stream; a
            # union branch materializes in-core, pinning the whole
            # batch (plus concat scratch) in HBM — decline
            return None
        sample = _sample_record(src_rdd)
        if sample is None:
            return None
        try:
            treedef, specs = layout.record_spec(sample)
        except (TypeError, ValueError):
            return None
        for dt, _ in specs:
            if dt == np.dtype(object) or dt.kind in "USO":
                return None
        source = ("ingest", src_rdd)
    elif isinstance(src_rdd, ShuffledRDD):
        dep = src_rdd.dep
        if dep.shuffle_id not in hbm_sids:
            return None
        if dep.partitioner.num_partitions > ndev:
            return None
        meta = hbm_sids[dep.shuffle_id]
        if "host_runs" in meta:
            return None
        if meta.get("encoded_keys"):
            return None              # concat + later ops would leak ids
        treedef, specs = meta["out_treedef"], meta["out_specs"]
        if is_list_agg(dep.aggregator):
            if not passthrough:
                return None          # (k, [v]) lists cannot concat flat
        else:
            src_combine = True
            try:
                nk = (meta.get("key_cols")
                      or layout.key_width(treedef, specs, kinds="if")
                      or 1)
                merge_fn = _leaves_merge_fn(
                    dep.aggregator.merge_combiners, treedef)
                vstructs = _batched_spec_struct(specs[nk:])
                jax.eval_shape(
                    lambda *v: merge_fn(list(v), list(v)), *vstructs)
            except Exception as e:
                logger.debug("union branch merge untraceable: %s", e)
                return None
        source = ("hbm", dep)
    else:
        return None
    cur_treedef, cur_specs = treedef, specs
    try:
        for op in ops:
            cur_treedef, cur_specs = op.probe(cur_treedef, cur_specs)
    except Exception as e:
        logger.debug("union branch not traceable (%s)", e)
        return None
    sub = StagePlan(source, ops, None, treedef, specs,
                    cur_treedef, cur_specs, stage)
    sub.src_combine = src_combine
    sub.group_output = False
    sub.epi_spec = None
    sub.epi_bounds = None
    sub.epi_nk = 1
    sub.src_nk = (layout.key_width(treedef, specs, kinds="if") or 1) \
        if source[0] == "hbm" else 1
    sub.logical_spill = False
    sub.reslice = reslice
    sub.program_key = sub.program_key + (src_combine, False, None,
                                         sub.src_nk)
    return sub


def _analyze_join_source(join_rdd, ndev, executor_or_store):
    """(treedef, specs, (dep_a, dep_b)) for an a.join(b) chain source
    whose cogroup inputs are both HBM-resident plain (k, v) no-combine
    shuffles, else None.  Mirrors the eligibility the driver-seeded
    join precompute enforces, but keeps the expansion ON DEVICE as an
    array-path source."""
    import jax.tree_util as jtu
    hbm_sids = getattr(executor_or_store, "shuffle_store",
                       executor_or_store)
    cg = join_rdd.prev
    deps = []
    for kind, obj in cg._dep_kinds:
        if kind != "shuffle" or not is_list_agg(obj.aggregator):
            return None
        if obj.shuffle_id not in hbm_sids:
            return None
        meta = hbm_sids[obj.shuffle_id]
        if "host_runs" in meta or meta.get("encoded_keys"):
            # encoded ids must not feed further device ops (the ids
            # would leak into user compute); host path decodes
            return None
        deps.append(obj)
    if len(deps) != 2:
        return None
    if deps[0].partitioner.num_partitions > ndev:
        return None
    metas = [hbm_sids[d.shuffle_id] for d in deps]
    samples = []
    nks = []
    for meta in metas:
        treedef, specs = meta["out_treedef"], meta["out_specs"]
        nk = layout.key_width(treedef, specs, kinds="if")
        if nk is None or len(specs) < nk + 1:
            return None      # join kernels need (k, v) / ((k...), v)
        sample = jtu.tree_unflatten(treedef, list(range(len(specs))))
        if len(sample) != 2:
            return None
        samples.append(sample)
        nks.append(nk)
    if nks[0] != nks[1]:
        return None              # key widths must agree across sides
    nk = nks[0]
    a_key = [np.dtype(dt) for dt, _ in metas[0]["out_specs"][:nk]]
    b_key = [np.dtype(dt) for dt, _ in metas[1]["out_specs"][:nk]]
    if a_key != b_key:
        return None              # id-vs-int equality would be spurious
    joined = (samples[0][0], (samples[0][1], samples[1][1]))
    treedef = jtu.tree_structure(joined)
    specs = (list(metas[0]["out_specs"][:nk])
             + list(metas[0]["out_specs"][nk:])
             + list(metas[1]["out_specs"][nk:]))
    return treedef, specs, (deps[0], deps[1])


def analyze_stage(stage, ndev, executor_or_store):
    """Decide whether `stage` can run on the array path; build its plan.

    executor_or_store: the JAXExecutor (HBM shuffle store + result cache)
    or a bare shuffle-store dict.  Returns StagePlan or None (fallback;
    last_fallback_reason() explains key-shape declines).
    """
    _last_fallback[0] = None
    hbm_sids = getattr(executor_or_store, "shuffle_store",
                       executor_or_store)
    cached_ids = getattr(executor_or_store, "result_cache_ids",
                         lambda: ())()
    top = stage.rdd
    extracted = extract_chain(top, cached_ids)
    if extracted is None:
        return analyze_text_stage(stage, ndev, executor_or_store)
    source_rdd, ops, passthrough = extracted
    group_output = False

    if (not stage.is_shuffle_map and not ops
            and isinstance(source_rdd, ParallelCollection)
            and source_rdd.id not in cached_ids):
        # a result stage that would only ingest + egest the input does
        # no device work at all — and egesting a huge columnar input as
        # Python rows is exactly what a lazy host read avoids (e.g.
        # sortByKey's bounds sample takes 250 rows per slice)
        return None

    # -- source record spec ---------------------------------------------
    reslice = False
    src_nk = 1
    if source_rdd.id in cached_ids:
        meta = executor_or_store.result_cache_meta(source_rdd.id)
        treedef, specs = meta["treedef"], meta["specs"]
        source = ("cached", source_rdd)
        src_combine = False
    elif isinstance(source_rdd, ParallelCollection):
        if source_rdd._slices is None:
            return None
        reslice = len(source_rdd._slices) != ndev
        if reslice and (not stage.is_shuffle_map
                        or _big_columnar(source_rdd)):
            # result-stage tasks index the RDD's own partition layout;
            # the wave stream consumes slices as-is — both need the
            # exact slicing.  A shuffle write redistributes by key, so
            # the executor re-slices the host rows to the mesh instead
            # of declining (e.g. parallelize(data, 2).reduceByKey on an
            # 8-device mesh — the DStream queue batch shape).
            return None
        sample = _sample_record(source_rdd)
        if sample is None:
            return None
        try:
            treedef, specs = layout.record_spec(sample)
        except (TypeError, ValueError):
            return None
        for dt, _ in specs:
            if dt == np.dtype(object) or dt.kind in "USO":
                return None
        source = ("ingest", source_rdd)
        src_combine = False
    elif isinstance(source_rdd, ShuffledRDD):
        dep = source_rdd.dep
        if dep.shuffle_id not in hbm_sids:
            return None                  # parent shuffle lives on host
        if dep.partitioner.num_partitions > ndev:
            return None                  # R <= ndev: extra devices idle
        # record spec of the stored rows — registered when the map ran
        meta = hbm_sids[dep.shuffle_id]
        if "host_runs" in meta:
            return None          # spilled runs: host merge consumes them
        if meta.get("encoded_keys") and (ops or stage.is_shuffle_map):
            # keys are dictionary-encoded ids: only a plain read (decode
            # at egest) may ride the device — anything else would show
            # the user ids where they expect strings.  The host path
            # sees decoded rows through the export bridge.
            return None
        treedef, specs = meta["out_treedef"], meta["out_specs"]
        src_nk = (meta.get("key_cols")
                  or layout.key_width(treedef, specs, kinds="if") or 1)
        if is_list_agg(dep.aggregator):
            # no-combine shuffle (partitionBy/groupByKey): rows pass
            # through flat; bare groupByKey groups at egest time
            src_combine = False
            if not passthrough:
                seg = None
                if ops:
                    f0 = getattr(ops[0], "mapvalue_f", None)
                    kind = (classify_segagg(f0) if f0 is not None
                            else None)
                    if kind is not None:
                        seg = SegAggOp(kind)
                if seg is not None:
                    # groupByKey().mapValues(provable aggregate): the
                    # group list never materializes — a segment scatter
                    # over the key-sorted no-combine rows yields flat
                    # (k, agg) records, and the rest of the chain (and
                    # any shuffle write) continues on device
                    ops[0] = seg
                elif ops or stage.is_shuffle_map:
                    return None          # (k, [v]) records: host only
                else:
                    group_output = True
        else:
            src_combine = True
            try:
                merge_fn = _leaves_merge_fn(
                    dep.aggregator.merge_combiners, treedef)
                vstructs = _batched_spec_struct(specs[src_nk:])
                jax.eval_shape(
                    lambda *v: merge_fn(list(v), list(v)), *vstructs)
            except Exception as e:
                logger.debug("merge_combiners not traceable: %s", e)
                return None
        source = ("hbm", dep)
    elif isinstance(source_rdd, UnionRDD):
        if not stage.is_shuffle_map:
            return None          # result tasks index the union's splits
        parents = source_rdd.rdds
        if not parents or len(parents) > MAX_UNION_SOURCES:
            return None
        subs = []
        for p in parents:
            sub = _analyze_union_parent(p, ndev, executor_or_store,
                                        cached_ids, stage)
            if sub is None:
                return None
            subs.append(sub)
        t0 = subs[0].out_treedef
        s0 = [(str(dt), shape) for dt, shape in subs[0].out_specs]
        for sub in subs[1:]:
            if sub.out_treedef != t0 or s0 != [
                    (str(dt), shape) for dt, shape in sub.out_specs]:
                return None      # branches must agree on record type
        treedef, specs = subs[0].out_treedef, subs[0].out_specs
        source = ("union", tuple(subs))
        src_combine = False
    elif isinstance(source_rdd, FlatMappedValuesRDD):
        # extract_chain only terminates here for the a.join(b) shape
        joined = _analyze_join_source(source_rdd, ndev,
                                      executor_or_store)
        if joined is None:
            return None
        treedef, specs, deps = joined
        source = ("join", deps)
        src_combine = False
    else:
        return None

    # -- probe the narrow ops -------------------------------------------
    cur_treedef, cur_specs = treedef, specs
    try:
        for op in ops:
            cur_treedef, cur_specs = op.probe(cur_treedef, cur_specs)
    except Exception as e:
        logger.debug("stage %s not traceable (%s); host fallback",
                     stage, e)
        return None

    # -- epilogue --------------------------------------------------------
    epilogue = None
    epi_spec = None
    epi_bounds = None
    epi_nk = 1
    logical_spill = False
    if stage.is_shuffle_map:
        dep = stage.shuffle_dep
        epi_spec = partitioner_spec(dep.partitioner)
        if epi_spec is None:
            return None
        if epi_spec[0] == "hash":
            epi_nk = layout.key_width(cur_treedef, cur_specs, kinds="i")
            if epi_nk is None:
                return _fallback(
                    "hash shuffle needs an int scalar (or flat "
                    "int-tuple, <= conf.MAX_KEY_LEAVES columns) key")
        else:
            epi_nk = layout.key_width(cur_treedef, cur_specs,
                                      kinds="if")
            if epi_nk is None:
                return _fallback(
                    "range shuffle needs a numeric scalar (or flat "
                    "numeric-tuple) key")
            epi_bounds = _range_bounds_array(
                dep.partitioner.bounds, cur_specs, epi_nk)
            if epi_bounds is None:
                return None
        if is_list_agg(dep.aggregator):
            pass                         # no-combine write: rows as-is
        else:
            create = dep.aggregator.create_combiner
            try:
                op = MapOp(lambda rec: (rec[0], create(rec[1])))
                cur_treedef, cur_specs = op.probe(cur_treedef, cur_specs)
                ops.append(op)
            except Exception as e:
                logger.debug("create_combiner not traceable: %s", e)
                return None
            if epi_spec[0] == "hash":
                epi_nk = layout.key_width(cur_treedef, cur_specs,
                                          kinds="i")
                if epi_nk is None:
                    return _fallback(
                        "hash shuffle needs an int scalar (or flat "
                        "int-tuple) key after create_combiner")
        if dep.partitioner.num_partitions > ndev:
            # more logical partitions than devices: only the spilled
            # no-combine stream supports this (rid rides the exchange,
            # runs land per logical partition) — list aggregators,
            # untraceable merges (combiner folded host-side at export),
            # and TRACEABLE merges (waves pre-reduce per (rid, key) on
            # device before spilling) all ride it.  Small inputs go to
            # the object path HERE, not via an executor error.
            if not (source[0] == "ingest"
                    and _big_columnar(source[1])):
                return None
            logical_spill = True
        epilogue = ("shuffle_write", dep)

    plan = StagePlan(source, ops, epilogue, treedef, specs,
                     cur_treedef, cur_specs, stage)
    plan.src_combine = src_combine
    plan.group_output = group_output
    plan.epi_spec = epi_spec
    plan.epi_bounds = epi_bounds
    plan.epi_nk = epi_nk
    # key width of the SOURCE records (hbm reduce side): the segment
    # reduce / no-combine key sort must span every key column — merging
    # tuple-keyed rows on column 0 alone would mix distinct keys
    plan.src_nk = src_nk if source[0] == "hbm" else 1
    plan.logical_spill = logical_spill
    plan.reslice = reslice
    plan.program_key = plan.program_key + (
        src_combine, group_output, epi_spec, epi_nk, plan.src_nk)
    return plan
