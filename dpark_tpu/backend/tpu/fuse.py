"""Stage fusion: narrow RDD chains -> one traceable per-device program.

The reference pipelines narrow dependencies as nested Python generators
(dpark/rdd.py MappedRDD.compute etc., SURVEY.md 3.1 hot loop #1).  Here the
same chain is *recorded* as a list of array ops and fused into a single
function: user record-level lambdas become columnar code via jax.vmap, so
the whole stage runs as one XLA program per device.

Graceful degradation (SURVEY.md 7.2 item 1): `analyze_stage` probes every
user function with jax.eval_shape on the record spec; anything untraceable
(strings, data-dependent control flow, side effects) returns None and the
scheduler falls back to the object path for that stage.
"""

import numpy as np

import jax
import jax.numpy as jnp

from dpark_tpu.backend.tpu import layout
from dpark_tpu.dependency import (
    HashPartitioner, RangePartitioner, SaltedHashPartitioner)
from dpark_tpu.rdd import (
    CoGroupedRDD, CSVFileRDD, CSVReaderRDD, DerivedRDD, FilteredRDD,
    FlatMappedRDD, FlatMappedValuesRDD, GZipFileRDD, KeyedRDD,
    MapPartitionsRDD, MappedRDD, MappedValuesRDD, ParallelCollection,
    ShuffledRDD, TextFileRDD, UnionRDD, _SortPartFn, _append, _extend,
    _identity, _join_values, _mk_list)
from dpark_tpu.utils.log import get_logger

logger = get_logger("tpu.fuse")

# why the LAST analyze_stage call declined the array path (set at the
# key-shape decline sites, cleared per call): the scheduler surfaces it
# in the per-stage job record and the host-fallback-key lint rule gives
# the same answer pre-flight.  Best-effort observability — never
# consulted for control flow.
_last_fallback = [None]


def _fallback(reason):
    _last_fallback[0] = reason
    return None


def last_fallback_reason():
    return _last_fallback[0]


def is_list_agg(agg):
    """The identity list-aggregator trio used by groupByKey/partitionBy:
    values need repartitioning but no combining (no-combine shuffle)."""
    return (agg.create_combiner is _mk_list
            and agg.merge_value is _append
            and agg.merge_combiners is _extend)


def partitioner_spec(part):
    """Device destination function spec for a partitioner, or None."""
    if isinstance(part, SaltedHashPartitioner):
        # mid-job re-plan target (ISSUE 19): the device hash kernel
        # buckets RAW keys — a salted exchange must decline to the
        # host object path or every row lands in the wrong bucket.
        # Checked BEFORE HashPartitioner on purpose (it is not a
        # subclass, but keep the decline explicit and named).
        return _fallback("salted partitioner (mid-job re-plan) "
                         "has no device hash kernel")
    if isinstance(part, HashPartitioner):
        return ("hash",)
    if isinstance(part, RangePartitioner):
        try:
            bounds = np.asarray(part.bounds)
        except Exception:
            return None
        if bounds.dtype == object or bounds.dtype.kind in "USO":
            return None
        return ("range", bool(part.ascending))
    return None


def _spec_struct(specs):
    return [jax.ShapeDtypeStruct(shape, dt) for dt, shape in specs]


def _batched_spec_struct(specs, n=4):
    return [jax.ShapeDtypeStruct((n,) + shape, dt) for dt, shape in specs]


# exact monoid identification lives in the SHARED jax-free core
# (utils/monoid.py) so the pre-flight linter classifies identically;
# this backend contributes its jnp identities to the by-identity table
from dpark_tpu.utils import monoid as _monoid

_monoid.register_direct({jnp.add: "add", jnp.multiply: "mul",
                         jnp.minimum: "min", jnp.maximum: "max"})


def classify_merge(merge):
    """EXACT algebraic classification of a user merge function —
    "add" | "min" | "max" | "mul" | None.  See utils/monoid.py for the
    proof obligations (only provable matches qualify; everything else
    returns None and runs through the traced user function)."""
    return _monoid.classify_merge(merge)


from dpark_tpu.utils import builtin_globals_ok as _builtin_globals_ok


def classify_segagg(f):
    """EXACT classification of a mapValues function applied to a
    groupByKey value LIST as a per-group aggregate (VERDICT r4 #3:
    group->aggregate chains ride the mesh as segment reductions, no
    (k, [v]) lists ever materialize).  Delegates to the shared
    jax-free core (utils/monoid.py) — same proof obligations as
    classify_merge; only provable matches qualify."""
    return _monoid.classify_segagg(f)


def _subscript_const_index(f):
    """The integer I when f is exactly ``lambda x: x[I]`` (closure-free,
    any spelling with the same bytecode, e.g. rdd._snd) — the provable
    select-one-leaf top() key.  None otherwise."""
    code = getattr(f, "__code__", None)
    if code is None or getattr(f, "__closure__", None):
        return None
    if code.co_argcount != 1 or code.co_flags & 0x0C:
        return None
    t = (lambda x: x[99]).__code__
    if not (code.co_code == t.co_code and code.co_names == t.co_names):
        return None
    ints = [c for c in code.co_consts
            if isinstance(c, int) and not isinstance(c, bool)]
    t_other = [c for c in t.co_consts
               if not isinstance(c, int) or isinstance(c, bool)]
    other = [c for c in code.co_consts
             if not isinstance(c, int) or isinstance(c, bool)]
    if len(ints) != 1 or other != t_other:
        return None
    return ints[0]


class _IntInterval:
    """Exact integer interval for the ranged-int top-k key probe: the
    user's key expression is EXECUTED once over per-column [min, max]
    intervals (Python big ints — no wrap), and every intermediate
    operation checks its bounds against int64.  If the whole expression
    stays in range, device i64 arithmetic provably never wraps and the
    device-computed key equals the host's exact Python int for every
    record — sound, unlike a corner check of the output alone (which
    misses interior extremes like x*(K-x) and overflowing
    intermediates).  Any operation outside +, -, *, // (positive
    divisor), and unary +/- raises and keeps the host path."""

    _LIMIT = 2 ** 63 - 1

    __slots__ = ("lo", "hi")

    def __init__(self, lo, hi):
        if abs(lo) > self._LIMIT or abs(hi) > self._LIMIT:
            raise OverflowError("interval exceeds int64")
        self.lo, self.hi = lo, hi

    @classmethod
    def _of(cls, other):
        if isinstance(other, _IntInterval):
            return other
        if isinstance(other, bool) or not isinstance(other, int):
            raise TypeError("non-int operand")
        return cls(other, other)

    def __add__(self, o):
        o = self._of(o)
        return _IntInterval(self.lo + o.lo, self.hi + o.hi)
    __radd__ = __add__

    def __sub__(self, o):
        o = self._of(o)
        return _IntInterval(self.lo - o.hi, self.hi - o.lo)

    def __rsub__(self, o):
        return self._of(o).__sub__(self)

    def __mul__(self, o):
        o = self._of(o)
        corners = [self.lo * o.lo, self.lo * o.hi,
                   self.hi * o.lo, self.hi * o.hi]
        return _IntInterval(min(corners), max(corners))
    __rmul__ = __mul__

    def __floordiv__(self, o):
        o = self._of(o)
        if o.lo <= 0:
            raise ValueError("floordiv needs a provably positive "
                             "divisor")
        return _IntInterval(min(self.lo // o.lo, self.lo // o.hi),
                            max(self.hi // o.lo, self.hi // o.hi))

    def __neg__(self):
        return _IntInterval(-self.hi, -self.lo)

    def __pos__(self):
        return self


def _ranged_int_key_ok(key, treedef, specs, col_ranges):
    """True when the user's int key expression provably stays inside
    int64 over the batch's actual per-column value ranges (the
    ranged-int probe: `col_ranges[i]` = exact (lo, hi) ints of leaf i,
    None for non-int leaves — any read of an unranged leaf aborts)."""
    import jax.tree_util as jtu
    if col_ranges is None or len(col_ranges) != len(specs):
        return False
    leaves = []
    for rng, (dt, shape) in zip(col_ranges, specs):
        if rng is None or shape != () or dt.kind != "i":
            return False
        leaves.append(_IntInterval(int(rng[0]), int(rng[1])))
    try:
        out = key(jtu.tree_unflatten(treedef, leaves))
        return isinstance(out, _IntInterval)
    except Exception:
        return False


def classify_top_key(key, treedef, specs, encoded, col_ranges=None):
    """Device top-k eligibility for one result batch: how to compute
    the ordering key of each record on device.

    Returns ("leaf", i) to order by leaf column i, ("fn", key) to
    order by the traced user key (scalar numeric output), or None
    (host path).  With dictionary-ENCODED string keys in leaf 0, only
    a provable value-leaf subscript (index >= 1) qualifies — anything
    that could read leaf 0 would order by the raw ids.

    Traced INT key expressions qualify only with `col_ranges` (exact
    per-column min/max of the batch): the interval probe re-executes
    the expression over those ranges in exact Python ints and admits it
    only when no intermediate can leave int64 — the device then
    computes the same exact value the host would (overflow-risk keys
    keep the host path, pinned by test_top_int_key_expression_falls_
    back)."""
    import jax.tree_util as jtu
    nl = len(specs)
    if key is None:
        if encoded or nl != 1:
            return None
        dt, shape = specs[0]
        if shape == () and dt.kind in "if":
            return ("leaf", 0)
        return None
    idx = _subscript_const_index(key)
    if idx is not None:
        if not (0 <= idx < nl):
            return None
        if treedef != jtu.tree_structure(tuple(range(nl))):
            return None          # nested records: subscript != leaf
        dt, shape = specs[idx]
        if shape != () or dt.kind not in "if":
            return None
        if encoded and idx == 0:
            return None
        return ("leaf", idx)
    if encoded:
        return None
    try:
        fn = _row_fn(key, treedef)
        out = jax.eval_shape(fn, *_spec_struct(specs))
        if len(out) != 1 or out[0].shape != ():
            return None
        kind = np.dtype(out[0].dtype).kind
        # FLOAT outputs ride unconditionally: float arithmetic is
        # IEEE-identical per record on both sides.  INT outputs ride
        # only past the ranged probe: the host computes exact Python
        # ints while the device wraps at i64 — an integer key that
        # overflows would silently reorder (review finding).
        if kind == "f":
            return ("fn", key)
        if kind == "i" and _ranged_int_key_ok(key, treedef, specs,
                                              col_ranges):
            return ("fn", key)
    except Exception:
        pass
    return None


def fn_key(f):
    """Structural identity of a user function: same code + same captured
    cell values => same compiled program.  Unhashable captures fall back to
    object identity (no cross-run sharing, still correct)."""
    try:
        cells = tuple(c.cell_contents for c in (f.__closure__ or ()))
        hash(cells)
        return (f.__code__, cells)
    except Exception:
        return ("id", id(f))


def _row_fn(f, in_treedef):
    """Wrap a record-level user fn as leaves -> leaves with output treedef
    discovered at trace time."""
    def fn(*leaves):
        rec = jax.tree_util.tree_unflatten(in_treedef, list(leaves))
        out = f(rec)
        out_leaves, out_treedef = jax.tree_util.tree_flatten(out)
        fn.out_treedef = out_treedef
        return tuple(out_leaves)
    return fn


class MapOp:
    """map / mapValue / keyBy — all are record->record functions."""

    def __init__(self, f, key=None):
        self.f = f
        self.key = ("map", key if key is not None else fn_key(f))

    def probe(self, treedef, specs):
        fn = _row_fn(self.f, treedef)
        out_structs = jax.eval_shape(fn, *_spec_struct(specs))
        out_specs = [(np.dtype(s.dtype), tuple(s.shape))
                     for s in out_structs]
        for dt, shape in out_specs:
            if dt == np.dtype(object):
                raise TypeError("object dtype")
        self._vfn = jax.vmap(fn)
        self._out_treedef = fn.out_treedef
        return self._out_treedef, out_specs

    def apply(self, leaves, n):
        out = self._vfn(*leaves)
        return list(out), n


class SortOp:
    """Per-partition sort by the key — one scalar leaf, or every column
    of a flat tuple key, compared lexicographically like the host's
    tuple sort (backs sortByKey's final mapPartitions(_SortPartFn) on
    device)."""

    def __init__(self, ascending):
        self.ascending = ascending
        self.nk = 1
        self.key = ("sort", ascending)

    def probe(self, treedef, specs):
        nk = layout.key_width(treedef, specs, kinds="if")
        if nk is None:
            raise TypeError("sort needs a numeric scalar (or flat "
                            "numeric tuple) key")
        self.nk = nk
        self.key = ("sort", self.ascending, nk)
        return treedef, specs

    def apply(self, leaves, n):
        from dpark_tpu.backend.tpu import collectives
        cap = leaves[0].shape[0]
        valid = jnp.arange(cap) < n
        # only key column 0 needs the sentinel: padding sorts last on
        # it alone, and no valid row can carry it (ingest guard)
        k = jnp.where(valid, leaves[0],
                      collectives._sentinel(leaves[0].dtype))
        packed = collectives._lex_sort((k,) + tuple(leaves[1:]),
                                       self.nk)
        out = [packed[0]] + list(packed[1:])
        if not self.ascending:
            # reverse the valid prefix, keep padding in place
            idx = jnp.arange(cap)
            ridx = jnp.where(idx < n, n - 1 - idx, idx)
            out = [l[ridx] for l in out]
        return out, n


class FilterOp:
    def __init__(self, f, key=None):
        self.f = f
        self.key = ("filter", key if key is not None else fn_key(f))

    def probe(self, treedef, specs):
        fn = _row_fn(self.f, treedef)
        out_structs = jax.eval_shape(fn, *_spec_struct(specs))
        if (len(out_structs) != 1 or out_structs[0].shape != ()):
            raise TypeError("filter predicate must return a scalar")
        self._vfn = jax.vmap(fn)
        return treedef, specs          # unchanged record type

    def apply(self, leaves, n):
        from dpark_tpu.backend.tpu import collectives
        cap = leaves[0].shape[0]
        (pred,) = self._vfn(*leaves)
        mask = pred.astype(bool) & (jnp.arange(cap) < n)
        return collectives.compact(leaves, mask)


class SegAggOp:
    """groupByKey().mapValues(provable aggregate) consumed ON DEVICE:
    the no-combine reduce leaves each device's rows key-sorted with the
    valid prefix first, so one boundary scan + segment scatter yields
    one (k, agg) row per key — ragged (k, [v]) groups never materialize
    and no host bridge runs (reference: dpark/rdd.py groupByKey +
    mapValue; SURVEY.md 2.2 CoGroupedRDD row, 7.1 step 6).

    REQUIRES key-sorted valid-prefix input: analyze_stage only installs
    this as ops[0] of a no-combine "hbm"-source plan, whose reduce
    program (_compile_reduce's no-combine branch) sorts rows by key
    before applying ops — any new install site must preserve that.

    Float NaN caveat: NaN values are treated as absent for min/max
    (the host fold's result for a NaN-bearing group depends on shuffle
    arrival order — it ignores NaNs unless one arrives first — so no
    vectorized form can reproduce it exactly; masking NaN to the
    identity matches the host in every NaN-not-first case)."""

    def __init__(self, kind):
        self.kind = kind
        self.nk = 1
        self.key = ("segagg", kind)

    def probe(self, treedef, specs):
        nk = layout.key_width(treedef, specs, kinds="if")
        if nk is None or len(specs) != nk + 1:
            raise TypeError("segagg needs flat (k, v) records (scalar "
                            "or flat-tuple key, one scalar value)")
        self.nk = nk
        self.key = ("segagg", self.kind, nk)
        vdt, vshape = specs[nk]
        if vshape != ():
            raise TypeError("segagg needs a scalar value")
        if vdt.kind not in "if" or any(dt.kind not in "if"
                                       for dt, _ in specs[:nk]):
            raise TypeError("segagg needs numeric key and value")
        if self.kind == "count":
            odt = np.dtype(np.int64)
        elif self.kind == "mean":
            # host semantics: int values true-divide to float; float
            # values keep their width (np.float32 sum / int is f32)
            odt = np.dtype(np.float64) if vdt.kind == "i" else vdt
        elif self.kind == "sum" and vdt.kind == "i":
            # device sums are 64-bit (the executor's x64 contract:
            # counting must not wrap at 2**31)
            odt = np.dtype(np.int64)
        else:
            odt = vdt
        return treedef, list(specs[:nk]) + [(odt, ())]

    def apply(self, leaves, n):
        from dpark_tpu.backend.tpu import collectives
        nk = self.nk
        k, v = leaves[0], leaves[nk]
        cap = k.shape[0]
        idx = jnp.arange(cap)
        valid = idx < n
        ks = jnp.where(valid, k, collectives._sentinel(k.dtype))
        # segment ids from sorted-key boundaries (ANY key column
        # changing starts a group); invalid rows land in segment cap-1,
        # past the n_out valid prefix (when every row is its own
        # segment there are no invalid rows to misplace)
        changed = ks != jnp.roll(ks, 1)
        for kc in leaves[1:nk]:
            changed = changed | (kc != jnp.roll(kc, 1))
        starts = valid & ((idx == 0) | changed)
        seg = jnp.where(valid, jnp.cumsum(starts.astype(jnp.int32)) - 1,
                        cap - 1)
        n_out = jnp.sum(starts).astype(jnp.int32)
        kind = self.kind
        op_kind = {"sum": "add", "count": "add", "mean": "add",
                   "min": "min", "max": "max"}[kind]
        if kind == "count":
            vals = jnp.ones((cap,), jnp.int64)
        elif v.dtype.kind == "i" and kind in ("sum", "mean"):
            vals = v.astype(jnp.int64)   # exact int sums, like the host
        else:
            vals = v
        from dpark_tpu.bagel import monoid_identity
        ident_v = monoid_identity(op_kind, vals.dtype)
        mask_v = valid
        if kind in ("min", "max") and vals.dtype.kind == "f":
            mask_v = valid & ~jnp.isnan(vals)   # NaN caveat: see class
        vals = jnp.where(mask_v, vals, ident_v)
        op = collectives._segment_op(op_kind)
        agg = op(vals, seg, num_segments=cap)
        if kind == "mean":
            cnt = collectives._segment_op("add")(
                jnp.where(valid, jnp.ones((cap,), jnp.int64),
                          jnp.zeros((), jnp.int64)),
                seg, num_segments=cap)
            # int sums true-divide to f64; float sums keep their width
            # (jax promotion: f32 / i64 -> f32) — both match the host
            agg = agg / jnp.maximum(cnt, 1)
        # per-segment keys: min over the segment (all equal within a
        # segment, for every key column); empty segments keep the
        # sentinel in column 0 and sit past the valid prefix
        seg_min = collectives._segment_op("min")
        out_ks = [seg_min(ks, seg, num_segments=cap)]
        out_ks += [seg_min(kc, seg, num_segments=cap)
                   for kc in leaves[1:nk]]
        return out_ks + [agg], n_out


def _seg_row_fn(f):
    """The user's per-group function wrapped as (B,) array -> tuple of
    scalar leaves, output treedef discovered at trace time."""
    def fn(vs):
        out = f(vs)
        leaves, treedef = jax.tree_util.tree_flatten(out)
        fn.out_treedef = treedef
        return tuple(leaves)
    return fn


def _seg_state_row_fns(update):
    """The user's update(values, prev) as two leaf fns: one traced with
    a prev scalar, one with the LITERAL None (so ``prev or 0`` /
    ``if prev is None`` branch exactly as on the host paths — the same
    dual-trace idea as bagel_obj's mail/no-mail bodies)."""
    def with_prev(vs, p):
        out = update(vs, p)
        leaves, treedef = jax.tree_util.tree_flatten(out)
        with_prev.out_treedef = treedef
        return tuple(leaves)

    def without_prev(vs):
        out = update(vs, None)
        leaves, treedef = jax.tree_util.tree_flatten(out)
        without_prev.out_treedef = treedef
        return tuple(leaves)
    return with_prev, without_prev


def _seg_pad_cases(vdt, rng):
    """Deterministic sample value vectors for the padding-invariance
    verification: small/large, all-negative, all-positive, zeros —
    the shapes that defeat a wrong fill (0 is NOT neutral for max over
    negatives; repeating the last row is NOT neutral for sums)."""
    sizes = (1, 2, 3, 5, 7, 12)
    cases = []
    for s in sizes:
        if np.dtype(vdt).kind == "i":
            draws = [rng.randint(-1000, 1000, size=s),
                     -rng.randint(1, 1000, size=s),
                     rng.randint(1, 1000, size=s),
                     np.zeros(s, np.int64)]
        else:
            draws = [(rng.standard_normal(s) * 100),
                     -np.abs(rng.standard_normal(s) * 100) - 1,
                     np.abs(rng.standard_normal(s) * 100) + 1,
                     np.zeros(s)]
        cases.extend(np.asarray(d, vdt) for d in draws)
    return cases


def _pad_vec(v, pad, width, vdt):
    """v padded to `width` with the strategy's fill."""
    fill = (v[-1] if (pad == "edge" and len(v)) else np.dtype(vdt).type(0))
    return np.concatenate([v, np.full(width - len(v), fill, vdt)])


def _seg_leaves_close(a_leaves, b_leaves):
    """Equality for the padding-invariance check.  Floats compare at
    1e-3 rel+abs: a WRONG fill or a length-dependent result is off by
    O(1) relative (max over negatives zero-padded reads 0; mean at the
    padded width scales by s/B), while legitimate rounding between the
    host's float64 list fold and the device-dtype array fold is ~1e-7
    — a tight 1e-9 bar here declined every accumulating float32
    function with a misleading 'needs the true group length' reason
    (review finding, CONFIRMED on the bench A/B's own function)."""
    for a, b in zip(a_leaves, b_leaves):
        a = np.asarray(a)
        b = np.asarray(b)
        if a.shape != b.shape:
            return False
        if a.dtype.kind == "f" or b.dtype.kind == "f":
            if not np.allclose(np.asarray(a, np.float64),
                               np.asarray(b, np.float64),
                               rtol=1e-3, atol=1e-3, equal_nan=True):
                return False
        elif not np.array_equal(a, b):
            return False
    return True


# classification is driver-side per job submission; the verification
# runs ~100 tiny eager evals of the user function, so memoize per
# (function identity, value dtype, mode) — DStream ticks classify the
# same function every batch
_SEG_CLASS_CACHE = {}
SEG_PAD_STRATEGIES = ("zero", "edge")


def classify_seg_map(f, vdt, state=False):
    """Admission for the device segmented apply: is `f` a traceable,
    padding-invariant per-group function?

    Returns (pad, out_vdef, out_specs) — pad in SEG_PAD_STRATEGIES,
    out_vdef the output value pytree, out_specs its scalar leaf specs —
    or (None, reason, None).

    Two obligations, both checked here:
      * f traces over a 1-D value array (jax.eval_shape at two bucket
        widths; the output leaf specs must not depend on the width);
      * f is PADDING-INVARIANT under one of the fill strategies: the
        device pads each group to its power-of-two bucket, so
        f(padded) must equal f(exact) — verified CONCRETELY on seeded
        sample vectors (positive/negative/zero/large draws at several
        sizes, padded to 1x and 2x the bucket width), against the HOST
        call form f(list) so the list->array representation change is
        covered by the same check.  sum-like shapes pass "zero",
        order-statistic shapes (max, top-2, range) pass "edge"
        (repeat-last); anything needing the true group length (mean
        beyond the provable form, variance) fails both and keeps the
        host path — this check can only admit wrongly if the function
        distinguishes paddings on data the samples don't reach, which
        is the same empirical-verification contract the text
        tokenizer's sample check documents."""
    try:
        ck = (fn_key(f), bool(state), str(vdt))
    except Exception:
        ck = None
    if ck is not None and ck in _SEG_CLASS_CACHE:
        # the entry PINS the classified function: fn_key's
        # unhashable-capture fallback keys by id(f), and a recycled id
        # must never serve another function a stale verdict
        return _SEG_CLASS_CACHE[ck][1]
    out = _classify_seg_map(f, np.dtype(vdt), state)
    if ck is not None:
        if len(_SEG_CLASS_CACHE) >= 512:
            _SEG_CLASS_CACHE.pop(next(iter(_SEG_CLASS_CACHE)))
        _SEG_CLASS_CACHE[ck] = (f, out)
    return out


def _classify_seg_map(f, vdt, state):
    import jax.tree_util as jtu
    # -- trace probe at two widths ----------------------------------
    def specs_at(width):
        if state:
            fn_p, fn_n = _seg_state_row_fns(f)
            outs_p = jax.eval_shape(
                fn_p, jax.ShapeDtypeStruct((width,), vdt),
                jax.ShapeDtypeStruct((), vdt))
            outs_n = jax.eval_shape(
                fn_n, jax.ShapeDtypeStruct((width,), vdt))
            if ([(np.dtype(s.dtype), s.shape) for s in outs_p]
                    != [(np.dtype(s.dtype), s.shape) for s in outs_n]
                    or fn_p.out_treedef != fn_n.out_treedef):
                raise TypeError("update(values, prev) and "
                                "update(values, None) disagree on the "
                                "output spec")
            return outs_p, fn_p.out_treedef
        fn = _seg_row_fn(f)
        outs = jax.eval_shape(fn, jax.ShapeDtypeStruct((width,), vdt))
        return outs, fn.out_treedef

    try:
        outs4, vdef4 = specs_at(4)
        outs8, vdef8 = specs_at(8)
    except Exception as e:
        return (None, "per-group function is not traceable (%s)"
                % str(e)[:160], None)
    s4 = [(np.dtype(s.dtype), tuple(s.shape)) for s in outs4]
    s8 = [(np.dtype(s.dtype), tuple(s.shape)) for s in outs8]
    if s4 != s8 or vdef4 != vdef8:
        return (None, "per-group function output depends on the "
                "padded width", None)
    if not s4:
        return (None, "per-group function returns no leaves", None)
    for dt, shape in s4:
        if shape != () or dt.kind not in "if":
            return (None, "per-group function output is not a pytree "
                    "of numeric scalars", None)
    if state and len(s4) != 1:
        return (None, "state update must produce one scalar state "
                "leaf", None)

    # -- concrete padding-invariance verification -------------------
    rng = np.random.RandomState(0x5E90)
    cases = _seg_pad_cases(vdt, rng)
    prevs = [None]
    if state:
        prevs = [None, np.dtype(vdt).type(3), np.dtype(vdt).type(-7)]

    def call(vs, prev, as_list):
        # SCOPED warning suppression: without the executor's
        # jax_enable_x64 the i64 request downcasts and jax warns per
        # eval — the comparison logic is width-agnostic, and a global
        # filter would swallow the diagnostic for user code too
        import warnings
        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message="Explicitly requested dtype")
            arg = list(np.asarray(vs).tolist()) if as_list \
                else jnp.asarray(vs, vdt)
            out = f(arg, prev) if state else f(arg)
        leaves, treedef = jtu.tree_flatten(out)
        return leaves, treedef

    for pad in SEG_PAD_STRATEGIES:
        ok = True
        try:
            for v in cases:
                b = 1 << max(0, int(len(v) - 1).bit_length())
                for prev in prevs:
                    base, bdef = call(v, prev, as_list=True)
                    if bdef != vdef4:
                        ok = False
                        break
                    for width in (b, 2 * b):
                        got, _ = call(_pad_vec(v, pad, width, vdt),
                                      prev, as_list=False)
                        if not _seg_leaves_close(base, got):
                            ok = False
                            break
                    if not ok:
                        break
                if not ok:
                    break
            if ok and state:
                # empty groups: keys present only in the carried state
                # call update([], prev) on the host — the device sees
                # an all-fill vector
                for prev in prevs[1:]:
                    base, _ = call(np.zeros(0, vdt), prev, as_list=True)
                    for width in (1, 2, 4):
                        got, _ = call(np.zeros(width, vdt), prev,
                                      as_list=False)
                        if not _seg_leaves_close(base, got):
                            ok = False
                            break
                    if not ok:
                        break
        except Exception:
            ok = False
        if ok:
            return (pad, vdef4, s4)
    return (None, "per-group function is not padding-invariant "
            "(its result needs the true group length; zero-fill and "
            "repeat-last fills both change it)", None)


class SegMapOp:
    """Device segmented apply: groupByKey().mapValues(f) with an
    arbitrary TRACEABLE per-group f consumed ON DEVICE.  The group
    lists never materialize: the key-sorted no-combine rows split into
    segments, segments bucket by power-of-two size class (the
    degree-class idea of backend/tpu/bagel_obj.py generalized through
    collectives.segment_spans/bucket_histogram — at most one trace of
    `f` per power of two, <= ~11 for any distribution), each bucket
    pads its groups to the class width with the admission-verified
    fill (classify_seg_map), and jax.vmap applies `f` across the
    groups of each bucket.  Output rows are (key, f(group)) in key
    order, and the rest of the chain (and any shuffle write) continues
    on device.

    REQUIRES key-sorted valid-prefix input, like SegAggOp: the
    executor's _run_seg_map feeds it the exchange-sorted batch (or the
    premerged spilled runs) and sets `layout` from the device bucket
    histogram before compiling — `layout` is part of the compiled
    program's identity (executor passes it as extra_key).

    state_mode (the general-updateStateByKey rider): records are
    (k, (v, flag)) — flag 1 marks the carried state row — and `f` is
    the user's update(values, prev), traced twice (prev scalar /
    literal None) with the group's new values compacted to the front
    before padding."""

    state_mode = False

    def __init__(self, f, pad):
        self.f = f
        self.pad = pad
        self.nk = 1
        self.layout = None          # ((bucket, width, G), ...) per run
        self.key = ("segmap", fn_key(f), pad)

    def probe(self, treedef, specs):
        import jax.tree_util as jtu
        nk = layout.key_width(treedef, specs, kinds="if")
        nv = 2 if self.state_mode else 1
        if nk is None or len(specs) != nk + nv:
            raise TypeError("seg_map needs flat (k, v) records (scalar "
                            "or flat-tuple key, one scalar value)")
        self.nk = nk
        self.key = ("segmap", fn_key(self.f), self.pad,
                    self.state_mode, nk)
        vdt, vshape = specs[nk]
        if vshape != () or vdt.kind not in "if":
            raise TypeError("seg_map needs a scalar numeric value")
        self.vdt = np.dtype(vdt)
        pad, vdef_or_reason, out_specs = classify_seg_map(
            self.f, vdt, state=self.state_mode)
        if pad is None:
            raise TypeError(vdef_or_reason or "per-group fn declined")
        self.pad = pad
        vdef = vdef_or_reason
        self._out_vdef = vdef
        sample = jtu.tree_unflatten(treedef, list(range(len(specs))))
        out_sample = (sample[0],
                      jtu.tree_unflatten(vdef, list(range(len(out_specs)))))
        out_treedef = jtu.tree_structure(out_sample)
        return out_treedef, list(specs[:nk]) + [
            (dt, shape) for dt, shape in out_specs]

    # -- traced per-bucket application ---------------------------------
    def _apply_bucket(self, vals, fl, gvalid):
        """(G, B) padded value rows -> tuple of (G,) output leaves."""
        if not self.state_mode:
            return jax.vmap(_seg_row_fn(self.f))(vals)
        fn_p, fn_n = _seg_state_row_fns(self.f)
        # at most one state row per group (the carried state RDD has
        # unique keys), so a masked sum extracts it exactly
        prevs = jnp.sum(jnp.where(fl == 1, vals,
                                  jnp.zeros((), vals.dtype)), axis=1)
        has_prev = jnp.any(fl == 1, axis=1)
        new_vals = self._new_vals(vals, fl)
        outs_p = jax.vmap(fn_p)(new_vals, prevs)
        outs_n = jax.vmap(fn_n)(new_vals)
        return tuple(jnp.where(has_prev, op_, on_)
                     for op_, on_ in zip(outs_p, outs_n))

    def _new_vals(self, vals, fl):
        """State mode: compact each group's NEW values (flag 0) to the
        front and re-fill the tail with the admission-verified pad."""
        G, B = vals.shape
        new_mask = fl == 0
        order = jnp.argsort(~new_mask, axis=1, stable=True)
        vs_c = jnp.take_along_axis(
            jnp.where(new_mask, vals, jnp.zeros((), vals.dtype)),
            order, axis=1)
        n_new = jnp.sum(new_mask, axis=1)
        pos = jnp.arange(B)[None, :]
        if self.pad == "edge":
            last = jnp.take_along_axis(
                vs_c, jnp.maximum(n_new - 1, 0)[:, None], axis=1)
            fill = jnp.where((n_new > 0)[:, None], last,
                             jnp.zeros((), vals.dtype))
            return jnp.where(pos < n_new[:, None], vs_c, fill)
        return jnp.where(pos < n_new[:, None], vs_c,
                         jnp.zeros((), vals.dtype))

    def apply(self, leaves, n):
        from jax import lax
        from dpark_tpu.backend.tpu import collectives
        assert self.layout is not None, "executor must set the bucket " \
            "layout before compiling (see _run_seg_map)"
        nk = self.nk
        kcols = list(leaves[:nk])
        vcol = leaves[nk]
        flcol = leaves[nk + 1] if self.state_mode else None
        cap = vcol.shape[0]
        start_rows, sizes, _seg, n_seg = collectives.segment_spans(
            kcols, n)
        live = jnp.arange(cap) < n_seg
        st_safe = jnp.clip(start_rows, 0, cap - 1)
        out_keys = [jnp.where(
            live, kcols[0][st_safe],
            collectives._sentinel(kcols[0].dtype))]
        out_keys += [jnp.where(live, kc[st_safe],
                               jnp.zeros((), kc.dtype))
                     for kc in kcols[1:]]
        outs = None
        for bucket, width, G in self.layout:
            # cumsum-rank + scatter packs each bucket's members — no
            # sorts anywhere in the apply (XLA:CPU argsort measured 4x
            # an O(n) pass at 1M rows; the first cut paid three)
            seg_sel, gvalid = collectives.bucket_members(
                sizes, n_seg, bucket, G)
            vals = collectives.gather_bucket_groups(
                start_rows, sizes, seg_sel, gvalid, width, vcol,
                self.pad if not self.state_mode else "zero")
            fl = None
            if self.state_mode:
                fl = collectives.gather_bucket_groups(
                    start_rows, sizes, seg_sel, gvalid, width, flcol,
                    "zero")
                # out-of-range slots must read as NOT-new AND NOT-state:
                # rebuild the in-range mask and pin pads to flag 2
                sz = sizes[jnp.clip(seg_sel, 0, cap - 1)]
                in_range = jnp.arange(width)[None, :] < sz[:, None]
                fl = jnp.where(in_range, fl, jnp.full((), 2, fl.dtype))
            res = self._apply_bucket(vals, fl, gvalid)
            if outs is None:
                outs = [jnp.zeros((cap + 1,), r.dtype) for r in res]
            # invalid group lanes scatter to the dummy row `cap`; valid
            # lanes hold distinct segment ids, so no clobbering
            tgt = jnp.where(gvalid, seg_sel, cap)
            for oi, r in enumerate(res):
                outs[oi] = outs[oi].at[tgt].set(r)
        return out_keys + [o[:cap] for o in outs], n_seg


class StagePlan:
    """Everything needed to run one stage on the array path."""

    def __init__(self, source, ops, epilogue, in_treedef, in_specs,
                 out_treedef, out_specs, stage):
        self.source = source        # ("ingest", pc) | ("hbm", dep)
        self.ops = ops
        self.epilogue = epilogue    # None | ("shuffle_write", dep)
        self.in_treedef = in_treedef
        self.in_specs = in_specs
        self.out_treedef = out_treedef
        self.out_specs = out_specs
        self.stage = stage
        self.program_key = self._make_key()

    def _make_key(self):
        """Structural program identity: same ops/specs/aggregators compile
        to the same XLA program regardless of RDD/stage ids — repeated jobs
        (benchmark loops, DStream batches) reuse the jit cache.  The
        record TREEDEFS are part of the identity: ((k1, k2), v) and
        (k, (v1, v2)) flatten to the same leaf specs but compile
        different programs (key width drives the epilogue's hash/sort
        operand count; the value structure drives the lifted merge)."""
        spec_key = (tuple((str(dt), shape) for dt, shape in self.in_specs),
                    str(self.in_treedef), str(self.out_treedef))
        op_keys = tuple(op.key for op in self.ops)
        if self.epilogue is None:
            epi_key = None
        else:
            dep = self.epilogue[1]
            agg = dep.aggregator
            epi_key = ("shuffle", dep.partitioner.num_partitions,
                       fn_key(agg.create_combiner),
                       fn_key(agg.merge_combiners))
        src_key = self.source[0]
        if src_key == "hbm":
            src_key = ("hbm",
                       fn_key(self.source[1].aggregator.merge_combiners))
        return (src_key, spec_key, op_keys, epi_key)


def plan_adapt_signature(plan):
    """(stable program id, shape class) — the cross-process identity
    the adaptive-execution store (dpark_tpu/adapt.py, ISSUE 7) keys
    cost records by.  The program id hashes plan.program_key with
    code-object-aware stable hashing (fn_key carries live code objects
    whose default repr embeds a memory address); the shape class
    buckets the source row count by power of two and carries the row
    width, so observations generalize across small data drift but not
    across scale jumps.  Memoized on the plan."""
    sig = getattr(plan, "_adapt_sig", None)
    if sig is None:
        from dpark_tpu import adapt
        rows = 0
        row_bytes = 16
        if plan.source[0] == "ingest":
            slices = plan.source[1]._slices or ()
            rows = sum(len(s) for s in slices)
            row_bytes = _columnar_row_bytes(slices)
        cls = "r%d" % row_bytes
        if rows:
            cls += "x%d" % (1 << max(0, int(rows - 1).bit_length()))
        sig = (adapt.stable_key(plan.program_key), cls)
        plan._adapt_sig = sig
    return sig


def _mapvalue_as_record_fn(f):
    def fn(rec):
        return (rec[0], f(rec[1]))
    return fn


def _keyby_as_record_fn(f):
    def fn(rec):
        return (f(rec), rec)
    return fn


def extract_chain(top, cached_ids=()):
    """Walk narrow one-parent links from the stage's top RDD to its source.
    Returns (source_rdd, ops list root->top, passthrough) or None.
    `passthrough` is True when the chain unwrapped partitionBy's
    FlatMappedValues(identity) over a no-combine shuffle (rows stay flat
    (k, v) on device; no lists ever exist).  A chain node whose batch is
    HBM-cached terminates the walk (source = that node)."""
    ops = []
    cur = top
    passthrough = False
    while True:
        if getattr(cur, "_snapshot_path", None) is not None \
                or cur._checkpoint_path is not None \
                or cur._checkpoint_rdd is not None:
            # snapshot()/checkpoint(): the user asked for disk
            # materialization — the object path honors the read/write
            # (and the lazy checkpoint's promotion); fusing past it
            # would silently skip both
            return None
        if cur.id in cached_ids:
            ops.reverse()
            return cur, ops, passthrough
        if isinstance(cur, FlatMappedValuesRDD) \
                and cur.f is _join_values \
                and isinstance(cur.prev, CoGroupedRDD) \
                and len(cur.prev.rdds) == 2:
            # a.join(b): terminates the chain — analyze_stage checks
            # both cogroup inputs are HBM-resident and makes this a
            # device "join" source (expand on device, no host rows)
            ops.reverse()
            return cur, ops, passthrough
        if isinstance(cur, FlatMappedValuesRDD) and cur.f is _identity \
                and isinstance(cur.prev, ShuffledRDD) \
                and is_list_agg(cur.prev.aggregator):
            passthrough = True
            cur = cur.prev
        elif isinstance(cur, MappedValuesRDD):
            op = MapOp(_mapvalue_as_record_fn(cur.f),
                       ("mapvalue", fn_key(cur.f)))
            op.mapvalue_f = cur.f    # analyze may consume f as a segagg
            ops.append(op)
            cur = cur.prev
        elif isinstance(cur, KeyedRDD):
            ops.append(MapOp(_keyby_as_record_fn(cur.f),
                             ("keyby", fn_key(cur.f))))
            cur = cur.prev
        elif isinstance(cur, MappedRDD):
            ops.append(MapOp(cur.f))
            cur = cur.prev
        elif isinstance(cur, FilteredRDD):
            ops.append(FilterOp(cur.f))
            cur = cur.prev
        elif isinstance(cur, MapPartitionsRDD) \
                and isinstance(cur.f, _SortPartFn) and not cur.with_index:
            ops.append(SortOp(cur.f.ascending))
            cur = cur.prev
        elif isinstance(cur, (ParallelCollection, ShuffledRDD,
                              UnionRDD)):
            ops.reverse()
            return cur, ops, passthrough
        else:
            return None


def _sample_record(pc):
    """First record of a ParallelCollection (driver-side only)."""
    for s in pc._slices:
        if s:
            return s[0]
    return None


# ----------------------------------------------------------------------
# text-source stages (SURVEY.md 3.1 hot loop #1): the narrow chain over a
# file source is string-typed and untraceable, so it runs as a HOST
# PROLOGUE per split (the user's own generators), records are
# dictionary-encoded to int64 columns, and the shuffle write + combine
# ride the device.  The canonical wordcount shape additionally replaces
# the Python per-record loop with the C++ tokenizer (verified per run
# against the user's functions on a sample prefix).
# ----------------------------------------------------------------------

def _text_sources():
    """File-backed record sources whose narrow chains run as a host
    prologue feeding the device shuffle (lazy: tabular imports rdd)."""
    from dpark_tpu.tabular import TabularRDD
    return (TextFileRDD, GZipFileRDD, CSVReaderRDD, CSVFileRDD,
            TabularRDD)


def extract_text_chain(top):
    """Walk one-parent narrow links to a file source.  Returns
    (source_rdd, chain root->top) or None."""
    sources = _text_sources()
    chain = []
    cur = top
    while True:
        if getattr(cur, "_snapshot_path", None) is not None \
                or cur._checkpoint_path is not None \
                or cur._checkpoint_rdd is not None:
            return None          # disk materialization: object path
        if isinstance(cur, sources):
            chain.reverse()
            return cur, chain
        if isinstance(cur, DerivedRDD):
            chain.append(cur)
            cur = cur.prev
        else:
            return None


def _code_matches(f, template):
    """f is a closure-free function with the template's bytecode."""
    code = getattr(f, "__code__", None)
    if code is None or getattr(f, "__closure__", None):
        return False
    t = template.__code__
    return (code.co_code == t.co_code
            and code.co_consts == t.co_consts
            and code.co_names == t.co_names
            and code.co_argcount == t.co_argcount)


def _is_whitespace_split(f):
    # 'split' in the template is an attribute load on the argument, not
    # a global — bytecode equality is sufficient
    return f is str.split or _code_matches(f, lambda line: line.split())


def _const_split_sep(f):
    """The separator when f is exactly `lambda line: line.split(SEP)`
    with a single-byte ASCII constant (not \\n or \\r), else None.
    Bytecode must equal the template's; only the string const (the
    separator itself) may differ — it is extracted, not assumed."""
    code = getattr(f, "__code__", None)
    if code is None or getattr(f, "__closure__", None):
        return None
    t = (lambda line: line.split("\x00")).__code__
    if not (code.co_code == t.co_code
            and code.co_names == t.co_names
            and code.co_argcount == t.co_argcount):
        return None
    strs = [c for c in code.co_consts if isinstance(c, str)]
    others = [c for c in code.co_consts if not isinstance(c, str)]
    t_others = [c for c in t.co_consts if not isinstance(c, str)]
    if len(strs) != 1 or others != t_others:
        return None
    sep = strs[0]
    if len(sep) == 1 and ord(sep) < 0x80 and sep not in "\n\r":
        return sep
    return None


def _is_pair_one(f):
    return _code_matches(f, lambda w: (w, 1))


def canonical_wordcount(chain):
    """The separator string when chain is exactly
    flatMap(split) -> map(w -> (w, 1)): "" for whitespace split,
    a 1-char string for a constant-separator split, None otherwise."""
    if len(chain) != 2:
        return None
    fm, mp = chain
    if not (isinstance(fm, FlatMappedRDD) and isinstance(mp, MappedRDD)
            and _is_pair_one(mp.f)):
        return None
    if _is_whitespace_split(fm.f):
        return ""
    return _const_split_sep(fm.f)


def _sample_text_record(top):
    """First record of the narrow chain, read from the first non-empty
    split (driver-side; cached per RDD — a tabular source decompresses
    a whole chunk to produce it, so once is enough)."""
    if hasattr(top, "_tpu_sample_record"):
        return top._tpu_sample_record
    sample = None
    for sp in top.splits[:8]:
        it = top.iterator(sp)
        try:
            for rec in it:
                sample = rec
                break
        finally:
            close = getattr(it, "close", None)
            if close:
                close()
        if sample is not None:
            break
    top._tpu_sample_record = sample
    return sample


def analyze_text_stage(stage, ndev, executor_or_store):
    """Shuffle-map stage rooted at a file source: build a text StagePlan
    (host-prologue ingest + device shuffle write) or return None."""
    if not getattr(stage, "is_shuffle_map", False):
        return None
    top = stage.rdd
    extracted = extract_text_chain(top)
    if extracted is None:
        return None
    text_rdd, chain = extracted
    dep = stage.shuffle_dep
    logical_spill = False
    epi_spec = partitioner_spec(dep.partitioner)
    if epi_spec is None:
        return None

    sample = _sample_text_record(top)
    if not (isinstance(sample, tuple) and len(sample) == 2):
        return None
    k, v = sample
    key_is_str = isinstance(k, (str, bytes))
    if not key_is_str and not isinstance(k, (int, np.integer)):
        return None
    if key_is_str and epi_spec[0] != "hash":
        return None                      # str keys have no range bounds
    try:
        treedef, specs = layout.record_spec((0, v))
    except (TypeError, ValueError):
        return None
    for dt, _ in specs:
        if dt == np.dtype(object) or dt.kind in "USO":
            return None
    epi_bounds = None
    if epi_spec[0] == "range":
        epi_bounds = np.asarray(dep.partitioner.bounds,
                                dtype=np.int64)

    ops = []
    cur_treedef, cur_specs = treedef, specs
    if not is_list_agg(dep.aggregator):
        create = dep.aggregator.create_combiner
        try:
            op = MapOp(lambda rec: (rec[0], create(rec[1])))
            cur_treedef, cur_specs = op.probe(cur_treedef, cur_specs)
            ops.append(op)
        except Exception as e:
            logger.debug("create_combiner not traceable: %s", e)
            return None
        if layout.key_leaf_index(cur_treedef, cur_specs) is None:
            return None

    if dep.partitioner.num_partitions > ndev:
        # more logical partitions than devices: only the spilled-run
        # stream supports this (the rid rides the exchange, runs land
        # per logical partition) — list aggregators, untraceable
        # merges (combiner folded host-side at export), and TRACEABLE
        # merges (waves pre-reduce per (rid, key) on device before
        # spilling) all ride it.  Small inputs go to the object path.
        if not _big_text(stage):
            return None
        logical_spill = True

    plan = StagePlan(("text", None), ops, ("shuffle_write", dep),
                     treedef, specs, cur_treedef, cur_specs, stage)
    plan.src_combine = False
    plan.group_output = False
    plan.epi_spec = epi_spec
    plan.epi_bounds = epi_bounds
    plan.epi_nk = 1
    plan.src_nk = 1
    plan.text_rdd = text_rdd
    plan.text_chain = chain
    plan.encoded_keys = key_is_str
    plan.logical_spill = logical_spill
    sep = (canonical_wordcount(chain)
           if key_is_str and type(text_rdd) is TextFileRDD else None)
    plan.canonical = sep is not None
    plan.canonical_sep = sep or None      # "" (whitespace) -> None
    plan.program_key = plan.program_key + (False, False, epi_spec)
    return plan


def _leaves_merge_fn(merge, record_treedef):
    """User merge_combiners (value, value) -> value lifted to leaf
    lists, vmapped for use inside segment scans.  The value's REAL
    pytree structure is rebuilt from the record treedef before calling
    the user function — a nested combiner like avg's (sum, (s, c))
    must see its own shape, not a flat leaf tuple (flattening broke
    every nested-accumulator aggregate, e.g. Table avg)."""
    import jax.tree_util as jtu
    children = jtu.treedef_children(record_treedef)
    if len(children) == 2:
        vdef = children[1]               # records are (k, value)
        nleaves = vdef.num_leaves

        def _unwrap(leaves):
            return jtu.tree_unflatten(vdef, list(leaves))
    else:                                # flat (k, v1, v2, ...) record
        nleaves = record_treedef.num_leaves - 1

        def _unwrap(leaves):
            return leaves[0] if nleaves == 1 else tuple(leaves)

    def leaf_merge(*flat):
        va = flat[:nleaves]
        vb = flat[nleaves:]
        out = merge(_unwrap(va), _unwrap(vb))
        out_leaves = jax.tree_util.tree_leaves(out)
        return tuple(out_leaves)

    vfn = jax.vmap(leaf_merge)

    def merged(va_leaves, vb_leaves):
        return list(vfn(*(list(va_leaves) + list(vb_leaves))))
    return merged


def _columnar_row_bytes(slices):
    """Bytes per record across a slice's columns (for HBM wave sizing)."""
    for s in slices:
        cols = getattr(s, "columns", None)
        if cols is not None and len(s):
            import numpy as np
            return sum(np.asarray(c).dtype.itemsize
                       * int(np.prod(np.asarray(c).shape[1:] or (1,)))
                       for c in cols)
    return 16


def _big_columnar(pc):
    """ParallelCollection big enough for the wave stream (the r > ndev
    spill requires streaming).  The threshold is the EFFECTIVE chunk
    (HBM-sized on a real device) so data that fits one wave keeps the
    lower-overhead in-core path."""
    from dpark_tpu import conf
    from dpark_tpu.rdd import _ColumnarSlice
    slices = pc._slices
    return (all(isinstance(s, _ColumnarSlice) for s in slices)
            and max((len(s) for s in slices), default=0)
            > conf.stream_chunk_rows(_columnar_row_bytes(slices)))


def _split_bytes(sp):
    """Best-effort on-disk size of one file split: byte range when the
    split carries one (TextSplit), whole-file size otherwise (tabular /
    whole-file splits)."""
    end = getattr(sp, "end", None)
    if end is not None:
        return max(0, end - getattr(sp, "begin", 0))
    path = getattr(sp, "path", None)
    if path and "://" not in path:
        try:
            import os
            return os.path.getsize(path)
        except OSError:
            return 0
    return 0


def _big_text(stage):
    """Text source big enough for the wave stream."""
    from dpark_tpu import conf
    return (sum(_split_bytes(sp) for sp in stage.rdd.splits)
            > conf.STREAM_TEXT_BYTES)


def _range_bounds_array(bounds, specs, nk):
    """The RangePartitioner bounds as the device array the range
    epilogue compares against: 1D cast to the key spec dtype for a
    scalar key, (len(bounds), nk) for a flat tuple key — requiring one
    SHARED spec dtype across the key columns (mixed int/float tuple
    bounds have host bisect semantics no single-dtype device compare
    reproduces).  None = host fallback."""
    dt = np.dtype(specs[0][0])
    if nk == 1:
        return np.asarray(bounds, dtype=dt)
    if any(np.dtype(s[0]) != dt for s in specs[1:nk]):
        return _fallback("range partitioner over a tuple key with "
                         "mixed column dtypes")
    if not bounds:
        return np.zeros((0, nk), dtype=dt)
    arr = np.asarray(bounds, dtype=dt)
    if arr.ndim != 2 or arr.shape[1] != nk:
        return _fallback("range bounds do not match the key width")
    return arr


# a union stage materializes every branch before concatenating on
# device; bound the fan-in so one stage cannot pin arbitrarily many
# parent batches in HBM at once
MAX_UNION_SOURCES = 12


def _analyze_union_parent(parent, ndev, executor_or_store, cached_ids,
                          stage):
    """Sub-plan (epilogue=None) turning ONE UnionRDD branch into a
    device Batch of its post-ops rows, or None.  The windowed-stream
    shape — union of per-batch reduceByKey outputs feeding another
    reduceByKey — is all hbm branches (BASELINE config #4)."""
    hbm_sids = getattr(executor_or_store, "shuffle_store",
                       executor_or_store)
    extracted = extract_chain(parent, cached_ids)
    if extracted is None:
        return None
    src_rdd, ops, passthrough = extracted
    src_combine = False
    reslice = False
    if src_rdd.id in cached_ids:
        meta = executor_or_store.result_cache_meta(src_rdd.id)
        treedef, specs = meta["treedef"], meta["specs"]
        source = ("cached", src_rdd)
    elif isinstance(src_rdd, ParallelCollection):
        if src_rdd._slices is None:
            return None
        reslice = len(src_rdd._slices) != ndev
        if _big_columnar(src_rdd):
            # over-chunk inputs must ride the bounded wave stream; a
            # union branch materializes in-core, pinning the whole
            # batch (plus concat scratch) in HBM — decline
            return None
        sample = _sample_record(src_rdd)
        if sample is None:
            return None
        try:
            treedef, specs = layout.record_spec(sample)
        except (TypeError, ValueError):
            return None
        for dt, _ in specs:
            if dt == np.dtype(object) or dt.kind in "USO":
                return None
        source = ("ingest", src_rdd)
    elif isinstance(src_rdd, ShuffledRDD):
        dep = src_rdd.dep
        if dep.shuffle_id not in hbm_sids:
            return None
        if dep.partitioner.num_partitions > ndev:
            return None
        meta = hbm_sids[dep.shuffle_id]
        if "host_runs" in meta:
            return None
        if meta.get("encoded_keys"):
            return None              # concat + later ops would leak ids
        treedef, specs = meta["out_treedef"], meta["out_specs"]
        if is_list_agg(dep.aggregator):
            if not passthrough:
                return None          # (k, [v]) lists cannot concat flat
        else:
            src_combine = True
            try:
                nk = (meta.get("key_cols")
                      or layout.key_width(treedef, specs, kinds="if")
                      or 1)
                merge_fn = _leaves_merge_fn(
                    dep.aggregator.merge_combiners, treedef)
                vstructs = _batched_spec_struct(specs[nk:])
                jax.eval_shape(
                    lambda *v: merge_fn(list(v), list(v)), *vstructs)
            except Exception as e:
                logger.debug("union branch merge untraceable: %s", e)
                return None
        source = ("hbm", dep)
    else:
        return None
    cur_treedef, cur_specs = treedef, specs
    try:
        for op in ops:
            cur_treedef, cur_specs = op.probe(cur_treedef, cur_specs)
    except Exception as e:
        logger.debug("union branch not traceable (%s)", e)
        return None
    sub = StagePlan(source, ops, None, treedef, specs,
                    cur_treedef, cur_specs, stage)
    sub.src_combine = src_combine
    sub.group_output = False
    sub.epi_spec = None
    sub.epi_bounds = None
    sub.epi_nk = 1
    sub.src_nk = (layout.key_width(treedef, specs, kinds="if") or 1) \
        if source[0] == "hbm" else 1
    sub.logical_spill = False
    sub.reslice = reslice
    sub.program_key = sub.program_key + (src_combine, False, None,
                                         sub.src_nk)
    return sub


def _analyze_join_source(join_rdd, ndev, executor_or_store):
    """(treedef, specs, (dep_a, dep_b)) for an a.join(b) chain source
    whose cogroup inputs are both HBM-resident plain (k, v) no-combine
    shuffles, else None.  Mirrors the eligibility the driver-seeded
    join precompute enforces, but keeps the expansion ON DEVICE as an
    array-path source."""
    import jax.tree_util as jtu
    hbm_sids = getattr(executor_or_store, "shuffle_store",
                       executor_or_store)
    cg = join_rdd.prev
    deps = []
    for kind, obj in cg._dep_kinds:
        if kind != "shuffle" or not is_list_agg(obj.aggregator):
            return None
        if obj.shuffle_id not in hbm_sids:
            return None
        meta = hbm_sids[obj.shuffle_id]
        if "host_runs" in meta or meta.get("encoded_keys"):
            # encoded ids must not feed further device ops (the ids
            # would leak into user compute); host path decodes
            return None
        deps.append(obj)
    if len(deps) != 2:
        return None
    if deps[0].partitioner.num_partitions > ndev:
        return None
    metas = [hbm_sids[d.shuffle_id] for d in deps]
    samples = []
    nks = []
    for meta in metas:
        treedef, specs = meta["out_treedef"], meta["out_specs"]
        nk = layout.key_width(treedef, specs, kinds="if")
        if nk is None or len(specs) < nk + 1:
            return None      # join kernels need (k, v) / ((k...), v)
        sample = jtu.tree_unflatten(treedef, list(range(len(specs))))
        if len(sample) != 2:
            return None
        samples.append(sample)
        nks.append(nk)
    if nks[0] != nks[1]:
        return None              # key widths must agree across sides
    nk = nks[0]
    a_key = [np.dtype(dt) for dt, _ in metas[0]["out_specs"][:nk]]
    b_key = [np.dtype(dt) for dt, _ in metas[1]["out_specs"][:nk]]
    if a_key != b_key:
        return None              # id-vs-int equality would be spurious
    joined = (samples[0][0], (samples[0][1], samples[1][1]))
    treedef = jtu.tree_structure(joined)
    specs = (list(metas[0]["out_specs"][:nk])
             + list(metas[0]["out_specs"][nk:])
             + list(metas[1]["out_specs"][nk:]))
    return treedef, specs, (deps[0], deps[1])


def _meta_row_estimate(meta):
    """Total stored rows of an HBM shuffle store, or None (spilled-run
    stores register no device counts)."""
    counts = meta.get("counts")
    if counts is None:
        return None
    try:
        return int(layout.host_read(counts).sum())
    except Exception:
        return None


def _try_seg_map(f0, meta, ndev):
    """(SegMapOp or None, fallback reason or None) for a groupByKey
    consumer that did not classify as a provable aggregate — the
    admission pipeline of the device segmented apply: conf gate, value
    shape, traceability + padding-invariance (classify_seg_map), and
    the compile-budget guard."""
    from dpark_tpu import conf
    state_update = getattr(f0, "__dpark_seg_state__", None)
    if not conf.SEG_MAP:
        return None, "grouped consumer stays on host: DPARK_SEG_MAP=0"
    treedef, specs = meta["out_treedef"], meta["out_specs"]
    nk = layout.key_width(treedef, specs, kinds="if")
    nv = 2 if state_update is not None else 1
    if nk is None or len(specs) != nk + nv or specs[nk][1] != () \
            or np.dtype(specs[nk][0]).kind not in "if":
        return None, ("unsupported value pytree for grouped "
                      "consumption (seg_map needs a single scalar "
                      "numeric value per record)")
    fn = state_update if state_update is not None else f0
    pad, reason_or_vdef, _ = classify_seg_map(
        fn, specs[nk][0], state=state_update is not None)
    if pad is None:
        return None, reason_or_vdef
    if conf.SEG_MIN_ROWS_PER_TRACE:
        rows = _meta_row_estimate(meta)
        if rows is not None:
            per_dev = max(1, rows // max(1, ndev))
            est = min(11, max(1, int(per_dev).bit_length()))
            if rows < conf.SEG_MIN_ROWS_PER_TRACE * est:
                return None, (
                    "seg_map compile budget: ~%d rows over ~%d "
                    "estimated traces is under conf."
                    "SEG_MIN_ROWS_PER_TRACE=%d per trace — host loop"
                    % (rows, est, conf.SEG_MIN_ROWS_PER_TRACE))
    op = SegMapOp(fn, pad)
    op.state_mode = state_update is not None
    return op, None


def analyze_stage(stage, ndev, executor_or_store):
    """Decide whether `stage` can run on the array path; build its plan.

    executor_or_store: the JAXExecutor (HBM shuffle store + result cache)
    or a bare shuffle-store dict.  Returns StagePlan or None (fallback;
    last_fallback_reason() explains key-shape declines).
    """
    _last_fallback[0] = None
    hbm_sids = getattr(executor_or_store, "shuffle_store",
                       executor_or_store)
    cached_ids = getattr(executor_or_store, "result_cache_ids",
                         lambda: ())()
    top = stage.rdd
    extracted = extract_chain(top, cached_ids)
    if extracted is None:
        return analyze_text_stage(stage, ndev, executor_or_store)
    source_rdd, ops, passthrough = extracted
    group_output = False

    if (not stage.is_shuffle_map and not ops
            and isinstance(source_rdd, ParallelCollection)
            and source_rdd.id not in cached_ids):
        # a result stage that would only ingest + egest the input does
        # no device work at all — and egesting a huge columnar input as
        # Python rows is exactly what a lazy host read avoids (e.g.
        # sortByKey's bounds sample takes 250 rows per slice)
        return None

    # -- source record spec ---------------------------------------------
    reslice = False
    src_nk = 1
    if source_rdd.id in cached_ids:
        meta = executor_or_store.result_cache_meta(source_rdd.id)
        treedef, specs = meta["treedef"], meta["specs"]
        source = ("cached", source_rdd)
        src_combine = False
    elif isinstance(source_rdd, ParallelCollection):
        if source_rdd._slices is None:
            return None
        reslice = len(source_rdd._slices) != ndev
        if reslice and (not stage.is_shuffle_map
                        or _big_columnar(source_rdd)):
            # result-stage tasks index the RDD's own partition layout;
            # the wave stream consumes slices as-is — both need the
            # exact slicing.  A shuffle write redistributes by key, so
            # the executor re-slices the host rows to the mesh instead
            # of declining (e.g. parallelize(data, 2).reduceByKey on an
            # 8-device mesh — the DStream queue batch shape).
            return None
        sample = _sample_record(source_rdd)
        if sample is None:
            return None
        try:
            treedef, specs = layout.record_spec(sample)
        except (TypeError, ValueError):
            return None
        for dt, _ in specs:
            if dt == np.dtype(object) or dt.kind in "USO":
                return None
        source = ("ingest", source_rdd)
        src_combine = False
    elif isinstance(source_rdd, ShuffledRDD):
        dep = source_rdd.dep
        if dep.shuffle_id not in hbm_sids:
            return None                  # parent shuffle lives on host
        if dep.partitioner.num_partitions > ndev:
            return None                  # R <= ndev: extra devices idle
        # record spec of the stored rows — registered when the map ran
        meta = hbm_sids[dep.shuffle_id]
        # spilled runs (streamed no-combine shuffle): the host merge
        # consumes them — EXCEPT when a segment op takes the stage
        # (SegAggOp/SegMapOp read the premerged key-sorted runs back
        # into a device batch; see executor._seg_batch_from_runs), so
        # the decision moves below the op classification
        from_runs = "host_runs" in meta
        if from_runs and meta.get("host_combine"):
            return None          # runs hold created combiners, not rows
        if meta.get("encoded_keys") and (ops or stage.is_shuffle_map):
            # keys are dictionary-encoded ids: only a plain read (decode
            # at egest) may ride the device — anything else would show
            # the user ids where they expect strings.  The host path
            # sees decoded rows through the export bridge.
            return None
        treedef, specs = meta["out_treedef"], meta["out_specs"]
        src_nk = (meta.get("key_cols")
                  or layout.key_width(treedef, specs, kinds="if") or 1)
        if is_list_agg(dep.aggregator):
            # no-combine shuffle (partitionBy/groupByKey): rows pass
            # through flat; bare groupByKey groups at egest time
            src_combine = False
            if not passthrough:
                seg = None
                seg_reason = None
                if ops:
                    f0 = getattr(ops[0], "mapvalue_f", None)
                    kind = (classify_segagg(f0) if f0 is not None
                            else None)
                    if kind is not None:
                        seg = SegAggOp(kind)
                    elif f0 is not None:
                        # beyond the five provable aggregates: an
                        # arbitrary TRACEABLE per-group function rides
                        # the segmented apply (power-of-two bucket
                        # vmap); _try_seg_map explains every decline
                        seg, seg_reason = _try_seg_map(f0, meta, ndev)
                if seg is not None:
                    # groupByKey().mapValues(aggregate-or-traceable):
                    # the group list never materializes — a segment
                    # scatter/vmap over the key-sorted no-combine rows
                    # yields flat (k, out) records, and the rest of the
                    # chain (and any shuffle write) continues on device
                    ops[0] = seg
                elif ops or stage.is_shuffle_map:
                    # (k, [v]) records: host only — record WHY (the
                    # host-fallback-group lint rule gives the same
                    # answer pre-flight)
                    return _fallback(
                        seg_reason
                        or "grouped values consumed on the host "
                        "((k, [v]) lists have no device form for this "
                        "chain)")
                else:
                    group_output = True
        else:
            src_combine = True
            try:
                merge_fn = _leaves_merge_fn(
                    dep.aggregator.merge_combiners, treedef)
                vstructs = _batched_spec_struct(specs[src_nk:])
                jax.eval_shape(
                    lambda *v: merge_fn(list(v), list(v)), *vstructs)
            except Exception as e:
                logger.debug("merge_combiners not traceable: %s", e)
                return None
        if from_runs and not (ops and isinstance(ops[0],
                                                 (SegAggOp, SegMapOp))):
            return None          # spilled runs: host merge consumes them
        source = ("hbm", dep)
    elif isinstance(source_rdd, UnionRDD):
        if not stage.is_shuffle_map:
            return None          # result tasks index the union's splits
        parents = source_rdd.rdds
        if not parents or len(parents) > MAX_UNION_SOURCES:
            return None
        subs = []
        for p in parents:
            sub = _analyze_union_parent(p, ndev, executor_or_store,
                                        cached_ids, stage)
            if sub is None:
                return None
            subs.append(sub)
        t0 = subs[0].out_treedef
        s0 = [(str(dt), shape) for dt, shape in subs[0].out_specs]
        for sub in subs[1:]:
            if sub.out_treedef != t0 or s0 != [
                    (str(dt), shape) for dt, shape in sub.out_specs]:
                return None      # branches must agree on record type
        treedef, specs = subs[0].out_treedef, subs[0].out_specs
        source = ("union", tuple(subs))
        src_combine = False
    elif isinstance(source_rdd, FlatMappedValuesRDD):
        # extract_chain only terminates here for the a.join(b) shape
        joined = _analyze_join_source(source_rdd, ndev,
                                      executor_or_store)
        if joined is None:
            return None
        treedef, specs, deps = joined
        source = ("join", deps)
        src_combine = False
    else:
        return None

    # -- probe the narrow ops -------------------------------------------
    cur_treedef, cur_specs = treedef, specs
    try:
        for op in ops:
            cur_treedef, cur_specs = op.probe(cur_treedef, cur_specs)
    except Exception as e:
        logger.debug("stage %s not traceable (%s); host fallback",
                     stage, e)
        return None

    # -- epilogue --------------------------------------------------------
    epilogue = None
    epi_spec = None
    epi_bounds = None
    epi_nk = 1
    logical_spill = False
    if stage.is_shuffle_map:
        dep = stage.shuffle_dep
        epi_spec = partitioner_spec(dep.partitioner)
        if epi_spec is None:
            return None
        if epi_spec[0] == "hash":
            epi_nk = layout.key_width(cur_treedef, cur_specs, kinds="i")
            if epi_nk is None:
                return _fallback(
                    "hash shuffle needs an int scalar (or flat "
                    "int-tuple, <= conf.MAX_KEY_LEAVES columns) key")
        else:
            epi_nk = layout.key_width(cur_treedef, cur_specs,
                                      kinds="if")
            if epi_nk is None:
                return _fallback(
                    "range shuffle needs a numeric scalar (or flat "
                    "numeric-tuple) key")
            epi_bounds = _range_bounds_array(
                dep.partitioner.bounds, cur_specs, epi_nk)
            if epi_bounds is None:
                return None
        if is_list_agg(dep.aggregator):
            pass                         # no-combine write: rows as-is
        else:
            create = dep.aggregator.create_combiner
            try:
                op = MapOp(lambda rec: (rec[0], create(rec[1])))
                cur_treedef, cur_specs = op.probe(cur_treedef, cur_specs)
                ops.append(op)
            except Exception as e:
                logger.debug("create_combiner not traceable: %s", e)
                return None
            if epi_spec[0] == "hash":
                epi_nk = layout.key_width(cur_treedef, cur_specs,
                                          kinds="i")
                if epi_nk is None:
                    return _fallback(
                        "hash shuffle needs an int scalar (or flat "
                        "int-tuple) key after create_combiner")
        if dep.partitioner.num_partitions > ndev:
            # more logical partitions than devices: only the spilled
            # no-combine stream supports this (rid rides the exchange,
            # runs land per logical partition) — list aggregators,
            # untraceable merges (combiner folded host-side at export),
            # and TRACEABLE merges (waves pre-reduce per (rid, key) on
            # device before spilling) all ride it.  Small inputs go to
            # the object path HERE, not via an executor error.
            if not (source[0] == "ingest"
                    and _big_columnar(source[1])):
                return None
            logical_spill = True
        epilogue = ("shuffle_write", dep)

    plan = StagePlan(source, ops, epilogue, treedef, specs,
                     cur_treedef, cur_specs, stage)
    plan.src_combine = src_combine
    plan.group_output = group_output
    plan.epi_spec = epi_spec
    plan.epi_bounds = epi_bounds
    plan.epi_nk = epi_nk
    # key width of the SOURCE records (hbm reduce side): the segment
    # reduce / no-combine key sort must span every key column — merging
    # tuple-keyed rows on column 0 alone would mix distinct keys
    plan.src_nk = src_nk if source[0] == "hbm" else 1
    plan.logical_spill = logical_spill
    plan.reslice = reslice
    plan.program_key = plan.program_key + (
        src_combine, group_output, epi_spec, epi_nk, plan.src_nk)
    return plan
