"""The `-m tpu` master: DAG scheduling on the driver, stages as fused SPMD
programs on the device mesh.

Reference parity: replaces dpark's MesosScheduler + executor + file shuffle
(SURVEY.md section 3.1 "TPU mapping"): everything below submitMissingTasks
becomes one shard_map program per stage; narrow hot loops fuse into the
stage program; the shuffle hop is all_to_all + segmented reduce.  Stages
whose user code is not jnp-traceable fall back to the in-process object
path — graceful degradation, never an error (SURVEY.md 7.2 item 1).
"""

from dpark_tpu.env import env
from dpark_tpu.schedule import DAGScheduler, _run_task_inline
from dpark_tpu.task import ResultTask
from dpark_tpu.utils.log import get_logger

logger = get_logger("tpu")


# the known XLA:CPU capability gap (PR 2 notes): collective/aliasing
# programs over a PROCESS-SPANNING mesh raise "Multiprocess
# computations aren't implemented on the CPU backend".  Real TPU/GPU
# pods implement them; a CPU-emulated multi-controller run records
# this as the stage's fallback_reason and serves the job through the
# object path instead of dying on a raw assert (ISSUE 12 satellite).
SPMD_CPU_FALLBACK = ("multi-controller SPMD unsupported on the CPU "
                     "backend (XLA:CPU implements no cross-process "
                     "computations); object path")


def _multiproc_cpu_gap(e):
    """Is this the CPU backend refusing a cross-process computation
    (a CAPABILITY gap, not a runtime fault)?  Matched by message so
    every jax version's concrete error type classifies."""
    for exc in (e, getattr(e, "__cause__", None)):
        if exc is None:
            continue
        text = str(exc)
        if "Multiprocess computations" in text:
            return True
        if "implemented" in text and "CPU backend" in text:
            return True
    return False


def _device_error(e):
    """Is this a device RUNTIME error (XlaRuntimeError, HBM
    RESOURCE_EXHAUSTED) — the class the stage-level degradation ladder
    owns — as opposed to a plan/user-code error?  Matched by type name
    and message so injected stand-ins (faults.py kind=oom) and every
    jax version's concrete type all classify."""
    for exc in (e, getattr(e, "__cause__", None)):
        if exc is None:
            continue
        if type(exc).__name__ in ("XlaRuntimeError", "JaxRuntimeError"):
            return True
        text = str(exc)
        if "RESOURCE_EXHAUSTED" in text or "RESOURCE EXHAUSTED" in text:
            return True
        if "out of memory" in text.lower():
            return True
    return False


class TPUScheduler(DAGScheduler):
    # plan analysis mutates module state (fuse.last_fallback_reason)
    # and probes with shared tracers: with a resident job server's
    # slot threads (ISSUE 9) analyzing concurrently, serialize it so
    # the recorded fallback reason belongs to the stage it names
    _analyze_lock = __import__("threading").Lock()

    def __init__(self, ndev=None):
        super().__init__()
        self._requested_ndev = ndev
        self.executor = None

    def start(self):
        super().start()
        if self.executor is None:
            import jax
            # select the mesh platform before backend init (e.g. `cpu`
            # with --xla_force_host_platform_device_count for a virtual
            # mesh without touching a TPU tunnel)
            from dpark_tpu.utils import apply_platform_override
            apply_platform_override()
            from dpark_tpu.backend.tpu.executor import JAXExecutor
            devices = jax.devices()
            if self._requested_ndev:
                devices = devices[:self._requested_ndev]
            self.executor = JAXExecutor(devices)
            # HBM eviction spills re-point stage output locations
            # (ISSUE 9 satellite): a later job reusing an available
            # stage must see the disk uris, not stale hbm:// ones
            self.executor._spill_notify = self._on_store_spilled
            logger.info("tpu master on %d %s device(s)",
                        len(devices), devices[0].platform)

    def _on_store_spilled(self, sid, uri):
        stage = self.shuffle_to_stage.get(sid)
        if stage is None:
            return
        for i, loc in enumerate(stage.output_locs):
            if loc and str(loc).startswith("hbm://"):
                stage.output_locs[i] = uri

    def _job_started(self, record):
        """Pin this job's HBM buckets against disk spill and snapshot
        the program-cache counters.  The snapshot is only the FALLBACK
        for probes no thread tagged; since ISSUE 15 the cache counts
        hits/misses per job exactly (the probing thread's job stamp),
        so concurrent jobs' record["program_cache"] deltas no longer
        overlap — the PR 9 caveat is closed."""
        ex = self.executor
        if ex is not None:
            ex.live_jobs.add(record["id"])
            record["_pc_base"] = True

    def _job_finished(self, record):
        ex = self.executor
        if ex is None:
            return
        ex.live_jobs.discard(record["id"])
        if record.pop("_pc_base", None) is None:
            return
        # exact per-job attribution (ISSUE 15 satellite): every stage
        # submission stamps the executing thread with the job id, so
        # the cache's per-job buckets carry this job's own probes —
        # exact even while other jobs compile concurrently.  A job
        # with no tagged probes (pure host work) reads 0/0, which is
        # the truth the old process-wide delta could not tell.
        record["program_cache"] = ex._compiled.job_stats(record["id"])

    def stop(self):
        super().stop()
        if self.executor is not None:
            self.executor.stop()
            self.executor = None

    def default_parallelism(self):
        self.start()
        return self.executor.ndev

    def submit_tasks(self, stage, tasks, report):
        self.start()
        import time as _time

        from dpark_tpu import adapt
        from dpark_tpu.backend.tpu import fuse
        # stamp the job this thread is executing for (ISSUE 9): the
        # executor tags shuffle stores with it so the HBM eviction
        # arbiter knows which buckets belong to live jobs
        record = self._current_record
        self.executor._job_tls.job = \
            record["id"] if record is not None else None
        plan = None
        adapt_sig = None
        if len(tasks) >= stage.num_partitions:
            # single-task retries skip the array path: run_stage always
            # processes all partitions, so replaying it for one failed
            # task would redo the whole stage
            with self._analyze_lock:
                analysis_gap = False
                try:
                    plan = fuse.analyze_stage(stage, self.executor.ndev,
                                              self.executor)
                except Exception as e:
                    logger.debug("analysis failed for %s: %s", stage, e)
                    analysis_gap = _multiproc_cpu_gap(e)
                reason = None if plan is not None \
                    else fuse.last_fallback_reason()
                if plan is None and not reason and analysis_gap:
                    # the CPU backend's multi-controller gap raised
                    # during analysis itself: record the capability
                    # reason, not silence (ISSUE 12 satellite)
                    reason = SPMD_CPU_FALLBACK
            if plan is None:
                if reason:
                    # why the plan left the array path (key shape,
                    # non-numeric leaf, ...): rides the per-stage job
                    # record next to kind=object, and the
                    # host-fallback-key lint rule reports the same
                    # answer pre-flight
                    self.note_stage(stage.id, fallback_reason=reason)
            elif adapt.enabled():
                # off mode pays nothing past this flag check — the
                # signature (sha1 over the stable program-key repr)
                # is only worth computing when observations record
                try:
                    adapt_sig = fuse.plan_adapt_signature(plan)
                except Exception:
                    adapt_sig = None
                # cost model (ISSUE 7 decision point 2): with recorded
                # ms for BOTH paths of this program class, the cheaper
                # one wins — predicted, not assumed, admission.  The
                # choice is per stage and recorded as `adapt_reason`
                # (the cost-model sibling of fallback/degrade_reason).
                choice = adapt.choose_path(adapt_sig)
                if choice is not None and choice["choice"] == "object":
                    self.note_stage(stage.id,
                                    adapt_reason=choice["reason"])
                    plan = None
        if plan is not None:
            # the mesh lock spans the WHOLE degradable run, not just
            # run_stage: the OOM ladder swaps conf.STREAM_CHUNK_ROWS
            # around its retry, which must stay invisible to another
            # job's concurrently dispatched device stage (ISSUE 9)
            with self.executor._mesh_lock:
                handled = self._run_degradable(stage, tasks, plan,
                                               report)
            if handled:
                return
        # object path: run tasks inline on the driver (golden semantics);
        # cogroup stages first pre-materialize their CoGroupedRDD via the
        # device exchange so only the group-merge runs in Python
        t0 = _time.time()
        precomputed = None
        try:
            precomputed = self._precompute_join(stage)
        except Exception as e:
            logger.debug("device join skipped: %s", e)
        if precomputed is None:
            try:
                precomputed = self._precompute_cogroup(stage)
            except Exception as e:
                logger.debug("cogroup precompute skipped: %s", e)
        all_ok = False
        from dpark_tpu import bulkplane
        rx0 = bulkplane.total_received_bytes()
        try:
            statuses = []
            for task in tasks:
                status, payload = _run_task_inline(task)
                statuses.append(status)
                report(task, status, payload)
            all_ok = all(s == "success" for s in statuses)
            self._note_remote_fetch(stage.id, rx0)
        finally:
            if precomputed is not None:
                # free the seeded partitions (unless the USER cached this
                # cogroup): later retries recompute through the export
                # bridge instead of leaking the dataset in driver memory
                cg, nparts, was_cached = precomputed
                if not was_cached:
                    from dpark_tpu.env import env
                    env.cache.drop(cg.id, nparts)
                    cg.should_cache = False
        # an analyzable stage that ran the object path CLEANLY
        # (cost-model choice, analysis-time fallback with a plan, or
        # runtime degrade) feeds the cost model its observed host ms —
        # a failed/fetch-failed attempt must NOT record its short wall
        # as a valid host cost (it would wrongly cheapen the object
        # path and steer future runs off the device)
        if adapt_sig is not None and all_ok:
            adapt.observe_path(adapt_sig, "host",
                               (_time.time() - t0) * 1e3)

    def _spill_write_failed(self, stage, tasks, report, e):
        """ENOSPC & co mid-spill: NOT a device fault, and the object
        path would spill to the same disk — surface it on the stage's
        tasks as task failures so the scheduler's retry/escalation
        accounting owns it (single-task retries then run the object
        path inline).  Never a silent fallback, never a job abort
        before MAX_TASK_FAILURES."""
        logger.warning("spill write failed for %s: %s", stage, e)
        self.note_stage(stage.id,
                        degrade_reason="spill write failed: %s" % e)
        for task in tasks:
            report(task, "failed", "spill write failed: %s" % e)

    def _run_degradable(self, stage, tasks, plan, report):
        """Array path with runtime graceful degradation (ISSUE 5
        tentpole): a device runtime error (XlaRuntimeError /
        RESOURCE_EXHAUSTED) first retries the stage with a HALVED wave
        budget — an HBM OOM usually just means the auto-sized wave was
        too greedy — then falls back to the object path for THIS STAGE
        ONLY.  Each step is recorded as the stage's `degrade_reason`
        (the runtime mirror of `fallback_reason`); the job never
        aborts on a device error.  Returns True when the stage was
        fully reported (success or surfaced task failures); False
        means "run the object path".

        FLOAT CAVEAT (documented in README): an object-path fallback
        of a reassociated float aggregate can differ in low-order bits
        from the device fold — same contract as GROUP_AGG_REWRITE.
        Integer workloads (the chaos parity suite) are exact."""
        from dpark_tpu import conf
        from dpark_tpu.shuffle import SpillWriteError
        try:
            self._run_array_stage(stage, tasks, plan, report)
            self._adapt_note_stream_budget()
            return True
        except SpillWriteError as e:
            self._spill_write_failed(stage, tasks, report, e)
            return True
        except Exception as e:
            if _multiproc_cpu_gap(e):
                # a CAPABILITY gap, not a runtime fault: the CPU
                # backend implements no cross-process computations
                # (pre-existing per PR 2 notes).  Record it as the
                # stage's fallback_reason — the SPMD dryrun reads it
                # to SKIP cleanly instead of raw-asserting — and
                # serve the stage through the object path.
                logger.warning(
                    "array path unavailable for %s (%s); object path",
                    stage, SPMD_CPU_FALLBACK)
                self.note_stage(stage.id,
                                fallback_reason=SPMD_CPU_FALLBACK)
                return False
            if not (conf.DEGRADE and _device_error(e)):
                logger.warning(
                    "array path failed for %s (%s); object fallback",
                    stage, e)
                self.note_stage(stage.id, degrade_reason=(
                    "array path error (%s: %s); object path"
                    % (type(e).__name__, str(e)[:160])))
                self._adapt_observe_device_error(plan)
                return False
            first = "%s: %s" % (type(e).__name__, str(e)[:160])
        # degrade step 1: halve the wave budget and retry the stage.
        # Device errors raise during run_stage, BEFORE any task is
        # reported, so the whole-stage retry cannot double-report.
        # The budget is applied through conf.STREAM_CHUNK_ROWS (not a
        # per-plan field) DELIBERATELY: fuse._big_columnar's streaming
        # eligibility reads the same knob, so halving can flip an
        # in-core stage that OOM'd onto the wave stream — the actual
        # cure.  Safe because this scheduler runs stages serially on
        # the event-loop thread (restored in the finally); a future
        # parallel-stage scheduler must thread it through the plan.
        old = conf.STREAM_CHUNK_ROWS
        row_bytes = 16
        if isinstance(old, int):
            eff = old
        else:
            # "auto" sizes waves to HBM / row WIDTH: halve the budget
            # the executor actually used, not the 16-byte-row default
            # (for wide rows that default is a LARGER wave than the
            # one that just OOM'd)
            try:
                from dpark_tpu.backend.tpu import fuse
                if plan.source[0] == "ingest":
                    row_bytes = fuse._columnar_row_bytes(
                        plan.source[1]._slices)
            except Exception:
                pass
            eff = conf.stream_chunk_rows(row_bytes)
        halved = max(64, int(eff) // 2)
        # the ladder's outcomes feed the adaptive store (ISSUE 7): the
        # budget that OOM'd is recorded as failing NOW — even if the
        # job ultimately falls back to the object path, the next run
        # of this row-width class starts below the failed rung instead
        # of re-OOMing.  A user-pinned budget records nothing (pins
        # bypass the auto derivation entirely).
        from dpark_tpu import adapt
        auto_budget = not isinstance(old, int)
        if auto_budget:
            adapt.record_wave_budget(row_bytes, int(eff), ok=False,
                                     source="oom")
        conf.STREAM_CHUNK_ROWS = halved
        logger.warning("device error on %s (%s); retrying with halved "
                       "wave budget (%d rows/device)", stage, first,
                       halved)
        try:
            self._run_array_stage(stage, tasks, plan, report)
            self.note_stage(stage.id, degrade_reason=(
                "%s; stage retried with halved wave budget "
                "(%d rows/device)" % (first, halved)))
            if auto_budget:
                adapt.record_wave_budget(row_bytes, halved, ok=True,
                                         source="oom_ladder")
            return True
        except SpillWriteError as e:
            self._spill_write_failed(stage, tasks, report, e)
            return True
        except Exception as e2:
            # degrade step 2: object path for this stage only
            logger.warning(
                "halved-wave retry failed for %s (%s); object "
                "fallback for this stage", stage, e2)
            self.note_stage(stage.id, degrade_reason=(
                "%s; halved-wave retry failed (%s: %s); object path "
                "for this stage" % (first, type(e2).__name__,
                                    str(e2)[:120])))
            if auto_budget:
                # a halved rung that failed for a NON-memory reason
                # still did not OOM — it is the ladder's final working
                # budget and the next run seeds from it; a rung that
                # OOM'd again records as failing, so the next run
                # starts below it
                adapt.record_wave_budget(row_bytes, halved,
                                         ok=not _device_error(e2),
                                         source="oom_ladder")
            self._adapt_observe_device_error(plan)
            return False
        finally:
            conf.STREAM_CHUNK_ROWS = old

    def _adapt_observe_device_error(self, plan):
        """Count a device-path failure for this program class in the
        adaptive store (observability; path pricing needs observed ms
        on both sides and never decides on errors alone)."""
        try:
            from dpark_tpu import adapt
            from dpark_tpu.backend.tpu import fuse
            if adapt.enabled():
                adapt.observe_path(fuse.plan_adapt_signature(plan),
                                   "device", error=True)
        except Exception:
            pass

    def _adapt_note_stream_budget(self):
        """Persist the wave budget a successful auto-sized streamed
        stage ran with as known-good (ISSUE 7): the next run of this
        row-width class seeds from it instead of re-deriving.  Pinned
        budgets (tests, the ladder's halved retry) record via the
        ladder paths, not here."""
        from dpark_tpu import adapt, conf
        ex = self.executor
        if (ex.last_stream_stats is not None
                and ex.last_wave_budget is not None
                and conf.STREAM_CHUNK_ROWS == "auto"):
            budget, row_bytes = ex.last_wave_budget
            adapt.record_wave_budget(row_bytes, budget, ok=True,
                                     source="stream")

    def _resident_nocombine_deps(self, cg):
        """All of a CoGroupedRDD's inputs as HBM-resident no-combine
        shuffle deps, or None (narrow side / host-resident / combining).
        Keep in sync with fuse._analyze_join_source, the array-path twin
        of this eligibility (it additionally rejects encoded keys and
        r > mesh, which the host-seeding paths here tolerate)."""
        from dpark_tpu.backend.tpu import fuse
        deps = []
        for kind, obj in cg._dep_kinds:
            if kind != "shuffle" or not fuse.is_list_agg(obj.aggregator) \
                    or not self.executor.has_shuffle(obj.shuffle_id):
                return None
            if "host_runs" in self.executor.shuffle_store[
                    obj.shuffle_id]:
                return None      # spilled runs: host merge consumes them
            deps.append(obj)
        return deps

    def _precompute_join(self, stage):
        """Full device join: when the stage's top RDD is exactly
        a.join(b) over two HBM-resident no-combine shuffles, expand the
        key-matched pairs on device and seed the join RDD's partitions."""
        from dpark_tpu.backend.tpu import fuse
        from dpark_tpu.env import env
        from dpark_tpu.rdd import (CoGroupedRDD, FlatMappedValuesRDD,
                                   _join_values)
        top = stage.rdd
        if not (isinstance(top, FlatMappedValuesRDD)
                and top.f is _join_values
                and isinstance(top.prev, CoGroupedRDD)
                and len(top.prev.rdds) == 2):
            return None
        if getattr(top, "_tpu_precomputed", False):
            return None
        cg = top.prev
        deps = self._resident_nocombine_deps(cg)
        if deps is None:
            return None
        # join kernels require (k, v) records whose key is a scalar or
        # flat numeric tuple, with the SAME width and dtypes both sides
        from dpark_tpu.backend.tpu import layout
        import numpy as np
        import jax.tree_util as jtu
        key_sigs = []
        for dep in deps:
            store = self.executor.shuffle_store[dep.shuffle_id]
            treedef = store["out_treedef"]
            specs = store["out_specs"]
            nk = layout.key_width(treedef, specs, kinds="if")
            sample = jtu.tree_unflatten(treedef,
                                        list(range(len(specs))))
            if nk is None or len(sample) != 2:
                return None          # records must be (k, value) pairs
            key_sigs.append((nk, tuple(np.dtype(dt)
                                       for dt, _ in specs[:nk])))
        if key_sigs[0] != key_sigs[1]:
            return None
        rows_per_part = self.executor.run_device_join(deps[0], deps[1])
        for p, rows in enumerate(rows_per_part):
            env.cache.put((top.id, p), rows, disk=False)
        was_cached = top.should_cache
        top.should_cache = True
        top._tpu_precomputed = True
        logger.debug("join %d expanded on device", top.id)
        return top, len(rows_per_part), was_cached

    def _precompute_cogroup(self, stage):
        """If this stage reads a CoGroupedRDD whose inputs are all
        HBM-resident no-combine shuffles, run the exchanges on device
        (sorted rows per partition), merge the sorted runs on host, and
        seed the partition cache so the object path never touches the
        per-bucket export bridge."""
        from dpark_tpu.backend.tpu import fuse
        from dpark_tpu.dependency import ShuffleDependency
        from dpark_tpu.env import env
        from dpark_tpu.rdd import CoGroupedRDD

        # find the nearest CoGroupedRDD through narrow deps
        seen = set()
        cg = None
        frontier = [stage.rdd]
        while frontier:
            r = frontier.pop()
            if id(r) in seen:
                continue
            seen.add(id(r))
            if isinstance(r, CoGroupedRDD):
                cg = r
                break
            for d in r.dependencies:
                if not isinstance(d, ShuffleDependency):
                    frontier.append(d.rdd)
        if cg is None:
            return None
        if getattr(cg, "_tpu_precomputed", False):
            return None
        deps = self._resident_nocombine_deps(cg)
        if deps is None:
            return None
        per_source = [self.executor.gather_rows(dep) for dep in deps]
        nsrc = len(per_source)
        nparts = cg.partitioner.num_partitions
        for p in range(nparts):
            slots = {}
            for si in range(nsrc):
                for k, v in per_source[si][p]:
                    slot = slots.get(k)
                    if slot is None:
                        slot = slots[k] = tuple([] for _ in range(nsrc))
                    slot[si].append(v)
            env.cache.put((cg.id, p), list(slots.items()), disk=False)
        was_cached = cg.should_cache
        cg.should_cache = True
        cg._tpu_precomputed = True
        logger.debug("cogroup %d precomputed on device (%d sources)",
                     cg.id, nsrc)
        return cg, nparts, was_cached

    def _run_array_stage(self, stage, tasks, plan, report):
        import time as _time
        from dpark_tpu.backend.tpu import fuse
        from dpark_tpu.rdd import _count_iter, _PartReduce
        t0 = _time.time()
        # count() needs no rows on the driver — the object path sums
        # per-executor counts, and the array path can answer straight
        # from the device counts leaf, skipping the whole egest (on a
        # tunneled chip that is the difference between one scalar read
        # and streaming every row at ~37 MB/s)
        plan.count_only = (not stage.is_shuffle_map and bool(tasks)
                           and all(isinstance(t, ResultTask)
                                   and t.func is _count_iter
                                   for t in tasks))
        # top(k): per-device pre-top with a classifiable ordering key —
        # ndev*k rows egest instead of the whole batch; the per-task
        # _TopN and the driver heap merge then run unchanged
        from dpark_tpu.rdd import _TopN
        plan.top_candidate = None
        if (not stage.is_shuffle_map and tasks
                and all(isinstance(t, ResultTask)
                        and isinstance(t.func, _TopN)
                        for t in tasks)
                and len({(t.func.n, id(t.func.key), t.func.smallest)
                         for t in tasks}) == 1):
            tf = tasks[0].func
            plan.top_candidate = (tf.n, tf.key, tf.smallest)
        plan.topk_used = False
        # reduce(f) with a PROVABLE monoid over scalar records likewise
        # answers from one per-device reduction (ndev scalars on the
        # wire); unprovable reduces keep the egest + host fold
        plan.reduce_monoid = None
        if (not stage.is_shuffle_map and tasks
                and all(isinstance(t, ResultTask)
                        and isinstance(t.func, _PartReduce)
                        for t in tasks)
                and len({id(t.func.f) for t in tasks}) == 1):
            try:
                plan.reduce_monoid = fuse.classify_merge(
                    tasks[0].func.f)
            except Exception:
                plan.reduce_monoid = None
        wire0 = self.executor.exchange_wire_bytes
        real0 = self.executor.exchange_real_rows
        slot0 = self.executor.exchange_slot_rows
        islot0 = self.executor.ingest_slot_rows
        # live per-wave pipeline updates: a long streamed stage reports
        # its ingest/compute/exchange/spill ms and device-idle fraction
        # into stage_info WHILE it runs (web UI), not just at the end
        self.executor._stage_note = (
            lambda **kw: self.note_stage(stage.id, **kw))
        try:
            kind, result = self.executor.run_stage(plan)
        finally:
            self.executor._stage_note = None
        note = {"kind": "array",
                "run_seconds": round(_time.time() - t0, 3)}
        if self.executor.last_stream_stats is not None:
            note["pipeline"] = self.executor.last_stream_stats
        wire = self.executor.exchange_wire_bytes - wire0
        slot_rows = self.executor.exchange_slot_rows - slot0
        ingest_rows = self.executor.ingest_slot_rows - islot0
        if wire or slot_rows:
            # per-stage exchange accounting (HARDWARE_CHECKLIST.md
            # items 2-3: the tuning signals, visible in the web UI)
            note["wire_bytes"] = wire
            note["pad_efficiency"] = round(
                (self.executor.exchange_real_rows - real0)
                / max(1, slot_rows), 4)
        elif ingest_rows:
            # single-chip identity exchange: no wire moved; report the
            # ingest slot fill under its own name so the UI never
            # presents ingest padding as wire padding
            note["ingest_pad_efficiency"] = round(
                (self.executor.exchange_real_rows - real0)
                / max(1, ingest_rows), 4)
        if kind == "shuffle":
            store = self.executor.shuffle_store.get(result)
            if store is not None:
                note["hbm_bytes"] = store.get("nbytes", 0)
                if "host_runs" in store:
                    note["kind"] = "array+spill"
            uri = "hbm://%d" % result
            for task in tasks:
                report(task, "success", (uri, {}, {}))
        elif kind == "counts":
            note["kind"] = "array+counts"    # observable: no egest ran
            for task in tasks:
                report(task, "success", (result[task.partition], {}, {}))
        elif kind == "reduced":
            from dpark_tpu.rdd import _EMPTY
            note["kind"] = "array+reduced"
            for task in tasks:
                v, n = result[task.partition]
                report(task, "success",
                       (v if n else _EMPTY, {}, {}))
        else:
            if getattr(plan, "topk_used", False):
                note["kind"] = "array+top"   # observable: pre-top ran
            rows_per_part = result
            for task in tasks:
                assert isinstance(task, ResultTask)
                value = task.func(iter(rows_per_part[task.partition]))
                report(task, "success", (value, {}, {}))
        self.note_stage(stage.id, **note)
        # feed the cost model (ISSUE 7): observed device ms for this
        # program class — the other half of the device-vs-object price
        try:
            from dpark_tpu import adapt
            if adapt.enabled():
                adapt.observe_path(fuse.plan_adapt_signature(plan),
                                   "device", note["run_seconds"] * 1e3)
        except Exception:
            pass
        logger.debug("array path ran %s (%d tasks)", stage, len(tasks))
