"""Device-side shuffle primitives: bucketize, exchange, segmented reduce.

This is the TPU-native replacement for the reference's shuffle data plane
(dpark/shuffle.py write/fetch/merge + dpark/task.py ShuffleMapTask bucket
loop, SURVEY.md section 3.1 hot loops #2/#3):

  host hash+dict-combine  ->  phash_device + sort by destination
  bucket files + HTTP     ->  lax.all_to_all over ICI, count-exchange first
  dict/heap merge         ->  sort by key + segmented associative reduce

All functions here operate on ONE device's block inside shard_map (leading
mesh dim already squeezed).  Raggedness is handled with padded slots and a
multi-round overflow loop (the "external merge" equivalent, SURVEY.md 5.7):
each round every device sends at most `slot` records per destination; the
psum'd overflow tells the host loop whether another round is needed.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from dpark_tpu.utils.phash import phash_device, phash_device_cols

def _sentinel(dtype):
    """Max value of the key dtype — padding rows sort last.  ingest()
    rejects int keys equal to this value (host fallback); float keys use
    +inf (real +inf keys are a documented range-sort limitation)."""
    if jnp.issubdtype(jnp.dtype(dtype), jnp.floating):
        return jnp.asarray(jnp.inf, dtype)
    return jnp.asarray(jnp.iinfo(dtype).max, dtype)


def hash_dst(key, n_dst, valid, r=None):
    """Destination partition by portable hash (HashPartitioner).

    `r` is the logical partition count (<= n_dst, the mesh size): dst in
    [0, r), padding rows get the sentinel bucket n_dst; devices >= r
    simply receive nothing."""
    r = n_dst if r is None else r
    dst = (phash_device(key) % jnp.uint32(r)).astype(jnp.int32)
    return jnp.where(valid, dst, n_dst)


def hash_dst_cols(key_cols, n_dst, valid, r=None):
    """hash_dst over a COMPOSITE key (one or more key columns): the
    destination is the pair-extended portable hash over all columns —
    bit-identical to host HashPartitioner.get_partition((k1, ..., kn))
    — so tuple-keyed shuffles land where the host partitioner (lookup,
    co-partitioned joins) expects."""
    r = n_dst if r is None else r
    h = phash_device_cols(list(key_cols))
    dst = (h % jnp.uint32(r)).astype(jnp.int32)
    return jnp.where(valid, dst, n_dst)


def range_dst(key, bounds, ascending, n_dst, valid, r=None):
    """Destination partition by sorted bounds (RangePartitioner): the
    device twin of host bisect_left over the sampled bounds."""
    r = n_dst if r is None else r
    idx = jnp.searchsorted(bounds, key, side="left").astype(jnp.int32)
    dst = idx if ascending else (r - 1 - idx)
    return jnp.where(valid, dst, n_dst)


def _lex_less_cols(a_cols, b_cols):
    """Row-wise lexicographic a < b over parallel column lists (the
    device twin of Python tuple comparison)."""
    lt = a_cols[0] < b_cols[0]
    eq = a_cols[0] == b_cols[0]
    for a, b in zip(a_cols[1:], b_cols[1:]):
        lt = lt | (eq & (a < b))
        eq = eq & (a == b)
    return lt


def lex_searchsorted(sorted_cols, query_cols, side="left"):
    """Multi-column searchsorted: for each query row (one value per
    column), the insertion index into rows of `sorted_cols` (sorted
    lexicographically ascending).  jnp.searchsorted has no multi-key
    form, so this runs a vectorized binary search — ceil(log2(m+1))
    fixed steps of a row-wise lexicographic compare; every query
    resolves in one fused program, no per-row host work."""
    m = int(sorted_cols[0].shape[0])
    nq = query_cols[0].shape[0]
    lo = jnp.zeros((nq,), jnp.int32)
    hi = jnp.full((nq,), m, jnp.int32)
    for _ in range(max(1, m.bit_length())):
        active = lo < hi
        mid = (lo + hi) >> 1
        safe = jnp.clip(mid, 0, max(m - 1, 0))
        mid_cols = [c[safe] for c in sorted_cols]
        if side == "left":
            pred = _lex_less_cols(mid_cols, query_cols)
        else:
            pred = ~_lex_less_cols(query_cols, mid_cols)
        lo = jnp.where(active & pred, mid + 1, lo)
        hi = jnp.where(active & ~pred, mid, hi)
    return lo


def range_dst_cols(key_cols, bounds_cols, ascending, n_dst, valid,
                   r=None):
    """range_dst over a COMPOSITE key: bisect_left of each (k1, ..., kn)
    row into the sampled tuple bounds, compared lexicographically —
    exactly host RangePartitioner.get_partition on tuple keys."""
    key_cols = list(key_cols)
    if len(key_cols) == 1 and bounds_cols[0].ndim <= 1:
        return range_dst(key_cols[0], bounds_cols[0], ascending, n_dst,
                         valid, r=r)
    r = n_dst if r is None else r
    idx = lex_searchsorted(list(bounds_cols), key_cols,
                           side="left").astype(jnp.int32)
    dst = idx if ascending else (r - 1 - idx)
    return jnp.where(valid, dst, n_dst)


def _take(leaves, idx):
    return [leaf[idx] for leaf in leaves]


def _lex_sort(ops, num_keys):
    """Stable lexicographic sort of `ops` by its first num_keys operands.

    Formulated as permutation-compose + gather on every backend: XLA's
    multi-operand Sort lowers (on TPU) to a comparison network whose
    cost grows with total operand bytes — real-chip profiling (round 3,
    v5e) measured a 4-operand i64 sort at 16M rows ~40x slower than a
    single i32 sort.  Successive 2-operand (key, iota) argsorts
    radix-compose the permutation instead, and every operand is
    gathered exactly once; this also carries rank>1 payloads, which
    XLA Sort cannot."""
    order = jnp.arange(ops[0].shape[0], dtype=jnp.int32)
    for k in range(num_keys - 1, -1, -1):
        # keep indices i32: under jax_enable_x64 argsort returns i64,
        # and 64-bit gather indices hit the same emulated-i64 tax
        order = order[jnp.argsort(ops[k][order],
                                  stable=True).astype(jnp.int32)]
    return tuple(o[order] for o in ops)


def _bcast(flag, leaf):
    """Broadcast a (n,) bool against a (n, ...) leaf."""
    extra = leaf.ndim - flag.ndim
    return flag.reshape(flag.shape + (1,) * extra)


def compact(leaves, mask):
    """Move rows where mask is True to the front (stable); returns
    (leaves, new_count)."""
    sorted_ops = _lex_sort((~mask,) + tuple(leaves), 1)
    return list(sorted_ops[1:]), jnp.sum(mask).astype(jnp.int32)


def _dst_order(dst, n_dst):
    """Stable permutation grouping rows by destination WITHOUT a
    comparison sort: per-bucket cumsum ranks + one scatter (a counting
    sort over the tiny destination domain — mesh size + the sentinel
    bucket).  XLA:CPU's sort runs ~4x slower than these O(n) passes at
    a million rows (measured while profiling the segmented apply);
    output is bit-identical to jnp.argsort(dst, stable=True)."""
    cap = dst.shape[0]
    counts = jnp.bincount(dst, length=n_dst + 1)
    offs = jnp.concatenate([jnp.zeros((1,), counts.dtype),
                            jnp.cumsum(counts)[:-1]])
    pos = jnp.zeros((cap,), jnp.int32)
    for b in range(n_dst + 1):
        m = dst == b
        rank = jnp.cumsum(m.astype(jnp.int32)) - 1
        pos = jnp.where(m, offs[b].astype(jnp.int32) + rank, pos)
    return jnp.zeros((cap,), jnp.int32).at[pos].set(
        jnp.arange(cap, dtype=jnp.int32))


def bucketize(key, leaves, n, n_dst, dst=None, r=None):
    """Sort one device's rows by destination partition.

    Returns (sorted_leaves, counts[n_dst], offsets[n_dst]).  Invalid rows
    sort into a sentinel bucket past the end.
    """
    cap = key.shape[0]
    valid = jnp.arange(cap) < n
    if dst is None:
        dst = hash_dst(key, n_dst, valid, r)
    if n_dst <= 16:
        order = _dst_order(dst, n_dst)
    else:
        order = jnp.argsort(dst, stable=True).astype(jnp.int32)
    sorted_leaves = _take(leaves, order)
    counts = jnp.bincount(dst, length=n_dst + 1)[:n_dst].astype(jnp.int32)
    offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts)[:-1]])
    return sorted_leaves, counts, offsets


def exchange_round(axis, leaves, offsets, counts, sent, slot,
                   narrow=None):
    """One all_to_all round: send up to `slot` records to each destination.

    leaves: destination-sorted rows (cap, ...); offsets/counts/sent: (R,).
    `narrow`: optional per-leaf wire dtype (or None) — leaves proven to
    fit ride the collective narrowed (e.g. int64 -> int32: TPUs have no
    native 64-bit integer datapath, XLA emulates i64 as i32 pairs, so an
    i64 exchange moves 2x the ICI bytes; the executor's runtime min/max
    guard decides per exchange).  The cast happens right around the
    collective — callers always see the original dtypes.
    Returns (recv_leaves (R, slot, ...), recv_cnt (R,), new_sent,
    overflow_scalar) where overflow is the psum of still-unsent records
    across all devices — 0 means the exchange is complete.
    """
    n_dst = counts.shape[0]
    cap = leaves[0].shape[0]
    sendable = jnp.minimum(counts - sent, slot).astype(jnp.int32)
    j = jnp.arange(slot)
    idx = offsets[:, None] + sent[:, None] + j[None, :]        # (R, slot)
    idx = jnp.clip(idx, 0, cap - 1)
    mask = j[None, :] < sendable[:, None]
    send = []
    for li, leaf in enumerate(leaves):
        g = leaf[idx]                                          # (R, slot, ..)
        g = jnp.where(_bcast(mask, g), g, jnp.zeros((), g.dtype))
        if narrow is not None and narrow[li] is not None:
            g = g.astype(narrow[li])
        send.append(g)
    recv = _grouped_all_to_all(send, axis)
    for li, leaf in enumerate(leaves):
        if narrow is not None and narrow[li] is not None:
            recv[li] = recv[li].astype(leaf.dtype)
    recv_cnt = lax.all_to_all(sendable, axis, 0, 0, tiled=True)
    new_sent = sent + sendable
    overflow = lax.psum(jnp.sum(counts - new_sent), axis)
    return recv, recv_cnt, new_sent, overflow


def _grouped_all_to_all(buffers, axis):
    """Exchange the per-destination buffers with as few collectives as
    possible: scalar leaves of the same dtype stack into one all_to_all
    (one ICI launch instead of one per column)."""
    groups = {}
    for i, g in enumerate(buffers):
        key = (str(g.dtype), g.shape) if g.ndim == 2 else ("solo%d" % i,)
        groups.setdefault(key, []).append(i)
    out = [None] * len(buffers)
    for key, idxs in groups.items():
        if len(idxs) == 1 or key[0].startswith("solo"):
            for i in idxs:
                out[i] = lax.all_to_all(buffers[i], axis, 0, 0, tiled=True)
            continue
        packed = jnp.stack([buffers[i] for i in idxs], axis=-1)
        exchanged = lax.all_to_all(packed, axis, 0, 0, tiled=True)
        for pos, i in enumerate(idxs):
            out[i] = exchanged[..., pos]
    return out


def flatten_received(recv_rounds, cnt_rounds, key_index=0):
    """Concatenate per-round receive buffers (lists of (R, slot, ...)) into
    flat row arrays with a validity mask; invalid keys get the sentinel.

    Returns (leaves, valid_mask) with leading dim rounds*R*slot.
    """
    nleaves = len(recv_rounds[0])
    flat = []
    for li in range(nleaves):
        parts = [r[li].reshape((-1,) + r[li].shape[2:]) for r in recv_rounds]
        flat.append(jnp.concatenate(parts, axis=0))
    # rebuild validity masks per round from the exchanged counts
    masks = []
    for r, cnt in zip(recv_rounds, cnt_rounds):
        slot = r[0].shape[1]
        j = jnp.arange(slot)
        m = (j[None, :] < cnt[:, None]).reshape(-1)
        masks.append(m)
    mask = jnp.concatenate(masks, axis=0)
    flat[key_index] = jnp.where(
        mask, flat[key_index], _sentinel(flat[key_index].dtype))
    return flat, mask


_SEGMENT_OPS = {}


def _segment_op(kind):
    if not _SEGMENT_OPS:
        from jax import ops as jops
        _SEGMENT_OPS.update({
            "add": jops.segment_sum, "min": jops.segment_min,
            "max": jops.segment_max, "mul": jops.segment_prod})
    return _SEGMENT_OPS[kind]


def _monoid_segment_totals(starts, val_leaves, kind):
    """Single-pass per-segment reduction for a classified monoid: one
    scatter instead of the log-n associative scan.  Returns per-segment
    totals indexed by segment id (= cumsum(starts)-1)."""
    seg = jnp.cumsum(starts.astype(jnp.int32)) - 1
    op = _segment_op(kind)
    m = starts.shape[0]
    return seg, [op(v, seg, num_segments=m) for v in val_leaves]


def segmented_combine(starts, val_leaves, merge_leaves):
    """Inclusive segmented scan: scanned[i] = reduction of values from the
    segment start through i.  starts: (m,) bool segment-start flags."""
    def comb(a, b):
        fa, va = a
        fb, vb = b
        merged = merge_leaves(va, vb)
        out = [jnp.where(_bcast(fb, mg), nb, mg)
               for mg, nb in zip(merged, vb)]
        return (fa | fb, out)

    _, scanned = lax.associative_scan(comb, (starts, list(val_leaves)))
    return scanned


def bucketize_combine(key, val_leaves, n, n_dst, merge_leaves,
                      monoid=None, dst=None, r=None):
    """Map-side pre-combine (the classic combiner optimization): sort one
    device's rows by (destination, key), merge equal keys within each
    destination run, compact.  Cuts exchange volume to O(#distinct keys per
    device per destination) — decisive for low-cardinality reduceByKey.

    Returns (key', val_leaves', counts[n_dst], offsets[n_dst]) where rows
    are destination-sorted and combined.
    """
    ks, vv, counts, offsets = bucketize_combine_keys(
        [key], val_leaves, n, n_dst, merge_leaves, monoid=monoid,
        dst=dst, r=r)
    return ks[0], vv, counts, offsets


def bucketize_combine_keys(key_cols, val_leaves, n, n_dst, merge_leaves,
                           monoid=None, dst=None, r=None):
    """bucketize_combine over a COMPOSITE key: sort one device's rows by
    (destination, k1, ..., kn), merge rows equal in EVERY key column,
    compact.  Returns (key_cols', vals', counts, offsets).  Only key
    column 0 carries the sentinel on invalid rows — invalid rows sort
    into the sentinel bucket and are dropped by the keep mask, so the
    other columns never need guarding."""
    key_cols = list(key_cols)
    cap = key_cols[0].shape[0]
    valid = jnp.arange(cap) < n
    if dst is None:
        dst = hash_dst_cols(key_cols, n_dst, valid, r)
    ks = [jnp.where(valid, key_cols[0], _sentinel(key_cols[0].dtype))]
    ks += key_cols[1:]
    # composite keys: one hash ordering pass instead of n key argsorts
    # (the reduce side re-sorts by the true key columns; see
    # _bucketize_combine_cols on why adjacency is sufficient here)
    order_col = (phash_device_cols(key_cols) if len(key_cols) > 1
                 else None)
    return _bucketize_combine_cols(dst, ks, val_leaves, n_dst,
                                   merge_leaves, monoid,
                                   order_col=order_col)


def _changed_adjacent(cols):
    """(m-1,) bool: any of the key columns differs from its neighbor."""
    changed = cols[0][1:] != cols[0][:-1]
    for c in cols[1:]:
        changed = changed | (c[1:] != c[:-1])
    return changed


def _segment_merge(key_cols, val_leaves, keep_valid, merge_leaves,
                   monoid):
    """Shared segment-combine core over rows sorted by `key_cols`:
    merge values of adjacent rows equal in ALL key columns, keeping one
    representative row per segment (keep_valid(row_flags) restricts
    which rows qualify).

    Returns (keep_mask, reduced_val_leaves), both row-aligned with the
    input order — callers compact kept rows to the front with their
    own pack sort and derive counts from the mask."""
    changed = _changed_adjacent(key_cols)
    starts = jnp.concatenate([jnp.ones((1,), bool), changed])
    vs = list(val_leaves)
    if monoid is not None:
        seg, totals = _monoid_segment_totals(starts, vs, monoid)
        keep = keep_valid(starts)
        reduced = [t[seg] for t in totals]
    else:
        scanned = segmented_combine(starts, vs, merge_leaves)
        is_last = jnp.concatenate([changed, jnp.ones((1,), bool)])
        keep = keep_valid(is_last)
        reduced = scanned
    return keep, reduced


def _bucketize_combine_cols(dst, key_cols, val_leaves, n_dst,
                            merge_leaves, monoid, order_col=None):
    """Sort by (dst, *key_cols) carrying values, merge rows equal in
    every key column, compact; dst and key_cols must already carry the
    sentinel / sentinel-bucket on invalid rows.  Returns
    (key_cols', vals', counts[n_dst], offsets[n_dst]).

    `order_col` (optional, composite keys): a single synthetic
    ordering column (e.g. the 32-bit composite key hash) used INSTEAD
    of the n key columns for the sort — one argsort pass regardless of
    key width.  Correct because the map-side combine only needs equal
    keys ADJACENT within their destination run (boundaries are still
    detected by comparing every real key column, so a hash collision
    merely splits one group into two partial combiners — the reduce
    side merges them anyway).  Do NOT use it where callers require
    true key-sorted output (the spilled-run stream's export relies on
    lexicographic run order)."""
    nk = len(key_cols)
    if order_col is not None:
        sorted_ops = _lex_sort(
            (dst, order_col) + tuple(key_cols) + tuple(val_leaves), 2)
        d = sorted_ops[0]
        ks = list(sorted_ops[2:2 + nk])
        vals = sorted_ops[2 + nk:]
    else:
        sorted_ops = _lex_sort(
            (dst,) + tuple(key_cols) + tuple(val_leaves), 1 + nk)
        d = sorted_ops[0]
        ks = list(sorted_ops[1:1 + nk])
        vals = sorted_ops[1 + nk:]
    keep, reduced = _segment_merge(
        [d] + ks, vals,
        lambda flags: flags & (d < n_dst), merge_leaves, monoid)
    dd_full = jnp.where(keep, d, n_dst)
    k_fulls = [jnp.where(keep, k, _sentinel(k.dtype)) for k in ks]
    packed = _lex_sort((~keep, dd_full) + tuple(k_fulls)
                       + tuple(reduced), 1)
    dd = packed[1]
    counts = jnp.bincount(dd, length=n_dst + 1)[:n_dst].astype(jnp.int32)
    offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts)[:-1]])
    return list(packed[2:2 + nk]), list(packed[2 + nk:]), counts, offsets


def bucketize_combine_rid(rid, key_cols, val_leaves, n, n_dst,
                          merge_leaves, monoid=None):
    """Map-side pre-combine for the spilled-run stream (r > mesh): sort
    one device's rows by (device, rid, k1, ..., kn) — device =
    rid % n_dst — merge rows equal in (rid, every key column), compact.
    Cuts exchange volume to O(#distinct keys per wave) before the wire.
    `key_cols` is a list (composite tuple keys ride as multiple
    columns).

    Returns (sorted_leaves=[rid', key cols'...] + vals', counts[n_dst],
    offsets[n_dst]) with rows device-sorted and combined."""
    key_cols = list(key_cols)
    cap = key_cols[0].shape[0]
    valid = jnp.arange(cap) < n
    dev = jnp.where(valid, (rid % n_dst).astype(jnp.int32), n_dst)
    rd = jnp.where(valid, rid, _sentinel(rid.dtype))
    ks = [jnp.where(valid, key_cols[0], _sentinel(key_cols[0].dtype))]
    ks += key_cols[1:]
    out_ks, vv, counts, offsets = _bucketize_combine_cols(
        dev, [rd] + ks, val_leaves, n_dst, merge_leaves, monoid)
    return out_ks + vv, counts, offsets


def _segment_reduce_cols(key_cols, val_leaves, valid_mask, merge_leaves,
                         monoid):
    """segment_reduce over a composite key (rows equal in ALL columns
    merge); key_cols[0] carries the sentinel on invalid rows.  Returns
    (packed_key_cols, reduced_vals, n_unique), uniques at the front
    sorted by the key columns."""
    m = key_cols[0].shape[0]
    nk = len(key_cols)
    sorted_ops = _lex_sort(tuple(key_cols) + tuple(val_leaves), nk)
    ks = list(sorted_ops[:nk])
    nvalid = jnp.sum(valid_mask).astype(jnp.int32)
    keep, reduced = _segment_merge(
        ks, sorted_ops[nk:],
        lambda flags: (flags & (jnp.arange(m) < nvalid)
                       & (ks[0] != _sentinel(ks[0].dtype))),
        merge_leaves, monoid)
    k_fulls = [jnp.where(keep, k, _sentinel(k.dtype)) for k in ks]
    packed = _lex_sort((~keep,) + tuple(k_fulls) + tuple(reduced), 1)
    return (list(packed[1:1 + nk]), list(packed[1 + nk:]),
            jnp.sum(keep).astype(jnp.int32))


def segment_reduce_keys(key_cols, val_leaves, valid_mask, merge_leaves,
                        monoid=None):
    """segment_reduce over a COMPOSITE key: merge values of rows equal
    in EVERY key column (key column 0 carries the sentinel on invalid
    rows, as set by flatten_received).  Returns (key_cols', reduced
    vals', n_unique) with uniques packed to the front, sorted
    lexicographically by the key columns."""
    return _segment_reduce_cols(list(key_cols), val_leaves, valid_mask,
                                merge_leaves, monoid)


# ----------------------------------------------------------------------
# segment spans + power-of-two degree buckets: the shared infrastructure
# behind the device segmented apply (fuse.SegMapOp) and the histogram
# program that sizes its bucket layout.  The bucket idea generalizes the
# degree-class slicing of backend/tpu/bagel_obj.py: group sizes collapse
# into ceil(log2) classes, so an arbitrary size distribution costs at
# most one trace per power of two instead of one per distinct size.
# ----------------------------------------------------------------------

def bucket_index(sizes):
    """Per-segment power-of-two bucket index: size s -> ceil(log2(s))
    (sizes 0/1 -> bucket 0, 2 -> 1, 3..4 -> 2, ...).  Bit-twiddled in
    int space — float log2 rounding must not shift a 2^k-sized group
    into the next bucket."""
    x = jnp.maximum(sizes.astype(jnp.int64), 1) - 1
    bits = jnp.zeros(x.shape, jnp.int32)          # bit_length(x)
    for shift in (32, 16, 8, 4, 2, 1):
        big = x >= (jnp.int64(1) << shift)
        bits = bits + jnp.where(big, shift, 0).astype(jnp.int32)
        x = jnp.where(big, x >> shift, x)
    return bits + (x > 0).astype(jnp.int32)


def _segment_table(key_cols, n):
    """Shared boundary scan of one device's KEY-SORTED valid-prefix
    rows (a segment starts where ANY key column changes).  Returns
    (starts, seg_of_row, sizes, n_seg) — the core both segment_spans
    and segment_sizes build on, so the boundary rule lives once."""
    k0 = key_cols[0]
    cap = k0.shape[0]
    idx = jnp.arange(cap)
    valid = idx < n
    ks0 = jnp.where(valid, k0, _sentinel(k0.dtype))
    changed = ks0 != jnp.roll(ks0, 1)
    for kc in key_cols[1:]:
        changed = changed | (kc != jnp.roll(kc, 1))
    starts = valid & ((idx == 0) | changed)
    seg = jnp.where(valid, jnp.cumsum(starts.astype(jnp.int32)) - 1,
                    cap - 1)
    from jax import ops as jops
    sizes = jops.segment_sum(valid.astype(jnp.int32), seg,
                             num_segments=cap)
    # the all-rows-valid case can leave real rows in segment cap-1; the
    # sizes entry is still correct because only valid rows contribute
    return starts, seg, sizes, jnp.sum(starts).astype(jnp.int32)


def segment_spans(key_cols, n):
    """Segment table of one device's KEY-SORTED valid-prefix rows.

    key_cols: list of (cap,) key columns, rows sorted lexicographically
    with the valid prefix first (the no-combine reduce's row order —
    the same precondition SegAggOp documents).

    Returns (start_rows, sizes, seg_of_row, n_seg):
      start_rows (cap,) int32 — row index of segment j's first row for
        j < n_seg (ascending; padding past n_seg is garbage);
      sizes (cap,) int32 — rows in segment j (0 past n_seg);
      seg_of_row (cap,) int32 — segment id per row (invalid rows get
        cap - 1, same convention as SegAggOp);
      n_seg () int32.
    """
    starts, seg, sizes, n_seg = _segment_table(key_cols, n)
    cap = starts.shape[0]
    # start rows by SCATTER, not by sort: segment j's first row writes
    # its own index at position j (XLA:CPU sorts run ~4x slower than
    # the equivalent O(n) scatter at a million rows — round-3 lesson,
    # re-learned while profiling the segmented apply)
    tgt = jnp.where(starts, seg, cap)
    start_rows = jnp.zeros((cap + 1,), jnp.int32) \
        .at[tgt].set(jnp.arange(cap, dtype=jnp.int32))[:cap]
    return start_rows, sizes, seg, n_seg


def segment_sizes(key_cols, n):
    """(sizes, n_seg) of the key-sorted valid prefix — the cheap subset
    of segment_spans (no start-row scatter) that the bucket histogram
    needs."""
    _, _, sizes, n_seg = _segment_table(key_cols, n)
    return sizes, n_seg


def bucket_histogram(key_cols, n, nbuckets=32):
    """(counts[nbuckets], max_size) of the segment-size power-of-two
    buckets of one device's key-sorted rows — the host reads this to
    build a SegMapOp bucket layout before compiling the apply
    program."""
    sizes, n_seg = segment_sizes(key_cols, n)
    cap = sizes.shape[0]
    live = jnp.arange(cap) < n_seg
    b = jnp.where(live, bucket_index(sizes), nbuckets)
    counts = jnp.bincount(b, length=nbuckets + 1)[:nbuckets] \
        .astype(jnp.int32)
    max_size = jnp.max(jnp.where(live, sizes, 0)).astype(jnp.int32)
    return counts, max_size


def bucket_members(sizes, n_seg, bucket, G):
    """(seg_sel (G,), gvalid (G,)) — the segment ids of bucket
    `bucket`, packed in segment order WITHOUT a sort: one cumsum ranks
    the members, one scatter packs them (XLA:CPU sorts cost ~4x the
    equivalent O(n) passes at a million rows)."""
    cap = sizes.shape[0]
    live = jnp.arange(cap) < n_seg
    mask = live & (bucket_index(sizes) == bucket)
    rank = jnp.cumsum(mask.astype(jnp.int32)) - 1
    cnt = jnp.sum(mask).astype(jnp.int32)
    pos = jnp.where(mask & (rank < G), rank, G)
    seg_sel = jnp.zeros((G + 1,), jnp.int32) \
        .at[pos].set(jnp.arange(cap, dtype=jnp.int32))[:G]
    return seg_sel, jnp.arange(G) < cnt


def gather_bucket_groups(start_rows, sizes, seg_sel, gvalid, B,
                         val_col, pad):
    """Padded (G, B) value matrix of the groups selected by `seg_sel`
    (their segment ids, from bucket_members; garbage lanes masked by
    `gvalid`).  `pad` fills columns past each group's
    true size: "zero" writes the dtype zero, "edge" repeats the group's
    last row (admission verified the user function is invariant under
    the chosen fill)."""
    cap = sizes.shape[0]
    st = start_rows[jnp.clip(seg_sel, 0, cap - 1)]
    sz = sizes[jnp.clip(seg_sel, 0, cap - 1)]
    o = jnp.arange(B)
    if pad == "edge":
        off = jnp.minimum(o[None, :], jnp.maximum(sz, 1)[:, None] - 1)
        rows = st[:, None] + off
        vals = val_col[jnp.clip(rows, 0, cap - 1)]
    else:
        rows = st[:, None] + o[None, :]
        in_range = o[None, :] < sz[:, None]
        vals = jnp.where(
            in_range, val_col[jnp.clip(rows, 0, cap - 1)],
            jnp.zeros((), val_col.dtype))
    # whole-garbage groups: zero the inputs so the user fn computes on
    # benign data (its outputs are scatter-masked away regardless)
    vals = jnp.where(gvalid[:, None], vals, jnp.zeros((), vals.dtype))
    return vals


def segment_reduce(key, val_leaves, valid_mask, merge_leaves,
                   monoid=None):
    """Combine values of equal keys with an associative merge.

    key: (m,) int with invalid rows already set to the dtype sentinel.
    val_leaves: list of (m, ...) value arrays.
    merge_leaves: callable (va_leaves, vb_leaves) -> merged leaves, built
    from the user's merge_combiners by fuse.py (vmapped, leaf-level).

    Returns (unique_keys, reduced_val_leaves, n_unique) with uniques packed
    to the front (sorted ascending by key).
    """
    ks, vv, n = _segment_reduce_cols([key], val_leaves, valid_mask,
                                     merge_leaves, monoid)
    return ks[0], vv, n
