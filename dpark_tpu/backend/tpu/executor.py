"""JAXExecutor: compiles and runs fused stage programs over the device mesh.

This replaces the reference's executor + shuffle services for the tpu
master (dpark/executor.py, dpark/shuffle.py): partitions live in HBM as
sharded arrays, a stage is one jitted shard_map program, and the map->reduce
hop is a count-exchange + multi-round lax.all_to_all over ICI (SURVEY.md
sections 2.8 and 7.1 step 5).

Shuffle data written by the array path stays device-resident in
`shuffle_store`; a host bridge exports buckets as (k, combiner) items so
downstream host-path stages (untraceable user code) can consume them
through the ordinary ShuffleFetcher protocol.
"""

import os
import threading
import time
from collections import OrderedDict

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from dpark_tpu import aotcache, conf, faults, locks, trace
from dpark_tpu.backend.tpu import collectives, fuse, layout
from dpark_tpu.utils.log import get_logger

logger = get_logger("tpu.executor")


def _plan_sig(plan):
    """Short stable program signature for health-plane site keys
    (ISSUE 14): the adapt store's cross-process program id, memoized
    on the plan by fuse.plan_adapt_signature."""
    try:
        return fuse.plan_adapt_signature(plan)[0]
    except Exception:
        return "?"

AXIS = conf.MESH_AXIS


def _even_ranges(n, parts):
    """parts contiguous [lo, hi) ranges covering n rows as evenly as
    possible."""
    base, extra = divmod(n, parts)
    out = []
    lo = 0
    for d in range(parts):
        hi = lo + base + (1 if d < extra else 0)
        out.append((lo, hi))
        lo = hi
    return out


def _reslice_parts(slices, ndev):
    """Re-split host partitions to the mesh width (shuffle-map stages
    only: the write redistributes by key, so partition boundaries carry
    no semantics there).  Columnar slices re-slice without building
    Python rows."""
    from dpark_tpu.rdd import _ColumnarSlice
    if slices and all(isinstance(s, _ColumnarSlice) for s in slices):
        ncols = len(slices[0].columns)
        cols = [np.concatenate([np.asarray(s.columns[i])
                                for s in slices])
                for i in range(ncols)]
        return [_ColumnarSlice([c[lo:hi] for c in cols])
                for lo, hi in _even_ranges(len(cols[0]), ndev)]
    rows = [r for s in slices for r in s]
    return [rows[lo:hi] for lo, hi in _even_ranges(len(rows), ndev)]


def _prefetch_iter(it, depth=1, name="dpark-wave-prefetch"):
    """Run `it` in a background thread, `depth` items ahead: the host
    tokenizes/slices (or, for the ingest stage, device_puts) wave k+1
    while the device computes wave k.  If the consumer abandons the
    generator (exception mid-stream, GeneratorExit), the producer is
    told to stop — it must not sit blocked on a full queue holding a
    wave of columns — and the SOURCE iterator is closed from the
    producer thread, so a chain of pipeline stages (tokenize ->
    ingest) unwinds stage by stage instead of leaking the upstream
    thread blocked on its own full queue."""
    import queue
    import threading
    q = queue.Queue(maxsize=depth)
    done = object()
    stop = threading.Event()

    def _put(x):
        while not stop.is_set():
            try:
                q.put(x, timeout=0.5)
                return True
            except queue.Full:
                continue
        return False

    def run():
        try:
            for x in it:
                if not _put(x):
                    return
            _put(done)
        except BaseException as e:          # re-raised in the consumer
            _put(e)
        finally:
            close = getattr(it, "close", None)
            if close is not None:
                try:
                    close()
                except BaseException:
                    pass

    threading.Thread(target=run, daemon=True, name=name).start()
    try:
        while True:
            x = q.get()
            if x is done:
                return
            if isinstance(x, BaseException):
                raise x
            yield x
    finally:
        stop.set()


def _async_d2h(arrays):
    """Start device->host copies without blocking (the wave pipeline
    reads them one wave later, by which point the transfer has ridden
    along behind the next wave's compute).  Best-effort: a
    process-spanning array can refuse the direct async copy (host_read
    replicates it later anyway)."""
    for a in arrays:
        try:
            a.copy_to_host_async()
        except Exception:
            pass


class _StreamStats:
    """Per-stream pipeline accounting: ingest/compute/exchange/spill
    seconds plus a host-observed device-idle fraction.

    The idle fraction is computed from "device active" intervals, one
    per wave: [first program dispatch, the blocking host read of that
    wave's outputs returning].  With the pipeline on, a wave's interval
    stretches over its neighbors' host work (ingest of k+1, spill of
    k-1 happen while wave k computes), so the union covers more of the
    wall clock and the idle fraction drops — the observable the
    overlap is graded on.  It is an approximation from the host side
    (dispatch is async; the device may finish inside an interval), but
    it moves monotonically with real overlap."""

    PER_WAVE_CAP = 128

    def __init__(self, depth, donated):
        import time
        self._clock = time.perf_counter
        self.t0 = self._clock()
        self.wall_t0 = time.time()   # epoch twin of t0 (trace spans)
        self.depth = depth
        self.donated = donated
        self.waves = 0
        self.ingest_s = 0.0
        self.compute_s = 0.0
        self.exchange_s = 0.0
        self.spill_s = 0.0
        self._busy = []              # (start, end) device-active spans
        self.per_wave = []           # bounded per-wave ms dicts

    def now(self):
        return self._clock()

    def add_busy(self, start, end):
        if end > start:
            self._busy.append((start, end))

    def wave_done(self, ingest_s, compute_s, exchange_s, spill_s=0.0):
        self.waves += 1
        self.ingest_s += ingest_s
        self.compute_s += compute_s
        self.exchange_s += exchange_s
        self.spill_s += spill_s
        if len(self.per_wave) < self.PER_WAVE_CAP:
            self.per_wave.append({
                "ingest_ms": round(ingest_s * 1e3, 2),
                "compute_ms": round(compute_s * 1e3, 2),
                "exchange_ms": round(exchange_s * 1e3, 2),
                "spill_ms": round(spill_s * 1e3, 2)})

    def add_spill(self, seconds, wave=None):
        self.spill_s += seconds
        if wave is not None and wave < len(self.per_wave):
            self.per_wave[wave]["spill_ms"] = round(
                self.per_wave[wave]["spill_ms"] + seconds * 1e3, 2)

    def _busy_union(self, until):
        total = 0.0
        end_prev = None
        for s, e in sorted(self._busy):
            e = min(e, until)
            if end_prev is None or s > end_prev:
                total += max(0.0, e - s)
                end_prev = e
            elif e > end_prev:
                total += e - end_prev
                end_prev = e
        return total

    def snapshot(self):
        now = self._clock()
        wall = max(now - self.t0, 1e-9)
        idle = max(0.0, wall - self._busy_union(now))
        return {
            "waves": self.waves,
            "ingest_ms": round(self.ingest_s * 1e3, 1),
            "compute_ms": round(self.compute_s * 1e3, 1),
            "exchange_ms": round(self.exchange_s * 1e3, 1),
            "spill_ms": round(self.spill_s * 1e3, 1),
            "wall_ms": round(wall * 1e3, 1),
            "device_idle_frac": round(idle / wall, 4),
            "pipeline_depth": self.depth,
            "donated": self.donated,
            "per_wave": list(self.per_wave),
        }


class _SpillWriter:
    """Background run writer for the spilled-run stream: compress +
    write happen on a dedicated thread with a bounded queue, taking
    disk I/O off the wave loop.  Worker errors surface on the next
    put() or at finish(); abort() (the cancellation path) drops queued
    work and joins without writing it."""

    def __init__(self, write_fn, depth=4):
        import queue
        import threading
        self._write = write_fn
        self._q = queue.Queue(maxsize=depth)
        self._err = None
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="dpark-spill-writer")
        self._thread.start()

    def _run(self):
        import queue
        while True:
            try:
                item = self._q.get(timeout=0.5)
            except queue.Empty:
                if self._stop.is_set():
                    return          # aborted and drained
                continue
            try:
                if item is None:
                    return
                if self._stop.is_set():
                    continue        # aborted: drain without writing
                try:
                    self._write(*item)
                except BaseException as e:
                    # never leave a partial chunk file behind: a later
                    # reader would mistake it for a (short) valid run
                    try:
                        os.unlink(item[0])
                    except OSError:
                        pass
                    self._err = e
                    self._stop.set()
            finally:
                self._q.task_done()

    def _raise_pending(self):
        if self._err is not None:
            err, self._err = self._err, None
            raise err

    def put(self, path, cols):
        self._raise_pending()
        self._q.put((path, cols))

    def finish(self):
        """Wait for every queued run to hit disk; re-raise any writer
        error.  Must be called before the shuffle registers."""
        self._q.join()
        self._q.put(None)
        self._thread.join()
        self._raise_pending()

    def abort(self):
        """Cancellation: drop queued runs, stop the thread."""
        self._stop.set()
        try:
            self._q.put_nowait(None)
        except Exception:
            pass
        self._thread.join(timeout=10)


class _RunPremerger:
    """Export bridge for spilled runs: pre-merges a partition's
    key-sorted runs into ONE run file in the background as soon as the
    stream ends, instead of eagerly at the first reduce-task fetch.
    ensure(rid) is shared by the background walker and export_bucket
    (which may race from several fetcher threads): per-rid once,
    behind per-rid locks.  Runs are written key-sorted per wave, so a
    single-run partition is already merged and the export can skip its
    argsort."""

    def __init__(self, runs, read_run, write_run, spool, key_cols=1):
        import threading
        self._runs = runs            # the SAME list object the store holds
        self._read = read_run
        self._write = write_run
        self._spool = spool
        self._key_cols = max(1, key_cols)   # composite keys: sort ALL
        self._locks = [threading.Lock() for _ in runs]
        self._merged = [len(p) <= 1 for p in runs]
        self._stop = threading.Event()
        self._thread = None

    def start_background(self):
        import threading
        self._thread = threading.Thread(
            target=self._walk, daemon=True, name="dpark-run-premerge")
        self._thread.start()

    def _walk(self):
        for rid in range(len(self._runs)):
            if self._stop.is_set():
                return
            try:
                self.ensure(rid)
            except Exception as e:
                logger.debug("premerge of partition %d failed "
                             "(export will merge inline): %s", rid, e)

    def ensure(self, rid):
        """Merge partition `rid`'s runs if not yet merged.  Returns
        (paths, presorted): presorted means the (single) run is
        key-sorted and the export can skip its argsort."""
        import os
        with self._locks[rid]:
            if self._merged[rid]:
                return self._runs[rid], True
            paths = self._runs[rid]
            parts = [self._read(p) for p in paths]
            cols = [np.concatenate([pt[li] for pt in parts])
                    for li in range(len(parts[0]))]
            # lexicographic over every key column (np.lexsort sorts by
            # the LAST key first); equal-key group order must survive
            # the merge or the export's adjacent-group fold would emit
            # split groups for tuple keys
            nk = min(self._key_cols, len(cols))
            order = (np.argsort(cols[0], kind="stable") if nk == 1
                     else np.lexsort(tuple(cols[:nk][::-1])))
            merged = os.path.join(self._spool, "merged-%d" % rid)
            self._write(merged, [c[order] for c in cols])
            self._runs[rid] = [merged]
            self._merged[rid] = True
            for p in paths:
                try:
                    os.unlink(p)
                except OSError:
                    pass
            return self._runs[rid], True

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)


class _StoreInFlight(Exception):
    """An HBM shuffle store whose producing stage has not registered
    its outputs yet — the eviction scan must skip it, not drop it."""


class _ProgramCache:
    """Bounded LRU over compiled stage programs (ISSUE 9 satellite).

    The executor compiles one jitted program per (kind, program_key,
    size class, ...) — fine for a one-job process, unbounded for a
    RESIDENT service compiling across every job it ever serves.
    conf.PROGRAM_CACHE_MAX bounds the entry count (0 = unbounded, the
    pre-service behavior); hit/miss/evict counters ride /metrics
    (dpark_program_cache_*_total), the web UI's per-job cache column,
    and the bench `service` section — the warm-submit A/B asserts a
    re-submitted DAG compiles NOTHING from these counters.

    Thread-safe: the service's slot threads compile concurrently
    (device dispatch serializes on the mesh lock, but host-side
    tracing does not)."""

    def __init__(self, cap=None):
        self._d = OrderedDict()
        self.cap = conf.PROGRAM_CACHE_MAX if cap is None else cap
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self._lock = locks.named_lock("executor.program_cache")
        # exact per-job attribution (ISSUE 15 satellite): each probe
        # also counts against the job the probing THREAD is executing
        # for (`_job_of`, installed by the executor to read its
        # per-thread job stamp).  The process-wide delta the per-job
        # record used to ship overlapped under concurrency (the
        # documented PR 9 caveat); these buckets do not.  Bounded:
        # oldest job bucket evicts past the cap.
        self._job_of = None
        self._job_counts = OrderedDict()     # job -> [hits, misses]

    def _count_job(self, hit):
        # called under self._lock
        job_of = self._job_of
        if job_of is None:
            return
        try:
            job = job_of()
        except Exception:
            return
        if job is None:
            return
        ent = self._job_counts.get(job)
        if ent is None:
            ent = self._job_counts[job] = [0, 0]
            while len(self._job_counts) > 128:
                self._job_counts.popitem(last=False)
        else:
            # recency-refresh: a long-running job that keeps probing
            # must not lose its bucket to 128 short jobs minted after
            # it (eviction is least-recently-PROBED, not insertion
            # order — the exactness guarantee holds for any job still
            # doing work)
            self._job_counts.move_to_end(job)
        ent[0 if hit else 1] += 1

    def job_stats(self, job):
        """Exact {hits, misses} attributed to one job's threads (0/0
        for a job that never probed)."""
        with self._lock:
            ent = self._job_counts.get(job) or (0, 0)
            return {"hits": ent[0], "misses": ent[1]}

    # Speaks the plain-dict idiom every compile site already uses —
    # `if key in cache: return cache[key]` / `cache[key] = jitted` —
    # so bounding the cache changed no call site.  The membership
    # probe is where hit/miss counts: each compile site probes exactly
    # once per call, and a probe that misses is always followed by a
    # compile.

    def __contains__(self, key):
        with self._lock:
            if key in self._d:
                # LRU-touch at probe time: the caller's next statement
                # is `cache[key]`, and a concurrent insert at capacity
                # must never evict the key between the two (the probe
                # makes it MRU)
                self._d.move_to_end(key)
                self.hits += 1
                self._count_job(True)
                return True
            self.misses += 1
            self._count_job(False)
            return False

    def __getitem__(self, key):
        with self._lock:
            return self._d[key]     # probe already counted + touched

    def __setitem__(self, key, fn):
        # the AOT plane seam (ISSUE 17): with a plane installed every
        # inserted program wraps in the lazy two-tier proxy whose
        # first call consults disk before compiling; off costs this
        # one `is None` check (plane-contract rule)
        plane = aotcache._PLANE
        if plane is not None:
            fn = plane.wrap(key, fn)
        evicted = []
        with self._lock:
            self._d[key] = fn
            self._d.move_to_end(key)
            if self.cap:
                while len(self._d) > max(1, self.cap):
                    evicted.append(self._d.popitem(last=False)[1])
                    self.evictions += 1
        # write-back OUTSIDE the cache lock: serializing an evicted
        # executable is disk work no concurrent probe should wait on
        for old in evicted:
            wb = getattr(old, "writeback", None)
            if wb is not None:
                wb()

    def __len__(self):
        return len(self._d)

    def stats(self):
        with self._lock:
            return {"entries": len(self._d), "cap": self.cap,
                    "hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions}


class _MeshLock:
    """The mesh lock, metered (ISSUE 15 tentpole): a reentrant lock
    whose every DEPTH-0 acquisition measures its wait (how long the
    caller queued behind other tenants' device work — the invisible
    cost of the resident service) and its hold (mesh busy time, the
    denominator of the ledger's conservation check).

    Counters are always on — two clock reads per outer acquisition —
    and mutated only while the lock is HELD, so they need no lock of
    their own.  With a trace plane installed, each depth-0 release
    additionally emits a ``mesh.lock`` span: ts = the acquisition
    request, dur = the WAIT, args.hold_s = the hold — the ledger sink
    folds the wait into the owning job's ``lock_wait_ms`` account and
    the hold into the offline mesh-busy view."""

    __slots__ = ("_lock", "_tls", "wait_s", "busy_s", "acquisitions",
                 "contended", "t_created")

    def __init__(self):
        self._lock = threading.RLock()
        self._tls = threading.local()
        self.wait_s = 0.0
        self.busy_s = 0.0
        self.acquisitions = 0
        self.contended = 0
        self.t_created = time.time()

    def __enter__(self):
        tls = self._tls
        depth = getattr(tls, "depth", 0)
        if depth:
            # reentrant re-acquire by the holder: no wait, no second
            # busy interval
            self._lock.acquire()
            tls.depth = depth + 1
            return self
        # lockcheck plane: one global load + `is None` check when off;
        # noted BEFORE the acquire so a strict-mode cycle raises as a
        # stack trace instead of wedging here
        locks.note_acquire("executor.mesh")
        t0 = time.time()
        wait = 0.0
        if not self._lock.acquire(False):
            self._lock.acquire()
            wait = time.time() - t0
        tls.depth = 1
        tls.t_request = t0
        tls.t_acquired = time.time()
        tls.wait = wait
        return self

    def __exit__(self, *exc):
        tls = self._tls
        tls.depth -= 1
        if tls.depth:
            self._lock.release()
            return False
        hold = time.time() - tls.t_acquired
        wait = tls.wait
        t_req = tls.t_request
        # mutated while still holding: race-free by construction
        self.busy_s += hold
        self.acquisitions += 1
        if wait > 0.0:
            self.wait_s += wait
            self.contended += 1
        self._lock.release()
        locks.note_release("executor.mesh")
        if trace._PLANE is not None:
            trace.emit("mesh.lock", "exec", t_req, wait,
                       hold_s=round(hold, 6))
        return False

    def meter(self):
        return {"busy_s": round(self.busy_s, 6),
                "wait_s": round(self.wait_s, 6),
                "acquisitions": self.acquisitions,
                "contended": self.contended,
                "wall_s": round(time.time() - self.t_created, 6)}


def _shard_map(fn, mesh, in_specs, out_specs):
    try:
        from jax import shard_map as _sm
        return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_vma=False)
    except (ImportError, TypeError):
        from jax.experimental.shard_map import shard_map as _sm
        return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=False)


class JAXExecutor:
    def __init__(self, devices=None):
        # 64-bit ints on device: dpark semantics are Python ints, and a
        # counting/summing workload must not silently wrap at 2**31
        # (parity contract with the local master)
        jax.config.update("jax_enable_x64", True)
        # donation is best-effort: when XLA cannot alias a donated
        # buffer into an output (shape/layout mismatch) it falls back
        # to a copy and jax warns per program — correct behavior, noisy
        # at one-per-compiled-program volume.  Installed here, not at
        # import time, so merely importing the module doesn't mutate
        # the process-global warning filter.
        import warnings
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        self.mesh = layout.make_mesh(devices)
        # persistent XLA compilation cache: stream programs compile per
        # (size class, slot) and a real-chip compile runs 30-150s
        # (BENCH_REAL_r03.md) — pay each once per program EVER, not
        # once per process.  Device backends only: XLA:CPU AOT entries
        # are machine-feature-sensitive (observed "could lead to
        # SIGILL" loads), and CPU compiles are cheap anyway.
        # DPARK_COMPILE_CACHE overrides the location; "0" disables.
        platform = self.mesh.devices.flat[0].platform
        cache_dir = os.environ.get(
            "DPARK_COMPILE_CACHE",
            os.path.expanduser("~/.cache/dpark_tpu/xla-%s" % platform))
        if cache_dir and cache_dir != "0" and platform != "cpu":
            try:
                os.makedirs(cache_dir, exist_ok=True)
                jax.config.update("jax_compilation_cache_dir",
                                  cache_dir)
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 1.0)
            except Exception as e:
                logger.debug("compilation cache unavailable: %s", e)
        self.ndev = int(self.mesh.devices.size)
        self.shuffle_store = {}       # sid -> stored map output metadata
        self._store_bytes = 0
        self.result_cache = {}        # rdd id -> HBM-resident Batch meta
        self._result_bytes = 0
        self._hbm_seq = 0             # global LRU clock across both tiers
        self.exchange_wire_bytes = 0  # ICI bytes moved by all_to_all
        self.export_seconds = 0.0     # host bridge export wall time
        self._exchange_real_rows = 0  # valid rows offered for exchange
        self.exchange_slot_rows = 0   # padded slots moved over the wire;
        #   pad efficiency = real/slot (HARDWARE_CHECKLIST.md step 3)
        # slots that never cross a wire (ndev==1 identity exchange) are
        # tracked separately so single-chip runs measure ingest padding
        # under its own name, not as bogus wire padding
        self.ingest_slot_rows = 0
        # count arrays whose host sum is deferred (the ndev==1 fast
        # path must not pay a blocking readback per wave just for this
        # metric); flushed on first metric read, or opportunistically
        # once the list exceeds a small bound so an embedder that never
        # reads the metric doesn't pin device buffers forever
        self._pending_real_counts = []
        self._PENDING_COUNTS_MAX = 64
        # slots already compiled per leaf config: sizing snaps to a
        # cached slot within the padding tolerance so data-size drift
        # between jobs reuses programs instead of recompiling adjacent
        # 1/16-octave classes
        self._slot_memo = {}
        # bounded LRU over compiled programs (ISSUE 9 satellite):
        # conf.PROGRAM_CACHE_MAX entries, hit/miss/evict counters for
        # /metrics and the warm-submit A/B
        self._compiled = _ProgramCache()
        # buffer donation is gated off on multi-controller meshes:
        # donating a process-spanning global array switches XLA:CPU to
        # a multiprocess aliasing path it doesn't implement
        # (INVALID_ARGUMENT in the SPMD dryrun), and on real multi-host
        # meshes the reuse economics are per-process anyway
        try:
            self._single_proc = all(
                d.process_index == jax.process_index()
                for d in self.mesh.devices.flat)
        except Exception:
            self._single_proc = False
        # overlapped wave pipeline observability: per-stream snapshot of
        # ingest/compute/exchange/spill ms + device-idle fraction
        # (reset by run_stage; the scheduler attaches it to stage_info)
        self.last_stream_stats = None
        # (rows/device, row bytes) the last streamed stage budgeted its
        # waves at — the OOM degradation ladder persists this into the
        # adaptive store (ISSUE 7) so the next run seeds from it
        self.last_wave_budget = None
        # live per-wave stage_info callback, set by the scheduler around
        # run_stage so a long stream's progress shows in the web UI
        self._stage_note = None
        # let rdd.unpersist() reach device-resident caches
        from dpark_tpu import cache as cache_mod
        cache_mod.DEVICE_CACHES[id(self)] = self.drop_result
        self._cache_key = id(self)
        # register the host bridge so file-path stages can read HBM shuffles
        from dpark_tpu import shuffle as shuffle_mod
        shuffle_mod.HBM_EXPORTERS[id(self)] = self.export_bucket
        # columnar twin (ISSUE 12): the bulk data plane serves flat
        # (k, v) buckets as raw column bytes to peer controllers — no
        # per-row pickling on the cross-process path
        shuffle_mod.HBM_COL_EXPORTERS[id(self)] = self.export_bucket_cols
        self._exporter_key = id(self)
        # ONE mesh lock serializes every device-program dispatch path:
        # stage programs (run_stage), device joins/gathers, AND the
        # export bridge's sharded-leaf reads.  Two collective programs
        # dispatched concurrently deadlock the XLA:CPU rendezvous
        # (each run pins one device participant; observed as the
        # classic multi-thread lookup/fetch wedge — PR 3 addendum),
        # and with a resident job server (ISSUE 9) CONCURRENT jobs'
        # stages now genuinely race for the mesh.  Reentrant so the
        # eviction spiller can export under a stage's lock.  Disk-run
        # exports stay lock-free — they touch no device.  Lock order
        # where both are held: _mesh_lock -> _shard_build_lock.
        self._mesh_lock = _MeshLock()
        self._export_lock = self._mesh_lock
        # ledger plane (ISSUE 15): backend compiles become measured
        # compile.backend spans via jax.monitoring; the listener costs
        # one predicate per (rare) compile when tracing is off
        trace.install_compile_listener()
        # jobs currently RUNNING on the owning scheduler (ISSUE 9):
        # their HBM shuffle stores are preferred-KEEP when the budget
        # evicts; completed jobs' buckets spill to disk first
        self.live_jobs = set()
        self._job_tls = threading.local()   # job id of this thread's stage
        # exact per-job program-cache attribution (ISSUE 15 satellite,
        # closing the PR 9 caveat): hits/misses tag the slot thread's
        # CURRENT job, so concurrent jobs' record["program_cache"]
        # deltas no longer overlap
        self._compiled._job_of = \
            lambda: getattr(self._job_tls, "job", None)
        # scheduler hook: called as (sid, uri) after an HBM store is
        # spilled to disk so stage output locations follow the move
        self._spill_notify = None
        # coded-shuffle shard serving (ISSUE 6): each hbm bucket is
        # lazily serialized + erasure-encoded ONCE, then individual
        # framed shards answer per-shard fetches.  Builds serialize
        # behind one lock (the n concurrent shard reads of one bucket
        # must not each export the bucket); the cache is a small
        # byte-bounded FIFO — shard fetches for one bucket arrive
        # within one reduce task's fan-out, so entries age out fast.
        self._shard_cache = {}        # (sid, map, reduce) -> [frames]
        self._shard_cache_bytes = 0
        self._shard_build_lock = locks.named_lock(
            "executor.shard_build")
        self._tracing = False
        if conf.XPROF_DIR:
            try:
                jax.profiler.start_trace(conf.XPROF_DIR)
                self._tracing = True
                logger.info("jax profiler trace -> %s", conf.XPROF_DIR)
            except Exception as e:
                logger.warning("profiler trace unavailable: %s", e)

    @property
    def exchange_real_rows(self):
        """Valid rows offered for exchange.  Reading flushes deferred
        per-wave count arrays (one batched readback at metric-read
        time, e.g. the scheduler's per-stage accounting — never inside
        the wave loop)."""
        if self._pending_real_counts:
            pending, self._pending_real_counts = \
                self._pending_real_counts, []
            if all(getattr(c, "is_fully_addressable", True)
                   for c in pending):
                # one batched readback (the ndev==1 fast path only ever
                # defers fully-addressable arrays)
                for c in jax.device_get(pending):
                    self._exchange_real_rows += int(np.asarray(c).sum())
            else:
                for c in pending:
                    self._exchange_real_rows += int(
                        layout.host_read(c).sum())
        return self._exchange_real_rows

    @exchange_real_rows.setter
    def exchange_real_rows(self, value):
        self._exchange_real_rows = value

    # ------------------------------------------------------------------
    # compilation
    # ------------------------------------------------------------------
    def _sharding(self):
        return NamedSharding(self.mesh, P(AXIS))

    def _donation_enabled(self):
        """DONATE_BUFFERS, and the mesh lives in this one process (see
        __init__: multi-controller donation is unimplemented in
        XLA:CPU and unprofitable elsewhere)."""
        return conf.DONATE_BUFFERS and self._single_proc

    def _epilogue_merge(self, plan):
        """(merge_fn, monoid) for a combining shuffle write, or
        (None, None) for the no-combine (list-aggregator) mode.

        The two are independent: a PROVABLE monoid combines through
        segment scatters even when the user's function itself does not
        trace (``max(a, b)`` forces a tracer bool) — discarding the
        monoid with the failed trace crashed the streamed combine (r5
        fuzz finding).  Untraceable AND unclassified merges exchange
        raw created combiners.

        A classified monoid WITHOUT a traced merge_fn only stands in
        for the user's function when the record carries exactly one
        SCALAR value leaf: the host merges whole records (max over
        tuples compares lexicographically) while the monoid-only call
        sites (_epilogue_block, the carry_rid bucketize, and
        _prereduce_received — all of which get their pair from here
        via _merge_probe) reduce each leaf independently, mixing
        leaves from different records (r5 advisor finding: silent
        wrong answers for tuple-valued reduceByKey(min/max)).  For
        any other value shape the pair degrades to (None, None) and
        the raw-combiner exchange folds with the user's function on
        the host — slower, correct."""
        dep = plan.epilogue[1]
        if fuse.is_list_agg(dep.aggregator):
            return None, None
        try:
            monoid = fuse.classify_merge(dep.aggregator.merge_combiners)
        except Exception:
            monoid = None
        try:
            merge_fn = fuse._leaves_merge_fn(
                dep.aggregator.merge_combiners, plan.out_treedef)
            structs = fuse._batched_spec_struct(
                plan.out_specs[getattr(plan, "epi_nk", 1) or 1:])
            jax.eval_shape(lambda *v: merge_fn(list(v), list(v)),
                           *structs)
        except Exception:
            merge_fn = None
        if merge_fn is None and monoid is not None:
            specs = plan.out_specs
            nk = getattr(plan, "epi_nk", 1) or 1
            single_scalar_value = (len(specs) == nk + 1
                                   and specs[nk][1] == ())
            if not single_scalar_value:
                return None, None
        return merge_fn, monoid

    @staticmethod
    def _epilogue_block(plan, lv, n, n_dst, merge_fn, monoid, bounds):
        """Shared shuffle-write tail: destination assignment (hash or
        range bounds over the LOGICAL partition count r <= mesh size) +
        bucketize[-combine].  Composite (tuple) keys occupy the first
        plan.epi_nk columns: destinations hash over all of them with
        the pair-extended phash, and the combine merges rows equal in
        every key column."""
        nk = getattr(plan, "epi_nk", 1) or 1
        k = lv[0]
        r = plan.epilogue[1].partitioner.num_partitions
        valid = jnp.arange(k.shape[0]) < n
        if plan.epi_spec is not None and plan.epi_spec[0] == "range":
            if nk == 1:
                dst = collectives.range_dst(k, bounds,
                                            plan.epi_spec[1],
                                            n_dst, valid, r=r)
            else:
                bcols = [bounds[:, i] for i in range(nk)]
                dst = collectives.range_dst_cols(
                    lv[:nk], bcols, plan.epi_spec[1], n_dst, valid,
                    r=r)
        else:
            dst = collectives.hash_dst_cols(lv[:nk], n_dst, valid,
                                            r=r)
        if merge_fn is not None or monoid is not None:
            k2s, v2, cnts, offs = collectives.bucketize_combine_keys(
                lv[:nk], lv[nk:], n, n_dst, merge_fn, monoid=monoid,
                dst=dst, r=r)
        else:
            sorted_lv, cnts, offs = collectives.bucketize(
                k, lv, n, n_dst, dst=dst, r=r)
            k2s, v2 = sorted_lv[:nk], sorted_lv[nk:]
        return (cnts, offs) + tuple(k2s) + tuple(v2)

    def _widen_entry(self, plan, lv):
        """Cast program inputs up to the spec dtypes: ingest may ship
        int64 leaves over the host->device wire as i32 (layout.ingest's
        fit scan); compute always runs at spec width."""
        return [v if v.dtype == dt else v.astype(dt)
                for v, (dt, _) in zip(lv, plan.in_specs)]

    def _compile_narrow(self, plan, cap, nleaves_in, in_dtypes=(),
                        donate=False, extra_key=()):
        """Program A: (counts, [bounds,] in_leaves) -> ops -> result or
        bucketized shuffle output.  Shapes (ndev, cap, ...), dim 0
        sharded.  `donate` hands the input leaves to XLA for in-place
        reuse — STREAMED waves only, where the ingest buffers are dead
        after this program (in-core callers may pass result-cache or
        shuffle-store leaves, which must survive the call).
        `extra_key` extends the program identity for op state decided
        per run (the SegMapOp bucket layout)."""
        key = ("narrow", plan.program_key, cap, nleaves_in, in_dtypes,
               donate, extra_key)
        if key in self._compiled:
            return self._compiled[key]
        faults.hit("executor.compile")     # chaos site: per cache miss
        if trace._PLANE is not None:
            trace.event("compile", "exec", program="narrow", cap=cap,
                        sig=_plan_sig(plan))
        ops = plan.ops
        epilogue = plan.epilogue
        n_dst = self.ndev
        has_bounds = plan.epi_bounds is not None
        merge_fn = monoid = None
        if epilogue is not None:
            merge_fn, monoid = self._epilogue_merge(plan)

        def per_device(counts, *rest):
            n = counts[0]
            bounds = rest[0][0] if has_bounds else None
            leaves = rest[1:] if has_bounds else rest
            lv = self._widen_entry(plan, [l[0] for l in leaves])
            for op in ops:
                lv, n = op.apply(lv, n)
            if epilogue is None:
                return (jnp.expand_dims(n, 0),) + tuple(
                    jnp.expand_dims(l, 0) for l in lv)
            out = self._epilogue_block(plan, lv, n, n_dst, merge_fn,
                                       monoid, bounds)
            return tuple(jnp.expand_dims(o, 0) for o in out)

        n_in = 1 + nleaves_in + (1 if has_bounds else 0)
        n_out = (1 + len(plan.out_specs)) if epilogue is None \
            else (2 + len(plan.out_specs))
        fn = _shard_map(per_device, self.mesh,
                        in_specs=(P(AXIS),) * n_in,
                        out_specs=(P(AXIS),) * n_out)
        leaf0 = 1 + (1 if has_bounds else 0)
        jitted = jax.jit(fn, donate_argnums=tuple(
            range(leaf0, leaf0 + nleaves_in)) if donate else ())
        self._compiled[key] = jitted
        # read back through the cache: with the AOT plane on, the
        # stored value is the two-tier proxy, and EVERY call path must
        # route through it or the first call double-compiles
        return self._compiled[key]

    def _compile_exchange(self, dtypes, nleaves, slot, cap,
                          narrow=None, donate=False):
        """`donate` releases the destination-sorted send buffers for
        in-place reuse: only the LAST round of a streamed wave's
        exchange may donate (earlier rounds re-read the same buffers;
        the in-core path passes shuffle-store leaves, never donated)."""
        key = ("exchange", dtypes, nleaves, slot, cap, narrow, donate)
        if key in self._compiled:
            return self._compiled[key]

        def per_device(offsets, counts, sent, *leaves):
            lv = [l[0] for l in leaves]
            recv, recv_cnt, new_sent, overflow = collectives.exchange_round(
                AXIS, lv, offsets[0], counts[0], sent[0], slot,
                narrow=narrow)
            out = (recv_cnt, new_sent,
                   jnp.reshape(overflow, (1,))) + tuple(recv)
            return tuple(jnp.expand_dims(o, 0) for o in out)

        fn = _shard_map(per_device, self.mesh,
                        in_specs=(P(AXIS),) * (3 + nleaves),
                        out_specs=(P(AXIS),) * (3 + nleaves))
        jitted = jax.jit(fn, donate_argnums=tuple(
            range(3, 3 + nleaves)) if donate else ())
        self._compiled[key] = jitted
        return self._compiled[key]

    def _compile_minmax(self, nleaves, cap):
        """(counts, int64 leaves) -> per-device (lo, hi) over each
        leaf's VALID destination-sorted prefix (rows past sum(counts)
        are padding and may hold sentinels that would defeat
        narrowing)."""
        key = ("minmax", nleaves, cap)
        if key in self._compiled:
            return self._compiled[key]
        imax = jnp.iinfo(jnp.int64).max
        imin = jnp.iinfo(jnp.int64).min

        def per_device(counts, *leaves):
            total = jnp.sum(counts[0]).astype(jnp.int32)
            valid = jnp.arange(cap) < total
            outs = []
            for l in leaves:
                lv = l[0]
                lo = jnp.min(jnp.where(valid, lv, imax))
                hi = jnp.max(jnp.where(valid, lv, imin))
                outs.append(jnp.stack([lo, hi]))
            return tuple(jnp.expand_dims(o, 0) for o in outs)

        fn = _shard_map(per_device, self.mesh,
                        in_specs=(P(AXIS),) * (1 + nleaves),
                        out_specs=(P(AXIS),) * nleaves)
        self._compiled[key] = jax.jit(fn)
        return self._compiled[key]

    def _narrow_plan(self, leaves, counts):
        """Per-leaf wire dtype for the exchange (None = keep).

        TPUs (v5e) have no native 64-bit integer datapath — XLA emulates
        i64 as i32 pairs and an i64 all_to_all moves 2x the ICI bytes.
        dpark semantics demand i64 *compute* (counting must not wrap at
        2**31), so narrowing is decided per exchange by a runtime
        min/max guard over the valid rows: int64 scalar columns whose
        values all fit int32 ride the wire at i32 and widen back
        immediately after the collective (VERDICT r2 ask #1)."""
        if not conf.NARROW_EXCHANGE:
            return None
        cand = [li for li, l in enumerate(leaves)
                if l.dtype == jnp.int64 and l.ndim == 2]
        if not cand:
            return None
        cap = leaves[0].shape[1]
        probe = self._compile_minmax(len(cand), cap)
        ranges = probe(counts, *[leaves[li] for li in cand])
        plan = [None] * len(leaves)
        i32 = np.iinfo(np.int32)
        for li, rng in zip(cand, ranges):
            r = layout.host_read(rng)                # (ndev, 2)
            lo, hi = int(r[:, 0].min()), int(r[:, 1].max())
            if lo >= i32.min and hi <= i32.max:
                plan[li] = "int32"
        if not any(plan):
            return None
        return tuple(plan)

    def _compile_reduce(self, plan, rounds, slot, nleaves,
                        donate=False):
        """Program B: ([bounds,] recv counts, recv buffers over `rounds`)
        -> flatten -> segment reduce (or key-sort for no-combine) -> ops
        -> result or bucketize.  `donate` releases the receive buffers
        (exchange outputs, dead after this program) for in-place reuse;
        the single-device identity exchange aliases store leaves, so
        callers only donate on a real multi-device exchange."""
        key = ("reduce", plan.program_key, rounds, slot, nleaves,
               donate)
        if key in self._compiled:
            return self._compiled[key]
        dep = plan.source[1]
        merge_fn = monoid = None
        if plan.src_combine:
            merge_fn = fuse._leaves_merge_fn(
                dep.aggregator.merge_combiners, plan.in_treedef)
            try:
                monoid = fuse.classify_merge(
                    dep.aggregator.merge_combiners)
            except Exception:
                monoid = None
        ops = plan.ops
        epilogue = plan.epilogue
        n_dst = self.ndev
        has_bounds = plan.epi_bounds is not None
        out_merge_fn = out_monoid = None
        if epilogue is not None:
            out_merge_fn, out_monoid = self._epilogue_merge(plan)

        src_nk = getattr(plan, "src_nk", 1) or 1

        def per_device(*args):
            bounds = args[0][0] if has_bounds else None
            args = args[1:] if has_bounds else args
            cnts = [c[0] for c in args[:rounds]]
            buf_args = args[rounds:]
            recvs = []
            for r in range(rounds):
                recvs.append([buf_args[r * nleaves + li][0]
                              for li in range(nleaves)])
            flat, mask = collectives.flatten_received(recvs, cnts)
            if merge_fn is not None:
                ks, vs, n = collectives.segment_reduce_keys(
                    flat[:src_nk], flat[src_nk:], mask, merge_fn,
                    monoid=monoid)
                lv = list(ks) + list(vs)
            else:
                # no-combine repartition: sort rows by the FULL key
                # (every column of a tuple key), valid first
                packed = collectives._lex_sort(tuple(flat), src_nk)
                lv = list(packed)
                n = jnp.sum(mask).astype(jnp.int32)
            for op in ops:
                lv, n = op.apply(lv, n)
            if epilogue is None:
                return (jnp.expand_dims(n, 0),) + tuple(
                    jnp.expand_dims(l, 0) for l in lv)
            out = self._epilogue_block(plan, lv, n, n_dst, out_merge_fn,
                                       out_monoid, bounds)
            return tuple(jnp.expand_dims(o, 0) for o in out)

        n_in = rounds + rounds * nleaves + (1 if has_bounds else 0)
        n_out = (1 + len(plan.out_specs)) if epilogue is None \
            else (2 + len(plan.out_specs))
        fn = _shard_map(per_device, self.mesh,
                        in_specs=(P(AXIS),) * n_in,
                        out_specs=(P(AXIS),) * n_out)
        buf0 = rounds + (1 if has_bounds else 0)
        jitted = jax.jit(fn, donate_argnums=tuple(
            range(buf0, buf0 + rounds * nleaves)) if donate else ())
        self._compiled[key] = jitted
        return self._compiled[key]

    def _bounds_arg(self, plan):
        """plan.epi_bounds tiled per device and sharded, or None.
        Tuple-key range bounds are 2D (len(bounds), nk) and tile to
        (ndev, len(bounds), nk)."""
        if plan.epi_bounds is None:
            return None
        b = plan.epi_bounds
        reps = (self.ndev,) + (1,) * b.ndim
        tiled = np.tile(b, reps) if b.size else np.zeros(
            (self.ndev,) + b.shape, b.dtype)
        return layout.put_sharded(tiled, self._sharding())

    # ------------------------------------------------------------------
    # running
    # ------------------------------------------------------------------
    def run_stage(self, plan):
        """Execute the whole stage for all partitions at once.

        Returns ("result", list_of_row_lists) or ("shuffle", sid).
        Holds the mesh lock throughout: with a resident job server
        (ISSUE 9) concurrent jobs' stages race for the device, and two
        collective programs in flight wedge the XLA:CPU rendezvous."""
        # the span carries the adapt program signature (ISSUE 15): the
        # ledger's device-seconds account and the health plane's
        # wave sketches key by it — only worth computing when traced
        extra = {}
        if trace._PLANE is not None:
            sig = _plan_sig(plan)
            extra = {"sig": sig}
            # every backend compile inside this stage (narrow,
            # exchange, egest, ...) attributes to the stage's program
            trace.set_compile_sig(sig)
        if aotcache._PLANE is not None:
            # programs inserted under this stage carry its adapt
            # signature into the disk index / warm ranking
            aotcache.set_current_sig(fuse.plan_adapt_signature(plan))
        with self._mesh_lock, \
                trace.span("stage.exec", "exec", source=plan.source[0],
                           **extra):
            return self._run_stage(plan)

    def _run_stage(self, plan):
        self.last_stream_stats = None       # set by streamed runs only
        self.last_wave_budget = None
        mode = self._stream_mode(plan)
        if mode is not None:
            kind, waves = mode
            if kind == "combine":
                return self._run_streamed_shuffle(plan, waves)
            return self._run_streamed_nocombine(plan, waves)
        if getattr(plan, "logical_spill", False):
            # analyze only admits logical_spill when the input clears
            # the streaming bar, so this is a safety net, not a route
            raise ValueError("logical_spill plan without streaming")
        if plan.source[0] == "text":
            outs = self._run_narrow(plan, self._ingest_text(plan))
            return self._finish_stage(plan, outs)
        if plan.source[0] == "union":
            keyed = plan.epilogue is not None
            batch = self._concat_batches(
                [layout.Batch(sp.out_treedef, list(o[1:]), o[0])
                 for sp in plan.source[1]
                 for o in (self._source_outs(sp, keyed),)])
            outs = self._run_narrow(plan, batch)
        else:
            outs = self._source_outs(plan, plan.epilogue is not None)
        return self._finish_stage(plan, outs)

    def _source_outs(self, plan, keyed):
        """Load the plan's source and run its narrow/reduce program;
        shared by whole-stage runs and union-branch materialization."""
        if plan.source[0] == "ingest":
            pc = plan.source[1]
            slices = pc._slices
            if getattr(plan, "reslice", False):
                slices = _reslice_parts(slices, self.ndev)
            # any shuffle write pads with the key sentinel; a real key
            # equal to it must force the host path
            batch = layout.ingest(self.mesh, slices, plan.in_treedef,
                                  plan.in_specs,
                                  key_leaf=0 if keyed else None)
            return self._run_narrow(plan, batch)
        if plan.source[0] == "cached":
            meta = self.result_cache[plan.source[1].id]
            meta["seq"] = self._next_seq()           # LRU touch
            batch = layout.Batch(meta["treedef"], meta["leaves"],
                                 meta["counts"])
            if keyed:
                self._check_cached_keys(batch)
            return self._run_narrow(plan, batch)
        if plan.source[0] == "join":
            dep_a, dep_b = plan.source[1]
            batch = self.device_join_batch(dep_a, dep_b)
            return self._run_narrow(plan, batch)
        store = self.shuffle_store[plan.source[1].shuffle_id]
        if plan.ops and (isinstance(plan.ops[0], fuse.SegMapOp)
                         or (isinstance(plan.ops[0], fuse.SegAggOp)
                             and "host_runs" in store)):
            # segmented apply (and segment aggregates over spilled
            # runs): two-phase — sort the rows, read the group-size
            # histogram, compile with the bucket layout
            return self._run_seg_map(plan)
        if store.get("pre_reduced"):
            # streamed shuffle already exchanged+combined: device d
            # holds reduce partition d — just run the narrow tail
            store["seq"] = self._next_seq()
            batch = layout.Batch(store["out_treedef"], store["leaves"],
                                 store["counts"])
            return self._run_narrow(plan, batch)
        return self._run_exchange_and_reduce(plan)

    def _run_narrow(self, plan, batch, bounds=None, donate=False,
                    extra_key=()):
        """Compile + invoke the narrow stage program on one batch.
        `donate` is for streamed waves only: the batch's leaves are
        dead after this call and XLA may reuse them in place."""
        faults.hit("executor.dispatch")    # chaos site: per dispatch
        if trace._PLANE is not None:
            trace.event("dispatch", "exec", program="narrow",
                        sig=_plan_sig(plan))
            # backend compiles fired by the jitted call below
            # attribute to this program (ledger plane, ISSUE 15)
            trace.set_compile_sig(_plan_sig(plan))
        if aotcache._PLANE is not None:
            aotcache.set_current_sig(fuse.plan_adapt_signature(plan))
        jitted = self._compile_narrow(
            plan, batch.cap, len(batch.cols),
            tuple(str(c.dtype) for c in batch.cols), donate=donate,
            extra_key=extra_key)
        if bounds is None:
            bounds = self._bounds_arg(plan)
        args = (batch.counts,) + ((bounds,) if bounds is not None
                                  else ()) + tuple(batch.cols)
        self._capture_cost(plan, jitted, args)
        return jitted(*args)

    def _capture_cost(self, plan, jitted, args):
        """Static program cost profile at first dispatch (ISSUE 15):
        once per plan signature, BEFORE the call (donated buffers are
        dead after it; lower() reads only avals).  Gated on BOTH the
        ledger sink and an installed trace plane — the documented
        contract is that the whole attribution plane is inert with
        DPARK_TRACE=off, and the capture's re-trace must never ride
        an untraced production dispatch under the mesh lock."""
        from dpark_tpu import ledger
        if ledger._SINK is None or trace._PLANE is None:
            return
        try:
            ledger.capture_program_cost(
                fuse.plan_adapt_signature(plan), jitted, args)
        except Exception:
            pass

    # ------------------------------------------------------------------
    # text-source ingest (SURVEY.md 3.1 hot loop #1): the narrow chain
    # over a file source runs as a host prologue per split — the user's
    # own generators (always correct) or, for the verified canonical
    # wordcount shape, the C++ tokenizer — then string keys are
    # dictionary-encoded and the device shuffle takes over
    # ------------------------------------------------------------------
    def _token_dict(self):
        if not hasattr(self, "token_dict"):
            from dpark_tpu.native import TokenDict
            self.token_dict = TokenDict()
        return self.token_dict

    @staticmethod
    def _read_text_split(text_rdd, sp):
        """The bytes of one newline-aligned split (same boundary rule as
        TextFileRDD.compute: skip a partial first line, finish the line
        that crosses the end)."""
        from dpark_tpu import file_manager
        with file_manager.open_file(sp.path) as f:
            begin = sp.begin
            if begin > 0:
                f.seek(begin - 1)
                if f.read(1) != b"\n":
                    f.readline()
                begin = f.tell()
            else:
                f.seek(0)
            data = f.read(sp.end - begin) if sp.end > begin else b""
            if data and not data.endswith(b"\n"):
                data += f.readline()
            return data

    @staticmethod
    def _tokenizer_safe(data, sep=None):
        """True iff the ASCII byte tokenizer provably equals the
        Python chain on these bytes.

        Whitespace mode (sep=None): every byte must be printable ASCII
        or \\t \\n \\r — bytes >= 0x80 can decode to unicode whitespace
        (\\xc2\\xa0 etc.) and control bytes \\x0b \\x0c \\x1c-\\x1f ARE
        str.split() whitespace but not the byte tokenizer's (ADVICE r2:
        the 4KB first-split check alone missed divergence appearing
        later in the file).

        Separator mode: str.split(sep) splits ONLY on the separator, so
        control bytes pass through both paths verbatim — only >= 0x80
        (utf-8 'replace' decoding can rewrite token bytes) forces the
        host prologue."""
        if not data:
            return True
        a = np.frombuffer(data, np.uint8)
        if sep is not None:
            return not bool((a >= 0x80).any())
        bad = (a >= 0x80) | ((a < 0x20) & (a != 9) & (a != 10)
                             & (a != 13))
        return not bool(bad.any())

    def _verify_canonical(self, plan, data, td):
        """Run the user's own flatMap/map on a prefix of this split and
        compare with the C++ tokenizer: any divergence (e.g. unicode
        whitespace the byte tokenizer doesn't split on) disables the
        native path for this run — correctness first."""
        prefix = data[:4096]
        cut = prefix.rfind(b"\n")
        prefix = b"" if cut < 0 else prefix[:cut + 1]
        if not prefix:
            # nothing to verify against (empty split or a >4KB first
            # line): do NOT trust the byte tokenizer unverified
            return False
        fm, mp = plan.text_chain
        expect = []
        # EXACT TextFileRDD line iteration: \n-separated, trailing \r
        # stripped (str.splitlines would also split on \x0b etc.)
        for raw in prefix.split(b"\n")[:-1]:
            line = raw.rstrip(b"\r\n").decode("utf-8", "replace")
            for w in fm.f(line):
                rec = mp.f(w)
                if rec[1] != 1:
                    return False
                expect.append(rec[0])
        sep = getattr(plan, "canonical_sep", None)
        got = [td.decode(int(t)) for t in td.encode(prefix, sep=sep)]
        return got == expect

    def _encode_rows(self, plan, top, sp, td):
        """Host prologue for one split: run the user chain, columnarize,
        dictionary-encode string keys."""
        import jax.tree_util as jtu
        keys = []
        leaf_lists = [[] for _ in plan.in_specs[1:]]
        encode = plan.encoded_keys
        for rec in top.iterator(sp):
            k, v = rec
            keys.append(td.put(k) if encode else k)
            for li, leaf in enumerate(jtu.tree_leaves(v)):
                leaf_lists[li].append(leaf)
        cols = [np.asarray(keys, np.int64)]
        for ll, (dt, shape) in zip(leaf_lists, plan.in_specs[1:]):
            cols.append(np.asarray(ll, dt))
        return cols

    def _text_split_cols(self, plan, sp, td, state):
        """Columns for one split: C++ tokenizer on the canonical path
        (bytecode-proven chain + per-split byte-safety scan + a sample
        verification), the user's own generators otherwise."""
        if state["canonical"]:
            sep = getattr(plan, "canonical_sep", None)
            data = self._read_text_split(plan.text_rdd, sp)
            if not state["checked"] and self._tokenizer_safe(
                    data[:4096], sep):
                state["checked"] = True
                if not self._verify_canonical(plan, data, td):
                    logger.info("canonical tokenizer diverges from the "
                                "user chain; using the host prologue")
                    state["canonical"] = False
            if state["canonical"] and self._tokenizer_safe(data, sep):
                ids = td.encode(data, sep=sep)
                return [np.asarray(ids, np.int64),
                        np.ones(len(ids), np.int64)]
        return self._encode_rows(plan, plan.stage.rdd, sp, td)

    def _text_parts(self, plan, chunks):
        """Concatenate per-split columns and redistribute rows EVENLY
        across devices regardless of the file split layout (one big file
        = one split must not put everything on device 0); the hash
        exchange owns placement anyway.  The host bridge compensates via
        the store's single_map mode."""
        from dpark_tpu.rdd import _ColumnarSlice
        nleaves = len(plan.in_specs)
        if chunks:
            cols = [np.concatenate([c[li] for c in chunks])
                    for li in range(nleaves)]
        else:
            cols = [np.zeros((0,) + shape, dt)
                    for dt, shape in plan.in_specs]
        return [_ColumnarSlice([c[lo:hi] for c in cols])
                for lo, hi in _even_ranges(len(cols[0]), self.ndev)]

    def _split_cols_parallel(self, plan, splits, td, state):
        """Per-split columns with CONCURRENT tokenize/encode (VERDICT
        r2 ask #2 — the serial driver walk was the 10GB wordcount's
        bottleneck): worker threads read + tokenize each split into a
        PRIVATE TokenDict (ctypes releases the GIL, so the C++ loops
        run truly parallel), then the driver merges the private
        vocabularies into the global dict in split order — global ids
        come out identical to the serial walk.  The first split
        resolves the canonical-vs-prologue decision serially (it
        mutates shared state and runs the sample verification)."""
        import concurrent.futures as cf
        import os as _os
        nw = conf.INGEST_THREADS or (_os.cpu_count() or 1)
        nw = min(nw, max(1, len(splits)))
        if nw <= 1 or len(splits) <= 1:
            return [self._text_split_cols(plan, sp, td, state)
                    for sp in splits]
        # walk serially until the sample verification has actually run
        # (splits whose prefix is byte-unsafe take the host prologue and
        # leave state['checked'] False): the C++ path must NEVER run
        # unverified, in the parallel path exactly as in the serial one
        results = []
        i = 0
        while i < len(splits) and state["canonical"] \
                and not state["checked"]:
            results.append(self._text_split_cols(plan, splits[i], td,
                                                 state))
            i += 1
        rest = splits[i:]
        if not rest:
            return results
        if not (state["canonical"] and state["checked"]):
            # host-prologue chain: USER code — keep it on the driver
            # thread (the reference isolates user code in processes;
            # interleaving a stateful closure across threads would
            # silently change results), and the GIL would serialize it
            # anyway
            results.extend(self._text_split_cols(plan, sp, td, state)
                           for sp in rest)
            return results

        sep = getattr(plan, "canonical_sep", None)

        def work(sp):
            # C++ only in workers: read + byte-scan + tokenize into a
            # PRIVATE dict (ctypes releases the GIL).  Byte-unsafe
            # splits are handed back for the driver-thread prologue.
            data = self._read_text_split(plan.text_rdd, sp)
            if not self._tokenizer_safe(data, sep):
                return None
            from dpark_tpu.native import TokenDict
            ltd = TokenDict()
            return (ltd, ltd.encode(data, sep=sep))

        with cf.ThreadPoolExecutor(max_workers=nw) as pool:
            done = list(pool.map(work, rest))
        for sp, out in zip(rest, done):       # split order: ids stable
            if out is None:
                results.append(self._encode_rows(
                    plan, plan.stage.rdd, sp, td))
                continue
            ltd, local_ids = out
            ids = td.merge_from(ltd)[local_ids] if len(ltd) \
                else local_ids
            results.append([np.asarray(ids, np.int64),
                            np.ones(len(ids), np.int64)])
        return results

    def _ingest_text(self, plan):
        td = self._token_dict() if plan.encoded_keys else None
        state = {"canonical": plan.canonical, "checked": False}
        chunks = self._split_cols_parallel(plan, plan.stage.rdd.splits,
                                           td, state)
        parts = self._text_parts(plan, chunks)
        return layout.ingest(self.mesh, parts, plan.in_treedef,
                             plan.in_specs, key_leaf=0)

    # -- HBM result cache (rdd.cache() on the device path) --------------
    def result_cache_ids(self):
        return self.result_cache.keys()

    def result_cache_meta(self, rdd_id):
        return self.result_cache[rdd_id]

    def _next_seq(self):
        self._hbm_seq += 1
        return self._hbm_seq

    def store_result(self, rdd_id, batch):
        if rdd_id in self.result_cache:
            self.drop_result(rdd_id)        # re-store: no double count
        nbytes = sum(int(l.nbytes) for l in batch.cols)
        self.result_cache[rdd_id] = {
            "treedef": batch.treedef, "leaves": batch.cols,
            "counts": batch.counts, "nbytes": nbytes,
            "seq": self._next_seq(),
            "specs": [(np.dtype(l.dtype), tuple(l.shape[2:]))
                      for l in batch.cols],
        }
        self._result_bytes += nbytes
        self._evict_hbm(keep_rdd=rdd_id)

    def drop_result(self, rdd_id):
        meta = self.result_cache.pop(rdd_id, None)
        if meta:
            self._result_bytes -= meta["nbytes"]

    def _evict_hbm(self, keep_sid=None, keep_rdd=None):
        """One budget across BOTH HBM tiers (shuffle outputs + cached
        results): shed the least-recently-used entries until under
        conf.SHUFFLE_HBM_BUDGET.

        Shuffle stores SPILL TO DISK instead of dropping (ISSUE 9
        satellite): each bucket round-trips through the host bridge
        into the standard on-disk bucket files — crc-framed erasure
        SHARD CONTAINERS when a shuffle code is active, so coded reads
        still decode — and the map-output locations follow the move.
        A later consumer pays a disk read, never a lineage recompute
        (the pre-service behavior on eviction).  COMPLETED jobs' stores
        spill first, least-recently-fetched order; a store the live
        jobs still grow (keep_sid) stays pinned.  Cached results still
        drop — they recompute on next use and have no disk format.
        A spill that fails (disk full) falls back to dropping the
        store, which is exactly the old lineage-recovery contract."""
        budget = conf.SHUFFLE_HBM_BUDGET
        pinned = set()      # in-flight stores (outputs not registered)
        while self._store_bytes + self._result_bytes > budget:
            # spilled (host_runs) stores hold no HBM: evicting them
            # frees nothing and destroys on-disk runs
            live = self.live_jobs
            cands = [(meta["seq"], "sid", sid)
                     for sid, meta in self.shuffle_store.items()
                     if sid != keep_sid and sid not in pinned
                     and "host_runs" not in meta
                     and meta.get("job") not in live]
            if not cands:
                # every store belongs to a RUNNING job: prefer
                # dropping recomputable cached results before touching
                # a live job's working set
                cands = [(meta["seq"], "rdd", rid)
                         for rid, meta in self.result_cache.items()
                         if rid != keep_rdd]
            if not cands:
                # still over: spill live jobs' stores too (quota
                # arbitration — the job with the most HBM pays first,
                # least-recently-fetched bucket of that job)
                by_job = {}
                for sid, meta in self.shuffle_store.items():
                    if sid == keep_sid or sid in pinned \
                            or "host_runs" in meta:
                        continue
                    by_job.setdefault(meta.get("job"), []).append(
                        (meta["seq"], sid, meta["nbytes"]))
                if by_job:
                    biggest = max(
                        by_job.values(),
                        key=lambda ss: sum(b for _, _, b in ss))
                    seq, sid, _ = min(biggest)
                    cands = [(seq, "sid", sid)]
            if not cands:
                break
            _, kind, victim = min(cands)
            if kind == "sid":
                try:
                    self._spill_shuffle_to_disk(victim)
                except _StoreInFlight:
                    # its producing stage hasn't reported outputs yet:
                    # the buckets are in flight — pinned, try the next
                    # candidate instead
                    pinned.add(victim)
                except Exception as e:
                    logger.warning(
                        "spill of HBM shuffle %d failed (%s); "
                        "dropping it — consumers recover via lineage",
                        victim, e)
                    self.drop_shuffle(victim)
            else:
                logger.debug("evicting HBM cached result %d", victim)
                self.drop_result(victim)

    def _spill_shuffle_to_disk(self, sid):
        """Round-trip one HBM shuffle store into the standard on-disk
        bucket layout (shard containers when coding is active) and
        re-point its map-output locations at the files.  Runs under
        the mesh lock (the export reads device slices)."""
        from dpark_tpu.env import env
        from dpark_tpu.shuffle import LocalFileShuffle
        store = self.shuffle_store[sid]
        with self._mesh_lock:
            locs = env.map_output_tracker.get_outputs(sid)
            if locs is None:
                # the producing stage hasn't completed/registered yet:
                # its buckets are in flight — treat as pinned
                raise _StoreInFlight(sid)
            n_reduce = int(store.get(
                "n_reduce",
                layout.host_read(store["counts"]).shape[-1]))
            uri = None
            for map_id, old in enumerate(locs):
                if old is None or not str(old).startswith("hbm://"):
                    continue        # lost or already host-resident
                buckets = [self._export_bucket(sid, map_id, r)
                           for r in range(n_reduce)]
                uri = LocalFileShuffle.write_buckets(
                    sid, map_id, buckets)
            if uri is None:
                uri = LocalFileShuffle.get_server_uri()
            new_locs = [uri if (l and str(l).startswith("hbm://"))
                        else l for l in locs]
            env.map_output_tracker.register_outputs(sid, new_locs)
            notify = self._spill_notify
            if notify is not None:
                # the owning scheduler re-points its Stage.output_locs
                # so a later job reusing the stage sees disk locations
                notify(sid, uri)
            logger.info("spilled HBM shuffle %d (%d bytes) to disk "
                        "buckets at %s", sid, store["nbytes"], uri)
            self.drop_shuffle(sid, reason="spill")

    def _finish_stage(self, plan, outs):
        if plan.epilogue is None:
            counts, leaves = outs[0], list(outs[1:])
            batch = layout.Batch(plan.out_treedef, leaves, counts)
            encoded = (plan.source[0] == "hbm"
                       and self.shuffle_store.get(
                           plan.source[1].shuffle_id, {})
                       .get("encoded_keys", False))
            if plan.stage is not None \
                    and getattr(plan.stage.rdd, "should_cache", False) \
                    and not plan.group_output and not encoded:
                # encoded batches never enter the result cache: a later
                # device stage would see raw ids where the user expects
                # strings
                self.store_result(plan.stage.rdd.id, batch)
            if getattr(plan, "count_only", False):
                # count() consumes only cardinalities: one scalar-leaf
                # read instead of egesting every row.  group_output
                # counts KEYS — the no-combine reduce leaves each
                # device's rows key-sorted, so distinct keys count on
                # device with one boundary scan
                if plan.group_output:
                    counts = layout.host_read(
                        self._distinct_key_counts(
                            batch, nk=getattr(plan, "src_nk", 1) or 1))
                else:
                    counts = layout.host_read(batch.counts)
                return ("counts", [int(c) for c in counts])
            monoid = getattr(plan, "reduce_monoid", None)
            if (monoid is not None and not plan.group_output
                    and len(batch.cols) == 1
                    and batch.cols[0].ndim == 2
                    # bools have no monoid identity table; integer mul
                    # overflows almost immediately where the host fold
                    # used exact Python ints — both keep the egest path
                    and np.dtype(batch.cols[0].dtype).kind in "if"
                    and not (monoid == "mul"
                             and np.dtype(batch.cols[0].dtype).kind
                             == "i")):
                # reduce(provable monoid) over scalar records: one
                # per-device masked reduction, ndev scalars egested.
                # Float add/mul REASSOCIATES here (per-device tree
                # reduction vs the host's partition-order fold): results
                # can differ from the local master in low-order bits —
                # parity checks must compare floats with a tolerance
                # (ADVICE r4; test_parity_fuzz does)
                vals, lo, hi = (layout.host_read(a) for a in
                                self._monoid_reduce(batch, monoid))
                counts = layout.host_read(batch.counts)
                intk = vals.dtype.kind == "i"
                safe = True
                if intk and monoid == "add":
                    # host fold used exact Python ints: only answer
                    # from the device when the i64 sum provably cannot
                    # have wrapped (n * max|v| bound; empty devices
                    # hold identities — exclude them from the bound)
                    total = int(counts.sum())
                    nz = counts > 0
                    mabs = (max(abs(int(lo[nz].min())),
                                abs(int(hi[nz].max())))
                            if nz.any() else 0)
                    safe = total * mabs < 2 ** 62
                if safe:
                    py = float if not intk else int
                    return ("reduced", [(py(v), int(n))
                                        for v, n in zip(vals, counts)])
            top = getattr(plan, "top_candidate", None)
            if top is not None and not plan.group_output:
                # top(k): select each device's k best rows ON DEVICE
                # and egest ndev*k rows instead of the whole batch
                # (exact semantics: the per-partition _TopN then runs
                # on its own partition's pre-top — top-k of top-k —
                # and the driver heap merge is unchanged).  Through a
                # real tunnel this is the difference between one tiny
                # readback and streaming every row at ~37 MB/s.
                kspec = fuse.classify_top_key(
                    top[1], plan.out_treedef, plan.out_specs, encoded)
                if kspec is None and top[1] is not None \
                        and not encoded:
                    # ranged-int probe: integer key EXPRESSIONS ride
                    # the device when the interval check over the
                    # batch's actual per-column min/max proves no
                    # intermediate can leave int64 (one tiny masked
                    # min/max program per int column)
                    kspec = fuse.classify_top_key(
                        top[1], plan.out_treedef, plan.out_specs,
                        encoded, col_ranges=self._int_col_ranges(batch))
                if kspec is not None:
                    batch = self._device_topk(plan, batch, kspec,
                                              top[0], top[2])
                    plan.topk_used = True
            rows_per_part = layout.egest(batch)
            if plan.group_output:
                # bare groupByKey: rows arrive key-sorted; group runs
                # into (k, [v]) host-side
                import itertools as _it
                grouped = []
                for rows in rows_per_part:
                    parts = []
                    for k, grp in _it.groupby(rows, key=lambda r: r[0]):
                        parts.append((k, [r[1] for r in grp]))
                    grouped.append(parts)
                rows_per_part = grouped
            if encoded:
                store = self.shuffle_store[plan.source[1].shuffle_id]
                rows_per_part = [self._maybe_decode(store, rows)
                                 for rows in rows_per_part]
            return ("result", rows_per_part)
        dep = plan.epilogue[1]
        cnts, offs = outs[0], outs[1]
        leaves = list(outs[2:])
        return self._register_shuffle(dep, plan, {
            "leaves": leaves,            # (ndev, cap, ...) dst-sorted
            "counts": cnts,              # (ndev, R)
            "offsets": offs,             # (ndev, R)
            "no_combine": fuse.is_list_agg(dep.aggregator),
            "encoded_keys": getattr(plan, "encoded_keys", False),
            # text ingest, union concat, and resliced ingest all
            # redistribute rows across devices, so device index !=
            # logical map partition: the host bridge reads the whole
            # shuffle through map_id 0 (object-path consumers fetch
            # every reported map id; non-zero ids return empty)
            "single_map": (plan.source[0] in ("text", "union")
                           or getattr(plan, "reslice", False)),
        })

    def _int_col_ranges(self, batch):
        """Exact (lo, hi) Python ints per int64 scalar column of a
        result batch (valid rows only; None for other leaves) — the
        input of classify_top_key's ranged-int probe."""
        ranges = []
        for c in batch.cols:
            if c.ndim == 2 and np.dtype(c.dtype).kind == "i":
                try:
                    r = layout.host_read(
                        layout._masked_minmax(c, batch.counts))
                    ranges.append((int(r[0]), int(r[1])))
                except Exception:
                    ranges.append(None)
            else:
                ranges.append(None)
        return ranges

    def _device_topk(self, plan, batch, kspec, n, smallest):
        """Per-device top-n of a result batch by the classified key:
        one stable argsort per device, n rows kept (ties resolve by
        device row order — top()'s tie membership is already
        partition-order-dependent on every master)."""
        cap = batch.cap
        nlv = len(batch.cols)
        dtypes = tuple(str(c.dtype) for c in batch.cols)
        if kspec[0] == "leaf":
            skey = ("leaf", kspec[1])
        else:
            skey = ("fn", fuse.fn_key(kspec[1]))
        key = ("topk", plan.program_key, cap, nlv, dtypes, n,
               bool(smallest), skey)
        if key not in self._compiled:
            if kspec[0] == "fn":
                row_fn = fuse._row_fn(kspec[1], plan.out_treedef)
                vkey = jax.vmap(lambda *lv: row_fn(*lv)[0])
            leaf_i = kspec[1] if kspec[0] == "leaf" else None

            def per_device(counts, *leaves):
                nv = counts[0]
                lv = [l[0] for l in leaves]
                kcol = lv[leaf_i] if leaf_i is not None else vkey(*lv)
                valid = jnp.arange(cap) < nv
                # VALIDITY is the primary sort key, not a key-value
                # sentinel: a real key equal to the extreme (or a
                # padding slot) must never outrank data (review
                # finding — ±inf keys tied with padding and the
                # reversal picked the padding rows).  Largest-first
                # uses an order-REVERSING bijection (-1-k for ints,
                # -k for floats) so ties stay stable in row order.
                if smallest:
                    sk = kcol
                elif jnp.issubdtype(kcol.dtype, jnp.floating):
                    sk = -kcol
                else:
                    sk = -1 - kcol
                inval = (~valid).astype(jnp.int32)
                packed = collectives._lex_sort(
                    (inval, sk) + tuple(lv), 2)
                out = [l[:n] for l in packed[2:]]
                new_n = jnp.minimum(nv, n).astype(jnp.int32)
                return (jnp.expand_dims(new_n, 0),) + tuple(
                    jnp.expand_dims(o, 0) for o in out)

            fn = _shard_map(per_device, self.mesh,
                            in_specs=(P(AXIS),) * (1 + nlv),
                            out_specs=(P(AXIS),) * (1 + nlv))
            self._compiled[key] = jax.jit(fn)
        outs = self._compiled[key](batch.counts, *batch.cols)
        return layout.Batch(batch.treedef, list(outs[1:]), outs[0])

    def _monoid_reduce(self, batch, monoid):
        """Per-device (reduced, min, max) over the valid rows of a
        single-scalar-leaf batch, each (ndev,) (empty devices yield
        identities — the caller masks them out via the counts leaf;
        min/max feed the integer-add overflow bound)."""
        from dpark_tpu.backend.tpu.bagel import _local_reduce
        from dpark_tpu.bagel import monoid_identity
        cap = batch.cap
        col = batch.cols[0]
        ident = monoid_identity(monoid, col.dtype)
        lo_id = monoid_identity("min", col.dtype)
        hi_id = monoid_identity("max", col.dtype)
        key = ("monoid_reduce", monoid, cap, str(col.dtype))
        if key not in self._compiled:
            def per_device(counts, vals):
                n, x = counts[0], vals[0]
                valid = jnp.arange(cap) < n
                masked = jnp.where(valid, x, ident)
                lo = jnp.min(jnp.where(valid, x, lo_id))
                hi = jnp.max(jnp.where(valid, x, hi_id))
                return tuple(jnp.expand_dims(o, 0) for o in
                             (_local_reduce(monoid, masked), lo, hi))
            fn = _shard_map(per_device, self.mesh,
                            in_specs=(P(AXIS),) * 2,
                            out_specs=(P(AXIS),) * 3)
            self._compiled[key] = jax.jit(fn)
        return self._compiled[key](batch.counts, col)

    def _distinct_key_counts(self, batch, nk=1):
        """(ndev,) distinct-key counts of a per-device KEY-SORTED batch
        (the no-combine reduce's row order) — group cardinality without
        egesting a single row.  `nk` key columns: a boundary is ANY of
        them changing (tuple keys group on every column)."""
        cap = batch.cap
        kcols = batch.cols[:nk]
        key = ("distinct", cap, nk,
               tuple(str(k.dtype) for k in kcols))
        if key not in self._compiled:
            def per_device(counts, *keys):
                n = counts[0]
                ks = [k[0] for k in keys]
                idx = jnp.arange(cap)
                valid = idx < n
                changed = ks[0] != jnp.roll(ks[0], 1)
                for kc in ks[1:]:
                    changed = changed | (kc != jnp.roll(kc, 1))
                bound = valid & ((idx == 0) | changed)
                return (jnp.expand_dims(
                    jnp.sum(bound).astype(jnp.int32), 0),)
            fn = _shard_map(per_device, self.mesh,
                            in_specs=(P(AXIS),) * (1 + nk),
                            out_specs=(P(AXIS),))
            self._compiled[key] = jax.jit(fn)
        (out,) = self._compiled[key](batch.counts, *kcols)
        return out

    def _register_shuffle(self, dep, plan, store):
        """Shared HBM shuffle-store bookkeeping (re-run guard, byte
        accounting, eviction) for the in-core and streamed write paths."""
        sid = dep.shuffle_id
        if sid in self.shuffle_store:
            self.drop_shuffle(sid)          # re-run: no double count
        store["out_treedef"] = plan.out_treedef
        store["out_specs"] = plan.out_specs
        # composite keys span the first key_cols columns: readers (the
        # gather sort, run premerger, export bridge) order and group by
        # ALL of them, not just column 0
        store["key_cols"] = getattr(plan, "epi_nk", 1) or 1
        store["nbytes"] = sum(int(l.nbytes) for l in store["leaves"])
        store["seq"] = self._next_seq()
        # eviction metadata (ISSUE 9 satellite): the reduce width the
        # disk spiller writes bucket files for, and the owning job —
        # completed jobs' stores spill FIRST when a new exchange would
        # blow conf.SHUFFLE_HBM_BUDGET
        store["n_reduce"] = dep.partitioner.num_partitions
        store["job"] = getattr(self._job_tls, "job", None)
        self.shuffle_store[sid] = store
        self._store_bytes += store["nbytes"]
        if trace._PLANE is not None:
            # ledger plane (ISSUE 15): HBM residency starts — the
            # byte-seconds account accrues from here to the matching
            # hbm.release (drop or spill-to-disk eviction)
            trace.event("hbm.store", "exec", sid=sid,
                        bytes=store["nbytes"],
                        job=store["job"])
        self._evict_hbm(keep_sid=sid)
        self._observe_combine_ratio(dep, plan, store)
        return ("shuffle", sid)

    def _observe_combine_ratio(self, dep, plan, store):
        """Adaptive-store observation (ISSUE 7 decision point 4): a
        COMBINING shuffle write over a columnar ingest source knows
        both its input rows and its post-combine stored rows — the
        observed combine ratio prices the map-side-combine rewrite for
        this call site on the next run.  Never raises; no-op with
        DPARK_ADAPT=off."""
        from dpark_tpu import adapt
        try:
            if not adapt.enabled() or fuse.is_list_agg(dep.aggregator):
                return
            site = (getattr(dep, "adapt_combine_site", None)
                    or getattr(dep, "adapt_site", None))
            counts = store.get("counts")
            if not site or counts is None \
                    or plan.source[0] != "ingest":
                return
            rows_in = sum(len(s) for s in plan.source[1]._slices or ())
            rows_out = int(layout.host_read(counts).sum())
            if rows_in:
                adapt.record_combine_ratio(site, rows_in, rows_out)
        except Exception as e:
            logger.debug("combine-ratio observation failed: %s", e)

    def _run_exchange_and_reduce(self, plan):
        dep = plan.source[1]
        store = self.shuffle_store[dep.shuffle_id]
        store["seq"] = self._next_seq()              # LRU touch
        leaves = store["leaves"]
        nleaves = len(leaves)
        recv_rounds, cnt_rounds, slot = self._exchange_all(
            leaves, store["counts"], store["offsets"])
        rounds = len(recv_rounds)
        # receive buffers are exchange outputs, dead after the reduce —
        # donate them on a real multi-device exchange (the ndev==1
        # identity exchange aliases the store's leaves: never donated)
        reduce_fn = self._compile_reduce(
            plan, rounds, slot, nleaves,
            donate=self._donation_enabled() and self.ndev > 1)
        bounds = self._bounds_arg(plan)
        args = ([bounds] if bounds is not None else []) + list(cnt_rounds)
        for r in range(rounds):
            args.extend(recv_rounds[r])
        return reduce_fn(*args)

    # ------------------------------------------------------------------
    # device segmented apply (fuse.SegMapOp — ISSUE 4 tentpole): an
    # arbitrary traceable per-group function over groupByKey output
    # runs as a vmap over power-of-two padded group buckets.  Two-phase
    # like the device join: sort the rows (exchange, or premerged
    # spilled runs), read the bucket histogram back, compile the apply
    # program with that static layout.
    # ------------------------------------------------------------------
    def _run_seg_map(self, plan):
        dep = plan.source[1]
        store = self.shuffle_store[dep.shuffle_id]
        store["seq"] = self._next_seq()
        nk = plan.ops[0].nk if isinstance(plan.ops[0], fuse.SegMapOp) \
            else getattr(plan, "src_nk", 1) or 1
        if "host_runs" in store:
            batch = self._seg_batch_from_runs(store)
            hist_np = None
        else:
            counts, hist, leaves = self._seg_exchange_sorted(store, nk)
            batch = layout.Batch(store["out_treedef"], leaves, counts)
            hist_np = layout.host_read(hist)
            self._observe_seg_skew(dep, batch, hist_np)
        op = plan.ops[0]
        extra = ()
        if isinstance(op, fuse.SegMapOp):
            op.layout = self._seg_bucket_layout(op.nk, batch, hist_np)
            extra = (op.layout,)
        return self._run_narrow(plan, batch, extra_key=extra)

    def _observe_seg_skew(self, dep, batch, hist_np):
        """Adaptive-store observation (ISSUE 7 decision point 3): the
        segment path's bucket histogram — computed anyway for the
        apply layout — gives per-key-group sizes for free.  Record
        total rows, group count, the largest group's approximate size
        (size classes are powers of two), and the reduce width, keyed
        by the grouping call site: a dominant group widens the next
        run's default reduce side.  The same (rows, groups) pair
        doubles as the combine-ratio signal that can re-enable the
        map-side rewrite once the ratio drops.  Never raises."""
        from dpark_tpu import adapt
        try:
            if not adapt.enabled():
                return
            site = getattr(dep, "adapt_site", None)
            if not site:
                return
            rows = int(layout.host_read(batch.counts).sum())
            per_bucket = np.asarray(hist_np).max(axis=0)
            nonzero = np.nonzero(per_bucket)[0]
            if not rows or not len(nonzero):
                return
            groups = int(np.asarray(hist_np).sum())
            max_group = 1 << int(nonzero[-1])
            adapt.record_skew(site, rows, groups, max_group,
                              dep.partitioner.num_partitions)
            adapt.record_combine_ratio(site, rows, groups)
        except Exception as e:
            logger.debug("seg-skew observation failed: %s", e)

    def _seg_exchange_sorted(self, store, nk):
        """The seg path's gather: exchange + key sort, with the bucket
        HISTOGRAM computed inside the same program — one dispatch and
        one readback fewer per run than a separate histogram pass."""
        leaves = store["leaves"]
        nleaves = len(leaves)
        recv_rounds, cnt_rounds, slot = self._exchange_all(
            leaves, store["counts"], store["offsets"])
        rounds = len(recv_rounds)
        key = ("seg_gather", rounds, slot, nleaves, nk,
               tuple(str(l.dtype) for l in leaves))
        if key not in self._compiled:
            def per_device(*args):
                cnts = [c[0] for c in args[:rounds]]
                bufs = args[rounds:]
                recvs = []
                for r in range(rounds):
                    recvs.append([bufs[r * nleaves + li][0]
                                  for li in range(nleaves)])
                flat, mask = collectives.flatten_received(recvs, cnts)
                packed = collectives._lex_sort(tuple(flat), nk)
                n = jnp.sum(mask).astype(jnp.int32)
                hist, _ = collectives.bucket_histogram(
                    list(packed[:nk]), n)
                out = (n, hist) + tuple(packed)
                return tuple(jnp.expand_dims(o, 0) for o in out)

            fn = _shard_map(per_device, self.mesh,
                            in_specs=(P(AXIS),) * (rounds
                                                   + rounds * nleaves),
                            out_specs=(P(AXIS),) * (2 + nleaves))
            self._compiled[key] = jax.jit(fn)
        args = list(cnt_rounds)
        for r in range(rounds):
            args.extend(recv_rounds[r])
        outs = self._compiled[key](*args)
        return outs[0], outs[1], list(outs[2:])

    def _seg_bucket_layout(self, nk, batch, hist=None):
        """((bucket, width, group_capacity), ...) for the batch's
        power-of-two group-size classes: read from the gather program's
        fused histogram (already on host) when available, else one tiny
        histogram program (the spilled-run ingest path).  Group
        capacities round to power-of-two classes so data drift between
        runs (DStream ticks) reuses compiled apply programs."""
        if hist is None:
            cap = batch.cap
            key = ("seghist", cap, nk,
                   tuple(str(c.dtype) for c in batch.cols[:nk]))
            if key not in self._compiled:
                def per_device(counts, *kcols):
                    h, _ = collectives.bucket_histogram(
                        [k[0] for k in kcols], counts[0])
                    return (jnp.expand_dims(h, 0),)
                fn = _shard_map(per_device, self.mesh,
                                in_specs=(P(AXIS),) * (1 + nk),
                                out_specs=(P(AXIS),))
                self._compiled[key] = jax.jit(fn)
            (hist,) = self._compiled[key](batch.counts,
                                          *batch.cols[:nk])
        gmax = layout.host_read(hist).max(axis=0)
        lay = tuple((b, 1 << b, layout.round_capacity(int(g)))
                    for b, g in enumerate(gmax.tolist()) if g)
        return lay or ((0, 1, 8),)

    def _partition_run_cols(self, store, rid):
        """One spilled partition's columns, KEY-SORTED (the background
        premerger's single run when it got there first, sorted here
        otherwise) — shared by the export bridge and the seg-map batch
        loader so the run-reading convention lives once.  None when the
        partition has no runs."""
        runs = store["host_runs"]
        if rid >= len(runs) or not runs[rid]:
            return None
        premerge = store.get("premerge")
        if premerge is not None:
            paths, presorted = premerge.ensure(rid)
        else:
            paths, presorted = runs[rid], False
        if not paths:
            return None
        pieces = [self._read_run(p) for p in paths]
        cols = [np.concatenate([pt[li] for pt in pieces])
                for li in range(len(pieces[0]))]
        if not presorted and len(cols[0]) > 1:
            nk = min(store.get("key_cols", 1) or 1, len(cols))
            order = (np.argsort(cols[0], kind="stable") if nk == 1
                     else np.lexsort(tuple(cols[:nk][::-1])))
            cols = [c[order] for c in cols]
        return cols

    def _seg_batch_from_runs(self, store):
        """Premerged spilled runs -> per-device key-sorted Batch:
        reduce partition d loads on device d (analyze only admits
        r <= ndev spilled sources for segment ops).  A whole partition
        loads at once — groups must be contiguous for the segment scan
        — so partitions whose columns would blow the HBM budget raise
        here and the scheduler's object fallback consumes the runs
        through the (streaming) export bridge instead."""
        from dpark_tpu.rdd import _ColumnarSlice
        specs = store["out_specs"]
        budget = conf.SHUFFLE_HBM_BUDGET // 2
        total = 0
        parts = []
        for d in range(self.ndev):
            cols = self._partition_run_cols(store, d)
            if cols is None:
                parts.append(_ColumnarSlice(
                    [np.zeros((0,) + shape, dt) for dt, shape in specs]))
                continue
            total += sum(int(c.nbytes) for c in cols)
            if total > budget:
                raise ValueError(
                    "spilled partitions (%d MB so far) exceed the "
                    "seg-map load budget (%d MB): host merge consumes "
                    "the runs" % (total >> 20, budget >> 20))
            parts.append(_ColumnarSlice(cols))
        return layout.ingest(self.mesh, parts, store["out_treedef"],
                             specs)

    # ------------------------------------------------------------------
    # union-source stages (the windowed-stream shape, BASELINE config
    # #4): each branch materializes to a device Batch through its own
    # sub-plan (epilogue=None, via _source_outs), the batches
    # concatenate ON DEVICE, and the stage's narrow ops + shuffle write
    # run over the whole union
    # ------------------------------------------------------------------
    def _concat_batches(self, batches):
        """Per-device concatenation of same-spec Batches into one."""
        if len(batches) == 1:
            return batches[0]
        counts = [layout.host_read(b.counts) for b in batches]
        total = np.sum(np.stack(counts), axis=0)
        cap_out = layout.round_capacity(int(total.max()) or 1)
        caps = tuple(b.cap for b in batches)
        nleaves = len(batches[0].cols)
        dtypes = tuple(str(c.dtype) for c in batches[0].cols)
        jitted = self._compile_concat(len(batches), caps, dtypes,
                                      nleaves, cap_out)
        args = [b.counts for b in batches]
        for b in batches:
            args.extend(b.cols)
        outs = jitted(*args)
        return layout.Batch(batches[0].treedef, list(outs[1:]), outs[0])

    def _compile_concat(self, k, caps, dtypes, nleaves, cap_out):
        """Program: (counts x k, leaves x k) -> (total, leaves) with each
        device's valid rows packed contiguously.  Writes go into a
        sum(caps)-sized scratch (dynamic_update_slice never clamps:
        offset_j + cap_j <= sum(caps[:j+1])), then slice to cap_out.
        Input leaves are per-branch narrow outputs, dead after the
        concat — donated for in-place reuse when enabled."""
        donate = self._donation_enabled()
        key = ("concat", k, caps, dtypes, nleaves, cap_out, donate)
        if key in self._compiled:
            return self._compiled[key]
        scratch = max(sum(caps), cap_out)

        def per_device(*args):
            cnts = [c[0] for c in args[:k]]
            leaves = args[k:]
            total = cnts[0]
            for j in range(1, k):
                total = total + cnts[j]
            outs = []
            for li in range(nleaves):
                segs = [leaves[j * nleaves + li][0] for j in range(k)]
                buf = jnp.zeros((scratch,) + segs[0].shape[1:],
                                segs[0].dtype)
                off = jnp.int32(0)
                for j in range(k):
                    idx = (off,) + (0,) * (segs[j].ndim - 1)
                    buf = jax.lax.dynamic_update_slice(
                        buf, segs[j].astype(buf.dtype), idx)
                    off = off + cnts[j].astype(jnp.int32)
                outs.append(buf[:cap_out])
            out = (jnp.asarray(total, jnp.int32),) + tuple(outs)
            return tuple(jnp.expand_dims(o, 0) for o in out)

        fn = _shard_map(per_device, self.mesh,
                        in_specs=(P(AXIS),) * (k + k * nleaves),
                        out_specs=(P(AXIS),) * (1 + nleaves))
        jitted = jax.jit(fn, donate_argnums=tuple(
            range(k, k + k * nleaves)) if donate else ())
        self._compiled[key] = jitted
        return self._compiled[key]

    # ------------------------------------------------------------------
    # out-of-core streaming shuffle (SURVEY.md 7.2 item 4): input bigger
    # than a chunk runs in ingest -> exchange waves so HBM holds one
    # chunk (plus combined state for monoid reduces).  Covers columnar
    # parallelize AND text-source stages; no-combine shuffles (sortByKey
    # range exchange, groupByKey, partitionBy) spill key-sorted runs to
    # host disk and merge lazily at the export bridge.
    # ------------------------------------------------------------------
    def _stream_mode(self, plan):
        """None, or ("combine"|"nocombine", wave iterator).  Each wave
        is a list of per-device _ColumnarSlice parts."""
        if plan.epilogue is None:
            return None
        dep = plan.epilogue[1]
        no_combine = fuse.is_list_agg(dep.aggregator)
        monoid = None if no_combine else fuse.classify_merge(
            dep.aggregator.merge_combiners)
        # ONE eligibility predicate shared with fuse's analyze-time
        # logical_spill gate — divergence would turn the run_stage
        # safety net into a user-facing error
        if plan.source[0] == "ingest":
            if not fuse._big_columnar(plan.source[1]):
                return None
            row_bytes = fuse._columnar_row_bytes(plan.source[1]._slices)
            chunk = conf.stream_chunk_rows(row_bytes)
            self.last_wave_budget = (int(chunk), row_bytes)
            self._check_wave_oom(chunk)
            waves = self._wave_iter_columnar(plan, chunk)
        elif plan.source[0] == "text":
            if not fuse._big_text(plan.stage):
                return None
            sizes = [fuse._split_bytes(sp)
                     for sp in plan.stage.rdd.splits]
            waves = self._wave_iter_text(plan, sizes)
        else:
            return None
        # host tokenize/slice lookahead: STREAM_PIPELINE_DEPTH waves
        # ahead (the pre-pipeline behavior was a fixed depth of 1;
        # depth 0 keeps that single-wave lookahead — "off" only
        # disables the NEW ingest/readback overlap stages)
        tok_depth = max(1, conf.STREAM_PIPELINE_DEPTH)
        if no_combine:
            return ("nocombine", _prefetch_iter(waves, depth=tok_depth))
        # monoids combine via segment scatters; any other TRACEABLE
        # merge streams through the segmented associative scan — ONE
        # probe (shared with compile time), memoized per plan
        merge_fn, _ = self._merge_probe(plan)
        if monoid is not None or merge_fn is not None:
            if dep.partitioner.num_partitions <= self.ndev:
                return ("combine", _prefetch_iter(waves,
                                                  depth=tok_depth))
            # traceable merge but r exceeds the mesh: the per-device
            # combined state cannot hold r partitions — ride the
            # spilled-run stream, which pre-reduces each wave per
            # (rid, key) on device before spilling
            return ("nocombine", _prefetch_iter(waves, depth=tok_depth))
        # UNTRACEABLE merge (object-valued combiner semantics the
        # tracer can't see): ride the spilled-run stream — device
        # exchange of created combiners, key-sorted runs on host disk,
        # user's merge_combiners folded per key at export (the
        # reference's external merger; VERDICT r2 ask #7)
        return ("nocombine", _prefetch_iter(waves, depth=tok_depth))

    @staticmethod
    def _check_wave_oom(chunk_rows):
        """Deterministic stand-in for a device HBM ceiling
        (conf.EMULATED_WAVE_OOM_ROWS, bench/test aid): a wave budget
        over the ceiling raises the RESOURCE_EXHAUSTED class the
        degradation ladder halves on, so the OOM ladder — and the
        adaptive store's learned budgets (ISSUE 7) — can be exercised
        on backends that report no memory limit (XLA:CPU)."""
        limit = getattr(conf, "EMULATED_WAVE_OOM_ROWS", 0)
        if limit and chunk_rows > limit:
            raise MemoryError(
                "RESOURCE_EXHAUSTED: emulated HBM ceiling: wave "
                "budget %d rows/device exceeds "
                "DPARK_EMULATED_WAVE_OOM_ROWS=%d"
                % (chunk_rows, limit))

    def _merge_probe(self, plan):
        """Memoized (merge_fn, monoid) for the plan's shuffle write —
        the same probe _epilogue_merge runs at compile time."""
        if not hasattr(plan, "_merge_probe_result"):
            plan._merge_probe_result = self._epilogue_merge(plan)
        return plan._merge_probe_result

    def _wave_iter_columnar(self, plan, chunk=None):
        from dpark_tpu.rdd import _ColumnarSlice
        slices = plan.source[1]._slices
        if chunk is None:      # caller usually passes the budget it
            # already derived (one store consult per stage, not two)
            chunk = conf.stream_chunk_rows(
                fuse._columnar_row_bytes(slices))
        nchunks = (max(len(s) for s in slices) + chunk - 1) // chunk
        for c in range(nchunks):
            yield [
                _ColumnarSlice([col[c * chunk:(c + 1) * chunk]
                                for col in s.columns])
                for s in slices]

    def _wave_iter_text(self, plan, sizes):
        """Groups of splits whose byte size fits one wave budget; each
        wave's splits tokenize/encode concurrently."""
        td = self._token_dict() if plan.encoded_keys else None
        state = {"canonical": plan.canonical, "checked": False}
        budget = conf.STREAM_TEXT_BYTES
        group, acc = [], 0
        for sp, size in zip(plan.stage.rdd.splits, sizes):
            group.append(sp)
            acc += size if size > 0 else budget
            if acc >= budget:
                yield self._text_parts(plan, self._split_cols_parallel(
                    plan, group, td, state))
                group, acc = [], 0
        if group:
            yield self._text_parts(plan, self._split_cols_parallel(
                plan, group, td, state))

    def _ingest_stage(self, plan, waves, cap_state, stats):
        """Pipeline stage 2: host columns -> device Batch (device_put).
        Run through _prefetch_iter so wave k+1's H2D transfer overlaps
        wave k's compute; `cap_state` carries the sticky capacity class
        across waves (owned by whichever thread runs this generator).
        Yields (batch, ingest_seconds)."""
        try:
            for parts in waves:
                t0 = stats.now()
                batch = layout.ingest(self.mesh, parts, plan.in_treedef,
                                      plan.in_specs, key_leaf=0,
                                      cap_floor=cap_state[0])
                cap_state[0] = max(cap_state[0], batch.cap)
                yield batch, stats.now() - t0
        finally:
            # unwind the upstream tokenize stage too: a for loop does
            # not close an abandoned iterator on its own
            close = getattr(waves, "close", None)
            if close is not None:
                close()

    def _stream_batches(self, plan, waves, stats):
        """The ingest pipeline stage, threaded when the pipeline is on:
        wave k+1 device_puts while wave k computes (double-buffered
        ingest — up to one ingested wave queued plus one in flight)."""
        cap_state = [0]
        batches = self._ingest_stage(plan, waves, cap_state, stats)
        if conf.STREAM_PIPELINE_DEPTH > 0:
            batches = _prefetch_iter(batches, depth=1,
                                     name="dpark-wave-ingest")
        return batches

    def _note_pipeline(self, stats):
        """Live per-wave stage_info update (web UI) + the final stream
        snapshot the scheduler attaches to the stage record."""
        self.last_stream_stats = stats.snapshot()
        cb = getattr(self, "_stage_note", None)
        if cb is not None:
            try:
                cb(pipeline=self.last_stream_stats)
            except Exception:
                pass

    def _trace_stream_phases(self, stats):
        """Per-stage phase spans (trace plane, ISSUE 8) from the SAME
        snapshot scheduler.phase_table() reads, laid back-to-back from
        the stream's wall start — tools/dtrace's critical-path phase
        totals therefore reconcile with the phase table by
        construction."""
        if trace._PLANE is None or self.last_stream_stats is None:
            return
        snap = self.last_stream_stats
        ts = stats.wall_t0
        for phase, key in (("ingest_tokenize", "ingest_ms"),
                           ("narrow", "compute_ms"),
                           ("exchange", "exchange_ms"),
                           ("spill", "spill_ms")):
            dur = float(snap.get(key, 0.0) or 0.0) / 1e3
            trace.emit("phase." + phase, "phase", ts, dur,
                       waves=snap.get("waves"))
            ts += dur

    def _run_streamed_shuffle(self, plan, waves):
        dep = plan.epilogue[1]
        # classified monoids combine through segment scatters; any
        # other TRACEABLE user merge runs as a segmented associative
        # scan (_stream_mode verified it traces, same memoized probe)
        merge_fn, monoid = self._merge_probe(plan)
        donate = self._donation_enabled()
        stats = _StreamStats(conf.STREAM_PIPELINE_DEPTH, donate)
        state = None                    # (leaves, counts) combined so far
        busy_start = None               # dispatch time of state's wave
        bounds = self._bounds_arg(plan)      # loop-invariant
        slot_floor = 0                  # sticky size classes: a smaller
        # tail wave reuses earlier waves' compiled programs
        batches = self._stream_batches(plan, waves, stats)
        try:
            for c, (batch, ingest_s) in enumerate(batches):
                t_wall = time.time() if trace._PLANE is not None \
                    else 0.0
                t_disp = stats.now()
                outs = self._run_narrow(plan, batch, bounds=bounds,
                                        donate=donate)
                cnts, offs = outs[0], outs[1]
                leaves = list(outs[2:])
                t_x = stats.now()
                recv = self._exchange_all(leaves, cnts, offs,
                                          slot_floor=slot_floor,
                                          donate=donate)
                exchange_s = stats.now() - t_x
                slot_floor = max(slot_floor, recv[2])
                if state is not None:
                    # deferred from the PREVIOUS wave: its async counts
                    # copy has been in flight through this wave's ingest
                    # + narrow + exchange, so this read doesn't stall
                    state = self._shrink_state(state)
                    stats.add_busy(busy_start, stats.now())
                state = self._merge_into_state(plan, state, recv, monoid,
                                               merge_fn, donate=donate)
                busy_start = t_disp
                stats.wave_done(ingest_s,
                                (stats.now() - t_disp) - exchange_s,
                                exchange_s)
                self._note_pipeline(stats)
                if trace._PLANE is not None:
                    trace.emit("wave", "exec", t_wall,
                               time.time() - t_wall, wave=c,
                               sig=_plan_sig(plan))
                logger.debug("streamed wave %d", c + 1)
        finally:
            close = getattr(batches, "close", None)
            if close is not None:
                close()
        leaves, counts = self._shrink_state(state)
        stats.add_busy(busy_start, stats.now())
        self._note_pipeline(stats)
        self._trace_stream_phases(stats)
        return self._register_shuffle(dep, plan, {
            "leaves": leaves, "counts": counts,
            "pre_reduced": True,        # device d holds reduce part d
            "no_combine": False,
            "encoded_keys": getattr(plan, "encoded_keys", False),
            "single_map": plan.source[0] == "text",
        })

    def _compile_stream_nocombine(self, plan, cap, nleaves_in, r,
                                  in_dtypes=(), donate=False):
        """Map-side program for the spilled-run stream: narrow ops, then
        LOGICAL partition assignment (rid in [0, r), r may exceed the
        mesh), then bucketize by rid % ndev with rid riding along as an
        extra column.  `donate` reuses the ingest leaves in place (they
        are dead after this program in the wave loop)."""
        key = ("snc", plan.program_key, cap, nleaves_in, r, in_dtypes,
               donate)
        if key in self._compiled:
            return self._compiled[key]
        faults.hit("executor.compile")     # chaos site: per cache miss
        if trace._PLANE is not None:
            trace.event("compile", "exec", program="snc", cap=cap,
                        sig=_plan_sig(plan))
        ops = plan.ops
        ndev = self.ndev
        has_bounds = plan.epi_bounds is not None
        ascending = (plan.epi_spec[1] if plan.epi_spec[0] == "range"
                     else True)
        # the rid column rides the exchange only when needed: with
        # r <= ndev the receiving device IS the logical partition
        carry_rid = r > ndev
        # traceable merge riding the spilled stream: pre-combine equal
        # (rid, key) rows on the map side too, BEFORE the wire (the
        # program cache is safe to branch on this — program_key encodes
        # the merge function)
        merge_fn = monoid = None
        if carry_rid and not fuse.is_list_agg(plan.epilogue[1].aggregator):
            merge_fn, monoid = self._merge_probe(plan)

        nk = getattr(plan, "epi_nk", 1) or 1

        def per_device(counts, *rest):
            n = counts[0]
            bounds = rest[0][0] if has_bounds else None
            leaves = rest[1:] if has_bounds else rest
            lv = self._widen_entry(plan, [l[0] for l in leaves])
            for op in ops:
                lv, n = op.apply(lv, n)
            k = lv[0]
            capn = k.shape[0]
            valid = jnp.arange(capn) < n
            if has_bounds:
                if nk == 1:
                    rid = collectives.range_dst(k, bounds, ascending,
                                                r, valid, r=r)
                else:
                    bcols = [bounds[:, i] for i in range(nk)]
                    rid = collectives.range_dst_cols(
                        lv[:nk], bcols, ascending, r, valid, r=r)
            else:
                rid = collectives.hash_dst_cols(lv[:nk], r, valid,
                                                r=r)
            if carry_rid and (merge_fn is not None
                              or monoid is not None):
                cols, cnts, offs = collectives.bucketize_combine_rid(
                    rid, lv[:nk], lv[nk:], n, ndev, merge_fn,
                    monoid=monoid)
            elif carry_rid:
                dev = jnp.where(valid, rid % ndev,
                                ndev).astype(jnp.int32)
                cols, cnts, offs = collectives.bucketize(
                    k, [rid.astype(jnp.int64)] + lv, n, ndev, dst=dev)
            else:
                dev = jnp.where(valid, rid, ndev).astype(jnp.int32)
                cols, cnts, offs = collectives.bucketize(
                    k, lv, n, ndev, dst=dev)
            out = (cnts, offs) + tuple(cols)
            return tuple(jnp.expand_dims(o, 0) for o in out)

        n_in = 1 + nleaves_in + (1 if has_bounds else 0)
        n_out = 2 + (1 if carry_rid else 0) + len(plan.out_specs)
        fn = _shard_map(per_device, self.mesh,
                        in_specs=(P(AXIS),) * n_in,
                        out_specs=(P(AXIS),) * n_out)
        leaf0 = 1 + (1 if has_bounds else 0)
        self._compiled[key] = jax.jit(fn, donate_argnums=tuple(
            range(leaf0, leaf0 + nleaves_in)) if donate else ())
        return self._compiled[key]

    def _spill_wave(self, spool, runs, carry_rid, wave,
                    sorted_batch, writer, stats):
        """Host side of one wave's spill: read the (rid, key)-sorted
        columns back (the D2H copy was started async when the wave's
        sort finished, so this read rides behind the NEXT wave's
        compute), slice per logical partition, and hand runs to the
        background writer (or write inline when it's disabled)."""
        t0 = stats.now()
        counts = layout.host_read(sorted_batch.counts)
        cols = [layout.host_read(l) for l in sorted_batch.cols]
        read_done = stats.now()
        for d in range(self.ndev):
            n = int(counts[d])
            if not n:
                continue
            if not carry_rid:                # device IS the partition
                path = os.path.join(spool, "%d-%d" % (d, wave))
                # COPY the slices for the background writer: views would
                # pin the whole wave's (ndev, cap) host arrays across the
                # writer queue, multiplying peak host RSS
                run_cols = [np.ascontiguousarray(col[d, :n])
                            for col in cols] if writer is not None \
                    else [col[d, :n] for col in cols]
                if writer is not None:
                    writer.put(path, run_cols)
                else:
                    self._write_run(path, run_cols)
                runs[d].append(path)
                continue
            rid = cols[0][d, :n]
            uniq = np.unique(rid)
            los = np.searchsorted(rid, uniq, side="left")
            his = np.searchsorted(rid, uniq, side="right")
            for u, lo, hi in zip(uniq.tolist(), los.tolist(),
                                 his.tolist()):
                path = os.path.join(spool, "%d-%d-%d" % (u, wave, d))
                run_cols = [np.ascontiguousarray(col[d, lo:hi])
                            for col in cols[1:]] if writer is not None \
                    else [col[d, lo:hi] for col in cols[1:]]
                if writer is not None:
                    writer.put(path, run_cols)
                else:
                    self._write_run(path, run_cols)
                runs[int(u)].append(path)
        stats.add_spill(stats.now() - t0, wave=wave)
        return read_done

    def _run_streamed_nocombine(self, plan, waves):
        """No-combine shuffle (sortByKey range exchange, groupByKey,
        partitionBy) over big input: each wave exchanges (with the
        LOGICAL partition id riding along when r exceeds the mesh),
        sorts by (rid, key) on device, and spills one key-sorted COLUMN
        run per logical partition to host disk; the export bridge
        premerges a partition's runs in the background once the stream
        ends (see _RunPremerger).  HBM holds one wave (one copy with
        donation on); host RAM holds one wave of columns (no Python row
        objects until the reduce).  r may exceed the mesh size — the
        cure for partition-sized reduce memory.

        The wave loop is a pipeline: while wave k computes on device,
        wave k+1 is device_putting (ingest thread), wave k-1's columns
        — whose D2H copy was started when its sort was dispatched —
        are being read back, split, and handed to the spill-writer
        thread.  STREAM_PIPELINE_DEPTH=0 restores the serial loop."""
        from dpark_tpu.env import env
        dep = plan.epilogue[1]
        r = dep.partitioner.num_partitions
        # unique per run: a re-run must never write into (then delete,
        # via the old store's drop_shuffle) the same directory
        self._spool_seq = getattr(self, "_spool_seq", 0) + 1
        spool = os.path.join(env.workdir, "hbmruns", "%d-%d"
                             % (dep.shuffle_id, self._spool_seq))
        os.makedirs(spool, exist_ok=True)
        runs = [[] for _ in range(r)]
        bounds = self._bounds_arg(plan)
        carry_rid = r > self.ndev
        # TRACEABLE merge riding the spilled stream (r > mesh): each
        # wave pre-reduces per (rid, key) on device before spilling, so
        # runs hold one combiner per distinct key per wave instead of
        # every row; export still folds across waves with the user's
        # merge_combiners (host_combine below)
        pre_merge = pre_monoid = None
        if carry_rid and not fuse.is_list_agg(dep.aggregator):
            pre_merge, pre_monoid = self._merge_probe(plan)
        donate = self._donation_enabled()
        depth = conf.STREAM_PIPELINE_DEPTH
        stats = _StreamStats(depth, donate)
        writer = _SpillWriter(self._write_run) if conf.SPILL_WRITER \
            else None
        slot_floor = 0                  # sticky size classes (see
        # _run_streamed_shuffle)
        pending = None          # (wave, sorted_batch, dispatch_time)
        batches = self._stream_batches(plan, waves, stats)
        ok = False
        try:
            for c, (batch, ingest_s) in enumerate(batches):
                t_wall = time.time() if trace._PLANE is not None \
                    else 0.0
                t_disp = stats.now()
                faults.hit("executor.dispatch")   # chaos site: per wave
                if trace._PLANE is not None:
                    trace.set_compile_sig(_plan_sig(plan))
                if aotcache._PLANE is not None:
                    aotcache.set_current_sig(
                        fuse.plan_adapt_signature(plan))
                jitted = self._compile_stream_nocombine(
                    plan, batch.cap, len(batch.cols), r,
                    tuple(str(c.dtype) for c in batch.cols),
                    donate=donate)
                args = (batch.counts,) + ((bounds,)
                                          if bounds is not None
                                          else ()) + tuple(batch.cols)
                self._capture_cost(plan, jitted, args)
                outs = jitted(*args)
                cnts, offs = outs[0], outs[1]
                leaves = list(outs[2:])      # [rid +] row leaves
                t_x = stats.now()
                recv = self._exchange_all(leaves, cnts, offs,
                                          slot_floor=slot_floor,
                                          donate=donate)
                exchange_s = stats.now() - t_x
                slot_floor = max(slot_floor, recv[2])
                nk = getattr(plan, "epi_nk", 1) or 1
                if pre_merge is not None or pre_monoid is not None:
                    sorted_batch = self._prereduce_received(
                        plan, recv, pre_merge, pre_monoid,
                        donate=donate)
                else:
                    sorted_batch = self._sort_received(
                        plan, recv,
                        nkeys=(1 + nk) if carry_rid else nk,
                        donate=donate)
                # start the wave's D2H now; the blocking read happens
                # one wave later (or immediately when depth == 0)
                _async_d2h([sorted_batch.counts] + sorted_batch.cols)
                stats.wave_done(ingest_s,
                                (stats.now() - t_disp) - exchange_s,
                                exchange_s)
                if depth <= 0:
                    read_done = self._spill_wave(
                        spool, runs, carry_rid, c, sorted_batch,
                        writer, stats)
                    stats.add_busy(t_disp, read_done)
                else:
                    if pending is not None:
                        pw, pb, pd = pending
                        read_done = self._spill_wave(
                            spool, runs, carry_rid, pw, pb,
                            writer, stats)
                        stats.add_busy(pd, read_done)
                    pending = (c, sorted_batch, t_disp)
                self._note_pipeline(stats)
                if trace._PLANE is not None:
                    trace.emit("wave", "exec", t_wall,
                               time.time() - t_wall, wave=c,
                               sig=_plan_sig(plan))
                logger.debug("streamed no-combine wave %d", c + 1)
            if pending is not None:
                pw, pb, pd = pending
                read_done = self._spill_wave(spool, runs, carry_rid,
                                             pw, pb, writer, stats)
                stats.add_busy(pd, read_done)
                pending = None
            if writer is not None:
                writer.finish()
                writer = None
            ok = True
        finally:
            close = getattr(batches, "close", None)
            if close is not None:
                close()
            if writer is not None:      # error path: drop queued runs
                writer.abort()
            if not ok:
                # the store never registered — nothing will ever call
                # drop_shuffle for this spool
                import shutil
                shutil.rmtree(spool, ignore_errors=True)
        self._note_pipeline(stats)
        self._trace_stream_phases(stats)
        host_combine = not fuse.is_list_agg(dep.aggregator)
        premerge = _RunPremerger(runs, self._read_run, self._write_run,
                                 spool,
                                 key_cols=getattr(plan, "epi_nk", 1)
                                 or 1)
        if conf.SPILL_WRITER:
            # pre-merge each partition's runs in the background NOW —
            # the reduce tasks that fetch later find a single sorted
            # run instead of paying the merge at first fetch
            premerge.start_background()
        return self._register_shuffle(dep, plan, {
            "leaves": [], "counts": None, "offsets": None,
            "host_runs": runs, "spool_dir": spool,
            "premerge": premerge,
            "no_combine": not host_combine,
            # untraceable merge: runs hold CREATED combiners (the
            # create op ran device-side); export folds equal keys with
            # the user's merge_combiners
            "host_combine": host_combine,
            "agg": dep.aggregator if host_combine else None,
            "encoded_keys": getattr(plan, "encoded_keys", False),
            "single_map": True,
        })

    def _run_recv_program(self, plan, recv, tag, extra_key, body,
                          donate=False):
        """Shared scaffolding for compiled programs consuming the
        exchange output (_sort_received / _prereduce_received): slice
        per-round receive buffers per device, run body(recvs, cnts) ->
        (count, leaves...), cache the jitted program per
        (tag, program_key, rounds, slot, nleaves, *extra_key).
        `donate` releases the receive buffers (dead after this program
        in the streamed wave loop) for in-place reuse."""
        recv_rounds, cnt_rounds, slot = recv
        rounds = len(recv_rounds)
        nleaves = len(recv_rounds[0])
        key = (tag, plan.program_key, rounds, slot,
               nleaves, donate) + tuple(extra_key)
        if key not in self._compiled:
            def per_device(*args):
                cnts = [c[0] for c in args[:rounds]]
                bufs = args[rounds:]
                recvs = []
                for r in range(rounds):
                    recvs.append([bufs[r * nleaves + li][0]
                                  for li in range(nleaves)])
                outs = body(recvs, cnts)
                return tuple(jnp.expand_dims(o, 0) for o in outs)

            fn = _shard_map(per_device, self.mesh,
                            in_specs=(P(AXIS),) * (rounds
                                                   + rounds * nleaves),
                            out_specs=(P(AXIS),) * (1 + nleaves))
            self._compiled[key] = jax.jit(fn, donate_argnums=tuple(
                range(rounds, rounds + rounds * nleaves))
                if donate else ())
        args = list(cnt_rounds)
        for r in range(rounds):
            args.extend(recv_rounds[r])
        return self._compiled[key](*args)

    def _rid_prefixed_treedef(self, plan):
        """plan.out_treedef with the rid column prepended FLAT: egested
        rows read (rid, k, v...) so callers can strip row[0]."""
        import jax.tree_util as jtu
        sample = jtu.tree_unflatten(
            plan.out_treedef, list(range(len(plan.out_specs))))
        assert isinstance(sample, tuple), sample
        return jtu.tree_structure((0,) + sample)

    def _sort_received(self, plan, recv, nkeys=1, donate=False):
        """Flatten exchange rounds and sort per device by the first
        `nkeys` leaves -> Batch (extra leading leaves beyond
        plan.out_specs, e.g. the rid column, ride along)."""
        def body(recvs, cnts):
            flat, mask = collectives.flatten_received(recvs, cnts)
            packed = collectives._lex_sort(tuple(flat), nkeys)
            n = jnp.sum(mask).astype(jnp.int32)
            return (n,) + tuple(packed)

        outs = self._run_recv_program(plan, recv, "wave_sort",
                                      (nkeys,), body, donate=donate)
        leaves = list(outs[1:])
        extra = len(leaves) - len(plan.out_specs)
        treedef = plan.out_treedef
        if extra:
            assert extra == 1, extra
            treedef = self._rid_prefixed_treedef(plan)
        return layout.Batch(treedef, leaves, outs[0])

    def _prereduce_received(self, plan, recv, merge_fn, monoid,
                            donate=False):
        """Flatten exchange rounds and segment-reduce per (rid, key) on
        device — the spilled-run stream's per-wave pre-combine for
        traceable merges with r beyond the mesh.  Returns the same
        rid-prefixed Batch shape as _sort_received, with rows equal in
        (rid, every key column) already merged."""
        nk = getattr(plan, "epi_nk", 1) or 1

        def body(recvs, cnts):
            flat, mask = collectives.flatten_received(recvs, cnts)
            ks, vs, n = collectives.segment_reduce_keys(
                flat[:1 + nk], flat[1 + nk:], mask, merge_fn,
                monoid=monoid)
            return (n,) + tuple(ks) + tuple(vs)

        outs = self._run_recv_program(plan, recv, "wave_prereduce",
                                      (nk,), body, donate=donate)
        return layout.Batch(self._rid_prefixed_treedef(plan),
                            list(outs[1:]), outs[0])

    @staticmethod
    def _write_run(path, rows):
        """One spill run to disk, framed with its crc32c (ISSUE 5):
        corruption surfaces at read as SpillCorruption -> FetchFailed
        (lineage recompute), never unpickled garbage.  A failed write
        (ENOSPC & co, including the shuffle.spill_write chaos site)
        cleans up its partial file and raises SpillWriteError so the
        consuming stage fails VISIBLY into the scheduler's task
        retry/escalation accounting."""
        import pickle
        import struct
        from dpark_tpu import coding, faults
        from dpark_tpu.shuffle import SpillWriteError, spill_crc
        from dpark_tpu.utils import atomic_file, compress
        blob = compress(pickle.dumps(rows, -1))
        # a SPAN with the measured write wall (was an instant event):
        # the health plane's spill.write latency sketch needs real
        # durations (ISSUE 14)
        t_w0 = time.time() if trace._PLANE is not None else 0.0
        code = coding.active_code()
        try:
            if code is not None:
                # coded run (ISSUE 6): a shard container with
                # per-shard crcs — a corrupted region is decoded
                # around at read instead of failing the whole run
                body = coding.encode_container(
                    blob, code, fault_site="shuffle.spill_write")
                with atomic_file(path) as f:
                    f.write(body)
                if trace._PLANE is not None:
                    trace.emit("spill.write", "shuffle", t_w0,
                               time.time() - t_w0, bytes=len(body))
                return
            # over the TRUE bytes, pre-corruption
            crc = spill_crc(blob)
            blob = faults.hit("shuffle.spill_write", blob)
            # tmp+rename: a failed or killed write never leaves a
            # partial file a reader could mistake for a short run
            with atomic_file(path) as f:
                f.write(struct.pack("<I", crc))
                f.write(blob)
            if trace._PLANE is not None:
                trace.emit("spill.write", "shuffle", t_w0,
                           time.time() - t_w0, bytes=len(blob))
        except OSError as e:
            raise SpillWriteError(
                "spill run %s write failed: %s" % (path, e)) from e

    @staticmethod
    def _read_run(path):
        import pickle
        import struct
        from dpark_tpu import coding, faults
        from dpark_tpu.shuffle import SpillCorruption, spill_crc
        from dpark_tpu.utils import decompress
        t_r0 = time.time() if trace._PLANE is not None else 0.0
        with open(path, "rb") as f:
            raw = f.read()
        if trace._PLANE is not None:
            trace.emit("spill.read", "shuffle", t_r0,
                       time.time() - t_r0, bytes=len(raw))
        if coding.is_container(raw):
            # coded run: per-shard crcs; corruption repairs by decode,
            # and only a sub-k survivor count escalates to lineage
            try:
                blob = coding.decode_container(
                    raw, fault_site="shuffle.spill_read")
            except coding.ShardShortfall as e:
                raise SpillCorruption(
                    "spill run %s: %d of %d shards survived "
                    "(%d needed)" % (path, e.found, e.total,
                                     e.needed)) from e
            return pickle.loads(decompress(blob))
        (crc,) = struct.unpack("<I", raw[:4])
        blob = faults.hit("shuffle.spill_read", raw[4:])
        if spill_crc(blob) != crc:
            # the export bridge's readers turn this into FetchFailed:
            # the parent device stage recomputes through lineage
            raise SpillCorruption(
                "spill run %s: crc32c mismatch (corrupted run)" % path)
        return pickle.loads(decompress(blob))

    def _exchange_all(self, leaves, counts, offsets, slot_floor=0,
                      donate=False):
        """Run exchange rounds for already-bucketized buffers; returns
        (recv_rounds, cnt_rounds, slot).  `slot_floor` pins the slot
        size class from below (stream loops pass their running max so
        light tail waves reuse the compiled exchange/merge programs).
        `donate` (streamed waves only, where the bucketized buffers die
        with this call) lets the LAST round reuse them in place —
        earlier rounds re-read the same buffers and never donate."""
        nleaves = len(leaves)
        cap = leaves[0].shape[1]
        if self.ndev == 1:
            # single-device mesh: the exchange is the identity — the
            # bucketized valid prefix IS the received data.  Skip the
            # narrowing probe (there is no wire), the collective
            # program, and every blocking readback (a dispatch
            # round-trip costs 66 ms through the real-chip tunnel,
            # BENCH_REAL_r03.md, and this runs per wave); the row
            # metric readback is deferred to the next metric read.
            self._pending_real_counts.append(counts)
            if len(self._pending_real_counts) > self._PENDING_COUNTS_MAX:
                self.exchange_real_rows  # property read drains the list
            self.ingest_slot_rows += cap
            # consumers expect per-device (R=1, slot, ...) receive
            # buffers and (R=1,) counts — counts is already the (1, 1)
            # per-bucket array, leaves gain the source-device axis
            recv = [l.reshape((1, 1) + l.shape[1:]) for l in leaves]
            return [recv], [counts], cap
        host_counts = layout.host_read(counts)
        max_run = int(host_counts.max()) if host_counts.size else 1
        mean = int(host_counts.sum()) // max(1, host_counts.size)
        # slot sizing: fine (1/16-octave) classes — power-of-two slots
        # alone cost up to 2x wire padding (the measured 0.5 pad
        # efficiency of BENCH_r03); uniform loads now pad <=6.25%.
        # Sizing first snaps to an ALREADY-COMPILED slot within the
        # same tolerance, so a few percent of data drift between jobs
        # reuses the cached exchange/reduce programs instead of
        # compiling the adjacent fine class.
        ideal = min(max(64, 2 * mean), max(1, max_run))
        memo = self._slot_memo.setdefault(
            (tuple(str(l.dtype) for l in leaves), nleaves), set())
        cached = [s for s in memo if ideal <= s <= ideal + (ideal >> 4)]
        slot = min(cached) if cached else layout.round_capacity_fine(ideal)
        slot = max(slot, min(slot_floor, layout.round_capacity_fine(cap)))
        memo.add(slot)
        self.exchange_real_rows += int(host_counts.sum())
        narrow = self._narrow_plan(leaves, counts)
        exchange = self._compile_exchange(
            tuple(str(l.dtype) for l in leaves), nleaves, slot, cap,
            narrow=narrow)
        wire_itemsize = sum(
            (np.dtype(narrow[li]).itemsize if narrow and narrow[li]
             else leaves[li].dtype.itemsize)
            * int(np.prod(leaves[li].shape[2:], dtype=np.int64))
            for li in range(nleaves))
        sent = layout.put_sharded(
            np.zeros((self.ndev, self.ndev), np.int32), self._sharding())
        # the round count is KNOWN on the host (each round moves up to
        # `slot` rows of every src->dst bucket, so ceil(max_bucket/slot)
        # rounds drain everything) — no per-round blocking overflow
        # readback serializing dispatch against a 66 ms tunnel RTT
        # (VERDICT r3 #2); the program's overflow output is ignored
        rounds = max(1, -(-max_run // slot))
        recv_rounds, cnt_rounds = [], []
        for r in range(rounds):
            fn = exchange
            if donate and r == rounds - 1:
                fn = self._compile_exchange(
                    tuple(str(l.dtype) for l in leaves), nleaves, slot,
                    cap, narrow=narrow, donate=True)
            outs = fn(offsets, counts, sent, *leaves)
            recv_cnt, sent = outs[0], outs[1]
            recv_rounds.append(list(outs[3:]))
            cnt_rounds.append(recv_cnt)
            self.exchange_wire_bytes += (
                self.ndev * self.ndev * slot * wire_itemsize)
            self.exchange_slot_rows += self.ndev * self.ndev * slot
        return recv_rounds, cnt_rounds, slot

    def _merge_into_state(self, plan, state, recv, monoid,
                          merge_fn=None, donate=False):
        """Combine received rows (and the running state) into the new
        per-device unique-key state: one segment scatter for classified
        monoids, a segmented associative scan of the traced user merge
        otherwise.  `donate` releases the OLD state leaves (replaced by
        the program's output) and the receive buffers (dead after the
        merge) for in-place reuse; the per-round counts stay live (the
        ndev==1 fast path defers their host readback)."""
        recv_rounds, cnt_rounds, slot = recv
        rounds = len(recv_rounds)
        nleaves = len(recv_rounds[0])
        nk = getattr(plan, "epi_nk", 1) or 1
        has_state = state is not None
        state_cap = state[0][0].shape[1] if has_state else 0
        key = ("stream_merge", plan.program_key, rounds, slot, nleaves,
               state_cap, donate)
        if key not in self._compiled:
            def per_device(*args):
                i = 0
                if has_state:
                    st_leaves = [a[0] for a in args[:nleaves]]
                    st_n = args[nleaves][0]
                    i = nleaves + 1
                cnts = [c[0] for c in args[i:i + rounds]]
                bufs = args[i + rounds:]
                recvs = []
                for r in range(rounds):
                    recvs.append([bufs[r * nleaves + li][0]
                                  for li in range(nleaves)])
                flat, mask = collectives.flatten_received(recvs, cnts)
                if has_state:
                    stv = jnp.arange(state_cap) < st_n
                    kcol = jnp.where(
                        stv, st_leaves[0],
                        collectives._sentinel(st_leaves[0].dtype))
                    flat = [jnp.concatenate([kcol, flat[0]])] + [
                        jnp.concatenate([sl, fl])
                        for sl, fl in zip(st_leaves[1:], flat[1:])]
                    mask = jnp.concatenate([stv, mask])
                ks, vs, n = collectives.segment_reduce_keys(
                    flat[:nk], flat[nk:], mask, merge_fn,
                    monoid=monoid)
                out = (jnp.expand_dims(n, 0),) + tuple(
                    jnp.expand_dims(k, 0) for k in ks) + tuple(
                    jnp.expand_dims(v, 0) for v in vs)
                return out

            n_in = (nleaves + 1 if has_state else 0) \
                + rounds + rounds * nleaves
            dn = ()
            if donate:
                # old state leaves (args 0..nleaves-1 when present; NOT
                # the state counts at index nleaves) + receive buffers
                # (after the per-round counts)
                base = (nleaves + 1) if has_state else 0
                dn = (tuple(range(nleaves)) if has_state else ()) \
                    + tuple(range(base + rounds,
                                  base + rounds + rounds * nleaves))
            fn = _shard_map(per_device, self.mesh,
                            in_specs=(P(AXIS),) * n_in,
                            out_specs=(P(AXIS),) * (1 + nleaves))
            self._compiled[key] = jax.jit(fn, donate_argnums=dn)
        args = []
        if has_state:
            args.extend(state[0])
            args.append(state[1])
        args.extend(cnt_rounds)
        for r in range(rounds):
            args.extend(recv_rounds[r])
        outs = self._compiled[key](*args)
        counts, leaves = outs[0], list(outs[1:])
        # start the counts D2H without blocking: the caller shrinks the
        # state one wave later (_shrink_state), by which point the
        # transfer has ridden along behind the merge — the wave loop
        # never stalls on a 66 ms tunnel round-trip just for a slice
        # bound (VERDICT r3 #2: no per-wave blocking syncs)
        try:
            counts.copy_to_host_async()
        except AttributeError:
            pass
        return (leaves, counts)

    def _shrink_state(self, state):
        """Slice the combined state down to the size class its counts
        need — bounds state growth across waves and keeps the merge
        program's state_cap compile key sticky.  The counts readback
        was issued async at merge time; reading it here is (near-)free."""
        leaves, counts = state
        host_n = int(layout.host_read(counts).max() or 1)
        want_cap = layout.round_capacity(host_n)
        if leaves[0].shape[1] > want_cap:
            leaves = [l[:, :want_cap] for l in leaves]
        return (leaves, counts)

    # ------------------------------------------------------------------
    # cogroup support: exchange one dep's rows to their reduce partitions
    # and return them key-sorted per partition (no combining)
    # ------------------------------------------------------------------
    def gather_rows(self, dep):
        """Device exchange + key sort for one no-combine shuffle dep;
        returns per-partition sorted row lists (host)."""
        with self._mesh_lock:
            store = self.shuffle_store[dep.shuffle_id]
            counts, leaves = self._exchange_sorted(dep, store)
            batch = layout.Batch(store["out_treedef"], leaves, counts)
            return [self._maybe_decode(store, rows)
                    for rows in layout.egest(batch)]

    # ------------------------------------------------------------------
    # device join: two exchanged+sorted sides expand to key-matched pairs
    # entirely on device (two-phase: count totals, then a static-capacity
    # gather program) — replaces the host merge for a.join(b)
    # ------------------------------------------------------------------
    def _exchange_sorted(self, dep, store):
        """No-combine exchange leaving the result ON DEVICE: per-device
        key-sorted rows as (counts, leaves...) global arrays."""

        class _GatherPlan:
            source = ("hbm", dep)
            ops = []
            epilogue = None
            src_combine = False
            group_output = False
            epi_spec = None
            epi_bounds = None
            epi_nk = 1
            # sort gathered rows by the FULL key (tuple keys span
            # key_cols columns) so cogroup/join consumers see the same
            # lexicographic order the host merge expects
            src_nk = store.get("key_cols", 1) or 1
            in_treedef = store["out_treedef"]
            in_specs = store["out_specs"]
            out_treedef = store["out_treedef"]
            out_specs = store["out_specs"]
            stage = None
            program_key = ("gather", src_nk,
                           tuple((str(dt), shape)
                                 for dt, shape in store["out_specs"]))

        outs = self._run_exchange_and_reduce(_GatherPlan)
        return outs[0], list(outs[1:])          # counts, leaves

    def run_device_join(self, dep_a, dep_b):
        """Per-partition inner join of two HBM-resident no-combine
        shuffles; returns per-partition host rows (k, (va, vb))."""
        with self._mesh_lock:
            return self._run_device_join(dep_a, dep_b)

    def _run_device_join(self, dep_a, dep_b):
        store_a = self.shuffle_store[dep_a.shuffle_id]
        batch = self.device_join_batch(dep_a, dep_b)
        rows_per_part = layout.egest(batch)
        if store_a.get("encoded_keys"):
            # both sides of a str-keyed join encode through the SAME
            # executor dict, so id equality == string equality; decode
            # at this host exit like every other
            rows_per_part = [self._maybe_decode(store_a, rows)
                             for rows in rows_per_part]
        return rows_per_part

    def device_join_batch(self, dep_a, dep_b):
        """Inner join of two HBM no-combine shuffles as a device Batch
        of (k, (va, vb)) rows — the array-path "join" source (keys stay
        on device; downstream ops + shuffle writes ride the mesh)."""
        store_a = self.shuffle_store[dep_a.shuffle_id]
        store_b = self.shuffle_store[dep_b.shuffle_id]
        if store_a.get("encoded_keys", False) != \
                store_b.get("encoded_keys", False):
            # ids on one side, user ints on the other: id equality would
            # be spurious — the host path compares decoded keys
            raise ValueError("mixed encoded/plain join keys")
        cnt_a, lv_a = self._exchange_sorted(dep_a, store_a)
        cnt_b, lv_b = self._exchange_sorted(dep_b, store_b)
        na, nb = len(lv_a), len(lv_b)
        cap_a, cap_b = lv_a[0].shape[1], lv_b[0].shape[1]
        # composite (tuple) keys span the first nk columns on BOTH
        # sides (fuse._analyze_join_source / _precompute_join verified
        # the widths and dtypes agree); key matching runs a
        # lexicographic binary search instead of jnp.searchsorted
        nk = store_a.get("key_cols", 1) or 1

        def _key_ranges(a, b, A, B):
            """(lo, hi) match ranges of each A row in the key-sorted B
            rows.  Only key column 0 needs the sentinel: invalid rows
            sort last on it, and comparisons against them resolve on
            column 0 alone (no valid key ever carries the sentinel)."""
            sent = collectives._sentinel(A[0].dtype)
            A0 = jnp.where(jnp.arange(cap_a) < a, A[0], sent)
            B0 = jnp.where(jnp.arange(cap_b) < b, B[0], sent)
            if nk == 1:
                return (jnp.searchsorted(B0, A0, side="left"),
                        jnp.searchsorted(B0, A0, side="right"))
            acols = [A0] + list(A[1:nk])
            bcols = [B0] + list(B[1:nk])
            return (collectives.lex_searchsorted(bcols, acols, "left"),
                    collectives.lex_searchsorted(bcols, acols,
                                                 "right"))

        count_key = ("join_count", cap_a, cap_b, na, nb, nk,
                     tuple(str(l.dtype) for l in lv_a + lv_b))
        if count_key not in self._compiled:
            def count_dev(ca, cb, *keys):
                a, b = ca[0], cb[0]
                A = [k[0] for k in keys[:nk]]
                B = [k[0] for k in keys[nk:]]
                lo, hi = _key_ranges(a, b, A, B)
                per = jnp.where(jnp.arange(cap_a) < a, hi - lo, 0)
                return (jnp.expand_dims(jnp.sum(per), 0),)
            fn = _shard_map(count_dev, self.mesh,
                            in_specs=(P(AXIS),) * (2 + 2 * nk),
                            out_specs=(P(AXIS),))
            self._compiled[count_key] = jax.jit(fn)
        (totals,) = self._compiled[count_key](
            cnt_a, cnt_b, *lv_a[:nk], *lv_b[:nk])
        cap_out = layout.round_capacity(
            int(layout.host_read(totals).max() or 1))

        exp_key = ("join_expand", cap_a, cap_b, cap_out, na, nb, nk,
                   tuple(str(l.dtype) for l in lv_a + lv_b))
        if exp_key not in self._compiled:
            def expand_dev(ca, cb, *leaves):
                a, b = ca[0], cb[0]
                A = [l[0] for l in leaves[:na]]
                B = [l[0] for l in leaves[na:]]
                lo, hi = _key_ranges(a, b, A, B)
                per = jnp.where(jnp.arange(cap_a) < a, hi - lo, 0)
                offs = jnp.cumsum(per) - per          # exclusive
                total = jnp.sum(per)
                t = jnp.arange(cap_out)
                # source A row for each output slot
                i = jnp.clip(
                    jnp.searchsorted(offs + per, t, side="right"),
                    0, cap_a - 1)
                j = t - offs[i]
                bi = jnp.clip(lo[i] + j, 0, cap_b - 1)
                out = [x[i] for x in A] + [x[bi] for x in B[nk:]]
                return (jnp.expand_dims(total, 0),) + tuple(
                    jnp.expand_dims(o, 0) for o in out)
            n_out = 1 + na + (nb - nk)
            fn = _shard_map(expand_dev, self.mesh,
                            in_specs=(P(AXIS),) * (2 + na + nb),
                            out_specs=(P(AXIS),) * n_out)
            self._compiled[exp_key] = jax.jit(fn)
        outs = self._compiled[exp_key](cnt_a, cnt_b, *lv_a, *lv_b)
        counts, leaves = outs[0], list(outs[1:])

        # rows are (k..., va..., vb...); records are (k, (va, vb)) with
        # the key subtree (scalar or flat tuple) taken from side a
        import jax.tree_util as jtu
        ta = store_a["out_treedef"]
        tb = store_b["out_treedef"]
        sample_a = jtu.tree_unflatten(ta, list(range(na)))
        sample_b = jtu.tree_unflatten(tb, list(range(nb)))
        joined_sample = (sample_a[0], (sample_a[1], sample_b[1]))
        out_treedef = jtu.tree_structure(joined_sample)
        return layout.Batch(out_treedef, leaves, counts)

    # ------------------------------------------------------------------
    # host bridge
    # ------------------------------------------------------------------
    def has_shuffle(self, sid):
        return sid in self.shuffle_store

    def export_bucket(self, sid, map_id, reduce_id, shard=None):
        """Device-resident map output -> host (k, combiner) items, for
        host-path reduce stages (shuffle.read_bucket 'hbm://' uris).
        With `shard` set (coded shuffle, ISSUE 6) returns ONE framed
        erasure shard of the bucket's serialized payload instead —
        the fetch side decodes from the fastest k of n.  Wall time
        accumulates in `export_seconds` (the per-phase bench table's
        "export" column)."""
        import time as _time
        t0 = _time.perf_counter()
        t_wall = _time.time() if trace._PLANE is not None else 0.0
        try:
            if shard is not None:
                return self._export_shard(sid, map_id, reduce_id,
                                          shard)
            return self._export_bucket(sid, map_id, reduce_id)
        finally:
            self.export_seconds += _time.perf_counter() - t0
            if trace._PLANE is not None:
                # named phase.export so the critical-path analyzer's
                # export total matches phase_table()'s export column
                trace.emit("phase.export", "phase", t_wall,
                           _time.time() - t_wall, shuffle=sid,
                           map=map_id, reduce=reduce_id)

    def export_bucket_cols(self, sid, map_id, reduce_id):
        """Device-resident map output -> (meta, [numpy column arrays])
        for the bulk data plane (ISSUE 12): a peer controller receives
        the RAW COLUMN BYTES and assembles them zero-copy into
        np.frombuffer views / device_put batches — the per-row
        pickle/unpickle of the host bridge never runs.  Raises
        KeyError when this executor owns no such shuffle (the server
        tries the next exporter) and ValueError when the record shape
        cannot columnarize (encoded keys, spilled host runs, nested
        records) — the server then falls back to the pickled payload,
        still chunk-framed on the bulk channel.  The materialized
        columns are bit-equal sources of the rows export_bucket would
        have pickled (both sides materialize via .tolist())."""
        import time as _time
        t0 = _time.perf_counter()
        t_wall = _time.time() if trace._PLANE is not None else 0.0
        try:
            return self._export_bucket_cols(sid, map_id, reduce_id)
        finally:
            self.export_seconds += _time.perf_counter() - t0
            if trace._PLANE is not None:
                trace.emit("phase.export", "phase", t_wall,
                           _time.time() - t_wall, shuffle=sid,
                           map=map_id, reduce=reduce_id, cols=True)

    def _export_bucket_cols(self, sid, map_id, reduce_id):
        import jax.tree_util as jtu
        store = self.shuffle_store.get(sid)
        if store is None:
            raise KeyError("no HBM shuffle %d" % sid)
        if store.get("encoded_keys") or "host_runs" in store \
                or store.get("single_map"):
            raise ValueError("store %d cannot columnarize for the "
                             "bulk plane" % sid)
        if store["out_treedef"] != jtu.tree_structure((0, 0)):
            raise ValueError("columnar export needs flat (k, v) "
                             "records")
        store["seq"] = self._next_seq()     # least-recently-FETCHED
        if store.get("pre_reduced"):
            # device d holds reduce partition d fully combined: the
            # whole bucket exposes as map 0 (same contract as
            # _export_bucket)
            if map_id != 0:
                return {"no_combine": False}, []
            with self._export_lock:
                counts = layout.host_read(store["counts"])
                cnt = int(counts[reduce_id])
                if not cnt:
                    return {"no_combine": False}, []
                mats = [np.ascontiguousarray(
                    self._read_dev_slice(l, reduce_id)[:cnt])
                    for l in store["leaves"]]
            return {"no_combine": False}, mats
        wrap = bool(store.get("no_combine"))
        with self._export_lock:
            counts = layout.host_read(store["counts"])
            offsets = layout.host_read(store["offsets"])
            off = int(offsets[map_id, reduce_id])
            cnt = int(counts[map_id, reduce_id])
            if not cnt:
                return {"no_combine": wrap}, []
            mats = [np.ascontiguousarray(
                self._read_dev_slice(l, map_id)[off:off + cnt])
                for l in store["leaves"]]
        return {"no_combine": wrap}, mats

    # serialized+encoded bucket shards kept for re-fetch; beyond this
    # the oldest buckets drop (re-encoding is cheap vs re-exporting)
    _SHARD_CACHE_BYTES = 64 << 20

    def _export_shard(self, sid, map_id, reduce_id, idx):
        from dpark_tpu import coding
        from dpark_tpu.utils import compress
        # per-exchange code (ISSUE 19): an adaptively-escalated
        # shuffle serves coded frames even with the global code off,
        # and a pinned-uncoded one refuses the shard protocol so the
        # fetch side falls back to whole buckets
        code = coding.shuffle_code(sid)
        if code is None:
            raise ValueError(
                "shard export requested with no shuffle code active")
        import pickle
        key = (sid, map_id, reduce_id)
        # lock-free fast path: a built bucket's n shard requests must
        # not queue behind another bucket's export (dict reads are
        # GIL-atomic; entries are only ever replaced whole)
        frames = self._shard_cache.get(key)
        if frames is None:
            # lock ORDER on the build path: mesh before shard_build —
            # _export_bucket's device read takes the mesh lock, and a
            # stage registering a shuffle holds the mesh lock while
            # drop_shuffle takes shard_build; acquiring shard_build
            # first here would deadlock those two threads (ISSUE 9:
            # concurrent jobs make this race real)
            with self._mesh_lock, self._shard_build_lock:
                frames = self._shard_cache.get(key)
                if frames is None:
                    # KeyError (no such hbm shuffle) propagates so the
                    # fetch side tries the next exporter, same as the
                    # whole-bucket protocol
                    rows = self._export_bucket(sid, map_id, reduce_id)
                    blob = compress(pickle.dumps(rows, -1))
                    frames = coding.encode_bucket_frames(blob, code)
                    self._shard_cache[key] = frames
                    self._shard_cache_bytes += sum(
                        len(f) for f in frames)
                    # insertion-ordered (FIFO) eviction: shard fetches
                    # for one bucket arrive within one reduce task's
                    # fan-out, so age tracks usefulness closely enough
                    while (self._shard_cache_bytes
                           > self._SHARD_CACHE_BYTES
                           and len(self._shard_cache) > 1):
                        old_key = next(iter(self._shard_cache))
                        if old_key == key:
                            break
                        dropped = self._shard_cache.pop(old_key)
                        self._shard_cache_bytes -= sum(
                            len(f) for f in dropped)
        if not 0 <= idx < len(frames):
            raise ValueError("shard index %d out of range (n=%d)"
                             % (idx, len(frames)))
        return frames[idx]

    def program_cache_stats(self):
        """Hit/miss/evict counters of the bounded compiled-program
        cache (ISSUE 9): /metrics, the web UI per-job cache column,
        and the warm-submit bench read these.  With the AOT plane
        installed (ISSUE 17) the disk tier's load/store/warm counters
        ride along under "aot"."""
        out = self._compiled.stats()
        aot = aotcache.stats()
        if aot is not None:
            out["aot"] = aot
        return out

    def _export_bucket(self, sid, map_id, reduce_id):
        store = self.shuffle_store.get(sid)
        if store is None:
            raise KeyError("no HBM shuffle %d" % sid)
        store["seq"] = self._next_seq()     # least-recently-FETCHED
        #   ordering for the disk spiller (ISSUE 9 satellite)
        if store.get("pre_reduced"):
            # device d holds reduce partition d fully combined: expose it
            # as map 0's bucket (other maps contribute nothing)
            if map_id != 0:
                return []
            with self._export_lock:
                counts = layout.host_read(store["counts"])
                cnt = int(counts[reduce_id])
                if not cnt:
                    return []
                mats = [self._read_dev_slice(l, reduce_id)[:cnt]
                        for l in store["leaves"]]
            lists = [m.tolist() for m in mats]
            treedef = store["out_treedef"]
            rows = [jax.tree_util.tree_unflatten(
                treedef, [pl[i] for pl in lists]) for i in range(cnt)]
            return self._maybe_decode(store, rows)
        if "host_runs" in store:
            # streamed no-combine shuffle: per-partition COLUMN runs on
            # host disk.  The background premerger usually got here
            # first (one merged key-sorted run per partition); a
            # not-yet-merged partition merges via the same per-rid
            # once-lock, so first-fetch never races the walker.  The
            # whole shuffle exports through map 0.
            if map_id != 0:
                return []
            cols = self._partition_run_cols(store, reduce_id)
            if cols is None:
                return []
            lists = [c.tolist() for c in cols]
            flat2 = jax.tree_util.tree_structure((0, 0))
            treedef = store["out_treedef"]
            if store.get("host_combine"):
                # fold the user's merge_combiners over each sorted key
                # group: values in the runs are already CREATED
                # combiners, so this is exactly the reference's
                # external merge of sorted runs — O(1) state per key
                mc = store["agg"].merge_combiners
                rows = []
                cur_k = cur_c = None
                have = False
                for i in range(len(lists[0])):
                    if treedef == flat2:
                        k, v = lists[0][i], lists[1][i]
                    else:
                        rec = jax.tree_util.tree_unflatten(
                            treedef, [pl[i] for pl in lists])
                        k, v = rec[0], rec[1]
                    if have and k == cur_k:
                        cur_c = mc(cur_c, v)
                    else:
                        if have:
                            rows.append((cur_k, cur_c))
                        cur_k, cur_c, have = k, v, True
                if have:
                    rows.append((cur_k, cur_c))
            elif treedef == flat2:
                # flat (k, v) records — one zip, no per-row treedef work
                rows = [(k, [v]) for k, v in zip(lists[0], lists[1])]
            else:
                rows = []
                for i in range(len(lists[0])):
                    rec = jax.tree_util.tree_unflatten(
                        treedef, [pl[i] for pl in lists])
                    rows.append((rec[0], [rec[1]]))
            return self._maybe_decode(store, rows)
        if store.get("single_map"):
            # device rows don't correspond to logical map partitions
            # (text ingest): the whole shuffle exports through map 0
            if map_id != 0:
                return []
            with self._export_lock:
                counts = layout.host_read(store["counts"])
                offsets = layout.host_read(store["offsets"])
                rows = []
                for dev in range(counts.shape[0]):
                    rows.extend(self._export_one(store, dev, reduce_id,
                                                 counts, offsets))
            return self._maybe_decode(store, rows)
        with self._export_lock:
            counts = layout.host_read(store["counts"])
            offsets = layout.host_read(store["offsets"])
            rows = self._export_one(store, map_id, reduce_id, counts,
                                    offsets)
        return self._maybe_decode(store, rows)

    @staticmethod
    def _read_dev_slice(arr, dev):
        """One device's row of a (ndev, ...) store leaf as numpy.  The
        fully-addressable case pulls just that slice off the device; a
        process-spanning leaf replicates through host_read first (the
        host bridge is the slow path — correctness over bytes here)."""
        if getattr(arr, "is_fully_addressable", True):
            return np.asarray(jax.device_get(
                lax.slice_in_dim(arr, dev, dev + 1, axis=0)))[0]
        return layout.host_read(arr)[dev]

    def _export_one(self, store, dev, reduce_id, counts, offsets):
        """One device's bucket for one reduce partition as host rows."""
        off = int(offsets[dev, reduce_id])
        cnt = int(counts[dev, reduce_id])
        if not cnt:
            return []
        treedef = store["out_treedef"]
        mats = [self._read_dev_slice(l, dev)[off:off + cnt]
                for l in store["leaves"]]
        lists = [m.tolist() for m in mats]
        wrap = store.get("no_combine", False)
        rows = []
        for i in range(cnt):
            rec = jax.tree_util.tree_unflatten(
                treedef, [pl[i] for pl in lists])
            if wrap:
                # no-combine rows are raw (k, v); the host merge
                # contract expects (k, combiner=[v])
                rec = (rec[0], [rec[1]])
            rows.append(rec)
        return rows

    def _maybe_decode(self, store, rows):
        """Dictionary-encoded string keys leave the device as ids; every
        host-facing exit decodes them back."""
        if not store.get("encoded_keys") or not rows:
            return rows
        td = self.token_dict
        return [(td.decode(int(r[0])),) + tuple(r[1:]) for r in rows]

    def drop_shuffle(self, sid, reason="drop"):
        with self._shard_build_lock:
            for key in [k for k in self._shard_cache if k[0] == sid]:
                self._shard_cache_bytes -= sum(
                    len(f) for f in self._shard_cache.pop(key))
        store = self.shuffle_store.pop(sid, None)
        if store:
            self._store_bytes -= store["nbytes"]
            if trace._PLANE is not None:
                # ledger plane (ISSUE 15): residency ends — the sink
                # accrues bytes x held seconds against the account
                # that STORED it (reason "spill" marks an eviction
                # adjusting the live HBM picture, not a data drop)
                trace.event("hbm.release", "exec", sid=sid,
                            bytes=store["nbytes"], reason=reason,
                            job=store.get("job"))
            else:
                # tracing turned off after the store registered: the
                # sink's residency entry must still settle, or the
                # live gauge reports freed memory forever and the
                # tenant's byte-seconds never accrue
                from dpark_tpu import ledger
                sink = ledger._SINK
                if sink is not None:
                    try:
                        sink.fold({"name": "hbm.release",
                                   "ts": time.time(),
                                   "job": store.get("job"),
                                   "args": {"sid": sid,
                                            "bytes": store["nbytes"],
                                            "reason": reason}})
                    except Exception:
                        pass
            if store.get("premerge") is not None:
                # stop the background merger BEFORE deleting the spool
                # it is reading/writing
                store["premerge"].stop()
            if store.get("spool_dir"):
                import shutil
                shutil.rmtree(store["spool_dir"], ignore_errors=True)

    @staticmethod
    def _check_cached_keys(batch):
        """Cached batches feeding a shuffle get the same sentinel guard as
        ingest: a real key equal to the padding sentinel (or inf/nan)
        would be silently dropped by the reduce — force host fallback."""
        import jax.numpy as jnp
        keys = batch.cols[0]
        counts = batch.counts
        valid = jnp.arange(keys.shape[1])[None, :] < counts[:, None]
        if jnp.issubdtype(keys.dtype, jnp.floating):
            bad = jnp.any(valid & (jnp.isinf(keys) | jnp.isnan(keys)))
        else:
            sent = jnp.iinfo(keys.dtype).max
            bad = jnp.any(valid & (keys == sent))
        if bool(layout.host_read(bad)):
            raise ValueError("cached key equals the device sentinel; "
                             "taking the host path")

    def stop(self):
        from dpark_tpu import cache as cache_mod
        from dpark_tpu import shuffle as shuffle_mod
        shuffle_mod.HBM_EXPORTERS.pop(self._exporter_key, None)
        shuffle_mod.HBM_COL_EXPORTERS.pop(self._exporter_key, None)
        cache_mod.DEVICE_CACHES.pop(self._cache_key, None)
        if self._tracing:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._tracing = False
        for sid in list(self.shuffle_store):
            self.drop_shuffle(sid)      # also removes spool dirs
        self.result_cache.clear()
