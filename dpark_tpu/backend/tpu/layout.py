"""Columnar array-partition layout for the TPU backend.

The object path (dpark/rdd.py generators) represents a partition as a Python
iterator of records.  The array path represents a *stage's worth* of
partitions as a struct-of-arrays batch sharded over the device mesh:

  * a record is a JAX pytree (e.g. ``(k, v)`` or a bare scalar);
  * each pytree leaf becomes one column array of shape ``(ndev, cap)``
    (+ trailing dims), sharded ``P('parts', None)`` so device d holds
    logical partition d;
  * ``counts`` (shape ``(ndev,)``) gives the number of valid rows per
    device; rows past the count are padding.

This is the TPU-native replacement for the reference's pickled partition
streams (dpark/shuffle.py file buckets): data never leaves HBM between
stages.  Reference parity anchor: SURVEY.md section 7.0 "array partitions".
"""

import math

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dpark_tpu import conf

AXIS = conf.MESH_AXIS
# int64 sentinel: keys must be < 2**63 - 1; ingest() rejects the sentinel
# value itself (-> host fallback) so no real key can collide with padding
KEY_SENTINEL = np.int64(2 ** 63 - 1)


def make_mesh(devices=None):
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.array(devices), (AXIS,))


# ---------------------------------------------------------------------
# multi-controller SPMD support (SURVEY.md section 2.5): when the mesh
# spans jax processes (mrun + jax.distributed), every rank runs the
# same driver program; host->device and device->host crossings go
# through these two helpers so the same scheduler code works unchanged
# on one process or many.
# ---------------------------------------------------------------------
def put_sharded(arr, sharding):
    """numpy -> sharded jax.Array.  Fully-addressable shardings take
    the direct device_put; process-spanning shardings build the global
    array from each rank's addressable shards (every rank holds the
    same full host array, so any index slice is available locally)."""
    if sharding.is_fully_addressable:
        return jax.device_put(arr, sharding)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx])


_REPLICATORS = {}


def host_read(x):
    """device -> host numpy for metric/sizing readbacks.  A global
    array whose shards live on other processes cannot be device_get
    directly; replicate it across the mesh first (one all_gather) —
    every rank then reads the SAME value, which also keeps multi-rank
    scheduler decisions (slot sizing, round counts) deterministic."""
    if getattr(x, "is_fully_addressable", True):
        return np.asarray(jax.device_get(x))
    mesh = x.sharding.mesh           # Mesh is hashable — key by value,
    fn = _REPLICATORS.get(mesh)      # not id() (ids recycle); bound the
    if fn is None:                   # cache so executor churn can't pin
        if len(_REPLICATORS) >= 8:   # dead meshes forever
            _REPLICATORS.pop(next(iter(_REPLICATORS)))
        fn = jax.jit(lambda a: a,
                     out_shardings=NamedSharding(mesh, P()))
        _REPLICATORS[mesh] = fn
    return np.asarray(jax.device_get(fn(x)))


def round_capacity(n):
    """Pad capacities to power-of-two size classes so recompilation only
    happens when the class changes (SURVEY.md 7.2 item 5)."""
    return max(8, 1 << math.ceil(math.log2(max(n, 1))))


def round_capacity_fine(n):
    """Pad to 1/16th-octave size classes (16 classes per power of two):
    worst-case padding drops from 2x to 6.25%.  Used for exchange SLOT
    sizing, where power-of-two rounding measurably halved wire
    efficiency (BENCH_r03 pad_efficiency 0.5 at uniform key loads vs
    the >=0.9 bar of HARDWARE_CHECKLIST step 3); capacity classes for
    compiled stage programs stay power-of-two."""
    n = max(n, 1)
    if n <= 128:
        return round_capacity(n)
    k = (n - 1).bit_length() - 1          # n in (2^k, 2^(k+1)]
    step = 1 << (k - 4)                   # 16 classes per octave
    return -(-n // step) * step


class Batch:
    """A sharded struct-of-arrays batch: one stage's partitions in HBM."""

    def __init__(self, treedef, cols, counts):
        self.treedef = treedef          # record pytree structure
        self.cols = list(cols)          # leaf arrays, each (ndev, cap, ...)
        self.counts = counts            # (ndev,) int32
        self.ndev = cols[0].shape[0]
        self.cap = cols[0].shape[1]

    def unflatten_record(self, leaves):
        return jax.tree_util.tree_unflatten(self.treedef, leaves)


def record_spec(sample):
    """(treedef, leaf dtypes/shapes) for a sample record."""
    leaves, treedef = jax.tree_util.tree_flatten(sample)
    specs = []
    for leaf in leaves:
        arr = np.asarray(leaf)
        dt = arr.dtype
        if dt == np.float64:
            # device path computes in float32 (TPU-native); parity tests
            # use allclose for float reductions (SURVEY.md 7.2 item 6)
            dt = np.dtype(np.float32)
        elif np.issubdtype(dt, np.integer):
            # int64 so counting/summing workloads cannot silently wrap —
            # exact parity with the local master's Python ints up to 2**63
            dt = np.dtype(np.int64)
        elif dt == np.bool_:
            dt = np.dtype(np.bool_)
        specs.append((dt, arr.shape))
    return treedef, specs


def ingest(mesh, partitions, treedef, specs, key_leaf=None,
           cap_floor=0):
    """Host rows -> sharded Batch.

    `partitions`: list (len == mesh size) of lists of records.  Each record
    must match `treedef`/`specs`.  When `key_leaf` is given, that leaf is
    checked against KEY_SENTINEL (raises ValueError -> host fallback).
    `cap_floor` pins the capacity class from below — stream loops pass
    their running max so a smaller tail wave reuses the compiled
    programs of earlier waves instead of compiling a new size class.
    """
    ndev = mesh.devices.size
    assert len(partitions) == ndev, (len(partitions), ndev)
    counts = np.array([len(p) for p in partitions], dtype=np.int32)
    cap = max(round_capacity(int(counts.max()) if len(counts) else 1),
              cap_floor)
    # host->device wire narrowing: int64 scalar leaves whose values
    # provably fit int32 ride the PCIe/tunnel at i32 (halving H2D
    # bytes — the projected large-scale bound, FEASIBILITY_100GB.md);
    # the stage program widens back to the spec dtype at entry, so
    # compute semantics are unchanged.  Columnar partitions only (the
    # big-data path, where the min/max scan is one vectorized pass).
    from dpark_tpu import conf as _conf
    tight = [None] * len(specs)
    col_stats = {}
    all_columnar = _conf.NARROW_EXCHANGE and any(
        len(p) for p in partitions) and all(
        getattr(p, "columns", None) is not None
        and len(p.columns) == len(specs)
        for p in partitions if len(p))
    if all_columnar:
        i32 = np.iinfo(np.int32)
        for li, (dt, shape) in enumerate(specs):
            if np.dtype(dt) == np.int64 and shape == ():
                los, his = [], []
                for p in partitions:
                    if len(p):
                        c = np.asarray(p.columns[li])
                        if c.size:
                            los.append(int(c.min()))
                            his.append(int(c.max()))
                lo = min(los) if los else 0
                hi = max(his) if his else 0
                col_stats[li] = (lo, hi)
                if lo >= i32.min and hi <= i32.max:
                    tight[li] = np.dtype(np.int32)
    cols = []
    for li, (dt, shape) in enumerate(specs):
        col = np.zeros((ndev, cap) + shape, dtype=tight[li] or dt)
        cols.append(col)
    flat_scalars = all(shape == () for _, shape in specs)
    for d, part in enumerate(partitions):
        if not part:
            continue
        part_cols = getattr(part, "columns", None)
        if part_cols is not None and len(part_cols) == len(specs):
            # columnar parallelize: memcpy + cast, no row objects
            for li, (dt, shape) in enumerate(specs):
                cols[li][d, :counts[d]] = part_cols[li].astype(
                    dt, copy=False)
            continue
        if flat_scalars and len(specs) > 1 and isinstance(part[0], tuple) \
                and len(part[0]) == len(specs):
            # fast path: rows are flat tuples of scalars -> one 2D array
            mat = np.asarray(part)
            for li, (dt, shape) in enumerate(specs):
                cols[li][d, :counts[d]] = mat[:, li].astype(dt)
            continue
        if flat_scalars and len(specs) == 1:
            cols[0][d, :counts[d]] = np.asarray(part, dtype=specs[0][0])
            continue
        # general path: flatten rows to leaves column-wise
        leaf_lists = [[] for _ in specs]
        for rec in part:
            leaves = jax.tree_util.tree_leaves(rec)
            for li, leaf in enumerate(leaves):
                leaf_lists[li].append(leaf)
        for li, (dt, shape) in enumerate(specs):
            cols[li][d, :counts[d]] = np.asarray(leaf_lists[li], dtype=dt)
    if key_leaf is not None and cols[key_leaf].size:
        kc = cols[key_leaf]
        if np.issubdtype(kc.dtype, np.floating):
            if np.isinf(kc).any() or np.isnan(kc).any():
                raise ValueError("inf/nan float key collides with device "
                                 "padding; taking the host path")
        else:
            # sentinel check against the SPEC dtype (a narrowed i32
            # column can never hold the i64 sentinel; reuse the fit
            # scan's max instead of rescanning)
            hi = (col_stats[key_leaf][1] if key_leaf in col_stats
                  else int(kc.max()))
            if hi == int(np.iinfo(np.dtype(specs[key_leaf][0])).max):
                raise ValueError("key equal to the device sentinel; "
                                 "taking the host path")
    sharding = NamedSharding(mesh, P(AXIS))
    dev_cols = [put_sharded(c, sharding) for c in cols]
    dev_counts = put_sharded(counts, NamedSharding(mesh, P(AXIS)))
    return Batch(treedef, dev_cols, dev_counts)


@jax.jit
def _masked_minmax(c, counts):
    """(min, max) over the VALID rows of a (ndev, cap) column (padding
    content — e.g. the int64 key sentinel — must not block narrowing)."""
    valid = jnp.arange(c.shape[1])[None, :] < counts[:, None]
    lo = jnp.min(jnp.where(valid, c, jnp.iinfo(c.dtype).max))
    hi = jnp.max(jnp.where(valid, c, jnp.iinfo(c.dtype).min))
    return jnp.stack([lo, hi])


@jax.jit
def _cast_i32(c):
    return c.astype(jnp.int32)


def _egest_read(c, dev_counts):
    """One column device->host, narrowed to int32 on the wire when the
    column is large and every valid value fits: the real-chip tunnel
    egests at ~37 MB/s (BENCH_REAL_r03.md), so halving D2H bytes on
    int64 results halves collect() wall time.  Row lists are built via
    .tolist() downstream, so the narrowed dtype is invisible to
    callers; padding may wrap in the cast — no caller reads past the
    per-device counts."""
    if (conf.NARROW_EXCHANGE and c.ndim == 2
            and c.dtype == jnp.int64
            and int(c.nbytes) >= conf.EGEST_NARROW_MIN_BYTES):
        lo, hi = host_read(_masked_minmax(c, dev_counts))
        i32 = np.iinfo(np.int32)
        if lo >= i32.min and hi <= i32.max:
            return host_read(_cast_i32(c))
    return host_read(c)


def egest(batch):
    """Sharded Batch -> list of per-partition row lists (host).
    Multi-controller meshes replicate through host_read, so every rank
    egests the same full result set."""
    counts = host_read(batch.counts)
    total = sum(int(c.nbytes) for c in batch.cols)
    if total >= conf.EGEST_WARN_BYTES:
        from dpark_tpu.utils.log import get_logger
        get_logger("layout").warning(
            "egesting %.1f MB of device results to the host; on a "
            "tunneled chip this path runs at ~37 MB/s — prefer "
            "reducing on device (reduceByKey/aggregate) before "
            "collect(), or saveAs* sinks", total / (1 << 20))
    host_cols = [_egest_read(c, batch.counts) for c in batch.cols]
    # fast paths: scalar records, and arbitrarily-nested TUPLE records
    # (e.g. join's (k, (a, b))) rebuild with zips instead of a per-row
    # tree_unflatten
    sample = jax.tree_util.tree_unflatten(
        batch.treedef, list(range(len(batch.cols))))
    all_2d = all(c.ndim == 2 for c in host_cols)

    def _tuple_only(struct):
        if isinstance(struct, int):
            return True
        return (isinstance(struct, tuple)
                and all(_tuple_only(x) for x in struct))

    def _zip_build(struct, lists):
        if isinstance(struct, int):
            return lists[struct]
        parts = [_zip_build(x, lists) for x in struct]
        return list(zip(*parts))

    zipable = all_2d and _tuple_only(sample)
    bare_scalar = (len(batch.cols) == 1 and sample == 0 and all_2d)
    out = []
    for d in range(batch.ndev):
        n = int(counts[d])
        rows = []
        if n:
            if bare_scalar:
                rows = host_cols[0][d, :n].tolist()
            elif zipable:
                rows = _zip_build(
                    sample, [c[d, :n].tolist() for c in host_cols])
            else:
                per_leaf = [c[d, :n].tolist() for c in host_cols]
                for i in range(n):
                    rows.append(batch.unflatten_record(
                        [pl[i] for pl in per_leaf]))
        out.append(rows)
    return out


def key_width(treedef, specs, kinds="i"):
    """Number of leading KEY COLUMNS of a ``(key, value...)`` record.

    The key is leaf 0 when it is a scalar, or leaves 0..n-1 when it is
    a FLAT tuple of n scalars (``((k1, ..., kn), v)`` — the composite
    keys real dpark jobs use: ``((user, item), v)``, ``((src, dst),
    w)``).  Every key leaf must be a scalar whose dtype kind is in
    `kinds` ("i" for hash shuffles — portable_hash semantics are only
    reproduced on device for ints — "if" for range repartitioning).
    Nested key pytrees, >conf.MAX_KEY_LEAVES columns, or a disabled
    conf.TUPLE_KEYS return None (host fallback)."""
    from dpark_tpu import conf
    if not specs:
        return None
    sample = jax.tree_util.tree_unflatten(
        treedef, list(range(len(specs))))
    if not (isinstance(sample, tuple) and len(sample) >= 2):
        return None
    key = sample[0]
    if key == 0:
        nk = 1
    elif (conf.TUPLE_KEYS and isinstance(key, tuple)
          and 2 <= len(key) <= conf.MAX_KEY_LEAVES
          and all(key[i] == i for i in range(len(key)))):
        nk = len(key)
    else:
        return None
    for dt, shape in specs[:nk]:
        if shape != () or dt.kind not in kinds:
            return None
    return nk


def key_leaf_index(treedef, specs):
    """Back-compat shim: 0 when the record has a device-hashable key
    (scalar int leaf 0 — see key_width for the composite-key form),
    else None."""
    return 0 if key_width(treedef, specs, kinds="i") == 1 else None
