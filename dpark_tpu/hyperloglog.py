"""HyperLogLog approximate distinct counter.

Reference parity: dpark/hyperloglog.py (SURVEY.md section 2.1) — backs the
table DSL's adcount() and RDD-level approximate distinct counting.
Standard HLL with 2^p registers and the small/large-range corrections.
"""

import math

from dpark_tpu.utils.phash import portable_hash, fmix32


class HyperLogLog:
    def __init__(self, p=12):
        self.p = p
        self.m = 1 << p
        self.registers = bytearray(self.m)
        if p == 4:
            self.alpha = 0.673
        elif p == 5:
            self.alpha = 0.697
        elif p == 6:
            self.alpha = 0.709
        else:
            self.alpha = 0.7213 / (1 + 1.079 / self.m)

    def add(self, value):
        # 64-bit-ish hash from two independent 32-bit mixes
        h1 = portable_hash(value)
        h2 = fmix32(h1 ^ 0x9E3779B9)
        h = (h1 << 32) | h2
        idx = h & (self.m - 1)
        w = h >> self.p
        rank = 1
        # rank = position of the leftmost 1-bit of w within 64-p bits
        bits = 64 - self.p
        rank = bits - w.bit_length() + 1 if w else bits + 1
        if rank > self.registers[idx]:
            self.registers[idx] = rank

    def update(self, other):
        if other.p != self.p:
            raise ValueError("cannot merge HLLs of different precision")
        for i, r in enumerate(other.registers):
            if r > self.registers[i]:
                self.registers[i] = r
        return self

    def __len__(self):
        est = self.alpha * self.m * self.m / sum(
            2.0 ** -r for r in self.registers)
        if est <= 2.5 * self.m:
            zeros = self.registers.count(0)
            if zeros:
                est = self.m * math.log(self.m / float(zeros))
        elif est > (1 << 62):
            est = -(1 << 64) * math.log(1 - est / (1 << 64))
        return int(round(est))
