"""Broadcast: ship a read-only value to every worker once.

Reference parity: dpark/broadcast.py — Broadcast.__getstate__ ships only the
id; workers lazily fetch on first deref.  The reference distributes ~1MB
compressed chunks P2P/tree-style over zmq (SURVEY.md section 2.1).

Single-host design: the value is dumped once, compressed, to a file in the
shared workdir; worker processes mmap-read it on first access.  On the TPU
backend a broadcast value that is a jax.Array (or numpy) is realised as a
replicated device array via jax.device_put with a fully-replicated sharding
(backend/tpu/), which is the ICI equivalent of the reference's tree
broadcast.
"""

import os
import pickle
import threading

from dpark_tpu.utils import atomic_file, compress, decompress

_local_values = {}          # bid -> value, populated in creating process
_lock = threading.Lock()


class Broadcast:
    _next_id = [0]

    def __init__(self, value):
        Broadcast._next_id[0] += 1
        self.bid = Broadcast._next_id[0]
        self._value = value
        _local_values[self.bid] = value
        self._write_file(value)

    def _path(self):
        from dpark_tpu.env import env
        d = os.path.join(env.workdir, "broadcast")
        return os.path.join(d, "b%d" % self.bid)

    def _write_file(self, value):
        path = self._path()
        with atomic_file(path) as f:
            f.write(compress(pickle.dumps(value, -1)))

    @property
    def value(self):
        if self._value is None:
            with _lock:
                if self.bid in _local_values:
                    self._value = _local_values[self.bid]
                else:
                    with open(self._path(), "rb") as f:
                        self._value = pickle.loads(decompress(f.read()))
                    _local_values[self.bid] = self._value
        return self._value

    def __getstate__(self):
        return (self.bid,)

    def __setstate__(self, state):
        (self.bid,) = state
        self._value = _local_values.get(self.bid)

    def clear(self):
        _local_values.pop(self.bid, None)
        try:
            os.unlink(self._path())
        except OSError:
            pass
