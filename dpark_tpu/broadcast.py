"""Broadcast: ship a read-only value to every worker once.

Reference parity: dpark/broadcast.py — Broadcast.__getstate__ ships only
the id; workers lazily fetch on first deref.  The reference distributes
~1MB compressed chunks P2P/tree-style over zmq (SURVEY.md section 2.1).

Layout: the value pickles+compresses once, then splits into CHUNK-sized
pieces under workdir/broadcast (b<id>.meta + b<id>.<i>).  Same-host
workers read the files directly; remote workers fetch the chunks over
TCP (dpark_tpu/dcn.py).  On the TPU backend a broadcast value that is a
jax.Array (or numpy) is realised as a replicated device array via
jax.device_put with a fully-replicated sharding — the ICI equivalent of
the reference's tree broadcast.

P2P fan-out (the reference's defining broadcast mechanism): when a
tracker is configured (DPARK_TRACKER), every host that HOLDS a chunk is
registered per chunk under "bcast:<bid>:<i>", and fetchers pick a
random NON-ORIGIN holder for each chunk when one exists — so the origin
serves each chunk O(1) times and the serving capacity grows with every
completed fetch.  Fetchers that run a bucket server register themselves
chunk-by-chunk AS THEY FETCH, so a large value fans out through peers
even while the first fetch is still in flight.  Without a tracker the
handle falls back to fetching everything from the origin.
"""

import os
import pickle
import random
import struct
import threading

from dpark_tpu.utils import atomic_file, compress, decompress

CHUNK = 1 << 20                      # ~1MB compressed per chunk

_local_values = {}          # bid -> value, populated in creating process
_lock = threading.Lock()
_trackers = {}              # tracker addr -> TrackerClient (per process)


def _tracker_for(addr):
    if addr is None:
        return None
    cli = _trackers.get(addr)
    if cli is None:
        from dpark_tpu.tracker import TrackerClient
        cli = _trackers[addr] = TrackerClient(addr)
    return cli


def _fetch_chunk(pool, src, bid, i):
    """One chunk from one holder — over the bulk data plane
    (ISSUE 12: the P2P fan-out rides the same chunk-framed channel,
    per-peer window, and retry schedule as shuffle data) unless
    disabled or the holder predates the protocol."""
    from dpark_tpu import conf
    if conf.BULK_PLANE:
        from dpark_tpu import bulkplane
        try:
            return bulkplane.fetch_bcast(src, bid, i)
        except bulkplane.BulkUnsupported:
            pass
    return pool.fetch(src, ("bcast", bid, i))


class Broadcast:
    _next_id = [0]

    def __init__(self, value):
        Broadcast._next_id[0] += 1
        self.bid = Broadcast._next_id[0]
        self._value = value
        self._origin = None
        self._tracker_addr = None
        _local_values[self.bid] = value
        nchunks = self._write_chunks(value)
        from dpark_tpu.env import env
        if env.bucket_server is not None:
            self._origin = env.bucket_server.addr
        if env.tracker_client is not None and self._origin is not None:
            # one RPC regardless of value size: the ORIGIN is an
            # implicit holder of every chunk (fetchers fall back to it
            # whenever the per-chunk holder set has no peers), so only
            # the chunk count needs publishing here
            self._tracker_addr = env.tracker_addr
            env.tracker_client.set("bcast_meta:%d" % self.bid, nchunks)

    def _dir(self):
        from dpark_tpu.env import env
        return os.path.join(env.workdir, "broadcast")

    def _write_chunks(self, value):
        blob = compress(pickle.dumps(value, -1))
        d = self._dir()
        nchunks = max(1, (len(blob) + CHUNK - 1) // CHUNK)
        for i in range(nchunks):
            with atomic_file(os.path.join(
                    d, "b%d.%d" % (self.bid, i))) as f:
                f.write(blob[i * CHUNK:(i + 1) * CHUNK])
        with atomic_file(os.path.join(d, "b%d.meta" % self.bid)) as f:
            f.write(struct.pack("!I", nchunks))
        return nchunks

    def _read_local(self):
        d = self._dir()
        with open(os.path.join(d, "b%d.meta" % self.bid), "rb") as f:
            (nchunks,) = struct.unpack("!I", f.read(4))
        parts = []
        for i in range(nchunks):
            with open(os.path.join(d, "b%d.%d" % (self.bid, i)),
                      "rb") as f:
                parts.append(f.read())
        return pickle.loads(decompress(b"".join(parts)))

    def _fetch_remote(self):
        """Chunked fetch with P2P holder selection.

        With a tracker: each chunk is pulled from a random NON-ORIGIN
        holder when one exists (origin only as first/fallback source),
        and if this process serves a bucket server it registers itself
        as a holder chunk-by-chunk as the bytes land — fan-out grows
        while the fetch is still running.  Each chunk re-plans its
        holder from the live registry and rides a pooled connection to
        that peer (connections are reused per peer, requests stay
        per-chunk so late-arriving holders spread load).  Without a
        tracker: everything from the origin over one connection.

        Fetched chunks are also re-written into the LOCAL workdir so
        CO-LOCATED workers (same workdir) read files instead of
        re-fetching."""
        from dpark_tpu import dcn
        from dpark_tpu.env import env
        tracker = _tracker_for(self._tracker_addr)
        nchunks = None
        if tracker is not None:
            nchunks = tracker.get("bcast_meta:%d" % self.bid)
        if nchunks is None:
            meta = dcn.fetch(self._origin, ("bcast_meta", self.bid))
            (nchunks,) = struct.unpack("!I", meta)
        my_uri = env.bucket_server.addr if env.bucket_server else None
        d = self._dir()
        parts = [None] * nchunks

        def land(i, blob):
            parts[i] = blob
            try:
                with atomic_file(os.path.join(
                        d, "b%d.%d" % (self.bid, i))) as f:
                    f.write(blob)
            except OSError:
                return                   # read-only workdir: no cache,
                                         # and never register as holder
            if tracker is not None and my_uri is not None:
                tracker.add_item("bcast:%d:%d" % (self.bid, i), my_uri)

        # per-chunk source re-planning over pooled connections:
        # concurrent fetchers start at RANDOM offsets, so they land
        # different chunks first, register them, and feed each other
        # while still fetching — the holder query happens per chunk,
        # not once up front
        pool = dcn.FetchPool()
        start = random.randrange(nchunks)
        try:
            for i in [(start + j) % nchunks for j in range(nchunks)]:
                src = self._origin
                if tracker is not None:
                    peers = sorted({h for h in (tracker.get(
                        "bcast:%d:%d" % (self.bid, i)) or [])
                        if h != my_uri and h != self._origin})
                    if peers:
                        src = random.choice(peers)
                try:
                    blob = _fetch_chunk(pool, src, self.bid, i)
                except (IOError, OSError):
                    if src == self._origin:
                        raise              # origin down: unrecoverable
                    blob = _fetch_chunk(pool, self._origin,
                                        self.bid, i)
                land(i, blob)
        finally:
            pool.close()
        try:
            with atomic_file(os.path.join(
                    d, "b%d.meta" % self.bid)) as f:
                f.write(struct.pack("!I", nchunks))
        except OSError:
            pass
        return pickle.loads(decompress(b"".join(parts)))

    @property
    def value(self):
        if self._value is None:
            with _lock:
                if self.bid in _local_values:
                    self._value = _local_values[self.bid]
                else:
                    try:
                        self._value = self._read_local()
                    except OSError:
                        if self._origin is None:
                            raise
                        self._value = self._fetch_remote()
                    _local_values[self.bid] = self._value
        return self._value

    def __getstate__(self):
        return (self.bid, self._origin, self._tracker_addr)

    def __setstate__(self, state):
        if len(state) == 2:              # handle from an older writer
            state = state + (None,)
        self.bid, self._origin, self._tracker_addr = state
        self._value = _local_values.get(self.bid)

    def clear(self):
        _local_values.pop(self.bid, None)
        d = self._dir()
        try:
            with open(os.path.join(d, "b%d.meta" % self.bid),
                      "rb") as f:
                (nchunks,) = struct.unpack("!I", f.read(4))
        except OSError:
            return
        for i in range(nchunks):
            try:
                os.unlink(os.path.join(d, "b%d.%d" % (self.bid, i)))
            except OSError:
                pass
        try:
            os.unlink(os.path.join(d, "b%d.meta" % self.bid))
        except OSError:
            pass
