"""Broadcast: ship a read-only value to every worker once.

Reference parity: dpark/broadcast.py — Broadcast.__getstate__ ships only
the id; workers lazily fetch on first deref.  The reference distributes
~1MB compressed chunks P2P/tree-style over zmq (SURVEY.md section 2.1).

Layout: the value pickles+compresses once, then splits into CHUNK-sized
pieces under workdir/broadcast (b<id>.meta + b<id>.<i>).  Same-host
workers read the files directly; remote workers fetch the chunks over
TCP from the origin's bucket server (dpark_tpu/dcn.py), whose address
rides along in the pickled handle.  On the TPU backend a broadcast value
that is a jax.Array (or numpy) is realised as a replicated device array
via jax.device_put with a fully-replicated sharding — the ICI equivalent
of the reference's tree broadcast.
"""

import os
import pickle
import struct
import threading

from dpark_tpu.utils import atomic_file, compress, decompress

CHUNK = 1 << 20                      # ~1MB compressed per chunk

_local_values = {}          # bid -> value, populated in creating process
_lock = threading.Lock()


class Broadcast:
    _next_id = [0]

    def __init__(self, value):
        Broadcast._next_id[0] += 1
        self.bid = Broadcast._next_id[0]
        self._value = value
        self._origin = None
        _local_values[self.bid] = value
        self._write_chunks(value)
        from dpark_tpu.env import env
        if env.bucket_server is not None:
            self._origin = env.bucket_server.addr

    def _dir(self):
        from dpark_tpu.env import env
        return os.path.join(env.workdir, "broadcast")

    def _write_chunks(self, value):
        blob = compress(pickle.dumps(value, -1))
        d = self._dir()
        nchunks = max(1, (len(blob) + CHUNK - 1) // CHUNK)
        for i in range(nchunks):
            with atomic_file(os.path.join(
                    d, "b%d.%d" % (self.bid, i))) as f:
                f.write(blob[i * CHUNK:(i + 1) * CHUNK])
        with atomic_file(os.path.join(d, "b%d.meta" % self.bid)) as f:
            f.write(struct.pack("!I", nchunks))

    def _read_local(self):
        d = self._dir()
        with open(os.path.join(d, "b%d.meta" % self.bid), "rb") as f:
            (nchunks,) = struct.unpack("!I", f.read(4))
        parts = []
        for i in range(nchunks):
            with open(os.path.join(d, "b%d.%d" % (self.bid, i)),
                      "rb") as f:
                parts.append(f.read())
        return pickle.loads(decompress(b"".join(parts)))

    def _fetch_remote(self):
        """Chunked fetch over ONE TCP connection to the origin's bucket
        server.  The fetched chunks are re-written into the LOCAL
        workdir so CO-LOCATED workers (same workdir) read files instead
        of re-fetching.  Handles still point every remote host at the
        single origin — the reference's tree/P2P fan-out (re-routing
        fetchers to peers that already hold the value) is not
        implemented."""
        from dpark_tpu import dcn
        meta = dcn.fetch(self._origin, ("bcast_meta", self.bid))
        (nchunks,) = struct.unpack("!I", meta)
        parts = dcn.fetch_many(
            self._origin,
            [("bcast", self.bid, i) for i in range(nchunks)])
        try:
            d = self._dir()
            for i, blob in enumerate(parts):
                with atomic_file(os.path.join(
                        d, "b%d.%d" % (self.bid, i))) as f:
                    f.write(blob)
            with atomic_file(os.path.join(
                    d, "b%d.meta" % self.bid)) as f:
                f.write(struct.pack("!I", nchunks))
        except OSError:
            pass                         # read-only workdir: skip cache
        return pickle.loads(decompress(b"".join(parts)))

    @property
    def value(self):
        if self._value is None:
            with _lock:
                if self.bid in _local_values:
                    self._value = _local_values[self.bid]
                else:
                    try:
                        self._value = self._read_local()
                    except OSError:
                        if self._origin is None:
                            raise
                        self._value = self._fetch_remote()
                    _local_values[self.bid] = self._value
        return self._value

    def __getstate__(self):
        return (self.bid, self._origin)

    def __setstate__(self, state):
        self.bid, self._origin = state
        self._value = _local_values.get(self.bid)

    def clear(self):
        _local_values.pop(self.bid, None)
        d = self._dir()
        try:
            with open(os.path.join(d, "b%d.meta" % self.bid),
                      "rb") as f:
                (nchunks,) = struct.unpack("!I", f.read(4))
        except OSError:
            return
        for i in range(nchunks):
            try:
                os.unlink(os.path.join(d, "b%d.%d" % (self.bid, i)))
            except OSError:
                pass
        try:
            os.unlink(os.path.join(d, "b%d.meta" % self.bid))
        except OSError:
            pass
