"""Shuffle data plane: map-output bucket files, reduce-side fetch + merge.

Reference parity: dpark/shuffle.py — LocalFileShuffle (bucket file layout
under the workdir), SimpleShuffleFetcher / ParallelShuffleFetcher (per-map
fetch + unpickle), and the Merger hierarchy (hash-dict combine, heap merge
for the sorted path, disk-spilling external merge, CoGroupMerger)
(SURVEY.md sections 2.1 and 3.1 hot loop #3).

Single-host layout: all processes share env.workdir, so "fetch" is a local
file read; a multi-host HTTP server can front the same layout later.  The
TPU backend bypasses this module entirely — its shuffle is lax.all_to_all
over ICI (backend/tpu/).
"""

import heapq
import os
import pickle
import struct
import threading
from queue import Queue

from dpark_tpu import conf, faults
from dpark_tpu.utils import atomic_file, compress, decompress
from dpark_tpu.utils.log import get_logger

logger = get_logger("shuffle")


class SpillWriteError(OSError):
    """A spill-run write failed (ENOSPC and friends).  The device
    path's background writer surfaces this on the CONSUMING stage as a
    task failure — the scheduler's retry/escalation accounting owns
    it — instead of dying silently on the writer thread."""


class SpillCorruption(IOError):
    """A spill run failed its crc32c integrity check.  Callers
    translate this into FetchFailed (lineage recompute) rather than
    unpickling garbage into a silently wrong answer."""


def spill_crc(blob):
    """Checksum for spill-run framing: native crc32c when the C
    library is loaded, else C-speed zlib.crc32 — the pure-Python
    crc32c table loop (~MB/s) would dominate the spill hot path the
    runs exist to accelerate.  Spill runs are written and read by the
    same host/installation, so the polynomial only needs to be
    consistent within a process, never across heterogeneous peers."""
    from dpark_tpu import native
    if native.get_lib() is not None:
        return native.crc32c(blob)
    import zlib
    return zlib.crc32(blob) & 0xFFFFFFFF


class LocalFileShuffle:
    @staticmethod
    def get_output_file(shuffle_id, map_id, reduce_id, workdir=None):
        if workdir is None:
            from dpark_tpu.env import env
            workdir = env.workdir
        d = os.path.join(workdir, "shuffle", str(shuffle_id), str(map_id))
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, str(reduce_id))

    @staticmethod
    def get_server_uri(workdir=None):
        from dpark_tpu.env import env
        if workdir is None:
            workdir = env.workdir
        # with a bucket server running, advertise the network uri so
        # other hosts can fetch; same-host readers go through TCP too
        # (loopback — still one copy)
        if env.bucket_server is not None:
            return env.bucket_server.addr
        return "file://" + workdir

    @staticmethod
    def write_buckets(shuffle_id, map_id, buckets):
        """buckets: list (len = n_reduce) of dict or list of (k, combiner).

        Returns the server URI advertising these outputs."""
        for reduce_id, bucket in enumerate(buckets):
            items = list(bucket.items()) if isinstance(bucket, dict) \
                else list(bucket)
            path = LocalFileShuffle.get_output_file(
                shuffle_id, map_id, reduce_id)
            with atomic_file(path) as f:
                f.write(compress(pickle.dumps(items, -1)))
        return LocalFileShuffle.get_server_uri()


# device-resident shuffle outputs: the TPU executor registers an exporter
# here so host-path stages can read HBM buckets through the same protocol
HBM_EXPORTERS = {}


def read_bucket(uri, shuffle_id, map_id, reduce_id):
    """Fetch one map output bucket, yielding (k, combiner) pairs."""
    if uri.startswith("hbm://"):
        for exporter in HBM_EXPORTERS.values():
            try:
                return exporter(shuffle_id, map_id, reduce_id)
            except KeyError:
                continue
        raise ValueError("no exporter for %r" % uri)
    if uri.startswith("file://"):
        workdir = uri[len("file://"):]
        path = os.path.join(workdir, "shuffle", str(shuffle_id),
                            str(map_id), str(reduce_id))
        with open(path, "rb") as f:
            return pickle.loads(decompress(f.read()))
    if uri.startswith("tcp://"):
        # cross-host fetch from the serving worker's bucket server
        from dpark_tpu import dcn
        payload = dcn.fetch(
            uri, ("bucket", shuffle_id, map_id, reduce_id))
        return pickle.loads(decompress(payload))
    raise ValueError("unsupported shuffle uri %r" % uri)


def uri_host(uri):
    """The host-health key of a shuffle location: the peer hostname for
    tcp:// uris, the uri itself otherwise (file/hbm locations fail for
    local reasons, but tracking them is still harmless)."""
    if uri.startswith("tcp://"):
        return uri[len("tcp://"):].rpartition(":")[0]
    return uri


def read_bucket_any(uris, shuffle_id, map_id, reduce_id):
    """Fetch one map output from the best of its REPLICA locations.

    `uris`: one uri string, or a list/tuple of replicas (a map output
    re-served from several hosts).  Replicas are tried in
    hostatus-ranked order — a blacklisted host is skipped while any
    healthy replica exists, and every attempt's outcome feeds back into
    the shared health view (SURVEY.md section 5.3: the blacklist must
    CHANGE where the bytes come from, not just count failures).
    Raises FetchFailed when every replica fails."""
    from dpark_tpu.env import env
    if isinstance(uris, str):
        uris = (uris,)
    hm = env.host_manager
    ordered = list(uris)
    if len(ordered) > 1:
        # hostatus ranking by each replica's HOST (two replicas on one
        # host share fate): healthy-first, then by recent failure rate
        ordered = hm.rank_items(ordered, uri_host)
    last_err = None
    for uri in ordered:
        try:
            # chaos site: one hit per fetch ATTEMPT, so replica
            # fallback and the FetchFailed translation below are both
            # exercised by injection
            faults.hit("shuffle.fetch")
            items = read_bucket(uri, shuffle_id, map_id, reduce_id)
        except Exception as e:
            hm.task_failed_on(uri_host(uri))
            logger.warning("fetch failed %s: %s", uri, e)
            last_err = e
            continue
        if uri.startswith("tcp://"):
            hm.task_succeed_on(uri_host(uri))
        return items
    if isinstance(last_err, FetchFailed):
        raise last_err
    err = FetchFailed(ordered[0] if ordered else None, shuffle_id,
                      map_id, reduce_id)
    err.__cause__ = last_err        # the real I/O error, not a blank tuple
    raise err


class SimpleShuffleFetcher:
    """Sequential fetch of every map output for one reduce partition."""

    def fetch(self, shuffle_id, reduce_id, merge_func):
        from dpark_tpu.env import env
        locs = env.map_output_tracker.get_outputs(shuffle_id)
        if locs is None:
            raise FetchFailed(None, shuffle_id, -1, reduce_id)
        for map_id, uri in enumerate(locs):
            if uri is None:
                raise FetchFailed(uri, shuffle_id, map_id, reduce_id)
            items = read_bucket_any(uri, shuffle_id, map_id, reduce_id)
            merge_func(items)

    def stop(self):
        pass


class ParallelShuffleFetcher(SimpleShuffleFetcher):
    """Thread-pool fetch (reference: ParallelShuffleFetcher).  On a single
    host file reads are fast; a small pool still overlaps decompression.

    Workers stop as soon as the consumer abandons the fetch (merge_func
    raised mid-merge) instead of fetching the remaining map outputs
    into buffers nobody will drain.

    Buckets are merged in MAP-ID ORDER, not thread-arrival order: the
    consumer holds out-of-order results in a reorder buffer until the
    next expected map id lands.  Combine ORDER is thereby deterministic
    and identical to the sequential fetcher — order-sensitive combiners
    (tuple `+` is concatenation) previously produced results that
    depended on thread scheduling, which surfaced as the order-dependent
    full-suite flake in test_analysis (ISSUE 4 satellite).  Unmerged
    buckets stay bounded by a PERMIT semaphore acquired before each
    fetch and released after each merge: in-flight + queued + reordered
    buckets never exceed 3 x nthreads, and progress is guaranteed
    because workers take map ids in order — the next-to-merge map's
    worker always already holds a permit (one stalled early map cannot
    let the others inflate the whole shuffle into RAM)."""

    def __init__(self, nthreads=4):
        self.nthreads = nthreads

    def fetch(self, shuffle_id, reduce_id, merge_func):
        from dpark_tpu.env import env
        locs = env.map_output_tracker.get_outputs(shuffle_id)
        if locs is None:
            raise FetchFailed(None, shuffle_id, -1, reduce_id)
        tasks = Queue()
        for map_id, uri in enumerate(locs):
            if uri is None:
                raise FetchFailed(uri, shuffle_id, map_id, reduce_id)
            tasks.put((map_id, uri))
        nthreads = min(self.nthreads, tasks.qsize() or 1)
        # the permit count bounds every fetched-but-unmerged bucket
        # (queue + reorder buffer + in-flight); the queue itself can be
        # unbounded because nothing enters it without a permit
        permits = threading.Semaphore(3 * nthreads)
        results = Queue()
        stop = threading.Event()

        def worker():
            while not stop.is_set():
                if not permits.acquire(timeout=0.5):
                    continue
                try:
                    map_id, uri = tasks.get_nowait()
                except Exception:
                    permits.release()
                    return
                try:
                    items = read_bucket_any(uri, shuffle_id, map_id,
                                            reduce_id)
                except BaseException as e:
                    # never die silently: the fetch loop counts results.
                    # A synthesized FetchFailed CHAINS the real error —
                    # "fetch failed" with the actual OSError/KeyError as
                    # __cause__, not a blank four-field tuple.
                    if isinstance(e, FetchFailed):
                        err = e
                    else:
                        err = FetchFailed(uri, shuffle_id, map_id,
                                          reduce_id)
                        err.__cause__ = e
                    results.put((map_id, err, None))
                    return
                results.put((map_id, None, items))

        threads = [threading.Thread(target=worker, daemon=True,
                                    name="dpark-fetch-worker")
                   for _ in range(nthreads)]
        for t in threads:
            t.start()
        try:
            pending = {}                  # map_id -> items, out of order
            next_id = 0
            for _ in range(len(locs)):
                map_id, err, items = results.get()
                if err is not None:
                    raise err             # fail fast, order irrelevant
                pending[map_id] = items
                while next_id in pending:
                    merge_func(pending.pop(next_id))
                    next_id += 1
                    permits.release()
        finally:
            stop.set()          # consumer done or raised: workers drain out


class FetchFailed(Exception):
    """Signals the DAG scheduler to resubmit the parent stage (lineage
    recovery — SURVEY.md section 5.3)."""

    def __init__(self, uri, shuffle_id, map_id, reduce_id):
        super().__init__(uri, shuffle_id, map_id, reduce_id)
        self.uri = uri
        self.shuffle_id = shuffle_id
        self.map_id = map_id
        self.reduce_id = reduce_id


# ---------------------------------------------------------------------------
# Mergers (reduce side)
# ---------------------------------------------------------------------------

class Merger:
    """Hash-dict combine of already-combined map outputs."""

    def __init__(self, aggregator):
        self.merge_combiners = aggregator.merge_combiners
        self.combined = {}

    def merge(self, items):
        d = self.combined
        mc = self.merge_combiners
        for k, c in items:
            if k in d:
                d[k] = mc(d[k], c)
            else:
                d[k] = c

    def __iter__(self):
        return iter(self.combined.items())


class SortMerger:
    """Heap k-way merge of sorted bucket runs (reference: heap_merged)."""

    def __init__(self, aggregator):
        self.merge_combiners = aggregator.merge_combiners
        self.runs = []

    def merge(self, items):
        self.runs.append(sorted(items, key=lambda kv: kv[0]))

    def __iter__(self):
        mc = self.merge_combiners
        cur_key, cur_val, have = None, None, False
        for k, v in heapq.merge(*self.runs, key=lambda kv: kv[0]):
            if have and k == cur_key:
                cur_val = mc(cur_val, v)
            else:
                if have:
                    yield cur_key, cur_val
                cur_key, cur_val, have = k, v, True
        if have:
            yield cur_key, cur_val


class DiskSpillMerger(Merger):
    """Memory-bounded merge: when the in-memory dict exceeds max_items the
    sorted contents spill to a run file; final iteration heap-merges the
    spills with the in-memory remainder (reference: external merger).

    Run files are written as length-prefixed COMPRESSED CHUNKS and read
    back through chunked streaming readers feeding heapq.merge, so the
    final merge holds one chunk per run in memory — re-inflating every
    run at once would hand back the whole dataset the spills existed to
    keep out of RAM.

    Each chunk is framed with its crc32c (ISSUE 5): a corrupted run
    surfaces as FetchFailed — the consuming task recomputes through
    lineage — instead of unpickling garbage.  `shuffle_id`/`reduce_id`
    tag that FetchFailed so the scheduler can route the recompute;
    without them corruption raises SpillCorruption (a plain task
    failure, still never a wrong answer)."""

    def __init__(self, aggregator, max_items=None, workdir=None,
                 shuffle_id=None, reduce_id=-1):
        super().__init__(aggregator)
        self.max_items = max_items or conf.SHUFFLE_CHUNK_RECORDS * 4
        self.workdir = workdir
        self.shuffle_id = shuffle_id
        self.reduce_id = reduce_id
        self.spills = []

    def merge(self, items):
        super().merge(items)
        if len(self.combined) >= self.max_items:
            self._spill()

    def _spill(self):
        if self.workdir is None:
            from dpark_tpu.env import env
            self.workdir = os.path.join(env.workdir, "spill")
        os.makedirs(self.workdir, exist_ok=True)
        path = os.path.join(self.workdir, "run-%d-%d"
                            % (id(self), len(self.spills)))
        items = sorted(self.combined.items(), key=lambda kv: kv[0])
        chunk = conf.SHUFFLE_CHUNK_RECORDS
        with atomic_file(path) as f:
            for i in range(0, len(items), chunk):
                blob = compress(pickle.dumps(items[i:i + chunk], -1))
                # crc over the TRUE bytes, computed before the chaos
                # site may corrupt them — exactly what disk rot does
                crc = spill_crc(blob)
                blob = faults.hit("shuffle.spill_write", blob)
                # 8-byte length: one chunk of giant combiners (a hot
                # key's list) must not overflow a 4 GiB prefix
                f.write(struct.pack("<QI", len(blob), crc))
                f.write(blob)
        self.spills.append(path)
        self.combined = {}

    def _iter_run(self, path):
        """Stream one spill run back chunk by chunk (sorted within and
        across chunks: the run was sorted before chunking), verifying
        each chunk's crc32c before unpickling."""
        with open(path, "rb") as f:
            while True:
                hdr = f.read(12)
                if not hdr:
                    return
                n, crc = struct.unpack("<QI", hdr)
                blob = faults.hit("shuffle.spill_read", f.read(n))
                if spill_crc(blob) != crc:
                    err = SpillCorruption(
                        "spill run %s: crc32c mismatch (corrupted "
                        "chunk)" % path)
                    if self.shuffle_id is not None:
                        # lineage recompute: the scheduler retries the
                        # consuming stage (its map outputs are intact)
                        ff = FetchFailed(None, self.shuffle_id, -1,
                                         self.reduce_id)
                        ff.__cause__ = err
                        raise ff
                    raise err
                for kv in pickle.loads(decompress(blob)):
                    yield kv

    def __iter__(self):
        if not self.spills:
            return iter(self.combined.items())
        runs = [iter(sorted(self.combined.items(),
                            key=lambda kv: kv[0]))]
        runs += [self._iter_run(path) for path in self.spills]
        mc = self.merge_combiners

        def gen():
            cur_key, cur_val, have = None, None, False
            for k, v in heapq.merge(*runs, key=lambda kv: kv[0]):
                if have and k == cur_key:
                    cur_val = mc(cur_val, v)
                else:
                    if have:
                        yield cur_key, cur_val
                    cur_key, cur_val, have = k, v, True
            if have:
                yield cur_key, cur_val
        return gen()


class CoGroupMerger:
    """Merge n sources into key -> tuple of n lists (reference:
    CoGroupMerger backing CoGroupedRDD)."""

    def __init__(self, n_sources):
        self.n = n_sources
        self.combined = {}

    def _slot(self, key):
        slot = self.combined.get(key)
        if slot is None:
            slot = tuple([] for _ in range(self.n))
            self.combined[key] = slot
        return slot

    def append(self, src_index, items):
        """items of (k, v) from a narrow (non-shuffled) source."""
        for k, v in items:
            self._slot(k)[src_index].append(v)

    def extend(self, src_index, items):
        """items of (k, list_of_v) from a shuffled source."""
        for k, vs in items:
            self._slot(k)[src_index].extend(vs)

    def __iter__(self):
        return iter(self.combined.items())
