"""Shuffle data plane: map-output bucket files, reduce-side fetch + merge.

Reference parity: dpark/shuffle.py — LocalFileShuffle (bucket file layout
under the workdir), SimpleShuffleFetcher / ParallelShuffleFetcher (per-map
fetch + unpickle), and the Merger hierarchy (hash-dict combine, heap merge
for the sorted path, disk-spilling external merge, CoGroupMerger)
(SURVEY.md sections 2.1 and 3.1 hot loop #3).

Single-host layout: all processes share env.workdir, so "fetch" is a local
file read; a multi-host HTTP server can front the same layout later.  The
TPU backend bypasses this module entirely — its shuffle is lax.all_to_all
over ICI (backend/tpu/).
"""

import heapq
import os
import pickle
import struct
import threading
import time
from queue import Empty, Queue

from dpark_tpu import coding, conf, faults, locks, trace
from dpark_tpu.utils import atomic_file, compress, decompress
from dpark_tpu.utils.log import get_logger

logger = get_logger("shuffle")


class SpillWriteError(OSError):
    """A spill-run write failed (ENOSPC and friends).  The device
    path's background writer surfaces this on the CONSUMING stage as a
    task failure — the scheduler's retry/escalation accounting owns
    it — instead of dying silently on the writer thread."""


class SpillCorruption(IOError):
    """A spill run failed its crc32c integrity check.  Callers
    translate this into FetchFailed (lineage recompute) rather than
    unpickling garbage into a silently wrong answer."""


def spill_crc(blob):
    """Checksum for spill-run framing: native crc32c when the C
    library is loaded, else C-speed zlib.crc32 — the pure-Python
    crc32c table loop (~MB/s) would dominate the spill hot path the
    runs exist to accelerate.  Spill runs are written and read by the
    same host/installation, so the polynomial only needs to be
    consistent within a process, never across heterogeneous peers."""
    from dpark_tpu import native
    if native.get_lib() is not None:
        return native.crc32c(blob)
    import zlib
    return zlib.crc32(blob) & 0xFFFFFFFF


class LocalFileShuffle:
    @staticmethod
    def get_output_file(shuffle_id, map_id, reduce_id, workdir=None):
        if workdir is None:
            from dpark_tpu.env import env
            workdir = env.workdir
        d = os.path.join(workdir, "shuffle", str(shuffle_id), str(map_id))
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, str(reduce_id))

    @staticmethod
    def get_server_uri(workdir=None):
        from dpark_tpu.env import env
        if workdir is None:
            workdir = env.workdir
        # with a bucket server running, advertise the network uri so
        # other hosts can fetch; same-host readers go through TCP too
        # (loopback — still one copy)
        if env.bucket_server is not None:
            return env.bucket_server.addr
        return "file://" + workdir

    @staticmethod
    def write_buckets(shuffle_id, map_id, buckets):
        """buckets: list (len = n_reduce) of dict or list of (k, combiner).

        With a shuffle code active (DPARK_SHUFFLE_CODE — ISSUE 6) each
        bucket is written as ONE shard-container file (n = k+m framed
        erasure shards with per-shard crc32c, `<reduce>.shards`): a
        local read decodes the container in one I/O, while remote
        peers fetch individual shard frames concurrently and decode
        from the fastest k — an injected/real fetch failure costs a
        decode, not a lineage recompute.

        Returns the server URI advertising these outputs."""
        # per-exchange override first (ISSUE 19): the adaptive policy
        # may have priced THIS shuffle coded while the global code is
        # off, or pinned it uncoded under a global rs(k,m)
        code = coding.shuffle_code(shuffle_id)
        for reduce_id, bucket in enumerate(buckets):
            items = list(bucket.items()) if isinstance(bucket, dict) \
                else list(bucket)
            path = LocalFileShuffle.get_output_file(
                shuffle_id, map_id, reduce_id)
            blob = compress(pickle.dumps(items, -1))
            # no fsync: bucket files are lineage-recomputable, and the
            # per-file durability barrier dominates the bucket write
            if code is None:
                with atomic_file(path, fsync=False) as f:
                    f.write(blob)
                continue
            data = coding.encode_container(blob, code)
            coding.note_parity_bytes(len(data) - len(blob))
            with atomic_file(path + ".shards", fsync=False) as f:
                f.write(data)
        return LocalFileShuffle.get_server_uri()


# device-resident shuffle outputs: the TPU executor registers an exporter
# here so host-path stages can read HBM buckets through the same protocol
HBM_EXPORTERS = {}

# columnar twin (ISSUE 12): exporters that answer with
# (meta, [numpy column arrays]) instead of Python rows, so the bulk
# data plane can serve RAW COLUMN BYTES to a peer controller — no
# per-row pickling anywhere on the wire path.  KeyError = not my
# shuffle (try the next exporter); ValueError = mine but the record
# shape can't columnarize (the serving side falls back to the pickled
# payload, still chunk-framed on the bulk channel).
HBM_COL_EXPORTERS = {}


def read_bucket(uri, shuffle_id, map_id, reduce_id):
    """Fetch one map output bucket, yielding (k, combiner) pairs."""
    if uri.startswith("hbm://"):
        for exporter in HBM_EXPORTERS.values():
            try:
                return exporter(shuffle_id, map_id, reduce_id)
            except KeyError:
                continue
        raise ValueError("no exporter for %r" % uri)
    if uri.startswith("file://"):
        workdir = uri[len("file://"):]
        path = os.path.join(workdir, "shuffle", str(shuffle_id),
                            str(map_id), str(reduce_id))
        with open(path, "rb") as f:
            return pickle.loads(decompress(f.read()))
    if uri.startswith("tcp://"):
        # cross-host fetch from the serving worker's bucket server —
        # over the chunked bulk data plane (ISSUE 12) unless disabled
        # or the peer predates the protocol
        if conf.BULK_PLANE:
            from dpark_tpu import bulkplane
            try:
                return bulkplane.fetch_bucket_items(
                    uri, shuffle_id, map_id, reduce_id)
            except bulkplane.BulkUnsupported:
                pass
        from dpark_tpu import dcn
        payload = dcn.fetch(
            uri, ("bucket", shuffle_id, map_id, reduce_id))
        return pickle.loads(decompress(payload))
    raise ValueError("unsupported shuffle uri %r" % uri)


def read_bucket_shard(uri, shuffle_id, map_id, reduce_id, idx):
    """Fetch ONE framed shard of a coded map output bucket (the
    remote fetch unit; local file:// fetches read the whole container
    instead — see _fetch_coded_local)."""
    if uri.startswith("hbm://"):
        for exporter in HBM_EXPORTERS.values():
            try:
                return exporter(shuffle_id, map_id, reduce_id,
                                shard=idx)
            except KeyError:
                continue
        raise ValueError("no exporter for %r" % uri)
    if uri.startswith("file://"):
        workdir = uri[len("file://"):]
        path = os.path.join(workdir, "shuffle", str(shuffle_id),
                            str(map_id), "%d.shards" % reduce_id)
        with open(path, "rb") as f:
            return coding.extract_container_frame(f.read(), idx)
    if uri.startswith("tcp://"):
        payload = None
        fetched = False
        if conf.BULK_PLANE:
            # coded shard frames ride the bulk channel too (ISSUE 12):
            # the fastest-k-of-n race runs process-to-process with the
            # same framing/retry/counters as whole buckets
            from dpark_tpu import bulkplane
            try:
                payload = bulkplane.fetch_shard(
                    uri, shuffle_id, map_id, reduce_id, idx)
                fetched = True
            except bulkplane.BulkUnsupported:
                pass
        if not fetched:
            from dpark_tpu import dcn
            payload = dcn.fetch(
                uri, ("bucket_shard", shuffle_id, map_id, reduce_id,
                      idx))
        if not payload:
            # the peer's miss sentinel: that bucket has no shard files
            # (written uncoded) — the caller falls back to the plain
            # bucket protocol
            raise FileNotFoundError(
                "no shard %d for %d/%d/%d at %s"
                % (idx, shuffle_id, map_id, reduce_id, uri))
        return payload
    raise ValueError("unsupported shuffle uri %r" % uri)


def uri_host(uri):
    """The host-health key of a shuffle location: the peer hostname for
    tcp:// uris, the uri itself otherwise (file/hbm locations fail for
    local reasons, but tracking them is still harmless)."""
    if uri.startswith("tcp://"):
        return uri[len("tcp://"):].rpartition(":")[0]
    return uri


def peer_label(uri):
    """BOUNDED peer identity for health-plane site keys (ISSUE 14):
    remote uris key by their serving host, every local scheme
    collapses to "local" — a per-path key would grow one sketch per
    spill file and blow the site cap."""
    if uri.startswith("tcp://"):
        return uri[len("tcp://"):].rpartition(":")[0] or "local"
    if uri.startswith("hbm://"):
        host = uri[len("hbm://"):].split("/", 1)[0].rpartition(":")[0]
        return host or "local"
    return "local"


class _Uncoded(Exception):
    """Internal: the bucket has no shard files anywhere — it was
    written without parity.  The caller retries the plain protocol."""


class PeerSuspect(ConnectionError):
    """A shard/bucket attempt was failed FAST because the serving
    peer's liveness lease expired (ISSUE 20): the coded race decodes
    from parity held by live peers instead of waiting out a socket
    timeout against a corpse.  A ConnectionError subclass so every
    existing transport-failure path (retry, FetchFailed, lineage)
    handles it unchanged."""


# per-exchange observation accumulator (ISSUE 19): which peers served
# each shuffle THIS process fetched from, with per-peer fetch/decode
# counts and the summed fetch wall ms.  The scheduler drains it at job
# finish into adapt "xch" records — the input the straggler-adaptive
# code policy prices the next run from.  Worker processes of the
# multiprocess master accumulate in their own processes (the same
# per-process caveat as the decode counters).  Zero cost with the
# adapt plane off: one mode check per bucket fetch.
_XCH_LOCK = threading.Lock()
_XCH_OBS = {}


def _xch_note(shuffle_id, peer, kind="fetches", ms=0.0):
    from dpark_tpu import adapt
    if not adapt.enabled():
        return
    with _XCH_LOCK:
        ent = _XCH_OBS.setdefault(shuffle_id,
                                  {"peers": {}, "ms": 0.0})
        pc = ent["peers"].setdefault(str(peer), {})
        pc[kind] = pc.get(kind, 0) + 1
        if ms:
            ent["ms"] += float(ms)


def drain_exchange_observations(shuffle_ids=None):
    """Pop accumulated per-exchange observations, all of them or just
    `shuffle_ids` — {sid: {"peers": {peer: counts}, "ms": wall_ms}}."""
    with _XCH_LOCK:
        if shuffle_ids is None:
            out = dict(_XCH_OBS)
            _XCH_OBS.clear()
        else:
            out = {sid: _XCH_OBS.pop(sid)
                   for sid in list(shuffle_ids) if sid in _XCH_OBS}
    return out


class _ShardPool:
    """Persistent daemon worker pool for shard fetch attempts: a fresh
    thread per shard (n per bucket, every bucket) costs more than the
    local file read it performs — workers park on the task queue and
    are reused across buckets/jobs.  Grows lazily to `size`; daemon
    threads so a stuck peer read never blocks interpreter exit."""

    def __init__(self, size=32):
        self.tasks = Queue()
        self.size = size
        self.nthreads = 0
        self.lock = locks.named_lock("shuffle.shard_pool")

    def submit(self, fn, *args):
        self.tasks.put((fn, args))
        with self.lock:
            if self.nthreads < self.size:
                self.nthreads += 1
                threading.Thread(target=self._worker, daemon=True,
                                 name="dpark-shard-fetch").start()

    def _worker(self):
        while True:
            fn, args = self.tasks.get()
            fn(*args)       # attempt() never raises (result queue)


_SHARD_POOL = _ShardPool()


def _shard_miss(err):
    """Errors that mean 'this bucket was never coded' (vs a transient
    fetch failure worth retrying): missing shard file, no HBM store,
    no exporter owning the shuffle."""
    return isinstance(err, (FileNotFoundError, KeyError)) or (
        isinstance(err, ValueError) and "no exporter" in str(err))


def _fetch_coded(ordered, shuffle_id, map_id, reduce_id, code, hm):
    """Fastest-k-of-n shard fetch: issue ALL n shard reads
    concurrently, decode as soon as any k arrive.  A failed shard
    attempt retries up to conf.SHUFFLE_SHARD_ATTEMPTS times (cycling
    through replica uris); a straggling shard simply loses the race.
    Translates a short count into FetchFailed carrying
    shards_found/shards_needed only when fewer than k survive."""
    n, k = code.n, code.k
    results = Queue()
    attempts_cap = max(1, conf.SHUFFLE_SHARD_ATTEMPTS)

    def attempt(idx, attempt_no):
        uri = ordered[(attempt_no - 1) % len(ordered)]
        try:
            # chaos site: one hit per shard ATTEMPT — under injection
            # the decode-instead-of-recompute path is what's exercised
            faults.hit("shuffle.fetch")
            if uri.startswith("tcp://"):
                from dpark_tpu import dcn
                if not dcn.peer_alive(uri):
                    # lease-dead peer (ISSUE 20): fail this shard fast
                    # so parity from LIVE peers wins the k-of-n race
                    # instead of waiting out a socket timeout
                    raise PeerSuspect("peer lease expired: %s" % uri)
            raw = read_bucket_shard(uri, shuffle_id, map_id,
                                    reduce_id, idx)
            fr = coding.unpack_shard(raw)
            results.put((idx, None, fr, uri))
        except BaseException as e:
            results.put((idx, e, None, uri))

    def spawn(idx, attempt_no):
        _SHARD_POOL.submit(attempt, idx, attempt_no)

    for idx in range(n):
        spawn(idx, 1)
    outstanding = n
    tries = dict.fromkeys(range(n), 1)
    got = {}
    errors = {}
    misses = 0
    orig_len = 0
    had_error = False
    frame_code = None
    masked_peers = set()    # lease-dead peers whose shards parity covered
    while len(got) < k and outstanding:
        try:
            idx, err, fr, uri = results.get(
                timeout=conf.SHUFFLE_FETCH_WAIT_S)
        except Empty:
            # a wedged shard pool (dead worker, lost peer) must not
            # park the reduce task forever: fall through to the
            # shortfall path below, which raises FetchFailed and
            # hands the bucket to lineage recovery
            break
        outstanding -= 1
        if err is None:
            if frame_code is None:
                # the shards are SELF-DESCRIBING: the writer's
                # geometry (header algo/k/m) governs the decode, not
                # the reader's config — a reader whose configured code
                # drifted from the writer's must not solve the wrong
                # matrix against the payload bytes.  Extra writer
                # shards the initial fan-out didn't know about are
                # requested as soon as the true n is known.
                frame_code = coding.Code(fr.algo, fr.k, fr.m)
                for extra in range(n, frame_code.n):
                    tries[extra] = 1
                    spawn(extra, 1)
                    outstanding += 1
                n, k = frame_code.n, frame_code.k
            elif (fr.algo, fr.k, fr.m) != (frame_code.algo,
                                           frame_code.k,
                                           frame_code.m):
                # geometry disagreement inside one bucket: the frame
                # is corrupt or foreign — drop it like a failed shard
                had_error = True
                errors.setdefault(idx, coding.ShardCorrupt(
                    "shard %d: geometry %r != bucket %r"
                    % (idx, (fr.algo, fr.k, fr.m),
                       frame_code.describe())))
                continue
            if idx not in got:
                got[idx] = fr.payload
                orig_len = fr.orig_len
            if uri.startswith("tcp://"):
                hm.task_succeed_on(uri_host(uri))
            continue
        if _shard_miss(err):
            # an absent shard never materializes on the SAME replica,
            # but another replica may still hold it (e.g. the first
            # host lost its files): try each uri once before the miss
            # becomes definitive
            if tries[idx] < len(ordered):
                tries[idx] += 1
                spawn(idx, tries[idx])
                outstanding += 1
                continue
            errors.setdefault(idx, err)
            misses += 1
            continue
        had_error = True
        if isinstance(err, PeerSuspect):
            masked_peers.add(peer_label(uri))
        hm.task_failed_on(uri_host(uri))
        logger.warning("shard fetch failed %s #%d: %s", uri, idx, err)
        if tries[idx] < attempts_cap:
            tries[idx] += 1
            spawn(idx, tries[idx])
            outstanding += 1
        else:
            errors[idx] = err
    if len(got) < k:
        if misses >= n and not had_error:
            raise _Uncoded()
        peer = peer_label(ordered[0]) if ordered else "local"
        coding.note("decode_failures", shuffle_id, peer=peer)
        _xch_note(shuffle_id, peer, "decode_failures")
        err = FetchFailed(ordered[0] if ordered else None, shuffle_id,
                          map_id, reduce_id, shards_found=len(got),
                          shards_needed=k)
        err.__cause__ = next(iter(errors.values()), None)
        raise err
    # scoop up results that landed in the same instant without
    # blocking: data shards already in the queue beat reconstructing
    # their chunks from parity via GF arithmetic
    while outstanding:
        try:
            idx, err, fr, uri = results.get_nowait()
        except Empty:
            break
        outstanding -= 1
        if err is None and idx not in got and frame_code is not None \
                and (fr.algo, fr.k, fr.m) == (frame_code.algo,
                                              frame_code.k,
                                              frame_code.m):
            got[idx] = fr.payload
    used_parity = any(j not in got for j in range(k))
    blob = (frame_code or code).decode(got, orig_len)
    if used_parity:
        # parity actually reconstructed data: a failed shard was
        # REPAIRED, or a merely-slow one lost the race (straggler
        # win) — either way, zero lineage recompute
        kind = "repair" if had_error else "straggler_win"
        peer = peer_label(ordered[0]) if ordered else "local"
        coding.note(kind, shuffle_id, peer=peer)
        _xch_note(shuffle_id, peer, kind)
        # peer-death masked by parity (ISSUE 20 acceptance): the
        # lease layer failed a dead peer's shards fast and the decode
        # still closed from live shards — zero lineage recompute
        for dead in masked_peers:
            coding.note("peer_masked", shuffle_id, peer=dead)
    return pickle.loads(decompress(blob))


def _fetch_coded_local(ordered, shuffle_id, map_id, reduce_id):
    """Local (file://) coded fetch: ONE read of the bucket's shard
    container, then per-shard chaos-site routing + crc verification.
    A shard verifies ONCE per pass — one that raises (or whose
    injected corruption trips the crc) is an ERASURE the decode works
    around, exactly like a lost remote shard (repair counter).  Only
    a SHORTFALL (fewer than k verified) re-verifies the failed shards
    from the pristine container bytes, up to
    conf.SHUFFLE_SHARD_ATTEMPTS passes total: transient faults still
    rescue a multi-loss bucket without masking the decode path.

    With the `shuffle.fetch` chaos site armed the verifications RACE
    through the shard pool and decode proceeds from the fastest k, so
    an injected delay loses the race (straggler_win) just as a slow
    peer would remotely.  Without it they run inline, data shards
    first — a local read has no real stragglers, and with all k data
    shards intact the parity crcs need not be touched at all."""
    attempts_cap = max(1, conf.SHUFFLE_SHARD_ATTEMPTS)
    raw = None
    for uri in ordered:
        if not uri.startswith("file://"):
            continue
        path = os.path.join(uri[len("file://"):], "shuffle",
                            str(shuffle_id), str(map_id),
                            "%d.shards" % reduce_id)
        try:
            with open(path, "rb") as f:
                raw = f.read()
            break
        except FileNotFoundError:
            continue
    if raw is None:
        raise _Uncoded()        # no container anywhere: plain path
    frames = coding.parse_container(raw)
    k = frames[0].k if frames else 1
    orig_len = frames[0].orig_len if frames else 0
    good = {}
    failed = []
    had_error = False

    def verify(fr):
        payload = faults.hit("shuffle.fetch", fr.payload)
        if coding._crc(payload) != fr.crc:
            raise coding.ShardCorrupt(
                "shard %d: crc32c mismatch" % fr.idx)
        return payload

    if faults.site_active("shuffle.fetch"):
        results = Queue()

        def attempt(fr):
            try:
                results.put((fr, None, verify(fr)))
            except BaseException as e:
                results.put((fr, e, None))

        for fr in frames:
            _SHARD_POOL.submit(attempt, fr)
        outstanding = len(frames)
        while len(good) < k and outstanding:
            try:
                fr, err, payload = results.get(
                    timeout=conf.SHUFFLE_FETCH_WAIT_S)
            except Empty:
                # wedged pool: the shortfall re-verify below retries
                # from the pristine container bytes instead of
                # parking here forever
                had_error = True
                break
            outstanding -= 1
            if err is None:
                good.setdefault(fr.idx, payload)
            else:
                had_error = True
                failed.append(fr)
        # scoop up same-instant arrivals without blocking: data
        # shards already verified beat reconstructing their chunks
        # from parity via GF arithmetic
        while outstanding:
            try:
                fr, err, payload = results.get_nowait()
            except Empty:
                break
            outstanding -= 1
            if err is None:
                good.setdefault(fr.idx, payload)
    else:
        data = [fr for fr in frames if fr.idx < k]
        parity = [fr for fr in frames if fr.idx >= k]
        for fr in data:
            try:
                good[fr.idx] = verify(fr)
            except Exception:
                had_error = True
                failed.append(fr)
        if len(good) < k:       # real corruption: decode from parity
            for fr in parity:
                try:
                    good[fr.idx] = verify(fr)
                except Exception:
                    had_error = True
                    failed.append(fr)
    for _ in range(attempts_cap - 1):
        if len(good) >= k or not failed:
            break
        still = []
        for fr in failed:
            try:
                good.setdefault(fr.idx, verify(fr))
            except Exception:
                still.append(fr)
        failed = still
    if not frames or len(good) < k:
        coding.note("decode_failures", shuffle_id, peer="local")
        _xch_note(shuffle_id, "local", "decode_failures")
        trace.flight("fetch.failed", "shuffle", shuffle=shuffle_id,
                     map=map_id, reduce=reduce_id, coded=True,
                     shards_found=len(good), shards_needed=k,
                     error="ShardShortfall")
        raise FetchFailed(ordered[0], shuffle_id, map_id, reduce_id,
                          shards_found=len(good), shards_needed=k)
    code = coding.Code(frames[0].algo, frames[0].k, frames[0].m)
    blob = code.decode(good, orig_len)
    if any(j not in good for j in range(k)):
        # parity reconstructed a data shard: a failed one was
        # REPAIRED, or a merely-slow one lost the race (straggler
        # win) — either way, zero lineage recompute
        kind = "repair" if had_error else "straggler_win"
        coding.note(kind, shuffle_id, peer="local")
        _xch_note(shuffle_id, "local", kind)
    return pickle.loads(decompress(blob))


def read_bucket_any(uris, shuffle_id, map_id, reduce_id):
    """Fetch one map output from the best of its REPLICA locations.

    `uris`: one uri string, or a list/tuple of replicas (a map output
    re-served from several hosts).  Replicas are DEDUPLICATED in
    first-seen order (a duplicated uri would waste an attempt and skew
    the first-error report), then tried in hostatus-ranked order — a
    blacklisted host is skipped while any healthy replica exists, and
    every attempt's outcome feeds back into the shared health view
    (SURVEY.md section 5.3: the blacklist must CHANGE where the bytes
    come from, not just count failures).  With a shuffle code active
    the bucket is fetched shard-wise (fastest k of n, decode instead
    of FetchFailed).  Raises FetchFailed when every replica fails."""
    from dpark_tpu import adapt
    if trace._PLANE is None and not adapt.enabled():
        return _read_bucket_any(uris, shuffle_id, map_id, reduce_id)
    first = uris if isinstance(uris, str) else (uris[0] if uris else "")
    # the peer arg keys the health plane's per-site fetch-latency
    # sketches (ISSUE 14) — the serving host, not the full uri, so
    # site cardinality stays bounded
    peer = peer_label(first) if first else "local"
    t0 = time.time()
    if trace._PLANE is None:
        items = _read_bucket_any(uris, shuffle_id, map_id, reduce_id)
    else:
        with trace.span("fetch.bucket", "shuffle", shuffle=shuffle_id,
                        map=map_id, reduce=reduce_id, peer=peer):
            items = _read_bucket_any(uris, shuffle_id, map_id,
                                     reduce_id)
    # per-exchange peer accounting (ISSUE 19): which peers served this
    # shuffle, and the fetch wall the code policy grades itself on
    _xch_note(shuffle_id, peer, "fetches",
              ms=(time.time() - t0) * 1e3)
    return items


def _read_bucket_any(uris, shuffle_id, map_id, reduce_id):
    from dpark_tpu.env import env
    if isinstance(uris, str):
        uris = (uris,)
    hm = env.host_manager
    ordered = list(dict.fromkeys(uris))
    if len(ordered) > 1:
        # hostatus ranking by each replica's HOST (two replicas on one
        # host share fate): healthy-first, then by recent failure rate
        ordered = hm.rank_items(ordered, uri_host)
    # per-exchange override first (ISSUE 19): an adaptively-escalated
    # exchange fetches coded even with the global code off, a pinned-
    # uncoded one skips the shard protocol under a global rs(k,m);
    # the _Uncoded fallback still covers spec-vs-disk disagreement
    code = coding.shuffle_code(shuffle_id)
    if code is not None and ordered:
        try:
            # the one-I/O container fast path only when EVERY replica
            # is local; with any remote replica in the list the
            # per-shard protocol runs so a short local container (or
            # a coded bucket that only exists remotely) still decodes
            # from the other replicas — per-shard attempts cycle
            # through the full uri list
            if all(u.startswith("file://") for u in ordered):
                return _fetch_coded_local(ordered, shuffle_id,
                                          map_id, reduce_id)
            return _fetch_coded(ordered, shuffle_id, map_id,
                                reduce_id, code, hm)
        except _Uncoded:
            pass        # bucket predates the code config: plain path
    last_err = None
    for uri in ordered:
        try:
            # chaos site: one hit per fetch ATTEMPT, so replica
            # fallback and the FetchFailed translation below are both
            # exercised by injection
            faults.hit("shuffle.fetch")
            items = read_bucket(uri, shuffle_id, map_id, reduce_id)
        except Exception as e:
            hm.task_failed_on(uri_host(uri))
            logger.warning("fetch failed %s: %s", uri, e)
            last_err = e
            continue
        if uri.startswith("tcp://"):
            hm.task_succeed_on(uri_host(uri))
        return items
    # flight recorder (ISSUE 14): every replica failed — a
    # warning-and-above event, armed even with DPARK_TRACE=off
    trace.flight("fetch.failed", "shuffle", shuffle=shuffle_id,
                 map=map_id, reduce=reduce_id,
                 replicas=len(ordered),
                 error=type(last_err).__name__ if last_err else "?")
    if isinstance(last_err, FetchFailed):
        raise last_err
    err = FetchFailed(ordered[0] if ordered else None, shuffle_id,
                      map_id, reduce_id)
    err.__cause__ = last_err        # the real I/O error, not a blank tuple
    raise err


class SimpleShuffleFetcher:
    """Sequential fetch of every map output for one reduce partition."""

    def fetch(self, shuffle_id, reduce_id, merge_func):
        from dpark_tpu.env import env
        locs = env.map_output_tracker.get_outputs(shuffle_id)
        if locs is None:
            raise FetchFailed(None, shuffle_id, -1, reduce_id)
        for map_id, uri in enumerate(locs):
            if uri is None:
                raise FetchFailed(uri, shuffle_id, map_id, reduce_id)
            items = read_bucket_any(uri, shuffle_id, map_id, reduce_id)
            merge_func(items)

    def stop(self):
        pass


class ParallelShuffleFetcher(SimpleShuffleFetcher):
    """Thread-pool fetch (reference: ParallelShuffleFetcher).  On a single
    host file reads are fast; a small pool still overlaps decompression.

    Workers stop as soon as the consumer abandons the fetch (merge_func
    raised mid-merge) instead of fetching the remaining map outputs
    into buffers nobody will drain.

    Buckets are merged in MAP-ID ORDER, not thread-arrival order: the
    consumer holds out-of-order results in a reorder buffer until the
    next expected map id lands.  Combine ORDER is thereby deterministic
    and identical to the sequential fetcher — order-sensitive combiners
    (tuple `+` is concatenation) previously produced results that
    depended on thread scheduling, which surfaced as the order-dependent
    full-suite flake in test_analysis (ISSUE 4 satellite).  Unmerged
    buckets stay bounded by a PERMIT semaphore acquired before each
    fetch and released after each merge: in-flight + queued + reordered
    buckets never exceed 3 x nthreads, and progress is guaranteed
    because workers take map ids in order — the next-to-merge map's
    worker always already holds a permit (one stalled early map cannot
    let the others inflate the whole shuffle into RAM)."""

    def __init__(self, nthreads=4):
        self.nthreads = nthreads

    def fetch(self, shuffle_id, reduce_id, merge_func):
        from dpark_tpu.env import env
        locs = env.map_output_tracker.get_outputs(shuffle_id)
        if locs is None:
            raise FetchFailed(None, shuffle_id, -1, reduce_id)
        tasks = Queue()
        for map_id, uri in enumerate(locs):
            if uri is None:
                raise FetchFailed(uri, shuffle_id, map_id, reduce_id)
            tasks.put((map_id, uri))
        nthreads = min(self.nthreads, tasks.qsize() or 1)
        # the permit count bounds every fetched-but-unmerged bucket
        # (queue + reorder buffer + in-flight); the queue itself can be
        # unbounded because nothing enters it without a permit
        permits = threading.Semaphore(3 * nthreads)
        results = Queue()
        stop = threading.Event()
        # fetch workers are POOL threads: the task's thread-local
        # trace context (job/stage/task) doesn't reach them, so
        # capture it here and re-install per worker — fetch.bucket
        # spans then parent correctly and the health plane's
        # per-stage fetch sketches attribute (ISSUE 14)
        span_ctx = trace.current_ctx() if trace._PLANE is not None \
            else None

        def worker():
            if span_ctx:
                trace._tls.ctx = dict(span_ctx)
            while not stop.is_set():
                if not permits.acquire(timeout=0.5):
                    continue
                try:
                    map_id, uri = tasks.get_nowait()
                except Exception:
                    permits.release()
                    return
                try:
                    items = read_bucket_any(uri, shuffle_id, map_id,
                                            reduce_id)
                except BaseException as e:
                    # never die silently: the fetch loop counts results.
                    # A synthesized FetchFailed CHAINS the real error —
                    # "fetch failed" with the actual OSError/KeyError as
                    # __cause__, not a blank four-field tuple.
                    if isinstance(e, FetchFailed):
                        err = e
                    else:
                        err = FetchFailed(uri, shuffle_id, map_id,
                                          reduce_id)
                        err.__cause__ = e
                    results.put((map_id, err, None))
                    return
                results.put((map_id, None, items))

        threads = [threading.Thread(target=worker, daemon=True,
                                    name="dpark-fetch-worker")
                   for _ in range(nthreads)]
        for t in threads:
            t.start()
        try:
            pending = {}                  # map_id -> items, out of order
            next_id = 0
            for _ in range(len(locs)):
                try:
                    map_id, err, items = results.get(
                        timeout=conf.SHUFFLE_FETCH_WAIT_S)
                except Empty:
                    # every worker is wedged or dead with buckets
                    # still owed: surface a recoverable fetch failure
                    # (stage resubmit) instead of parking this reduce
                    # task forever
                    err = FetchFailed(None, shuffle_id, next_id,
                                      reduce_id)
                    err.__cause__ = TimeoutError(
                        "no fetch result within %.0fs (%d/%d buckets "
                        "merged)" % (conf.SHUFFLE_FETCH_WAIT_S,
                                     next_id, len(locs)))
                    raise err
                if err is not None:
                    raise err             # fail fast, order irrelevant
                pending[map_id] = items
                while next_id in pending:
                    merge_func(pending.pop(next_id))
                    next_id += 1
                    permits.release()
        finally:
            stop.set()          # consumer done or raised: workers drain out


class FetchFailed(Exception):
    """Signals the DAG scheduler to resubmit the parent stage (lineage
    recovery — SURVEY.md section 5.3).

    When raised from a FAILED DECODE (coded shuffle, fewer than k
    shards survived) it carries `shards_found`/`shards_needed` so the
    error names how close the decode came; `recovery_summary()` counts
    these separately as `decode_failures` (ISSUE 6 satellite)."""

    def __init__(self, uri, shuffle_id, map_id, reduce_id,
                 shards_found=None, shards_needed=None):
        super().__init__(uri, shuffle_id, map_id, reduce_id)
        self.uri = uri
        self.shuffle_id = shuffle_id
        self.map_id = map_id
        self.reduce_id = reduce_id
        self.shards_found = shards_found
        self.shards_needed = shards_needed

    def __str__(self):
        base = super().__str__()
        if self.shards_needed is not None:
            base += " [decode failed: %s of %s shards needed]" % (
                self.shards_found, self.shards_needed)
        return base


# ---------------------------------------------------------------------------
# Mergers (reduce side)
# ---------------------------------------------------------------------------

class Merger:
    """Hash-dict combine of already-combined map outputs."""

    def __init__(self, aggregator):
        self.merge_combiners = aggregator.merge_combiners
        self.combined = {}

    def merge(self, items):
        d = self.combined
        mc = self.merge_combiners
        for k, c in items:
            if k in d:
                d[k] = mc(d[k], c)
            else:
                d[k] = c

    def __iter__(self):
        return iter(self.combined.items())


class SortMerger:
    """Heap k-way merge of sorted bucket runs (reference: heap_merged)."""

    def __init__(self, aggregator):
        self.merge_combiners = aggregator.merge_combiners
        self.runs = []

    def merge(self, items):
        self.runs.append(sorted(items, key=lambda kv: kv[0]))

    def __iter__(self):
        mc = self.merge_combiners
        cur_key, cur_val, have = None, None, False
        for k, v in heapq.merge(*self.runs, key=lambda kv: kv[0]):
            if have and k == cur_key:
                cur_val = mc(cur_val, v)
            else:
                if have:
                    yield cur_key, cur_val
                cur_key, cur_val, have = k, v, True
        if have:
            yield cur_key, cur_val


class DiskSpillMerger(Merger):
    """Memory-bounded merge: when the in-memory dict exceeds max_items the
    sorted contents spill to a run file; final iteration heap-merges the
    spills with the in-memory remainder (reference: external merger).

    Run files are written as length-prefixed COMPRESSED CHUNKS and read
    back through chunked streaming readers feeding heapq.merge, so the
    final merge holds one chunk per run in memory — re-inflating every
    run at once would hand back the whole dataset the spills existed to
    keep out of RAM.

    Each chunk is framed with its crc32c (ISSUE 5): a corrupted run
    surfaces as FetchFailed — the consuming task recomputes through
    lineage — instead of unpickling garbage.  `shuffle_id`/`reduce_id`
    tag that FetchFailed so the scheduler can route the recompute;
    without them corruption raises SpillCorruption (a plain task
    failure, still never a wrong answer)."""

    def __init__(self, aggregator, max_items=None, workdir=None,
                 shuffle_id=None, reduce_id=-1):
        super().__init__(aggregator)
        self.max_items = max_items or conf.SHUFFLE_CHUNK_RECORDS * 4
        self.workdir = workdir
        self.shuffle_id = shuffle_id
        self.reduce_id = reduce_id
        self.spills = []

    def merge(self, items):
        super().merge(items)
        if len(self.combined) >= self.max_items:
            self._spill()

    def _spill(self):
        if self.workdir is None:
            from dpark_tpu.env import env
            self.workdir = os.path.join(env.workdir, "spill")
        os.makedirs(self.workdir, exist_ok=True)
        path = os.path.join(self.workdir, "run-%d-%d"
                            % (id(self), len(self.spills)))
        items = sorted(self.combined.items(), key=lambda kv: kv[0])
        chunk = conf.SHUFFLE_CHUNK_RECORDS
        code = coding.active_code()
        t_w0 = time.time() if trace._PLANE is not None else 0.0
        with atomic_file(path) as f:
            for i in range(0, len(items), chunk):
                blob = compress(pickle.dumps(items[i:i + chunk], -1))
                if code is not None:
                    # coded chunk: a shard container with per-shard
                    # crcs — corruption drops one shard, the read
                    # decodes around it (no recompute); the outer crc
                    # field is unused on this path
                    body = coding.encode_container(
                        blob, code, fault_site="shuffle.spill_write")
                    f.write(struct.pack("<QI", len(body), 0))
                    f.write(body)
                    continue
                # crc over the TRUE bytes, computed before the chaos
                # site may corrupt them — exactly what disk rot does
                crc = spill_crc(blob)
                blob = faults.hit("shuffle.spill_write", blob)
                # 8-byte length: one chunk of giant combiners (a hot
                # key's list) must not overflow a 4 GiB prefix
                f.write(struct.pack("<QI", len(blob), crc))
                f.write(blob)
        if trace._PLANE is not None:
            # a SPAN with the measured write wall (was an instant
            # event): the health plane's spill.write latency sketch
            # needs real durations (ISSUE 14)
            trace.emit("spill.write", "shuffle", t_w0,
                       time.time() - t_w0, records=len(items))
        self.spills.append(path)
        self.combined = {}

    def _iter_run(self, path):
        """Stream one spill run back chunk by chunk (sorted within and
        across chunks: the run was sorted before chunking), verifying
        each chunk's crc32c before unpickling."""
        # accumulated I/O wall only (the generator interleaves with
        # consumer merge time, which must not pollute the health
        # plane's spill.read latency sketch — ISSUE 14)
        traced = trace._PLANE is not None
        t_r0 = time.time() if traced else 0.0
        t_io = 0.0
        nbytes = 0
        with open(path, "rb") as f:
            while True:
                t0 = time.time() if traced else 0.0
                hdr = f.read(12)
                if not hdr:
                    if traced:
                        trace.emit("spill.read", "shuffle", t_r0,
                                   t_io, bytes=nbytes)
                    return
                n, crc = struct.unpack("<QI", hdr)
                raw = f.read(n)
                if traced:
                    t_io += time.time() - t0
                    nbytes += len(raw) + 12
                if coding.is_container(raw):
                    # coded chunk (ISSUE 6): per-shard crcs inside the
                    # container; corruption is decoded around, and only
                    # a sub-k survivor count escalates to lineage
                    try:
                        blob = coding.decode_container(
                            raw, fault_site="shuffle.spill_read",
                            shuffle_id=self.shuffle_id)
                    except coding.ShardShortfall as e:
                        err = SpillCorruption(
                            "spill run %s: %d of %d shards survived "
                            "(%d needed)" % (path, e.found, e.total,
                                             e.needed))
                        if self.shuffle_id is not None:
                            ff = FetchFailed(
                                None, self.shuffle_id, -1,
                                self.reduce_id,
                                shards_found=e.found,
                                shards_needed=e.needed)
                            ff.__cause__ = err
                            raise ff
                        raise err
                    for kv in pickle.loads(decompress(blob)):
                        yield kv
                    continue
                blob = faults.hit("shuffle.spill_read", raw)
                if spill_crc(blob) != crc:
                    err = SpillCorruption(
                        "spill run %s: crc32c mismatch (corrupted "
                        "chunk)" % path)
                    if self.shuffle_id is not None:
                        # lineage recompute: the scheduler retries the
                        # consuming stage (its map outputs are intact)
                        ff = FetchFailed(None, self.shuffle_id, -1,
                                         self.reduce_id)
                        ff.__cause__ = err
                        raise ff
                    raise err
                for kv in pickle.loads(decompress(blob)):
                    yield kv

    def __iter__(self):
        if not self.spills:
            return iter(self.combined.items())
        runs = [iter(sorted(self.combined.items(),
                            key=lambda kv: kv[0]))]
        runs += [self._iter_run(path) for path in self.spills]
        mc = self.merge_combiners

        def gen():
            cur_key, cur_val, have = None, None, False
            for k, v in heapq.merge(*runs, key=lambda kv: kv[0]):
                if have and k == cur_key:
                    cur_val = mc(cur_val, v)
                else:
                    if have:
                        yield cur_key, cur_val
                    cur_key, cur_val, have = k, v, True
            if have:
                yield cur_key, cur_val
        return gen()


class CoGroupMerger:
    """Merge n sources into key -> tuple of n lists (reference:
    CoGroupMerger backing CoGroupedRDD)."""

    def __init__(self, n_sources):
        self.n = n_sources
        self.combined = {}

    def _slot(self, key):
        slot = self.combined.get(key)
        if slot is None:
            slot = tuple([] for _ in range(self.n))
            self.combined[key] = slot
        return slot

    def append(self, src_index, items):
        """items of (k, v) from a narrow (non-shuffled) source."""
        for k, v in items:
            self._slot(k)[src_index].append(v)

    def extend(self, src_index, items):
        """items of (k, list_of_v) from a shuffled source."""
        for k, vs in items:
            self._slot(k)[src_index].extend(vs)

    def __iter__(self):
        return iter(self.combined.items())
