"""Resource attribution plane (ISSUE 15 tentpole): who is consuming
the shared mesh, and what does each program cost?

The JobServer multiplexes N tenants onto one mesh (ISSUE 9) and the
health plane grades their latency (ISSUE 14), but nothing could answer
the ATTRIBUTION question — which tenant's which program burned the
device seconds, held the mesh lock, parked bytes in HBM.  This module
closes that gap the way health.py did: a :class:`LedgerSink` is a
second ``trace.TracePlane.record`` consumer (one ``is None`` check per
record when off; on/off job results are bit-identical — asserted
across the chaos matrix in tests/test_ledger.py) folding spans AS THEY
ARE EMITTED into bounded, merge-associative resource ACCOUNTS keyed by
(tenant, job, stage, program signature):

* **device wall ms** — ``stage.exec`` spans (the whole device stage,
  run under the mesh lock) plus per-wave detail from ``wave`` spans,
  both keyed by the adapt program signature.
* **compile ms** — measured ``compile.backend`` spans (a
  jax.monitoring listener the executor installs times the real XLA
  backend compile; the instant ``compile`` cache-miss events count
  alongside).
* **mesh-lock wait ms** — the new ``mesh.lock`` span the executor's
  :class:`~dpark_tpu.backend.tpu.executor._MeshLock` emits around
  every contended ``_mesh_lock`` acquisition.  Contention is the
  invisible cost of the resident service: a tenant that waits pays
  wall time no per-stage timer ever showed.
* **HBM byte-seconds** — ``hbm.store`` / ``hbm.release`` events from
  the executor's shuffle-store bookkeeping: bytes x residency seconds,
  accrued at release (spill-to-disk releases too, so eviction adjusts
  the account), with still-resident bytes reported as a live gauge.
* **shuffle / bulk / spill traffic** — fetch counts + wall from
  ``fetch.bucket``, bulk bytes from ``dcn.bulk.*`` / ``dcn.transfer``,
  spill bytes from ``spill.read`` / ``spill.write``.

Tenant resolution: accounts key internally by (job, stage, sig); the
scheduler registers job -> tenant at record mint (:func:`note_job`),
and the job span carries ``client`` so the OFFLINE twin
(``tools/dtrace --ledger``, :func:`fold_records`) resolves tenants
from a spool alone.  Everything is bounded: past
``conf.LEDGER_MAX_KEYS`` account keys, new keys fold into their job's
coarse account (stage/sig dropped) so totals stay honest.

Static **program cost profiles** ride alongside (the pricing prior
ROADMAP items 2/3 need before a program's first observed run): at
first dispatch of a freshly compiled stage program,
:func:`capture_program_cost` captures ``jitted.lower(args)``'s
``cost_analysis()`` (flops, bytes accessed — a host-side re-trace, no
extra XLA compile) and, under ``DPARK_LEDGER_COST=compile``, the
compiled ``memory_analysis()`` (measured arg/out/temp = peak-HBM
bytes), keyed by ``fuse.plan_adapt_signature`` and persisted to the
adapt store via ``adapt.record_program_cost``.

The **conservation check**: per-tenant attributed device-seconds must
reconcile with the measured mesh busy time (the mesh lock's depth-0
hold total) — :func:`conservation` computes the ratio,
``/api/health`` grades it with evidence, and the two-tenant bench
asserts it within 10%.

Everything here is advisory: a fold failure logs at debug and never
breaks a job.  With ``DPARK_LEDGER=off`` the sink is None and the
plane costs one predicate per trace record.
"""

import threading
import time

from dpark_tpu import conf
from dpark_tpu import locks
from dpark_tpu.utils.log import get_logger

logger = get_logger("ledger")

MODES = ("off", "on")

_SINK = None                 # the `is None` check trace.record makes
_lock = locks.named_lock("ledger.install")   # guards install/clear

# fields every account carries, all additive (merge = field-wise sum,
# associative and commutative — asserted in tests).  *_ms/*_s are
# float sums, the rest int counters.
FIELDS = ("device_ms", "stages", "wave_ms", "waves", "dispatches",
          "compiles", "compile_ms", "lock_wait_ms", "lock_waits",
          "lock_hold_ms", "hbm_byte_s", "hbm_stored_bytes",
          "hbm_spills", "spill_bytes", "bulk_bytes", "fetches",
          "fetch_ms", "rc_byte_s", "rc_stored_bytes", "rc_hits",
          "rc_served_bytes")
_FLOAT_FIELDS = frozenset(f for f in FIELDS
                          if f.endswith("_ms") or f.endswith("_s"))

# the catch-all coarse signature accounts fold into past the key cap
OVERFLOW = "~"


class Account:
    """One bounded resource account.  Folding is O(1) additions;
    merging is field-wise addition; memory is len(FIELDS) numbers no
    matter how many observations stream through."""

    __slots__ = FIELDS

    def __init__(self):
        for f in FIELDS:
            setattr(self, f, 0.0 if f in _FLOAT_FIELDS else 0)

    def merge(self, other):
        for f in FIELDS:
            setattr(self, f, getattr(self, f) + getattr(other, f))
        return self

    def to_dict(self):
        """JSON-safe digest (the wire/store format): only nonzero
        fields, floats rounded."""
        out = {}
        for f in FIELDS:
            v = getattr(self, f)
            if v:
                out[f] = round(v, 4) if f in _FLOAT_FIELDS else int(v)
        return out

    @classmethod
    def from_dict(cls, d):
        acct = cls()
        try:
            for f, v in (d or {}).items():
                if f in _FLOAT_FIELDS:
                    setattr(acct, f, float(v))
                elif f in Account.__slots__:
                    setattr(acct, f, int(v))
        except (TypeError, ValueError):
            pass
        return acct


def merge_account_digests(a, b):
    """Merge two account digests (the to_dict shape) — the
    cross-process sidecar merge and the offline-twin fold use it."""
    acct = Account.from_dict(a or {})
    acct.merge(Account.from_dict(b or {}))
    return acct.to_dict()


def _key_str(key):
    job, stage, sig = key
    return "%s|%s|%s" % ("-" if job is None else job,
                         "-" if stage is None else stage, sig or "-")


def parse_key(s):
    """Inverse of the account-key wire format ("job|stage|sig")."""
    job, stage, sig = str(s).split("|", 2)
    return (None if job == "-" else int(job),
            None if stage == "-" else int(stage),
            None if sig == "-" else sig)


class LedgerSink:
    """The in-process streaming aggregator.  fold() is called from
    TracePlane.record with every emitted record; everything is bounded
    (conf.LEDGER_MAX_KEYS accounts, live-store map the size of the HBM
    store) and guarded by one lock."""

    def __init__(self):
        self.lock = locks.named_lock("ledger.sink")
        self.accounts = {}       # (job, stage, sig) -> Account
        self.job_tenant = {}     # job id -> tenant/client name
        self._job_order = []
        # FINISHED jobs' accounts compact into a bounded per-(tenant,
        # sig) archive when their job span folds: live keys then stay
        # bounded by concurrency x stages x programs, a resident
        # server never exhausts the key cap into the unattributed
        # overflow, and per-tenant totals stay MONOTONIC (the archive
        # only ever grows) — the accounts surface a scrape reads is
        # live accounts + archive
        self.archive = {}        # (tenant, sig) -> Account
        self.retired = set()     # job ids whose accounts archived
        # live HBM stores: sid -> (bytes, t_registered, job, stage)
        self.hbm_live = {}
        # live result-cache entries: sid -> (bytes, t_registered,
        # storing tenant).  Tenant-keyed, not job-keyed: cache-served
        # queries run no job, so resultcache.* events carry the
        # tenant explicitly and byte-seconds settle straight into the
        # archive at release
        self.rc_live = {}
        self.folded = 0
        self.dropped_keys = 0
        # offline mesh view folded from mesh.lock spans (the live
        # endpoint prefers the executor's meter — see mesh_meter)
        self.mesh = {"busy_s": 0.0, "wait_s": 0.0,
                     "acquisitions": 0, "contended": 0}
        self._t_min = None
        self._t_max = None

    # -- accounts --------------------------------------------------------
    def _account(self, job, stage, sig):
        key = (job, stage, sig)
        acct = self.accounts.get(key)
        if acct is None:
            cap = int(getattr(conf, "LEDGER_MAX_KEYS", 512) or 0)
            if cap and len(self.accounts) >= cap:
                # overflow folds into the job's coarse account so
                # totals (and the conservation check) stay honest
                # past the key cap
                self.dropped_keys += 1
                key = (job, None, OVERFLOW)
                acct = self.accounts.get(key)
                if acct is None:
                    if len(self.accounts) >= cap + 16:
                        key = (None, None, OVERFLOW)
                        acct = self.accounts.get(key)
                        if acct is None:
                            acct = self.accounts[key] = Account()
                        return acct
                    acct = self.accounts[key] = Account()
                return acct
            acct = self.accounts[key] = Account()
        return acct

    def note_job(self, job, tenant):
        with self.lock:
            if job not in self.job_tenant:
                self._job_order.append(job)
                if len(self._job_order) > 4096:
                    # backstop for jobs that never folded a job span:
                    # archive their accounts BEFORE the tenant mapping
                    # goes (totals must move, not re-attribute), and
                    # SETTLE any still-resident HBM stores now — once
                    # the retired marker drops, a late release could
                    # otherwise resurrect a live account for a dead
                    # job under the wrong tenant
                    old = self._job_order.pop(0)
                    self._retire_locked(old)
                    old_tenant = self._tenant_of(old)
                    now = time.time()
                    for sid in [i for i, e in self.hbm_live.items()
                                if e[2] == old]:
                        nbytes, t0, _j, _st = self.hbm_live.pop(sid)
                        a = Account()
                        a.hbm_byte_s = nbytes * max(0.0, now - t0)
                        self._archive_locked(old_tenant, OVERFLOW, a)
                    self.job_tenant.pop(old, None)
                    self.retired.discard(old)
            self.job_tenant[job] = str(tenant or "local")

    def _archive_locked(self, tenant, sig, acct):
        cap = int(getattr(conf, "LEDGER_MAX_KEYS", 512) or 0)
        key = (tenant, sig or OVERFLOW)
        ent = self.archive.get(key)
        if ent is None:
            if cap and len(self.archive) >= cap:
                key = (tenant, OVERFLOW)
                ent = self.archive.get(key)
                if ent is None:
                    ent = self.archive[key] = Account()
            else:
                ent = self.archive[key] = Account()
        ent.merge(acct)

    def _retire_locked(self, job):
        """Compact one finished job's accounts into the per-(tenant,
        sig) archive.  The tenant mapping stays (late hbm releases
        and merged worker digests still resolve) until the job-order
        backstop prunes it."""
        if job is None or job in self.retired:
            return
        tenant = self._tenant_of(job)
        for key in [k for k in self.accounts if k[0] == job]:
            self._archive_locked(tenant, key[2],
                                 self.accounts.pop(key))
        self.retired.add(job)

    # -- folding ---------------------------------------------------------
    def fold(self, rec):
        name = rec.get("name", "")
        dur = float(rec.get("dur", 0.0) or 0.0)
        args = rec.get("args") or {}
        job = rec.get("job")
        stage = rec.get("stage")
        with self.lock:
            self.folded += 1
            ts = rec.get("ts")
            if ts:
                if self._t_min is None or ts < self._t_min:
                    self._t_min = ts
                end = ts + dur
                if self._t_max is None or end > self._t_max:
                    self._t_max = end
            if name == "stage.exec":
                a = self._account(job, stage, args.get("sig"))
                a.device_ms += dur * 1e3
                a.stages += 1
            elif name == "wave":
                a = self._account(job, stage, args.get("sig"))
                a.wave_ms += dur * 1e3
                a.waves += 1
            elif name == "dispatch":
                self._account(job, stage,
                              args.get("sig")).dispatches += 1
            elif name == "compile":
                self._account(job, stage,
                              args.get("sig")).compiles += 1
            elif name == "compile.backend":
                self._account(job, stage, args.get("sig")) \
                    .compile_ms += dur * 1e3
            elif name == "mesh.lock":
                hold = float(args.get("hold_s", 0.0) or 0.0)
                self.mesh["busy_s"] += hold
                self.mesh["acquisitions"] += 1
                a = self._account(job, stage, None)
                # the HOLD is the billable mesh occupancy: every
                # stage.exec / export / gather runs inside one, and
                # the span inherits the owning job from the thread
                # ctx — so per-tenant occupancy sums reconcile with
                # the meter's busy total (the conservation check)
                a.lock_hold_ms += hold * 1e3
                if dur > 0:
                    self.mesh["wait_s"] += dur
                    self.mesh["contended"] += 1
                    a.lock_wait_ms += dur * 1e3
                    a.lock_waits += 1
            elif name == "hbm.store":
                sid = args.get("sid")
                nbytes = int(args.get("bytes", 0) or 0)
                if sid is not None:
                    self.hbm_live[sid] = (nbytes, rec.get("ts")
                                          or time.time(), job, stage)
                a = self._account(job, stage, None)
                a.hbm_stored_bytes += nbytes
            elif name == "hbm.release":
                sid = args.get("sid")
                ent = self.hbm_live.pop(sid, None)
                if ent is not None:
                    nbytes, t0, sjob, sstage = ent
                    held = max(0.0, (rec.get("ts") or time.time())
                               - t0)
                    if sjob in self.retired:
                        # a store outliving its job (re-used shuffle
                        # outputs): accrue straight into the tenant's
                        # archive — never resurrect a live account
                        a = Account()
                        a.hbm_byte_s = nbytes * held
                        if args.get("reason") == "spill":
                            a.hbm_spills = 1
                        self._archive_locked(self._tenant_of(sjob),
                                             OVERFLOW, a)
                    else:
                        a = self._account(sjob, sstage, None)
                        a.hbm_byte_s += nbytes * held
                        if args.get("reason") == "spill":
                            a.hbm_spills += 1
            elif name == "resultcache.store":
                # shared result cache (ISSUE 18): residency bills to
                # the STORING tenant, carried in the event args (no
                # job exists when the planner stores or serves)
                sid = args.get("sid")
                tenant = str(args.get("tenant") or "local")
                nbytes = int(args.get("bytes", 0) or 0)
                if sid is not None:
                    self.rc_live[sid] = (nbytes, rec.get("ts")
                                         or time.time(), tenant)
                a = Account()
                a.rc_stored_bytes = nbytes
                self._archive_locked(tenant, "resultcache", a)
            elif name == "resultcache.release":
                ent = self.rc_live.pop(args.get("sid"), None)
                if ent is not None:
                    nbytes, t0, tenant = ent
                    held = max(0.0, (rec.get("ts") or time.time())
                               - t0)
                    a = Account()
                    a.rc_byte_s = nbytes * held
                    self._archive_locked(tenant, "resultcache", a)
            elif name == "resultcache.serve":
                # hits bill to the SERVED tenant: zero scan
                # device-seconds, just the hit count and served bytes
                a = Account()
                a.rc_hits = 1
                a.rc_served_bytes = int(args.get("bytes", 0) or 0)
                self._archive_locked(
                    str(args.get("tenant") or "local"),
                    "resultcache", a)
            elif name in ("spill.write", "spill.read"):
                self._account(job, stage, None).spill_bytes += \
                    int(args.get("bytes", 0) or 0)
            elif name in ("dcn.bulk.fetch", "dcn.bulk.serve",
                          "dcn.transfer"):
                self._account(job, stage, None).bulk_bytes += \
                    int(args.get("bytes", 0) or 0)
            elif name == "fetch.bucket":
                a = self._account(job, stage, None)
                a.fetches += 1
                a.fetch_ms += dur * 1e3
            elif name == "job":
                client = args.get("client")
                if client and job is not None:
                    # offline twin's tenant resolution (the job span
                    # is emitted at job END, after its stage spans)
                    self.job_tenant.setdefault(job, str(client))
                if job is not None:
                    # the job span only ever fires at finalize:
                    # compact its accounts into the archive so a
                    # resident server's live key set stays bounded by
                    # CONCURRENCY, not job history — identical in the
                    # live sink and the offline fold (both see this
                    # same record)
                    self._retire_locked(job)

    # -- reading back ----------------------------------------------------
    def _tenant_of(self, job):
        if job is None:
            return "unattributed"
        return self.job_tenant.get(job, "local")

    def account_digests(self):
        """{key_str: digest} under the lock — the wire/store shape the
        worker sidecar files and the offline twin merge."""
        with self.lock:
            return {_key_str(k): a.to_dict()
                    for k, a in self.accounts.items()}

    def snapshot(self, now=None):
        """Full digest view: accounts, per-job and per-tenant rollups,
        the folded mesh view, live HBM residency.  `now` pins the
        clock for the live byte-second gauge (the offline twin passes
        the spool's last timestamp so live and offline agree on
        everything the wall clock does not move)."""
        with self.lock:
            jobs = {}
            tenants = {}
            for (job, _stage, _sig), a in self.accounts.items():
                jobs.setdefault(job, Account()).merge(a)
            for job, a in jobs.items():
                tenants.setdefault(self._tenant_of(job),
                                   Account()).merge(a)
            for (tenant, _sig), a in self.archive.items():
                tenants.setdefault(tenant, Account()).merge(a)
            t_now = now if now is not None else time.time()
            live_bytes = sum(b for b, _, _, _ in
                             self.hbm_live.values())
            live_byte_s = sum(b * max(0.0, t_now - t0)
                              for b, t0, _, _ in
                              self.hbm_live.values())
            return {
                "accounts": {_key_str(k): a.to_dict()
                             for k, a in self.accounts.items()},
                "archive": {"%s|%s" % k: a.to_dict()
                            for k, a in self.archive.items()},
                "jobs": {str(j if j is not None else "-"):
                         a.to_dict() for j, a in jobs.items()},
                "tenants": {t: a.to_dict()
                            for t, a in tenants.items()},
                "job_tenant": {str(j): t for j, t in
                               self.job_tenant.items()},
                "mesh": dict(self.mesh),
                "hbm_live_bytes": int(live_bytes),
                "hbm_live_byte_s": round(live_byte_s, 4),
                "resultcache_live_bytes": int(sum(
                    b for b, _, _ in self.rc_live.values())),
                "resultcache_live_byte_s": round(sum(
                    b * max(0.0, t_now - t0)
                    for b, t0, _ in self.rc_live.values()), 4),
                "span_window_s": round(
                    (self._t_max - self._t_min), 6)
                if self._t_min is not None else 0.0,
                "folded": self.folded,
                "dropped_keys": self.dropped_keys,
            }


# ---------------------------------------------------------------------------
# lifecycle
# ---------------------------------------------------------------------------

def configure(mode=None):
    """Install (mode "on") or clear (mode "off") the process sink.
    None reads conf.DPARK_LEDGER.  Returns the sink or None.  The
    sink only ever sees records the TRACE plane emits — with
    DPARK_TRACE=off there is nothing to fold and the plane is inert
    either way."""
    global _SINK
    if mode is None:
        mode = str(getattr(conf, "DPARK_LEDGER", "on") or "on")
    mode = str(mode).lower()
    if mode not in MODES:
        raise ValueError("DPARK_LEDGER=%r (expected off|on)" % mode)
    with _lock:
        _SINK = LedgerSink() if mode == "on" else None
        return _SINK


def active():
    return _SINK is not None


def mode():
    return "on" if _SINK is not None else "off"


def sink():
    return _SINK


def note_job(job, tenant):
    """Scheduler hook: a job record was minted for `tenant` (the
    service client, or "local" on single-tenant masters).  One `is
    None` check when the plane is off."""
    s = _SINK
    if s is not None:
        s.note_job(job, tenant)


def snapshot():
    s = _SINK
    if s is None:
        return {"accounts": {}, "archive": {}, "jobs": {},
                "tenants": {}, "job_tenant": {}, "mesh": {},
                "hbm_live_bytes": 0, "hbm_live_byte_s": 0.0,
                "span_window_s": 0.0, "folded": 0, "dropped_keys": 0}
    return s.snapshot()


# ---------------------------------------------------------------------------
# offline twin: fold a record list (spool load) into a fresh sink
# ---------------------------------------------------------------------------

def fold_records(records):
    """Build a sink from already-collected trace records (the
    tools/dtrace --ledger path and the live-vs-offline consistency
    test).  Skips counter events' own rows but MERGES any worker
    ledger digests they carry, so the offline view matches the
    driver's merged live view.  Records fold in END-time order (ts +
    dur) — spans are EMITTED at completion, so this reproduces the
    live fold order: stage spans before their job span (whose ts is
    the job START), stores before their releases."""
    s = LedgerSink()
    worker = {}
    for rec in sorted(records, key=lambda r: (r.get("ts", 0.0)
                                              + r.get("dur", 0.0))):
        if rec.get("cat") == "counters":
            d = (rec.get("args") or {}).get("ledger")
            if d:
                worker[(rec.get("host"), rec.get("pid"))] = d
            continue
        try:
            s.fold(rec)
        except Exception:
            pass
    # worker sidecar digests: a worker's spans already folded above
    # when the span spool carried them, and adding its cumulative
    # digest on top would double-count — so digests only fill in
    # account keys the span fold never produced (a worker whose span
    # spool hit the byte cap still ships its sidecar).  Keys whose
    # job RETIRED skip too: their span-folded totals already live in
    # the archive under the tenant
    for digest in worker.values():
        for key_s, d in (digest or {}).items():
            try:
                key = parse_key(key_s)
            except (ValueError, TypeError):
                continue
            if key not in s.accounts and key[0] not in s.retired:
                s.accounts[key] = Account.from_dict(d)
    return s


def merged_account_digests(include_workers=True):
    """The driver's merged account view: the local sink's accounts
    plus (in spool mode) the latest worker-process ledger digests from
    the counters merge — multiproc workers' fetch/spill activity
    finally attributes to the jobs that caused it."""
    s = _SINK
    out = dict(s.account_digests()) if s is not None else {}
    if include_workers:
        try:
            from dpark_tpu import trace
            workers = trace.merged_worker_counters().get("ledger") \
                or {}
            for key_s, digest in workers.items():
                out[key_s] = merge_account_digests(out.get(key_s),
                                                   digest)
        except Exception:
            pass
    return out


def tenant_totals(include_workers=True):
    """{tenant: {device_seconds, lock_wait_seconds, hbm_byte_seconds,
    bulk_bytes, ...}} — the per-tenant /metrics rollup, merged across
    worker processes.  Monotonic: accounts only ever grow and
    byte-seconds accrue at release."""
    s = _SINK
    if s is None:
        return {}
    merged = merged_account_digests(include_workers)
    with s.lock:
        tenant_of = dict(s.job_tenant)
        archived = {k: a.to_dict() for k, a in s.archive.items()}
    out = {}
    for (tenant, _sig), d in archived.items():
        out.setdefault(tenant, Account()).merge(
            Account.from_dict(d))
    for key_s, d in merged.items():
        try:
            job, _stage, _sig = parse_key(key_s)
        except (ValueError, TypeError):
            continue
        tenant = "unattributed" if job is None \
            else tenant_of.get(job, "local")
        acct = out.setdefault(tenant, Account())
        acct.merge(Account.from_dict(d))
    return {t: _totals_shape(a) for t, a in out.items()}


def _totals_shape(a):
    """Account -> the per-tenant rollup shape /metrics and
    /api/ledger export (ONE definition — the offline twin ships the
    identical shape via tenant_totals_from_snapshot)."""
    return {
        # billable mesh occupancy: attributed lock-hold seconds when
        # a device master metered them, else the stage-execution wall
        # (host-only masters have no mesh lock but still run stages)
        "device_seconds": round(
            (a.lock_hold_ms or a.device_ms) / 1e3, 6),
        "stage_device_seconds": round(a.device_ms / 1e3, 6),
        "lock_wait_seconds": round(a.lock_wait_ms / 1e3, 6),
        "hbm_byte_seconds": round(a.hbm_byte_s, 4),
        "bulk_bytes": int(a.bulk_bytes),
        "spill_bytes": int(a.spill_bytes),
        "fetches": int(a.fetches),
        "compiles": int(a.compiles),
        "compile_ms": round(a.compile_ms, 3),
        "waves": int(a.waves),
        "resultcache_byte_seconds": round(a.rc_byte_s, 4),
        "resultcache_hits": int(a.rc_hits),
        "resultcache_served_bytes": int(a.rc_served_bytes),
    }


def tenant_totals_from_snapshot(snap):
    """The tenant_totals rollup shape computed from a snapshot's raw
    per-tenant accounts — tools/dtrace --ledger uses this so the
    offline twin's `tenants` field agrees field-for-field with the
    live /api/ledger."""
    return {t: _totals_shape(Account.from_dict(d))
            for t, d in (snap.get("tenants") or {}).items()}


# ---------------------------------------------------------------------------
# top-k evidence (ISSUE 15 satellite: /api/health names the consumer)
# ---------------------------------------------------------------------------

def top_programs(k=3, snap=None):
    """Top programs by attributed device-seconds: [(sig, device_s,
    tenant)] — the evidence a yellow executor grade attaches so the
    verdict names its likely consumer."""
    snap = snap or snapshot()
    per_sig = {}
    tenant_of = snap.get("job_tenant", {})
    for key_s, d in snap.get("accounts", {}).items():
        try:
            job, _stage, sig = parse_key(key_s)
        except (ValueError, TypeError):
            continue
        if not sig or sig == OVERFLOW:
            continue
        ms = float(d.get("device_ms", 0.0) or 0.0)
        if not ms:
            continue
        by_tenant = per_sig.setdefault(sig, {})
        tenant = "unattributed" if job is None \
            else tenant_of.get(str(job), "local")
        by_tenant[tenant] = by_tenant.get(tenant, 0.0) + ms
    for key_s, d in snap.get("archive", {}).items():
        # finished jobs' compacted accounts: tenant is the key.
        # rsplit, not split — tenant names are caller-supplied and
        # may contain "|"; the sig side never does
        tenant, _, sig = str(key_s).rpartition("|")
        if not sig or sig == OVERFLOW:
            continue
        ms = float(d.get("device_ms", 0.0) or 0.0)
        if not ms:
            continue
        by_tenant = per_sig.setdefault(sig, {})
        by_tenant[tenant] = by_tenant.get(tenant, 0.0) + ms
    rows = sorted(per_sig.items(),
                  key=lambda kv: -sum(kv[1].values()))[:k]
    # the named tenant is the DOMINANT consumer of the signature —
    # this is the evidence a yellow grade attaches, so it must not
    # depend on account iteration order
    return [{"sig": sig,
             "device_s": round(sum(by_tenant.values()) / 1e3, 4),
             "tenant": max(by_tenant, key=by_tenant.get)}
            for sig, by_tenant in rows]


def top_tenants(field="hbm_byte_seconds", k=3, totals=None):
    """Top tenants by an attributed field (default HBM byte-seconds)."""
    totals = totals if totals is not None else tenant_totals()
    rows = sorted(((t, d.get(field, 0)) for t, d in totals.items()),
                  key=lambda kv: -kv[1])[:k]
    return [{"tenant": t, field: v} for t, v in rows if v]


# ---------------------------------------------------------------------------
# conservation: attributed device-seconds vs measured mesh busy time
# ---------------------------------------------------------------------------

def mesh_meter(scheduler=None):
    """The live mesh occupancy counters: the executor's _MeshLock
    meter when a device scheduler is reachable, else the sink's folded
    mesh.lock view (the offline shape)."""
    try:
        ex = getattr(scheduler, "executor", None) \
            if scheduler is not None else None
        lock = getattr(ex, "_mesh_lock", None)
        if lock is not None and hasattr(lock, "meter"):
            return lock.meter()
    except Exception:
        pass
    s = _SINK
    if s is not None:
        with s.lock:
            out = dict(s.mesh)
            out["wall_s"] = round(s._t_max - s._t_min, 6) \
                if s._t_min is not None else 0.0
        return out
    return {"busy_s": 0.0, "wait_s": 0.0, "acquisitions": 0,
            "contended": 0, "wall_s": 0.0}


def meter_delta(before, after):
    """after - before over the numeric meter fields (the bench A/Bs
    grade conservation over the window they traced, not the
    executor's lifetime)."""
    return {k: (after[k] - before.get(k, 0)
                if isinstance(after.get(k), (int, float))
                else after.get(k)) for k in after}


def conservation(scheduler=None, meter=None, snap=None):
    """JOB-attributed mesh occupancy vs measured mesh busy seconds.
    Attributed = the lock-hold seconds of accounts that name a job
    (the span inherits the owning job from the thread context — stage
    execution, export-bridge reads for a fetching job, device joins
    all bill correctly); busy = the _MeshLock meter's depth-0 hold
    total.  ratio < conf.LEDGER_CONSERVE_YELLOW means more than
    (1 - ratio) of the mesh's busy time could not be billed to any
    tenant — untracked consumption the quota/preemption work cannot
    arbitrate.  ok is None when the mesh was never busy (nothing to
    conserve).  Stage-execution device-seconds ride as secondary
    evidence."""
    snap = snap or snapshot()
    if not snap.get("folded"):
        # the sink observed nothing (DPARK_TRACE=off, or tracing not
        # yet started): the always-on lock meter still accrued busy
        # time, but grading that as "unattributed consumption" would
        # flag every deliberately-untraced server — nothing to
        # conserve, not a violation.  The lifetime meter's busy rides
        # as evidence only.
        ev = meter or mesh_meter(scheduler)
        return {"attributed_device_s": 0.0, "stage_device_s": 0.0,
                "mesh_busy_s": round(float(ev.get("busy_s", 0.0)
                                           or 0.0), 6),
                "ratio": None,
                "floor": float(getattr(conf,
                                       "LEDGER_CONSERVE_YELLOW",
                                       0.9)),
                "ok": None}
    if meter is None:
        # grade against the SINK's folded mesh view — the SAME window
        # as the attribution by construction.  The executor's
        # lifetime meter would falsely flag tracing enabled mid-life
        # (busy accrued while untraced can never be attributed); the
        # bench A/Bs pass an explicit meter delta for their windows.
        meter = snap.get("mesh") or {}
    attributed = 0.0
    stage_s = 0.0
    for key_s, d in snap.get("accounts", {}).items():
        stage_s += float(d.get("device_ms", 0.0) or 0.0) / 1e3
        try:
            job, _stage, _sig = parse_key(key_s)
        except (ValueError, TypeError):
            continue
        if job is not None:
            attributed += float(d.get("lock_hold_ms", 0.0)
                                or 0.0) / 1e3
    for d in snap.get("archive", {}).values():
        # archived accounts were job-attributed when they folded
        stage_s += float(d.get("device_ms", 0.0) or 0.0) / 1e3
        attributed += float(d.get("lock_hold_ms", 0.0) or 0.0) / 1e3
    busy = float(meter.get("busy_s", 0.0) or 0.0)
    floor = float(getattr(conf, "LEDGER_CONSERVE_YELLOW", 0.9))
    ratio = attributed / busy if busy > 0 else None
    return {"attributed_device_s": round(attributed, 6),
            "stage_device_s": round(stage_s, 6),
            "mesh_busy_s": round(busy, 6),
            "ratio": round(ratio, 4) if ratio is not None else None,
            "floor": floor,
            "ok": None if ratio is None else ratio >= floor}


def utilization(scheduler=None):
    """The mesh busy/idle/contended split for the web UI bar: busy =
    lock held, contended = time spent WAITING for the lock (demand the
    mesh could not serve), idle = the rest of the wall."""
    m = mesh_meter(scheduler)
    wall = max(float(m.get("wall_s", 0.0) or 0.0), 1e-9)
    busy = min(1.0, float(m.get("busy_s", 0.0)) / wall)
    contended = min(1.0 - busy,
                    float(m.get("wait_s", 0.0)) / wall)
    return {"busy_frac": round(busy, 4),
            "contended_frac": round(contended, 4),
            "idle_frac": round(max(0.0, 1.0 - busy - contended), 4),
            "meter": m}


# ---------------------------------------------------------------------------
# the /api/ledger payload (and the bench `ledger` section)
# ---------------------------------------------------------------------------

def api_ledger(scheduler=None):
    """Everything the web UI's tenant table + utilization bar need,
    built from defensive snapshots (a scrape racing a running job
    returns valid JSON, never an error)."""
    snap = snapshot()
    # one merged-totals pass per request: tenant_totals re-reads the
    # worker sidecar files, and the UI polls this endpoint every tick
    totals = tenant_totals()
    out = {
        "mode": mode(),
        "accounts": snap["accounts"],
        "archive": snap["archive"],
        "tenants": totals,
        "jobs": snap["jobs"],
        "job_tenant": snap["job_tenant"],
        "utilization": utilization(scheduler),
        "conservation": conservation(scheduler, snap=snap),
        "hbm_live_bytes": snap["hbm_live_bytes"],
        "hbm_live_byte_s": snap["hbm_live_byte_s"],
        "top_programs": top_programs(snap=snap),
        "top_tenants": top_tenants(totals=totals),
        "folded": snap["folded"],
        "dropped_keys": snap["dropped_keys"],
    }
    return out


def summary():
    """The `ledger` section for bench artifacts: mode + per-tenant
    rollup + conservation.  {"mode": "off", "tenants": {}} when the
    plane is off."""
    s = _SINK
    if s is None:
        return {"mode": "off", "tenants": {}, "accounts": 0}
    snap = s.snapshot()
    return {"mode": "on",
            "tenants": tenant_totals(),
            "accounts": len(snap["accounts"]) + len(snap["archive"]),
            "mesh": snap["mesh"],
            "conservation": conservation(snap=snap),
            "folded": snap["folded"]}


# ---------------------------------------------------------------------------
# static program cost profiles (the items-2/3 pricing prior)
# ---------------------------------------------------------------------------

_cost_seen = set()
_cost_lock = locks.named_lock("ledger.cost")


def _cost_key(sig):
    return "%s|%s" % (sig[0], sig[1])


def capture_program_cost(sig, jitted, args):
    """Capture one program's static cost profile at FIRST dispatch:
    ``jitted.lower(*args)`` (a host-side re-trace — no extra XLA
    compile) -> ``cost_analysis()`` flops / bytes accessed, plus under
    DPARK_LEDGER_COST=compile the compiled ``memory_analysis()``
    (measured arg/out/temp bytes = the peak-HBM prior).  Persisted to
    the adapt store keyed by the cross-process-stable plan signature,
    so a FRESH process prices a program before ever running it.
    Must be called BEFORE the jitted call when buffers are donated
    (lower only reads avals, never the buffers).  Never raises."""
    try:
        if _SINK is None or sig is None:
            return None
        # the streaming dispatch loop calls this per wave: the
        # already-captured fast path must be one set probe (racy read
        # is fine — the add below re-checks under the lock)
        key = _cost_key(sig)
        if key in _cost_seen:
            return None
        cost_mode = str(getattr(conf, "LEDGER_COST", "lower") or
                        "lower").lower()
        if cost_mode == "off":
            return None
        from dpark_tpu import adapt
        if not adapt.enabled():
            return None
        with _cost_lock:
            if key in _cost_seen:
                return None
            _cost_seen.add(key)
        if adapt.program_cost(key) is not None:
            return None              # an earlier process already paid
        lowered = jitted.lower(*args)
        ca = lowered.cost_analysis() or {}
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        profile = {
            "flops": float(ca.get("flops", 0.0) or 0.0),
            "bytes_accessed": float(ca.get("bytes accessed", 0.0)
                                    or 0.0),
            "arg_bytes": int(sum(int(getattr(a, "nbytes", 0) or 0)
                                 for a in args)),
        }
        if cost_mode == "compile":
            # the extra compile is PLANE overhead: suppress its
            # compile.backend span so the program's compile_ms
            # account never double-bills the tenant for it
            from dpark_tpu import trace
            trace.suppress_compile_spans(True)
            try:
                m = lowered.compile().memory_analysis()
            finally:
                trace.suppress_compile_spans(False)
            if m is not None:
                profile["out_bytes"] = int(
                    getattr(m, "output_size_in_bytes", 0) or 0)
                profile["temp_bytes"] = int(
                    getattr(m, "temp_size_in_bytes", 0) or 0)
                profile["peak_hbm_bytes"] = (
                    int(getattr(m, "argument_size_in_bytes", 0) or 0)
                    + profile["out_bytes"] + profile["temp_bytes"])
        adapt.record_program_cost(key, profile)
        from dpark_tpu import trace
        trace.event("ledger.cost", "ledger", sig=sig[0],
                    flops=profile["flops"])
        return profile
    except Exception as e:
        logger.debug("capture_program_cost failed: %s", e)
        return None


def reset_cost_capture():
    """Forget which signatures this process already profiled
    (tests)."""
    with _cost_lock:
        _cost_seen.clear()


def _init_from_conf():
    m = str(getattr(conf, "DPARK_LEDGER", "on") or "on").lower()
    if m == "on":
        configure("on")


_init_from_conf()
