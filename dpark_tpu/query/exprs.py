"""Expression layer of the columnar query plane: parse the table DSL's
string expressions, discover referenced columns (the pruning substrate),
and compile the supported subset into VECTORIZED array programs that
evaluate over whole column batches before any row tuple materializes.

Admission is exact, not optimistic — an expression only vectorizes when
the array program provably computes what the host's per-row Python eval
computes for every value the batch can contain:

  * integer arithmetic is admitted through interval analysis over the
    batch's actual per-column [min, max] ranges (the same idea as
    fuse._IntInterval's ranged-int top-k probe): every intermediate
    must fit int64, because the host computes exact Python ints while
    the array path wraps;
  * division requires a provably nonzero divisor (constant, or a
    column whose range excludes 0) — the host raises ZeroDivisionError
    where numpy would emit inf;
  * ``min``/``max`` calls compile to ``np.where`` forms that reproduce
    Python's comparison semantics exactly (``np.minimum`` propagates
    NaN where Python ``min`` returns its first argument);
  * ``and``/``or``/``not`` are admitted only in BOOLEAN (predicate)
    context, where truthiness is all that survives — in value context
    Python's and/or return an operand, which has no array twin here.

Everything else declines with a recorded reason; the planner keeps the
declining operator on the host row path and the `table-host-fallback`
lint rule reports the same reason pre-flight.
"""

import ast

import numpy as np

_I64_MAX = 2 ** 63 - 1


class ExprDecline(Exception):
    """Why an expression cannot vectorize (carried as the reason)."""


class ColumnExpr:
    """One parsed DSL expression: its AST, referenced columns, and the
    original text.  Vectorization is a separate, per-batch admission
    (dtypes + value ranges in hand) via `vectorize`."""

    __slots__ = ("expr", "tree", "columns", "parse_error")

    def __init__(self, expr, fields):
        self.expr = expr
        self.tree = None
        self.parse_error = None
        self.columns = set()
        try:
            self.tree = ast.parse(expr, mode="eval")
        except SyntaxError as e:
            self.parse_error = "unparseable expression: %s" % e
            return
        fields = set(fields)
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Name) and node.id in fields:
                self.columns.add(node.id)

    def __repr__(self):
        return "<ColumnExpr %r cols=%s>" % (self.expr,
                                            sorted(self.columns))


def compile_expr(expr, fields):
    return ColumnExpr(expr, fields)


# ---------------------------------------------------------------------------
# vectorization
# ---------------------------------------------------------------------------

def _py_min2(a, b):
    """Python ``min(a, b)`` exactly: b if b < a else a (NaN-aware the
    way the host is — a NaN b never compares less, so `a` wins)."""
    return np.where(b < a, b, a)


def _py_max2(a, b):
    return np.where(b > a, b, a)


class _V:
    """One vectorized sub-expression: evaluator + static type facts.

    kind: "i" int, "f" float, "b" bool (comparison output), "o" object
    (string column / str literal).  bounds: exact (lo, hi) Python ints
    for int-kind nodes (None once unknown — which declines any further
    int arithmetic, keeping the no-wrap proof honest)."""

    __slots__ = ("fn", "kind", "bounds", "const")

    def __init__(self, fn, kind, bounds=None, const=None):
        self.fn = fn
        self.kind = kind
        self.bounds = bounds
        self.const = const


def _chk(lo, hi, what):
    if abs(lo) > _I64_MAX or abs(hi) > _I64_MAX:
        raise ExprDecline(
            "int expression may leave int64 (%s bounds [%d, %d]): the "
            "host computes exact Python ints — host path" % (what, lo, hi))
    return (lo, hi)


def _const_v(value):
    if isinstance(value, bool):
        return _V(lambda env: value, "b", (int(value), int(value)),
                  const=value)
    if isinstance(value, int):
        _chk(value, value, "literal")
        return _V(lambda env: value, "i", (value, value), const=value)
    if isinstance(value, float):
        return _V(lambda env: value, "f", const=value)
    if isinstance(value, str):
        return _V(lambda env: value, "o", const=value)
    raise ExprDecline("unsupported literal %r" % (value,))


class _Vectorizer:
    """AST -> vectorized evaluator, with per-node admission.

    dtypes: {column: numpy dtype} of the scanned batch (object dtype
    for string columns); ranges: {column: (lo, hi) exact ints} for
    int columns (None entries decline int arithmetic over them)."""

    def __init__(self, dtypes, ranges):
        self.dtypes = dtypes
        self.ranges = ranges or {}

    def build(self, node, boolean):
        meth = getattr(self, "_v_%s" % type(node).__name__, None)
        if meth is None:
            raise ExprDecline("unsupported syntax %s in a vectorized "
                              "expression" % type(node).__name__)
        return meth(node, boolean)

    # -- leaves ---------------------------------------------------------
    def _v_Expression(self, node, boolean):
        return self.build(node.body, boolean)

    def _v_Constant(self, node, boolean):
        return _const_v(node.value)

    def _v_Name(self, node, boolean):
        name = node.id
        if name == "True":
            return _const_v(True)
        if name == "False":
            return _const_v(False)
        if name not in self.dtypes:
            raise ExprDecline("unknown name %r" % name)
        dt = self.dtypes[name]
        if dt == np.dtype(object) or dt.kind in "US":
            return _V(lambda env: env[name], "o")
        if dt.kind == "b":
            raise ExprDecline("bool column %r stays on the host path"
                              % name)
        if dt.kind == "i":
            rng = self.ranges.get(name)
            if rng is None:
                raise ExprDecline(
                    "int column %r has no value range (needed for the "
                    "no-overflow proof)" % name)
            return _V(lambda env: env[name], "i",
                      (int(rng[0]), int(rng[1])))
        if dt.kind == "f":
            return _V(lambda env: env[name], "f")
        raise ExprDecline("unsupported column dtype %s for %r"
                          % (dt, name))

    # -- arithmetic -----------------------------------------------------
    def _numeric(self, v, what):
        if v.kind == "o":
            raise ExprDecline("string operand in %s" % what)
        if v.kind == "b":
            # Python arithmetic treats bools as ints (True + True = 2);
            # numpy bool arrays would logical-or under "+" — cast so
            # the array program keeps the host's semantics
            f = v.fn
            return _V(lambda env: np.asarray(f(env)).astype(np.int64),
                      "i", v.bounds or (0, 1), const=v.const)
        return v

    def _v_UnaryOp(self, node, boolean):
        if isinstance(node.op, ast.Not):
            v = self.build(node.operand, True)
            f = v.fn
            return _V(lambda env: ~_as_bool(f(env)), "b", (0, 1))
        v = self._numeric(self.build(node.operand, False), "unary op")
        f = v.fn
        if isinstance(node.op, ast.USub):
            bounds = None
            if v.kind in "ib":
                bounds = _chk(-v.bounds[1], -v.bounds[0], "negation")
            return _V(lambda env: -f(env),
                      "f" if v.kind == "f" else "i", bounds)
        if isinstance(node.op, ast.UAdd):
            return v
        raise ExprDecline("unsupported unary op")

    def _v_BinOp(self, node, boolean):
        a = self._numeric(self.build(node.left, False), "arithmetic")
        b = self._numeric(self.build(node.right, False), "arithmetic")
        op = node.op
        int_sides = a.kind in "ib" and b.kind in "ib"
        kind = "i" if int_sides else "f"
        if isinstance(op, ast.Add):
            bounds = _chk(a.bounds[0] + b.bounds[0],
                          a.bounds[1] + b.bounds[1], "+") \
                if int_sides else None
            return _V(lambda env: a.fn(env) + b.fn(env), kind, bounds)
        if isinstance(op, ast.Sub):
            bounds = _chk(a.bounds[0] - b.bounds[1],
                          a.bounds[1] - b.bounds[0], "-") \
                if int_sides else None
            return _V(lambda env: a.fn(env) - b.fn(env), kind, bounds)
        if isinstance(op, ast.Mult):
            bounds = None
            if int_sides:
                corners = [x * y for x in a.bounds for y in b.bounds]
                bounds = _chk(min(corners), max(corners), "*")
            return _V(lambda env: a.fn(env) * b.fn(env), kind, bounds)
        if isinstance(op, ast.Div):
            self._nonzero(b, "/")
            return _V(lambda env: a.fn(env) / b.fn(env), "f")
        if isinstance(op, (ast.FloorDiv, ast.Mod)):
            self._nonzero(b, "// or %")
            if not int_sides:
                # float // and % match numpy's floor conventions, but
                # the host's exact-float corner cases (signed zeros)
                # are not worth proving here
                raise ExprDecline("float // and % stay on the host")
            if b.bounds[0] <= 0 <= b.bounds[1]:
                raise ExprDecline("divisor range crosses zero")
            if isinstance(op, ast.FloorDiv):
                corners = [x // y for x in a.bounds for y in b.bounds]
                bounds = _chk(min(corners), max(corners), "//")
                return _V(lambda env: a.fn(env) // b.fn(env), "i",
                          bounds)
            if b.bounds[0] > 0:
                bounds = (0, b.bounds[1] - 1)
            else:
                bounds = (b.bounds[0] + 1, 0)
            return _V(lambda env: a.fn(env) % b.fn(env), "i", bounds)
        raise ExprDecline("unsupported operator %s"
                          % type(op).__name__)

    def _nonzero(self, v, what):
        if v.kind == "f":
            if v.const is not None and v.const != 0:
                return
            raise ExprDecline(
                "divisor of %s not provably nonzero (the host raises "
                "ZeroDivisionError where arrays emit inf)" % what)
        if v.bounds[0] <= 0 <= v.bounds[1]:
            raise ExprDecline("divisor of %s not provably nonzero"
                              % what)

    # -- comparisons / boolean ------------------------------------------
    def _v_Compare(self, node, boolean):
        parts = []
        left = self.build(node.left, False)
        for op, right_node in zip(node.ops, node.comparators):
            right = self.build(right_node, False)
            if (left.kind == "o") != (right.kind == "o"):
                if not isinstance(op, (ast.Eq, ast.NotEq)):
                    raise ExprDecline(
                        "ordering comparison between string and "
                        "numeric operands")
            npop = {ast.Lt: np.less, ast.LtE: np.less_equal,
                    ast.Gt: np.greater, ast.GtE: np.greater_equal,
                    ast.Eq: np.equal, ast.NotEq: np.not_equal}.get(
                        type(op))
            if npop is None:
                raise ExprDecline("unsupported comparison %s"
                                  % type(op).__name__)
            lf, rf = left.fn, right.fn
            parts.append(lambda env, lf=lf, rf=rf, npop=npop:
                         npop(lf(env), rf(env)))
            left = right

        def fn(env):
            out = _as_bool(parts[0](env))
            for p in parts[1:]:
                out = out & _as_bool(p(env))
            return out
        return _V(fn, "b", (0, 1))

    def _v_BoolOp(self, node, boolean):
        if not boolean:
            raise ExprDecline(
                "and/or outside a predicate (Python's and/or return "
                "an OPERAND, which has no array twin)")
        vs = [self.build(v, True) for v in node.values]
        fns = [v.fn for v in vs]
        if isinstance(node.op, ast.And):
            def fn(env):
                out = _as_bool(fns[0](env))
                for f in fns[1:]:
                    out = out & _as_bool(f(env))
                return out
        else:
            def fn(env):
                out = _as_bool(fns[0](env))
                for f in fns[1:]:
                    out = out | _as_bool(f(env))
                return out
        return _V(fn, "b", (0, 1))

    # -- calls ----------------------------------------------------------
    def _v_Call(self, node, boolean):
        if not isinstance(node.func, ast.Name) or node.keywords:
            raise ExprDecline("unsupported call form")
        name = node.func.id
        args = [self.build(a, False) for a in node.args]
        if name == "abs" and len(args) == 1:
            (v,) = args
            v = self._numeric(v, "abs")
            bounds = None
            if v.kind in "ib":
                lo, hi = v.bounds
                bounds = _chk(0 if lo <= 0 <= hi else min(abs(lo),
                                                          abs(hi)),
                              max(abs(lo), abs(hi)), "abs")
            f = v.fn
            return _V(lambda env: np.abs(f(env)),
                      "f" if v.kind == "f" else "i", bounds)
        if name in ("min", "max") and len(args) >= 2:
            pair = _py_min2 if name == "min" else _py_max2
            kinds = {self._numeric(a, name).kind for a in args}
            kind = "f" if "f" in kinds else "i"
            bounds = None
            if kind == "i":
                agg = min if name == "min" else max
                bounds = (agg(a.bounds[0] for a in args),
                          agg(a.bounds[1] for a in args))
            fns = [a.fn for a in args]

            def fn(env, fns=fns, pair=pair):
                out = fns[0](env)
                for f in fns[1:]:
                    out = pair(out, f(env))
                return out
            return _V(fn, kind, bounds)
        if name == "float" and len(args) == 1:
            v = self._numeric(args[0], "float()")
            f = v.fn
            return _V(lambda env: np.asarray(f(env), np.float64), "f")
        raise ExprDecline("unsupported function %r in a vectorized "
                          "expression" % name)


def _as_bool(arr):
    a = np.asarray(arr)
    if a.dtype == np.bool_:
        return a
    return a.astype(bool)


class VecExpr:
    """An admitted array program: fn({column: array}) -> value array
    (bool array for predicates); kind in "ifb"; bounds the exact
    (lo, hi) int interval for int-kind outputs (drives the no-overflow
    proof of any DOWNSTREAM expression over this derived column)."""

    __slots__ = ("fn", "kind", "bounds")

    def __init__(self, fn, kind, bounds=None):
        self.fn = fn
        self.kind = kind
        self.bounds = bounds


def vectorize(colexpr, dtypes, ranges=None, boolean=False):
    """Compile a ColumnExpr into an array program, or explain why not.

    Returns (VecExpr, None) on admission or (None, reason) on decline.
    `ranges` supplies exact (lo, hi) per int column for the
    no-overflow interval proof."""
    if colexpr.parse_error:
        return None, colexpr.parse_error
    try:
        dts = {k: np.dtype(v) for k, v in dtypes.items()}
        v = _Vectorizer(dts, ranges).build(colexpr.tree, boolean)
        if boolean:
            f = v.fn
            return VecExpr(lambda env: _as_bool(f(env)), "b"), None
        if v.kind == "o":
            return None, ("string-valued expressions have no "
                          "device column form")
        if v.kind == "b":
            return None, ("bool-valued projection stays on the "
                          "host (predicate context only)")
        return VecExpr(v.fn, v.kind, v.bounds), None
    except ExprDecline as e:
        return None, str(e)
    except Exception as e:          # never let admission kill a query
        return None, "vectorize failed: %s" % e


def int_ranges(cols):
    """Exact (lo, hi) per int column of a batch dict — the interval
    proof's inputs.  Empty columns map to (0, 0)."""
    out = {}
    for name, arr in cols.items():
        a = np.asarray(arr)
        if a.dtype.kind == "i":
            out[name] = ((int(a.min()), int(a.max())) if a.size
                         else (0, 0))
        elif a.dtype.kind == "b":
            out[name] = (0, 1)
    return out
