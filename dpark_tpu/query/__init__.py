"""dpark_tpu.query — the columnar query plane (ISSUE 13 tentpole).

The table/SQL DSL (dpark_tpu/table.py) and the SQL front end both lower
into a LOGICAL PLAN (scan -> project -> filter -> group-agg -> join ->
sort/top nodes, dpark_tpu/query/logical.py); a rule-driven physical
planner (dpark_tpu/query/planner.py) then compiles each node onto the
shipped device machinery instead of per-row Python lambdas:

  * column pruning + predicate pushdown into the tabular scan — only
    referenced columns are read, filter predicates evaluate as
    vectorized array programs over column batches BEFORE any row tuple
    materializes, and whole chunks skip via the per-chunk min/max
    footer stats (dpark_tpu/tabular.py v2);
  * group-by aggregates (sum/count/min/max/avg + traceable UDAs)
    lower onto the device combine exchange / SegAggOp / SegMapOp over
    the tuple-key shuffle (PRs 3-4);
  * equi-joins lower onto the device join source (PR 3);
  * string group/join keys ride dictionary-encoded (TokenDict) and
    decode at egest;
  * per-operator device-vs-host choice is priced through the adaptive
    store (adapt decision point 2) and every host choice is recorded
    with a reason — the `table-host-fallback` lint rule reports the
    same reasons pre-flight.

The planner's rewrite rules reuse the PR 1 lint rule engine's lineage
walk (analysis.plan_rules.iter_lineage over the logical nodes), so
every rule doubles as a lintable explanation.
"""

from dpark_tpu.query.logical import (Filter, GroupAgg, Join, Node,
                                     Project, Scan, Sort, iter_plan)
from dpark_tpu.query.exprs import ColumnExpr, compile_expr, vectorize
from dpark_tpu.query.planner import PlannedQuery, plan_query

__all__ = ["Node", "Scan", "Project", "Filter", "GroupAgg", "Join",
           "Sort", "iter_plan", "ColumnExpr", "compile_expr",
           "vectorize", "PlannedQuery", "plan_query"]
