"""Logical plan nodes of the columnar query plane.

TableRDD.select/where/groupBy/join/sort and the SQL ``execute()`` front
end both lower into this tree; the physical planner
(dpark_tpu/query/planner.py) walks it with rewrite rules and compiles
each node onto the device machinery.

The nodes deliberately speak the SAME traversal protocol as the RDD
lineage DAG (`dependencies` entries carrying `.rdd`), so the PR 1 lint
rule engine's walk — analysis.plan_rules.iter_lineage — iterates a
logical plan unchanged.  That is what makes every planner rule a
lintable explanation: rules see the exact artifact the linter can walk.
"""


class _Dep:
    """Edge shim: the lint walk reads `dep.rdd`."""

    __slots__ = ("rdd",)
    is_shuffle = False

    def __init__(self, child):
        self.rdd = child


class Node:
    """Base logical node.  `fields` is the node's output schema (column
    names in order); `children` its inputs."""

    children = ()

    def __init__(self, fields):
        self.fields = list(fields)

    @property
    def dependencies(self):
        return [_Dep(c) for c in self.children]

    @property
    def scope_name(self):
        return type(self).__name__.lower()

    def describe(self):
        return type(self).__name__

    def sketch(self, indent=0):
        out = ["%s%s" % ("  " * indent, self.describe())]
        for c in self.children:
            out.extend(c.sketch(indent + 1))
        return out


def iter_plan(root):
    """Walk every node reachable from `root` exactly once — literally
    the lint engine's lineage walk over the logical tree."""
    from dpark_tpu.analysis.plan_rules import iter_lineage
    return iter_lineage(root)


class Scan(Node):
    """Leaf: a columnar source.  `source` is a TabularRDD (file scan)
    or a driver-resident RDD with columnarizable slices
    (ParallelCollection).  The planner's pushdown rules fill `wanted`
    (column pruning), `pushed` (vectorized predicates evaluated over
    column batches before any row exists), and `ranges` (chunk-skip
    {col: (lo, hi)} intervals for the footer-stats pruning)."""

    def __init__(self, source, fields, table_name="table"):
        super().__init__(fields)
        self.source = source
        self.table_name = table_name
        self.wanted = None          # planner: subset of fields to read
        self.pushed = []            # planner: [(ColumnExpr, vec_fn)]
        self.ranges = None          # planner: {col: (lo, hi)}
        self.derived = []           # planner: [(name, ColumnExpr)]

    def describe(self):
        cols = sorted(self.wanted) if self.wanted is not None \
            else "*"
        extra = ""
        if self.pushed:
            extra += " pushed=%d" % len(self.pushed)
        if self.ranges:
            extra += " chunk-skip=%s" % sorted(self.ranges)
        return "Scan(%s cols=%s%s)" % (self.table_name, cols, extra)


class Project(Node):
    """exprs: [(out_name, ColumnExpr)] over the child's fields."""

    def __init__(self, child, exprs):
        super().__init__([n for n, _ in exprs])
        self.children = (child,)
        self.exprs = exprs

    def describe(self):
        return "Project(%s)" % ", ".join(n for n, _ in self.exprs)


class Filter(Node):
    """preds: [ColumnExpr], conjunctive."""

    def __init__(self, child, preds):
        super().__init__(child.fields)
        self.children = (child,)
        self.preds = preds

    def describe(self):
        return "Filter(%s)" % " and ".join(p.expr for p in self.preds)


class GroupAgg(Node):
    """keys: [(out_name, ColumnExpr)]; aggs: [(out_name, func,
    ColumnExpr|None, uda_fn|None)] with func in sum/count/min/max/avg
    or "uda" (a traceable per-group function over the single argument
    column)."""

    def __init__(self, child, keys, aggs):
        super().__init__([n for n, _ in keys] + [a[0] for a in aggs])
        self.children = (child,)
        self.keys = keys
        self.aggs = aggs

    def describe(self):
        return "GroupAgg(keys=%s aggs=%s)" % (
            [n for n, _ in self.keys],
            ["%s:%s" % (a[0], a[1]) for a in self.aggs])


class Join(Node):
    """Equi-join on one column name present in both inputs; output
    schema mirrors TableRDD.join ([on] + left-rest + right-rest with
    uniquified names)."""

    def __init__(self, left, right, on, fields):
        super().__init__(fields)
        self.children = (left, right)
        self.on = on

    def describe(self):
        return "Join(on=%s)" % self.on


class Sort(Node):
    """keys: [ColumnExpr]; applied at egest (result rows are
    driver-resident by then — the coordinator gather-sort)."""

    def __init__(self, child, keys, reverse=False):
        super().__init__(child.fields)
        self.children = (child,)
        self.keys = keys
        self.reverse = reverse

    def describe(self):
        return "Sort(%s%s)" % (", ".join(k.expr for k in self.keys),
                               " desc" if self.reverse else "")


class CachedResult(Node):
    """Leaf standing in for a subtree the result-cache plane served:
    `explain()` shows exactly what was NOT executed.  `replaced` keeps
    the original subtree's one-line describe for the sketch."""

    def __init__(self, fields, replaced, key):
        super().__init__(fields)
        self.replaced = replaced
        self.key = key

    def describe(self):
        return "CachedResult(%s key=%s)" % (self.replaced, self.key)


def plan_signature(node):
    """Canonical, process-stable signature of a logical subtree.

    Unlike `sketch()`/`describe()` this includes every expression TEXT
    (GroupAgg.describe prints only `name:func`, so sum(b) and sum(c)
    would collide on the sketch) — the result-cache plane and the
    `repeated-subplan` lint rule key on this.  Source CONTENT is
    deliberately absent: the cache composes this with a per-file
    fingerprint (tabular.source_fingerprint); the lint rule wants
    shape-equality within one plan.  Raises on expression objects that
    lack `.expr` — callers treat that subtree as unsignable."""
    t = type(node).__name__
    if isinstance(node, Scan):
        return ("Scan", node.table_name, tuple(node.fields))
    if isinstance(node, Project):
        return ("Project",
                tuple((n, ce.expr) for n, ce in node.exprs),
                plan_signature(node.children[0]))
    if isinstance(node, Filter):
        return ("Filter", tuple(sorted(p.expr for p in node.preds)),
                plan_signature(node.children[0]))
    if isinstance(node, GroupAgg):
        return ("GroupAgg",
                tuple((n, ce.expr) for n, ce in node.keys),
                tuple((a[0], a[1],
                       a[2].expr if a[2] is not None else None,
                       # UDAs carry opaque callables: their identity is
                       # not content-stable, so mark them unsignable-ish
                       # by name only (cache callers reject UDA plans)
                       getattr(a[3], "__name__", None)
                       if a[3] is not None else None)
                      for a in node.aggs),
                plan_signature(node.children[0]))
    if isinstance(node, Join):
        return ("Join", node.on, tuple(node.fields),
                plan_signature(node.children[0]),
                plan_signature(node.children[1]))
    if isinstance(node, Sort):
        return ("Sort", tuple(k.expr for k in node.keys),
                bool(node.reverse),
                plan_signature(node.children[0]))
    if isinstance(node, CachedResult):
        return ("CachedResult", node.key)
    return (t,) + tuple(plan_signature(c) for c in node.children)
