"""Rule-driven physical planner of the columnar query plane.

`plan_query` walks a logical plan (dpark_tpu/query/logical.py) with a
fixed sequence of rewrite rules — shape normalization, column pruning,
predicate pushdown (vectorized filters + chunk-skip ranges), string
dictionary encoding, group-agg and join lowering, and adaptive path
pricing — and compiles the admitted pipeline onto the shipped device
machinery:

  * the SCAN runs as a driver-side columnar pipeline: only `wanted`
    columns are read from the tabular part files (or columnarized from
    parallelize slices), whole chunks skip via the v2 footer's min/max
    stats, and filter predicates / derived columns evaluate as
    vectorized array programs over column batches — no row tuple ever
    materializes before the device ingest;
  * GROUP-AGG lowers onto the device exchange: multi-aggregate queries
    ride a reduceByKey whose accumulator merge traces (the PR 3
    tuple-key combine path), single provable aggregates ride
    groupByKey().mapValues(sum/min/max/len) (SegAggOp / the combiner
    rewrite — adapt decision point 4 prices which), and traceable UDAs
    ride the SegMapOp segmented apply (PR 4);
  * equi-JOINs lower onto the PR 3 device join source;
  * string group/join keys (and string passthrough columns crossing
    the device) ride TokenDict-encoded int64 ids, decoded at egest;
  * result finishing (HAVING, post-aggregate projections, ORDER BY,
    LIMIT) runs at EGEST on the driver with exact host eval semantics
    — result rows are one-per-group / driver-resident by then.

Every rule records its choice with a reason; host choices surface as
`fallbacks` which the `table-host-fallback` lint rule reports
pre-flight and the runtime records per stage.  Admission is exact:
anything the rules cannot PROVE equivalent to the host row path
declines with a reason, and the host object path serves the query.
"""

import time

import numpy as np

from dpark_tpu.query import exprs as E
from dpark_tpu.query.logical import (Filter, GroupAgg, Join, Project,
                                     Scan, Sort)
from dpark_tpu.utils.log import get_logger

logger = get_logger("query.planner")

_I64_MAX = 2 ** 63 - 1

DEVICE_AGGS = ("sum", "count", "avg", "min", "max")

# classified per-group consumers for the single-aggregate lowering:
# builtins the shipped monoid classifier proves exactly, so the chain
# rides SegAggOp or the map-side-combine rewrite (adapt decision 4
# prices which)
_CLASSIFIED = {"sum": sum, "min": min, "max": max, "count": len}


# ---------------------------------------------------------------------------
# stable device-function factories
# ---------------------------------------------------------------------------
# Closures over HASHABLE parameters: fuse.fn_key hashes (code, cell
# values), so two plans of the same query compile to the SAME program
# key and the executor's program cache serves warm runs across plan
# rebuilds.

def _make_pair(nk):
    """Flat (k1..knk, v) row -> (key, v) with the tuple key repacked."""
    if nk == 1:
        def f(rec):
            return (rec[0], rec[1])
    else:
        def f(rec):
            return (tuple(rec[:nk]), rec[nk])
    return f


def _make_create(nk, kinds):
    """Flat (k..., a...) row -> (key, acc tree): one accumulator leaf
    per aggregate (sum/min/max: the arg; count: int64 1; avg: the
    (sum, count) pair)."""
    def f(rec):
        key = rec[0] if nk == 1 else tuple(rec[:nk])
        vals = rec[nk:]
        accs = []
        vi = 0
        for kind in kinds:
            if kind == "count":
                accs.append(np.int64(1))
            elif kind == "avg":
                accs.append((vals[vi], np.int64(1)))
                vi += 1
            else:
                accs.append(vals[vi])
                vi += 1
        return (key, tuple(accs))
    return f


def _make_merge(kinds):
    """Accumulator merge, branchless so the device exchange traces it
    (min/max via the table layer's jnp.where forms)."""
    def f(a, b):
        from dpark_tpu.table import _branchless_max, _branchless_min
        out = []
        for kind, x, y in zip(kinds, a, b):
            if kind in ("sum", "count"):
                out.append(x + y)
            elif kind == "avg":
                out.append((x[0] + y[0], x[1] + y[1]))
            elif kind == "min":
                out.append(_branchless_min(x, y))
            else:
                out.append(_branchless_max(x, y))
        return tuple(out)
    return f


def _make_join_side(nvals):
    """Flat (k, v1..vn) row -> (k, (v1..vn)) for the join exchange."""
    def f(rec):
        return (rec[0], tuple(rec[1:1 + nvals]))
    return f


def _make_join_flat(nl, nr):
    """Joined (k, ((l...), (r...))) -> flat (k, l..., r...)."""
    def f(kv):
        k, (lv, rv) = kv
        return (k,) + tuple(lv) + tuple(rv)
    return f


def _make_group_over(key_idxs, arg_idxs, kinds):
    """Flat joined row -> (key, acc tree), keys/args picked by index."""
    def f(rec):
        if len(key_idxs) == 1:
            key = rec[key_idxs[0]]
        else:
            key = tuple(rec[i] for i in key_idxs)
        accs = []
        vi = 0
        for kind in kinds:
            if kind == "count":
                accs.append(np.int64(1))
            elif kind == "avg":
                accs.append((rec[arg_idxs[vi]], np.int64(1)))
                vi += 1
            else:
                accs.append(rec[arg_idxs[vi]])
                vi += 1
        return (key, tuple(accs))
    return f


def _make_pick(idxs):
    """Flat row -> sub-row by indices (projection after a join)."""
    def f(rec):
        return tuple(rec[i] for i in idxs)
    return f


# ---------------------------------------------------------------------------
# plan-time helpers
# ---------------------------------------------------------------------------

def _std_dtype(dt):
    """The scan's standardized dtype: the host row path materializes
    Python ints/floats (ndarray.tolist()), so the columnar twin
    computes in int64/float64 regardless of the stored width."""
    dt = np.dtype(dt)
    if dt.kind == "i":
        return np.dtype(np.int64)
    if dt.kind == "f":
        return np.dtype(np.float64)
    return dt


def _std_col(arr):
    a = np.asarray(arr) if not isinstance(arr, list) \
        else np.array(arr, dtype=object)
    dt = _std_dtype(a.dtype) if a.dtype.kind in "if" else a.dtype
    if a.dtype != dt:
        a = a.astype(dt)
    return a


def _is_bare_name(colexpr):
    import ast
    t = colexpr.tree
    return (t is not None and isinstance(t.body, ast.Name)
            and t.body.id in colexpr.columns)


def _skip_bounds(pred, source_cols, col_dtypes=None):
    """{col: (lo, hi)} chunk-skip ranges a simple predicate implies
    over RAW source columns: conjunctions of ``col <cmp> literal``
    (either operand order).  Conservative — anything else contributes
    nothing.  The strict-inequality tightening (``> c`` -> lo = c+1)
    applies ONLY to integer COLUMNS: an int literal compared against a
    float column must keep the untightened bound (a chunk whose max is
    10.5 still matches ``f > 10``)."""
    import ast
    out = {}
    col_dtypes = col_dtypes or {}

    def visit(node):
        if isinstance(node, ast.BoolOp) and isinstance(node.op, ast.And):
            for v in node.values:
                visit(v)
            return
        if not isinstance(node, ast.Compare) or len(node.ops) != 1:
            return
        left, op, right = node.left, node.ops[0], node.comparators[0]
        name = const = None
        flip = False
        if isinstance(left, ast.Name) and isinstance(right, ast.Constant):
            name, const = left.id, right.value
        elif isinstance(right, ast.Name) and isinstance(left,
                                                        ast.Constant):
            name, const = right.id, left.value
            flip = True
        if name not in source_cols or isinstance(const, bool) \
                or not isinstance(const, (int, float)):
            return
        opname = type(op).__name__
        if flip:
            opname = {"Lt": "Gt", "LtE": "GtE", "Gt": "Lt",
                      "GtE": "LtE"}.get(opname, opname)
        is_int = (isinstance(const, int)
                  and np.dtype(col_dtypes.get(name, object)).kind
                  == "i")
        lo = hi = None
        if opname == "Eq":
            lo = hi = const
        elif opname == "Gt":
            lo = const + 1 if is_int else const
        elif opname == "GtE":
            lo = const
        elif opname == "Lt":
            hi = const - 1 if is_int else const
        elif opname == "LtE":
            hi = const
        else:
            return
        plo, phi = out.get(name, (None, None))
        if lo is not None:
            plo = lo if plo is None else max(plo, lo)
        if hi is not None:
            phi = hi if phi is None else min(phi, hi)
        out[name] = (plo, phi)

    body = pred.tree.body if pred.tree is not None else None
    if body is not None:
        visit(body)
    return out


def _normalize(val):
    """np scalars -> exact Python scalars (recursively through acc
    tuples) so egest rows match the host row path's Python values."""
    if isinstance(val, tuple):
        return tuple(_normalize(v) for v in val)
    if isinstance(val, np.generic):
        return val.item()
    if isinstance(val, np.ndarray) and val.ndim == 0:
        return val.item()
    return val


class _Decline(Exception):
    def __init__(self, op, reason):
        super().__init__(reason)
        self.op = op
        self.reason = reason


# ---------------------------------------------------------------------------
# scan segments
# ---------------------------------------------------------------------------

class _ScanSeg:
    """One scan-side pipeline: which columns to read, the chunk-skip
    ranges, and the admitted vectorized steps (leaf-to-top order)."""

    def __init__(self, scan):
        self.scan = scan
        self.wanted = None          # ordered source columns to read
        self.skip_ranges = None     # {col: (lo, hi)} for read_chunks
        self.steps = []             # ("filter", [fn]) | ("project", [...])
        self.out = []               # final env field names, ordered
        self.dtypes = {}            # final env dtypes
        self.bounds = {}            # final env int bounds
        self._env = None            # run-time cache

    # -- plan-time -------------------------------------------------------
    def source_meta(self):
        """(dtypes, ranges, nrows) of the raw source columns,
        standardized — footer stats for tabular files (no data read),
        the columnarized slices for in-memory sources."""
        from dpark_tpu.tabular import TabularRDD, read_header
        src = self.scan.source
        if isinstance(src, TabularRDD):
            ranges, rows = {}, 0
            seen_stats = {}
            seen_kinds = {}         # name -> set of 'i'/'f'/'o'
            for path in src.files:
                header = read_header(path)
                for chunk in header["chunks"]:
                    rows += chunk["rows"]
                    for name, meta in zip(header["fields"],
                                          chunk["columns"]):
                        if name not in self.scan.fields:
                            continue
                        if meta["kind"] == "object":
                            seen_kinds.setdefault(name, set()).add("o")
                            continue
                        seen_kinds.setdefault(name, set()).add(
                            _std_dtype(meta["dtype"]).kind)
                        if "min" in meta:
                            lo, hi = seen_stats.get(name, (None, None))
                            lo = meta["min"] if lo is None \
                                else min(lo, meta["min"])
                            hi = meta["max"] if hi is None \
                                else max(hi, meta["max"])
                            seen_stats[name] = (lo, hi)
                        else:
                            seen_stats.setdefault(name, (None, None))
            # chunk dtypes PROMOTE across the file set: any object
            # chunk makes the column object, any float chunk makes a
            # numeric column float64 (run() re-casts int chunks up, so
            # the admitted float semantics hold for every row) —
            # taking the first chunk's dtype would admit int no-wrap
            # proofs over truncated stats
            dtypes = {}
            for name in self.scan.fields:
                kinds = seen_kinds.get(name, {"o"})
                if "o" in kinds:
                    dtypes[name] = np.dtype(object)
                elif "f" in kinds:
                    dtypes[name] = np.dtype(np.float64)
                else:
                    dtypes[name] = np.dtype(np.int64)
            for name, (lo, hi) in seen_stats.items():
                if lo is not None and dtypes[name].kind == "i":
                    ranges[name] = (int(lo), int(hi))
            return dtypes, ranges, rows
        cols = self._columnarize()
        dtypes = {n: c.dtype for n, c in cols.items()}
        return dtypes, E.int_ranges(cols), \
            len(next(iter(cols.values()))) if cols else 0

    def _columnarize(self):
        """In-memory source -> {field: standardized array} (cached —
        the data is driver-resident either way)."""
        if getattr(self, "_raw_cols", None) is not None:
            return self._raw_cols
        src = self.scan.source
        slices = getattr(src, "_slices", None)
        if slices is None:
            raise _Decline("scan", "source slices not driver-resident")
        fields = self.scan.fields
        from dpark_tpu.rdd import _ColumnarSlice
        if slices and all(isinstance(s, _ColumnarSlice) for s in slices):
            cols = [np.concatenate([np.asarray(s.columns[i])
                                    for s in slices])
                    for i in range(len(fields))]
        else:
            rows = [r for s in slices for r in s]
            if rows and not isinstance(rows[0], tuple):
                rows = [(r,) for r in rows]
            cols = []
            for i in range(len(fields)):
                vals = [r[i] for r in rows]
                kinds = {type(v) for v in vals}
                if kinds <= {int} and kinds:
                    try:
                        cols.append(np.array(vals, np.int64))
                        continue
                    except OverflowError:
                        raise _Decline(
                            "scan", "int column %r exceeds int64"
                            % fields[i])
                if kinds <= {int, float} and kinds:
                    cols.append(np.array(vals, np.float64))
                    continue
                cols.append(np.array(vals, dtype=object))
            if not rows:
                cols = [np.array([], dtype=object)
                        for _ in fields]
        self._raw_cols = {n: _std_col(c)
                          for n, c in zip(fields, cols)}
        return self._raw_cols

    # -- run-time --------------------------------------------------------
    def run(self, stats=None):
        """Execute the pipeline -> {field: array} (cached: repeated
        actions on one planned query re-use the scanned columns)."""
        if self._env is not None:
            return self._env
        from dpark_tpu.tabular import TabularRDD, read_chunks
        src = self.scan.source
        if isinstance(src, TabularRDD):
            parts = {name: [] for name in self.out}
            for path in src.files:
                for nrows, cols in read_chunks(
                        path, self.wanted, self.skip_ranges,
                        stats=stats):
                    env = {}
                    for nm, c in cols.items():
                        a = _std_col(c)
                        want = getattr(self, "src_dtypes", {}).get(nm)
                        # mixed-chunk promotion: an int chunk of a
                        # float-resolved column casts up so the
                        # admitted semantics hold for every row
                        if want is not None and want.kind == "f" \
                                and a.dtype.kind == "i":
                            a = a.astype(want)
                        env[nm] = a
                    env, n = self._apply(env, nrows)
                    for name in self.out:
                        parts[name].append(env[name])
            env = {}
            for name in self.out:
                chunks = parts[name]
                if not chunks:
                    env[name] = np.array(
                        [], dtype=self.dtypes.get(name, object))
                elif len(chunks) == 1:
                    env[name] = chunks[0]
                else:
                    env[name] = np.concatenate(chunks)
        else:
            raw = self._columnarize()
            n = len(next(iter(raw.values()))) if raw else 0
            env = {k: raw[k] for k in (self.wanted or raw)}
            if stats is not None:
                stats.setdefault("columns_read", set()).update(env)
                stats["chunks_total"] = stats.get("chunks_total", 0) + 1
            env, n = self._apply(env, n)
            env = {name: env[name] for name in self.out}
        self._env = env
        return env

    def _apply(self, env, n):
        for kind, items in self.steps:
            if kind == "filter":
                mask = None
                for fn in items:
                    m = fn(env)
                    mask = m if mask is None else mask & m
                env = {k: v[mask] for k, v in env.items()}
                n = int(mask.sum())
            else:
                out = {}
                for name, spec in items:
                    if spec[0] == "pass":
                        out[name] = env[spec[1]]
                    else:
                        r = spec[1](env)
                        if np.ndim(r) == 0:
                            r = np.full(n, r)
                        out[name] = r
                env = out
        return env, n


# ---------------------------------------------------------------------------
# the planned query
# ---------------------------------------------------------------------------

class PlannedQuery:
    """A lowered query: scan segments + the device RDD pipeline + the
    egest program, with every rule decision recorded."""

    def __init__(self, root, ctx):
        self.root = root
        self.ctx = ctx
        self.ok = False
        self.decisions = []
        self.fallbacks = []
        self.scan_stats = {}
        self.adapt_sig = None
        self.mode = None            # scan | group | join | join_group
        self.segs = []
        self.egest_ops = []         # leaf-to-top (code, kind, meta)
        self.decoders = {}          # out field -> TokenDict
        self._rdd = None
        self._rows_cache = None
        self._group = None
        self._join = None
        self._out_fields = None
        self._partial = None        # result-cache partial-merge recipe
        self._cache_offer = None    # result-cache store-back ticket

    # -- bookkeeping -----------------------------------------------------
    def decide(self, rule, op, choice, reason):
        self.decisions.append({"rule": rule, "op": op,
                               "choice": choice, "reason": reason})
        if choice == "host":
            self.fallbacks.append({"op": op, "reason": reason})

    def explain(self):
        lines = ["plan (%s):" % (self.mode or "declined")]
        lines += ["  " + ln for ln in self.root.sketch(1)]
        lines.append("decisions:")
        for d in self.decisions:
            lines.append("  [%s] %s -> %s: %s"
                         % (d["rule"], d["op"], d["choice"],
                            d["reason"]))
        return "\n".join(lines)

    # -- actions ---------------------------------------------------------
    def rows(self):
        if self._rows_cache is None:
            rows = None
            if self._partial is not None:
                rows = self._merge_partial(self._partial)
            if rows is None:
                rows = self._run()
            self._rows_cache = rows
            if self._cache_offer is not None:
                try:
                    from dpark_tpu import resultcache
                    resultcache.offer(self, rows)
                except Exception as e:
                    logger.debug("result cache offer: %s", e)
        return self._rows_cache

    def collect(self):
        return self.rows()

    def take(self, n):
        return self.rows()[:n]

    def count(self):
        has_filter = any(op[0] == "filter" for op in self.egest_ops)
        if self._rows_cache is not None or has_filter \
                or self._partial is not None:
            return len(self.rows())
        if self.mode == "scan":
            env = self.segs[0].run(self.scan_stats)
            return len(next(iter(env.values()))) if env else 0
        return self._build_rdd().count()

    # -- execution -------------------------------------------------------
    def _run(self):
        t0 = time.time()
        if self.mode == "scan":
            env = self.segs[0].run(self.scan_stats)
            names = self.segs[0].out
            rows = list(zip(*(env[n].tolist()
                              if isinstance(env[n], np.ndarray)
                              and env[n].dtype != object
                              else list(env[n]) for n in names))) \
                if names else []
            fields = names
        else:
            raw = self._build_rdd().collect()
            rows, fields = self._shape_rows(raw)
        rows = self._egest(rows, fields)
        self._observe("device", (time.time() - t0) * 1e3)
        return rows

    def _merge_partial(self, part):
        """Serve a partial-aggregate cache hit: run the residual plan
        the probe built (covering exactly the source region the cached
        entry does not), then fold the two disjoint aggregate row sets
        with the mergeable combiners.  Any failure returns None and
        the caller falls back to the full uncached run — the merge
        path is an optimization, never a correctness dependency."""
        try:
            from dpark_tpu import resultcache, trace
            t0 = time.time()
            rpq = plan_query(part["residual"], self.ctx, reuse=False)
            if not rpq.ok:
                return None
            res = rpq.rows()
            for k, v in rpq.scan_stats.items():
                if isinstance(v, set):
                    self.scan_stats.setdefault(k, set()).update(v)
                else:
                    self.scan_stats[k] = self.scan_stats.get(k, 0) + v
            rows = resultcache.merge_group_rows(
                part["rows"], res, part["nk"], part["kinds"])
            rows = self._egest(rows, list(part["fields"]))
            trace.event("resultcache.merge", "resultcache",
                        sid=part["key"], cached=len(part["rows"]),
                        residual=len(res),
                        ms=round((time.time() - t0) * 1e3, 2))
            return rows
        except Exception as e:
            logger.debug("partial-aggregate merge fell back: %s", e)
            return None

    def _observe(self, path, ms):
        try:
            from dpark_tpu import adapt
            if self.adapt_sig is not None and adapt.enabled():
                adapt.observe_path(self.adapt_sig, path, ms)
        except Exception:
            pass

    def _build_rdd(self):
        if self._rdd is not None:
            return self._rdd
        from dpark_tpu.rdd import Columns
        ctx = self.ctx
        npart = max(1, ctx.default_parallelism)
        if self.mode == "group":
            seg = self.segs[0]
            env = seg.run(self.scan_stats)
            g = self._group
            # decoders key by the OUTPUT field name (what _shape_rows
            # decodes), not the internal __k*/__a* pipeline names
            dec_names = list(g["key_names"]) + [None] * (
                len(g["cols"]) - g["nk"])
            cols = [self._encoded(env[c], dn or c)
                    for c, dn in zip(g["cols"], dec_names)]
            if len(cols) == g["nk"]:
                # count-only query: no aggregate argument columns —
                # records still need a value leaf (the count ignores
                # its content)
                cols.append(np.ones(len(cols[0]) if cols else 0,
                                    np.int64))
            base = ctx.parallelize(Columns(*cols), npart)
            nk = g["nk"]
            if g["lower"] == "classified":
                r = base.map(_make_pair(nk)).groupByKey(npart) \
                    .mapValues(_CLASSIFIED[g["kinds"][0]])
            elif g["lower"] == "uda":
                r = base.map(_make_pair(nk)).groupByKey(npart) \
                    .mapValues(g["uda"])
            else:
                r = base.map(_make_create(nk, g["kinds"])) \
                    .reduceByKey(_make_merge(g["kinds"]), npart)
        else:                       # join / join_group
            j = self._join
            sides = []
            for si, seg in enumerate(self.segs):
                env = seg.run(self.scan_stats)
                names = j["side_cols"][si]
                dec_names = j["side_dec"][si]
                n = len(next(iter(env.values()))) if env else 0
                cols = []
                for c, dn in zip(names, dec_names):
                    if c is None:       # key-only side: dummy value
                        cols.append(np.zeros(n, np.int64))
                        continue
                    cols.append(self._encoded(
                        env[c], dn or c, j["enc"].get((si, c))))
                rdd = ctx.parallelize(Columns(*cols), npart)
                sides.append(rdd.map(_make_join_side(len(names) - 1)))
            joined = sides[0].join(sides[1], npart)
            nl = len(j["side_cols"][0]) - 1
            nr = len(j["side_cols"][1]) - 1
            flat = joined.map(_make_join_flat(nl, nr))
            if self.mode == "join_group":
                g = self._group
                flat = flat.map(_make_group_over(
                    tuple(g["key_idxs"]), tuple(g["arg_idxs"]),
                    g["kinds"]))
                r = flat.reduceByKey(_make_merge(g["kinds"]), npart)
            else:
                r = flat.map(_make_pick(tuple(j["out_idxs"])))
        self._rdd = r
        return r

    def _encoded(self, col, name, dict_=None):
        """Dictionary-encode an object column for the device path (or
        pass a numeric column through).  `dict_` shares one TokenDict
        across the two sides of a join.  Only GENUINE str values
        encode — a bool/None/mixed object column raises, which the
        table action catches as a recorded host fallback (encoding
        them would silently turn True into the string 'True' at
        egest)."""
        if col.dtype != object and col.dtype.kind not in "US":
            return col
        from dpark_tpu.native import TokenDict
        td = dict_ if dict_ is not None else TokenDict()
        if len(col):
            # np.unique on a mixed-type object column raises on the
            # sort compare — also a (caught) host fallback
            uniq, inv = np.unique(col, return_inverse=True)
            for u in uniq.tolist():
                if type(u) is not str:
                    raise TypeError(
                        "non-string value %r in dictionary-encoded "
                        "column %r (host path serves it)" % (u, name))
            ids = np.array([td.put(u) for u in uniq.tolist()],
                           np.int64)
            out = ids[inv]
        else:
            out = np.array([], np.int64)
        self.decoders.setdefault(name, td)
        return out

    def _decode(self, name, val):
        td = self.decoders.get(name)
        if td is None:
            return val
        return td.decode(int(val))

    def _shape_rows(self, raw):
        """Collected device rows -> flat output tuples of the pre-egest
        schema, finalized (avg division etc.) and decoded, with exact
        Python scalars."""
        out = []
        if self.mode in ("group", "join_group"):
            g = self._group
            nk = g["nk"]
            key_names = g["key_names"]
            for k, acc in raw:
                keys = (k,) if nk == 1 else tuple(k)
                keys = tuple(
                    self._decode(key_names[i], _normalize(v))
                    for i, v in enumerate(keys))
                if g["lower"] in ("classified", "uda"):
                    out.append(keys + (_normalize(acc),))
                    continue
                vals = []
                for kind, a in zip(g["kinds"], acc):
                    a = _normalize(a)
                    if kind == "avg":
                        s, c = a
                        vals.append(s / c if c else None)
                    else:
                        vals.append(a)
                out.append(keys + tuple(vals))
            return out, list(g["key_names"]) + list(g["agg_names"])
        # join (no group): rows are already flat in out_idx order
        j = self._join
        fields = j["out_fields"]
        for rec in raw:
            rec = tuple(_normalize(v) for v in rec)
            rec = tuple(self._decode(fields[i], v)
                        for i, v in enumerate(rec))
            out.append(rec)
        return out, fields

    def _egest(self, rows, fields):
        """Result finishing with exact host eval semantics: HAVING
        filters, post-aggregate projections, ORDER BY — one row per
        group by now, driver-resident."""
        from dpark_tpu.table import _SAFE_BUILTINS
        for kind, meta in self.egest_ops:
            if kind == "filter":
                keep = []
                for row in rows:
                    env = dict(zip(fields, row))
                    if all(eval(code, {"__builtins__": _SAFE_BUILTINS},
                                dict(env)) for code in meta):
                        keep.append(row)
                rows = keep
            elif kind == "project":
                names = [n for n, _ in meta]
                new = []
                for row in rows:
                    env = dict(zip(fields, row))
                    new.append(tuple(
                        eval(code, {"__builtins__": _SAFE_BUILTINS},
                             dict(env)) for _, code in meta))
                rows = new
                fields = names
            else:                   # sort
                codes, reverse = meta
                def key(row, codes=codes, fields=fields):
                    env = dict(zip(fields, row))
                    ks = [eval(c, {"__builtins__": _SAFE_BUILTINS},
                               dict(env)) for c in codes]
                    return ks[0] if len(ks) == 1 else tuple(ks)
                rows = sorted(rows, key=key, reverse=reverse)
        self._out_fields = fields
        return rows


# ---------------------------------------------------------------------------
# the rules
# ---------------------------------------------------------------------------

def plan_query(root, ctx, reuse=True):
    """Plan a logical tree onto the device path.  Returns a
    PlannedQuery; `.ok` False means the host object path should serve
    the query (with `.fallbacks` carrying the reasons).  `reuse=False`
    skips the result-cache probe (residual plans must not re-probe)."""
    pq = PlannedQuery(root, ctx)
    try:
        _rule_shape(pq)
        _rule_prune(pq)
        _rule_scan_pipelines(pq)
        if pq.mode in ("join", "join_group"):
            _rule_lower_join(pq)
        if pq.mode in ("group", "join_group"):
            _rule_lower_group(pq)
        compile_egest(pq)
        _rule_price(pq)
        if reuse:
            _rule_reuse(pq)
        pq.ok = True
    except _Decline as d:
        pq.decide("planner", d.op, "host", d.reason)
        pq.ok = False
    except Exception as e:          # planner bugs must not kill queries
        logger.debug("query planning failed: %s", e)
        pq.decide("planner", "plan", "host",
                  "planner error: %s" % str(e)[:160])
        pq.ok = False
    return pq


def _linearize(node):
    ops = []
    while isinstance(node, (Project, Filter, Sort)):
        ops.append(node)
        node = node.children[0]
    return ops, node


def _rule_shape(pq):
    """Normalize the tree into (egest ops, core, scan pipelines);
    decline shapes outside the supported grammar."""
    ops1, core = _linearize(pq.root)
    if isinstance(core, Scan):
        pq.mode = "scan"
        pq.segs = [_ScanSeg(core)]
        # Sorts cannot vectorize into the columnar pipe; they (and
        # everything ABOVE them — order matters) finish at egest
        pipe, egest = [], []
        for op in reversed(ops1):          # leaf-to-top
            if egest or isinstance(op, Sort):
                egest.append(op)
            else:
                pipe.append(op)
        pq._shape = {"scan_ops": list(reversed(pipe)), "egest": egest}
        return
    if isinstance(core, GroupAgg):
        ops2, inner = _linearize(core.children[0])
        if any(isinstance(o, Sort) for o in ops2):
            raise _Decline("sort", "sort below a group-by has no "
                           "effect on grouped output; host path")
        if isinstance(inner, Scan):
            pq.mode = "group"
            pq.segs = [_ScanSeg(inner)]
            pq._shape = {"scan_ops": ops2, "egest": list(reversed(ops1)),
                         "group": core}
            return
        if isinstance(inner, Join):
            pq.mode = "join_group"
            pq._shape = {"egest": list(reversed(ops1)), "group": core,
                         "join": inner, "join_ops": ops2}
            _shape_join(pq, inner)
            return
        raise _Decline("plan", "unsupported plan below group-by")
    if isinstance(core, Join):
        pq.mode = "join"
        pq._shape = {"egest": list(reversed(ops1)), "join": core,
                     "join_ops": []}
        _shape_join(pq, core)
        return
    raise _Decline("plan", "unsupported plan shape (%s)"
                   % type(core).__name__)


def _shape_join(pq, join):
    sides = []
    for child in join.children:
        ops, leaf = _linearize(child)
        if not isinstance(leaf, Scan):
            raise _Decline("join", "join input is not a scan chain")
        if any(isinstance(o, Sort) for o in ops):
            raise _Decline("sort", "sort below a join stays on host")
        sides.append((ops, leaf))
    pq.segs = [_ScanSeg(leaf) for _, leaf in sides]
    pq._shape["side_ops"] = [ops for ops, _ in sides]


def _refs_of(ops, needed):
    """Columns a scan must produce so `ops` (leaf-to-top application
    order is reversed(ops)) can compute `needed` output names."""
    need = set(needed)
    for op in ops:                  # ops are top-down: walk downward
        if isinstance(op, Project):
            nxt = set()
            for name, ce in op.exprs:
                if name in need or not need:
                    nxt |= ce.columns
            need = nxt
        elif isinstance(op, Filter):
            for p in op.preds:
                need |= p.columns
        elif isinstance(op, Sort):
            for k in op.keys:
                need |= k.columns
    return need


def _rule_prune(pq):
    """Column pruning: each scan reads only the columns the query
    references."""
    sh = pq._shape
    if pq.mode == "scan":
        needed = set(pq.root.fields)
        for op in sh["egest"]:
            if isinstance(op, (Filter,)):
                for p in op.preds:
                    needed |= p.columns
            elif isinstance(op, Sort):
                for k in op.keys:
                    needed |= k.columns
            elif isinstance(op, Project):
                for _, ce in op.exprs:
                    needed |= ce.columns
        wanted = _refs_of(sh["scan_ops"],
                          needed & set(_pipe_out_fields(pq)))
        scan = pq.segs[0].scan
        if not sh["scan_ops"]:
            wanted = needed & set(scan.fields)
        pq.segs[0].wanted = [c for c in scan.fields if c in wanted] \
            or list(scan.fields[:1])
        pq.decide("prune-columns", "scan", "device",
                  "scan reads %s of %d columns"
                  % (pq.segs[0].wanted, len(scan.fields)))
        return
    if pq.mode == "group":
        g = sh["group"]
        needed = set()
        for _, ce in g.keys:
            needed |= ce.columns
        for (_name, _fn, arg, _uda) in g.aggs:
            if arg is not None:
                needed |= arg.columns
        wanted = _refs_of(sh["scan_ops"], needed)
        scan = pq.segs[0].scan
        pq.segs[0].wanted = [c for c in scan.fields if c in wanted] \
            or list(scan.fields[:1])
        pq.decide("prune-columns", "scan", "device",
                  "scan reads %s of %d columns"
                  % (pq.segs[0].wanted, len(scan.fields)))
        return
    # join modes: need the on-column + every referenced output column,
    # mapped back through the join's column map to each side
    join = sh["join"]
    needed_out = set()
    if pq.mode == "join_group":
        g = sh["group"]
        for _, ce in g.keys:
            needed_out |= ce.columns
        for (_name, _fn, arg, _uda) in g.aggs:
            if arg is not None:
                needed_out |= arg.columns
    else:
        needed_out = set(join.fields)
        for op in sh["egest"]:
            if isinstance(op, Filter):
                for p in op.preds:
                    needed_out |= p.columns
            elif isinstance(op, Sort):
                for k in op.keys:
                    needed_out |= k.columns
            elif isinstance(op, Project):
                for _, ce in op.exprs:
                    needed_out |= ce.columns
    for op in sh["join_ops"]:
        if isinstance(op, Filter):
            for p in op.preds:
                needed_out |= p.columns
        else:
            raise _Decline(
                "join", "non-filter operator between join and "
                "group-by stays on host")
    side_needed = [set(), set()]
    for out_name, side, src in join.colmap:
        if side == "on":
            continue
        if out_name in needed_out:
            side_needed[0 if side == "l" else 1].add(src)
    for si, (ops) in enumerate(sh["side_ops"]):
        scan = pq.segs[si].scan
        wanted = _refs_of(ops, side_needed[si] | {join.on})
        wanted |= {join.on}
        pq.segs[si].wanted = [c for c in scan.fields if c in wanted]
        pq.decide("prune-columns", "scan[%d]" % si, "device",
                  "scan reads %s of %d columns"
                  % (pq.segs[si].wanted, len(scan.fields)))
    pq._side_needed = side_needed


def _pipe_out_fields(pq):
    """Field names the scan pipeline ends with (after its projects)."""
    ops = pq._shape["scan_ops"]
    for op in ops:                  # topmost project wins
        if isinstance(op, Project):
            return [n for n, _ in op.exprs]
    return pq.segs[0].scan.fields


def _build_pipeline(pq, seg, ops, label):
    """Admit a scan-side op chain as vectorized steps; fills
    seg.steps/out/dtypes/bounds.  Declines with the exact reason."""
    dtypes, ranges, nrows = seg.source_meta()
    seg.nrows = nrows
    seg.src_dtypes = dict(dtypes)   # run() casts chunks up to these
    env = {}                        # name -> (dtype, bounds, src | None)
    for c in (seg.wanted or seg.scan.fields):
        env[c] = (dtypes.get(c, np.dtype(object)), ranges.get(c), c)
    first_filters = True
    skip = {}
    for op in reversed(ops):        # leaf-to-top application order
        if isinstance(op, Filter):
            fns = []
            for p in op.preds:
                ve, reason = E.vectorize(
                    p, {k: v[0] for k, v in env.items()},
                    {k: v[1] for k, v in env.items() if v[1]},
                    boolean=True)
                if ve is None:
                    raise _Decline(
                        "filter", "predicate %r stays on the host: %s"
                        % (p.expr, reason))
                fns.append(ve.fn)
                if first_filters:
                    for col, rng in _skip_bounds(
                            p, set(seg.wanted or ()),
                            {k: v[0] for k, v in env.items()}).items():
                        src = env.get(col, (None, None, None))[2]
                        if src is not None:
                            plo, phi = skip.get(src, (None, None))
                            lo, hi = rng
                            if lo is not None:
                                plo = lo if plo is None \
                                    else max(plo, lo)
                            if hi is not None:
                                phi = hi if phi is None \
                                    else min(phi, hi)
                            skip[src] = (plo, phi)
            seg.steps.append(("filter", fns))
        elif isinstance(op, Project):
            first_filters = False
            items = []
            nxt = {}
            for name, ce in op.exprs:
                if _is_bare_name(ce):
                    src = ce.tree.body.id
                    if src not in env:
                        raise _Decline("project",
                                       "unknown column %r" % src)
                    items.append((name, ("pass", src)))
                    nxt[name] = env[src]
                    continue
                ve, reason = E.vectorize(
                    ce, {k: v[0] for k, v in env.items()},
                    {k: v[1] for k, v in env.items() if v[1]})
                if ve is None:
                    raise _Decline(
                        "project", "expression %r stays on the host: "
                        "%s" % (ce.expr, reason))
                items.append((name, ("vec", ve.fn)))
                nxt[name] = (np.dtype(np.int64) if ve.kind == "i"
                             else np.dtype(np.float64), ve.bounds,
                             None)
            seg.steps.append(("project", [
                (n, s if s[0] == "pass" else ("vec", s[1]))
                for n, s in items]))
            env = nxt
        else:
            raise _Decline("sort", "sort inside a scan pipeline")
    if skip:
        seg.skip_ranges = skip
        pq.decide("pushdown-predicate", label, "device",
                  "chunk-skip ranges %s" % {
                      k: v for k, v in sorted(skip.items())})
    nfilters = sum(1 for k, _ in seg.steps if k == "filter")
    if nfilters:
        pq.decide("pushdown-predicate", label, "device",
                  "%d predicate(s) evaluate as vectorized array "
                  "programs before any row materializes" % nfilters)
    seg.env_meta = env
    seg.out = list(env)
    seg.dtypes = {k: v[0] for k, v in env.items()}
    seg.bounds = {k: v[1] for k, v in env.items() if v[1]}
    return env


def _rule_scan_pipelines(pq):
    sh = pq._shape
    if pq.mode in ("scan", "group"):
        _build_pipeline(pq, pq.segs[0], sh["scan_ops"], "scan")
    else:
        for si, ops in enumerate(sh["side_ops"]):
            _build_pipeline(pq, pq.segs[si], ops, "scan[%d]" % si)


def _key_decline(name, dt):
    if dt.kind == "f":
        return ("float group/join key %r: device hash routing needs "
                "int keys (floats ride range/sortByKey only)" % name)
    if dt.kind not in "i" and dt != np.dtype(object):
        return "unsupported key dtype %s for %r" % (dt, name)
    return None


def _rule_lower_group(pq):
    """Lower GroupAgg onto the device exchange: key shapes, aggregate
    kinds, int-sum overflow proofs, UDA admission."""
    from dpark_tpu import conf
    g = pq._shape["group"]
    seg = pq.segs[0] if pq.mode == "group" else None
    nrows = max(1, max(getattr(s, "nrows", 1) or 1 for s in pq.segs))
    # -- keys ------------------------------------------------------------
    key_cols, key_names, encode = [], [], []
    if len(g.keys) > int(getattr(conf, "MAX_KEY_LEAVES", 4)):
        raise _Decline("group-agg", "%d group keys exceed "
                       "conf.MAX_KEY_LEAVES=%d" % (
                           len(g.keys), conf.MAX_KEY_LEAVES))
    if pq.mode == "group":
        env = seg.env_meta
        extra = []                  # derived key/arg project items
        for name, ce in g.keys:
            cname = "__k%d" % len(key_cols)
            dt, reason = _group_col(pq, seg, env, ce, cname, extra)
            if reason is not None:
                raise _Decline("group-agg", "group key %r: %s"
                               % (ce.expr, reason))
            bad = _key_decline(ce.expr, dt)
            if bad:
                if dt == np.dtype(object):
                    encode.append(cname)
                else:
                    raise _Decline("group-agg", bad)
            elif dt == np.dtype(object):
                encode.append(cname)
            key_cols.append(cname)
            key_names.append(name)
        def _extra_pop(cname):
            extra[:] = [(n, s) for n, s in extra if n != cname]
            env.pop(cname, None)

        kinds, arg_cols, agg_names, uda = _admit_aggs(
            pq, g, nrows, lambda ce, nm:
            _group_col(pq, seg, env, ce, nm, extra), _extra_pop)
        if extra:
            # the derived key/arg project REPLACES the pipeline's
            # output env: from here on the exchange sees only the
            # __k*/__a* columns
            seg.steps.append(("project", list(extra)))
            seg.out = [n for n, _ in extra]
        pq._group = {
            "cols": key_cols + arg_cols, "nk": len(key_cols),
            "kinds": tuple(kinds), "key_names": key_names,
            "agg_names": agg_names, "encode": encode,
            "lower": ("uda" if uda is not None else
                      "classified" if _classified_ok(kinds) else
                      "reduce"),
            "uda": uda}
        if encode:
            pq.decide("encode-strings", "group-agg", "device",
                      "string group key(s) %s ride dictionary-encoded "
                      "(TokenDict int64 ids, decoded at egest)"
                      % [key_names[key_cols.index(c)] for c in encode])
        pq.decide("lower-group-agg", "group-agg", "device",
                  "lowered as %s over the %s-key exchange (aggs: %s)"
                  % (pq._group["lower"],
                     "tuple" if len(key_cols) > 1 else "scalar",
                     ",".join(kinds) if kinds else "uda"))
        return
    # -- join_group: keys/args picked from the flat joined row ----------
    j = pq._join
    idx_of = j["idx_of"]
    dtypes = j["out_dtypes"]
    key_idxs = []
    key_names = []
    for name, ce in g.keys:
        if not _is_bare_name(ce) or ce.tree.body.id not in idx_of:
            raise _Decline(
                "group-agg", "group key %r over a join must be a "
                "plain joined column" % ce.expr)
        src = ce.tree.body.id
        dt = dtypes[src]
        bad = _key_decline(src, dt)
        if bad and dt != np.dtype(object):
            raise _Decline("group-agg", bad)
        key_idxs.append(idx_of[src])
        key_names.append(name)
    kinds, arg_idxs, agg_names = [], [], []
    for (name, fn, arg, uda) in g.aggs:
        if uda is not None:
            raise _Decline("group-agg",
                           "UDA over a join stays on host")
        if fn not in DEVICE_AGGS:
            raise _Decline("group-agg", "non-device aggregate %r "
                           "(device aggregates: %s)"
                           % (fn, "/".join(DEVICE_AGGS)))
        if fn == "count" and arg is not None and _is_bare_name(arg) \
                and dtypes.get(arg.tree.body.id) == np.dtype(object):
            raise _Decline(
                "group-agg", "count(%s) over an object column counts "
                "non-null on the host" % arg.expr)
        if fn != "count":
            if arg is None or not _is_bare_name(arg) \
                    or arg.tree.body.id not in idx_of:
                raise _Decline(
                    "group-agg", "aggregate argument %r over a join "
                    "must be a plain joined column"
                    % (arg.expr if arg else None))
            src = arg.tree.body.id
            if dtypes[src] == np.dtype(object):
                raise _Decline("group-agg",
                               "string aggregate column %r" % src)
            arg_idxs.append(idx_of[src])
        kinds.append(fn)
        agg_names.append(name)
    pq._group = {"nk": len(key_idxs), "kinds": tuple(kinds),
                 "key_idxs": key_idxs, "arg_idxs": arg_idxs,
                 "key_names": key_names, "agg_names": agg_names,
                 "lower": "reduce", "uda": None}
    pq.decide("lower-group-agg", "group-agg", "device",
              "grouped join lowered as reduce over the joined rows")


def _classified_ok(kinds):
    return len(kinds) == 1 and kinds[0] in _CLASSIFIED


def _group_col(pq, seg, env, ce, cname, extra):
    """Admit one group key / aggregate-argument expression as a
    derived scan column; returns (dtype, None) or (None, reason)."""
    if _is_bare_name(ce):
        src = ce.tree.body.id
        if src not in env:
            return None, "unknown column %r" % src
        extra.append((cname, ("pass", src)))
        return env[src][0], None
    ve, reason = E.vectorize(
        ce, {k: v[0] for k, v in env.items()},
        {k: v[1] for k, v in env.items() if v[1]})
    if ve is None:
        return None, reason
    extra.append((cname, ("vec", ve.fn)))
    dt = np.dtype(np.int64) if ve.kind == "i" else np.dtype(np.float64)
    env[cname] = (dt, ve.bounds, None)
    return dt, None


def _admit_aggs(pq, g, nrows, admit_col, extra_pop):
    """Aggregate admission for the single-input group: device kinds,
    derived arg columns, overflow proofs, UDA traceability."""
    kinds, arg_cols, agg_names = [], [], []
    uda = None
    for (name, fn, arg, uda_fn) in g.aggs:
        if uda_fn is not None:
            if len(g.aggs) != 1:
                raise _Decline("group-agg", "a UDA must be the only "
                               "aggregate of its query")
            cname = "__a0"
            dt, reason = admit_col(arg, cname)
            if reason is not None:
                raise _Decline("group-agg", "UDA argument: %s" % reason)
            if dt == np.dtype(object):
                raise _Decline("group-agg", "string UDA argument")
            _check_uda(uda_fn, dt)
            arg_cols.append(cname)
            agg_names.append(name)
            uda = uda_fn
            continue
        if fn not in DEVICE_AGGS:
            raise _Decline(
                "group-agg", "non-device aggregate %r (device "
                "aggregates: %s; adcount/first/group_concat keep the "
                "host path)" % (fn, "/".join(DEVICE_AGGS)))
        if fn == "count":
            if arg is not None:
                # count(col) skips None arguments on the host; a
                # NUMERIC argument column can never hold None, so the
                # device count(*) form is exact — but an object
                # column can, and must keep the host path
                cname = "__cnt_probe"
                dt, reason = admit_col(arg, cname)
                if reason is None and dt == np.dtype(object):
                    reason = ("count(%s) over an object column "
                              "counts non-null on the host"
                              % arg.expr)
                if reason is not None:
                    raise _Decline("group-agg", "aggregate count(%s): "
                                   "%s" % (arg.expr, reason))
                extra_pop(cname)
            kinds.append("count")
            agg_names.append(name)
            continue
        cname = "__a%d" % len(arg_cols)
        dt, reason = admit_col(arg, cname)
        if reason is not None:
            raise _Decline("group-agg", "aggregate %s(%s): %s"
                           % (fn, arg.expr, reason))
        if dt == np.dtype(object):
            raise _Decline("group-agg",
                           "string aggregate column %r" % arg.expr)
        if fn in ("sum", "avg") and dt.kind == "i":
            # the host folds exact Python ints; the device wraps at
            # int64 — prove the total cannot leave int64
            bounds = _arg_bounds(pq, arg, cname)
            if bounds is None:
                raise _Decline(
                    "group-agg", "int %s(%s) has no value range for "
                    "the no-overflow proof" % (fn, arg.expr))
            peak = max(abs(bounds[0]), abs(bounds[1])) * max(1, nrows)
            if peak > _I64_MAX:
                raise _Decline(
                    "group-agg", "int %s(%s) may overflow int64 "
                    "(|value| <= %d over %d rows)"
                    % (fn, arg.expr, max(abs(bounds[0]),
                                         abs(bounds[1])), nrows))
        kinds.append(fn)
        arg_cols.append(cname)
        agg_names.append(name)
    return kinds, arg_cols, agg_names, uda


def _arg_bounds(pq, arg, cname):
    seg = pq.segs[0]
    b = seg.bounds.get(cname)
    if b is not None:
        return b
    env = getattr(seg, "env_meta", {})
    ent = env.get(cname)
    if ent is not None and ent[1] is not None:
        return ent[1]
    if _is_bare_name(arg):
        ent = env.get(arg.tree.body.id)
        if ent is not None:
            return ent[1]
    return None


def _check_uda(fn, dt):
    """A UDA must be a traceable, padding-invariant per-group function
    — the SegMapOp admission, checked HERE so a failing UDA is a
    recorded planner decline instead of a silent runtime fallback."""
    try:
        from dpark_tpu.backend.tpu import fuse
    except Exception:
        return                      # no jax: the host path serves it
    vdt = np.dtype(np.int64) if dt.kind == "i" else np.dtype(dt)
    pad, reason_or_vdef, _ = fuse.classify_seg_map(fn, vdt)
    if pad is None:
        raise _Decline("group-agg", "non-traceable UDA: %s"
                       % reason_or_vdef)


def _rule_lower_join(pq):
    """Lower the equi-join onto the device join source: shared key
    dtype (string keys share one TokenDict), side layouts, post-join
    filters pushed to their side's scan when single-sided."""
    join = pq._shape["join"]
    segs = pq.segs
    key_dts = []
    for si in range(2):
        dt = segs[si].dtypes.get(join.on)
        if dt is None:
            raise _Decline("join", "join column %r not produced by "
                           "side %d's scan" % (join.on, si))
        key_dts.append(dt)
    enc = {}
    if any(dt == np.dtype(object) for dt in key_dts):
        if key_dts[0] != key_dts[1]:
            raise _Decline("join", "join key dtypes disagree "
                           "(%s vs %s)" % tuple(key_dts))
        from dpark_tpu.native import TokenDict
        shared = TokenDict()
        enc[(0, join.on)] = shared
        enc[(1, join.on)] = shared
        pq.decide("encode-strings", "join", "device",
                  "string join key %r rides dictionary-encoded "
                  "(one shared TokenDict across both sides)" % join.on)
    else:
        bad = _key_decline(join.on, key_dts[0]) \
            or _key_decline(join.on, key_dts[1])
        if bad:
            raise _Decline("join", bad)
    # side column layouts: on-key first, then each side's needed
    # passthrough columns (join output order)
    side_needed = getattr(pq, "_side_needed", [set(), set()])
    on_out = next(o for (o, s, c) in join.colmap if s == "on")
    side_cols = [[join.on], [join.on]]
    side_dec = [[on_out], [on_out]]     # decoder names (output names)
    out_fields = []
    out_idxs = []
    idx_of = {}
    out_dtypes = {}
    # flat row layout: (on, l_needed..., r_needed...)
    lmap = [(o, s, c) for (o, s, c) in join.colmap if s == "l"]
    rmap = [(o, s, c) for (o, s, c) in join.colmap if s == "r"]
    side_outs = [[], []]
    for side_i, cmap in ((0, lmap), (1, rmap)):
        for out_name, _s, src in cmap:
            if src not in side_needed[side_i]:
                continue
            side_cols[side_i].append(src)
            side_dec[side_i].append(out_name)
            side_outs[side_i].append(out_name)
            out_dtypes[out_name] = segs[side_i].dtypes.get(
                src, np.dtype(object))
    # a side with only the key still needs one value column (the
    # device join carries (k, v) records) — a dummy zero rides along
    for si in range(2):
        if len(side_cols[si]) == 1:
            side_cols[si].append(None)      # dummy marker
            side_dec[si].append(None)
            side_outs[si].append(None)
    idx_of[on_out] = 0
    out_dtypes[on_out] = key_dts[0]
    flat_idx = 1
    for si in range(2):
        for out_name in side_outs[si]:
            if out_name is not None:
                idx_of[out_name] = flat_idx
            flat_idx += 1
    # join output order for the no-group mode
    if pq.mode == "join":
        for out_name in join.fields:
            if out_name not in idx_of:
                raise _Decline("join", "output column %r not mapped "
                               "through the join" % out_name)
            out_fields.append(out_name)
            out_idxs.append(idx_of[out_name])
    pq._join = {"side_cols": side_cols, "side_dec": side_dec,
                "enc": enc, "idx_of": idx_of,
                "out_dtypes": out_dtypes,
                "out_fields": out_fields, "out_idxs": out_idxs}
    # post-join filters: push single-side predicates into that side's
    # scan pipeline; anything cross-side declines (v1 surface)
    for op in pq._shape.get("join_ops", ()):
        for p in op.preds:
            pushed = False
            for si, cmap in ((0, lmap + [(on_out, "on", join.on)]),
                             (1, rmap + [(on_out, "on", join.on)])):
                names = {o: c for (o, _s, c) in cmap}
                if p.columns <= set(names):
                    seg = segs[si]
                    alias_dt = {names[o]: seg.dtypes.get(
                        names[o], np.dtype(object))
                        for o in p.columns}
                    remapped = E.compile_expr(
                        _rename_expr(p, names), list(alias_dt))
                    ve, reason = E.vectorize(
                        remapped, alias_dt,
                        {names[o]: seg.bounds.get(names[o])
                         for o in p.columns
                         if seg.bounds.get(names[o])},
                        boolean=True)
                    if ve is None:
                        raise _Decline(
                            "filter", "post-join predicate %r: %s"
                            % (p.expr, reason))
                    seg.steps.append(("filter", [ve.fn]))
                    pq.decide("pushdown-predicate", "join", "device",
                              "post-join predicate %r pushed below "
                              "the join into scan[%d]" % (p.expr, si))
                    pushed = True
                    break
            if not pushed:
                raise _Decline(
                    "filter", "cross-side post-join predicate %r "
                    "stays on the host" % p.expr)
    pq.decide("lower-join", "join", "device",
              "equi-join on %r lowered onto the device join source"
              % join.on)


def _rename_expr(colexpr, name_map):
    """Expression text with output names substituted by source names
    (token-level; names are \\w+ so a regex boundary is exact)."""
    import re
    text = colexpr.expr
    for out, src in sorted(name_map.items(), key=lambda kv: -len(kv[0])):
        if out != src:
            text = re.sub(r"\b%s\b" % re.escape(out), src, text)
    return text


def _rule_price(pq):
    """Adapt decision point 2 at query granularity: with observed ms
    for both paths of this (query shape, scale) class, the cheaper one
    wins; the losing device plan records the priced reason."""
    try:
        from dpark_tpu import adapt
        if not adapt.enabled():
            return
        desc = ("query", pq.mode,
                tuple(pq.root.sketch()),
                tuple(sorted((k, str(v)) for s in pq.segs
                             for k, v in s.dtypes.items())))
        rows = max((getattr(s, "nrows", 0) or 0) for s in pq.segs)
        cls = "q%d" % (1 << max(0, int(rows - 1).bit_length())) \
            if rows else "q0"
        pq.adapt_sig = (adapt.stable_key(desc), cls)
        choice = adapt.choose_path(pq.adapt_sig)
        if choice is not None and choice["choice"] == "object":
            raise _Decline("price-path", choice["reason"])
        if choice is not None:
            pq.decide("price-path", "plan", "device", choice["reason"])
    except _Decline:
        raise
    except Exception as e:
        logger.debug("query pricing skipped: %s", e)


def _rule_reuse(pq):
    """Probe the shared result-cache plane (resultcache.py) with the
    finished plan: a full hit presets the row cache and swaps the root
    for a CachedResult leaf; a partial-aggregate hit installs the
    merge recipe (`pq._partial`); a miss leaves a store-back offer so
    the first execution populates the cache.  One `is None` check when
    the plane is off; any plane error is logged and the plan proceeds
    uncached."""
    try:
        from dpark_tpu import resultcache
        resultcache.probe(pq)
    except Exception as e:
        logger.debug("result cache probe skipped: %s", e)


# ---------------------------------------------------------------------------
# egest compilation (shared by table.py)
# ---------------------------------------------------------------------------

def compile_egest(pq):
    """Turn the egest op list (leaf-to-top) into evaluated programs:
    code objects for filters/projects/sort keys (exact host eval
    semantics at driver-side result finishing)."""
    ops = []
    for op in pq._shape.get("egest", ()):
        if isinstance(op, Filter):
            codes = [compile(p.expr, "<egest:%s>" % p.expr, "eval")
                     for p in op.preds]
            ops.append(("filter", codes))
        elif isinstance(op, Project):
            items = [(n, compile(ce.expr, "<egest:%s>" % ce.expr,
                                 "eval")) for n, ce in op.exprs]
            ops.append(("project", items))
        elif isinstance(op, Sort):
            codes = [compile(k.expr, "<egest:%s>" % k.expr, "eval")
                     for k in op.keys]
            ops.append(("sort", (codes, op.reverse)))
    if ops:
        pq.decide("egest", "result", "egest",
                  "%d result-finishing op(s) run at egest with host "
                  "eval semantics (rows are driver-resident)"
                  % len(ops))
    pq.egest_ops = ops
    return pq
