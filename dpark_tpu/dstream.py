"""DStream: micro-batch stream processing over RDDs.

Reference parity: dpark/dstream.py (SURVEY.md sections 2.3 and 3.3) — a
DStream is a time-indexed sequence of RDDs; a recurring timer turns each
batch tick into ordinary RDD jobs generated from the output streams.
Windowing unions the parent's RDDs over the window; updateStateByKey
cogroups the previous state RDD with the new batch; reduceByKeyAndWindow
supports the incremental inverse-reduce optimization.

On the tpu master every batch reuses the structurally-keyed compiled stage
programs (backend/tpu/fuse.py), so the per-tick cost is execution, not
compilation — the DStream-specific recompile hazard of SURVEY.md 7.2.5.
"""

import numbers
import os
import socket as _socket
import threading
import time as _time

from dpark_tpu.utils.log import get_logger

logger = get_logger("dstream")


class StreamingContext:
    def __init__(self, ctx, batchDuration):
        from dpark_tpu.context import DparkContext
        if isinstance(ctx, str):
            ctx = DparkContext(ctx)
        self.ctx = ctx
        self._master = ctx.master
        self.batch_duration = float(batchDuration)
        self.zero_time = None
        self.output_streams = []
        self.input_streams = []
        self._timer = None
        self._stopped = threading.Event()
        self._thread = None
        self.checkpoint_interval = 10     # batches
        self.checkpoint_path = None
        self._batches_done = 0
        self._checkpoint_now = False
        self.last_checkpoint_t = None

    # -- checkpoint / recovery (reference: StreamingContext recovery from
    #    a checkpoint dir, SURVEY.md 5.4) --------------------------------
    def checkpoint(self, directory):
        os.makedirs(directory, exist_ok=True)
        self.checkpoint_path = directory
        self.ctx.setCheckpointDir(directory)
        return self

    def __getstate__(self):
        d = dict(self.__dict__)
        for k in ("ctx", "_thread", "_timer", "_stopped"):
            d[k] = None
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self._stopped = threading.Event()

    def _save_metadata(self, t):
        from dpark_tpu import serialize
        from dpark_tpu.context import DparkContext
        from dpark_tpu.utils import atomic_file
        self.last_checkpoint_t = t
        # persist the rdd-id high-water mark: checkpoint dirs are keyed
        # rdd-<id> in a persistent dir, so a recovered process must not
        # re-mint lower ids
        self._rdd_id_hwm = DparkContext._rdd_id_counter[0]
        path = os.path.join(self.checkpoint_path, "metadata")
        with atomic_file(path) as f:
            f.write(serialize.dumps(self))

    @classmethod
    def getOrCreate(cls, directory, create_fn):
        """Recover the stream graph + state from `directory`, or build a
        fresh context via create_fn() and enable checkpointing into it.
        Recovery resumes state streams from their last checkpointed batch;
        queue/socket input consumed after that checkpoint is not replayed
        (at-most-once, as in the reference's data-loss caveats)."""
        import os as _os
        from dpark_tpu import serialize
        path = _os.path.join(directory, "metadata")
        if _os.path.exists(path):
            with open(path, "rb") as f:
                ssc = serialize.loads(f.read())
            ssc._restore(directory)
            return ssc
        ssc = create_fn()
        ssc.checkpoint(directory)
        return ssc

    def _restore(self, directory):
        from dpark_tpu.context import DparkContext
        self.ctx = DparkContext(self._master)
        self.ctx.setCheckpointDir(directory)
        self.checkpoint_path = directory
        DparkContext.advance_rdd_ids(getattr(self, "_rdd_id_hwm", 0))
        self._recovered = True
        for stream in self._all_streams():
            stream.ssc = self
            for rdd in self._stream_rdds(stream):
                _fix_rdd_ctx(rdd, self.ctx)

    @staticmethod
    def _stream_rdds(stream):
        """Every RDD a stream holds: generated batches plus RDDs embedded
        in input streams (constant rdd, queued items, defaults)."""
        out = [r for r in stream.generated.values() if r is not None]
        for attr in ("rdd", "defaultRDD"):
            r = getattr(stream, attr, None)
            if hasattr(r, "dependencies"):
                out.append(r)
        for item in getattr(stream, "queue", []) or []:
            if hasattr(item, "dependencies"):
                out.append(item)
        return out

    def _rebase_timeline(self, new_zero):
        """After recovery, restart the clock at `new_zero`: each stream's
        latest checkpointed batch becomes the batch at new_zero so the
        first new batch (new_zero + batch) finds its predecessor state."""
        for stream in self._all_streams():
            if stream.generated:
                last_t = max(stream.generated)
                last_rdd = stream.generated[last_t]
                stream.generated = {round(new_zero, 6): last_rdd}
            stream._on_rebase()
        self.zero_time = new_zero
        self._recovered = False

    def _all_streams(self):
        out = []
        seen = set()
        frontier = list(self.output_streams) + list(self.input_streams)
        while frontier:
            s = frontier.pop()
            if id(s) in seen:
                continue
            seen.add(id(s))
            out.append(s)
            frontier.extend(s.parents)
        return out

    batchDuration = property(lambda self: self.batch_duration)

    # -- input stream constructors --------------------------------------
    def queueStream(self, queue, oneAtATime=True, defaultRDD=None):
        """queue: list/deque of RDDs or of plain lists (auto-parallelized)."""
        return QueueInputDStream(self, list(queue), oneAtATime, defaultRDD)

    def textFileStream(self, directory, filter_fn=None,
                       stamp_arrival=False):
        return FileInputDStream(self, directory, filter_fn,
                                stamp_arrival=stamp_arrival)

    fileStream = textFileStream

    def socketTextStream(self, hostname, port, stamp_arrival=False):
        return SocketInputDStream(self, hostname, port,
                                  stamp_arrival=stamp_arrival)

    def makeStream(self, rdd):
        return ConstantInputDStream(self, rdd)

    def union(self, *streams):
        return UnionDStream(list(streams))

    # -- lifecycle -------------------------------------------------------
    def start(self, t0=None):
        if not self.output_streams:
            raise ValueError("no output streams registered "
                             "(call foreachRDD / pprint)")
        self.ctx.start()
        for ins in self.input_streams:
            ins.start()
        bd = self.batch_duration
        if getattr(self, "_recovered", False):
            # recovered context: restart the clock NOW, carrying each
            # state stream's checkpointed batch over as the predecessor
            # (no replay storm over the downtime gap)
            now = t0 if t0 is not None else _time.time()
            self._rebase_timeline(now - (now % bd))
        elif self.zero_time is None or t0 is not None:
            now = t0 if t0 is not None else _time.time()
            self.zero_time = now - (now % bd)
        self._stopped.clear()
        self._thread = threading.Thread(target=self._run_loop, daemon=True)
        self._thread.start()

    def _run_loop(self):
        bd = self.batch_duration
        t = self.zero_time + bd
        while not self._stopped.is_set():
            now = _time.time()
            if now < t:
                self._stopped.wait(min(t - now, 0.05))
                continue
            try:
                self.run_batch(t)
            except Exception:
                logger.exception("batch at %s failed", t)
            t += bd

    def run_batch(self, t):
        """Generate and run one batch's jobs (called by the timer loop; in
        tests it can be driven manually for determinism).

        A TypeError escaping a batch whose state/window streams took
        the probe-based numeric union-reduce rewrite permanently
        disables that rewrite (the probe saw a numeric head; the tail
        proved it wrong) and regenerates the batch through the generic
        updateFunc/invFunc path — the 5-record probe is an accelerator
        heuristic, never the arbiter of correctness."""
        t = round(t, 6)
        self._batches_done += 1
        self._checkpoint_now = (
            self.checkpoint_path is not None
            and self._batches_done % self.checkpoint_interval == 0)
        from dpark_tpu import trace
        for out in self.output_streams:
            t0 = _time.perf_counter()
            try:
                with trace.span("stream.batch", "stream", t=t):
                    out.generate_job(t)
            except (TypeError, RuntimeError) as e:
                if not self._disable_numeric_rewrites(t, e, out):
                    raise
                try:
                    with trace.span("stream.batch", "stream", t=t,
                                    replay=True):
                        out.generate_job(t)  # regenerate, generic path
                except Exception:
                    # the generic path rejects this batch too (the
                    # user's own function raises on the data): drop the
                    # poisoned derived RDDs so LATER batches carry the
                    # last good state forward instead of replaying the
                    # failure forever.  Scope to THIS output's chain —
                    # sibling chains already emitted their batch
                    for s in self._chain_streams(out):
                        if not isinstance(s, InputDStream):
                            s.generated.pop(t, None)
                    raise
            # per-tick wall observed per output chain: pane streams
            # sample it into the adapt store (split-point pricing) —
            # chains sharing a pane stream attribute the same wall
            ms = (_time.perf_counter() - t0) * 1000.0
            for s in self._chain_streams(out):
                observe = getattr(s, "_observe_tick_ms", None)
                if observe is not None:
                    try:
                        observe(ms)
                    except Exception:
                        pass
        for out in self.output_streams:
            out.forget_old(t)
        if self._checkpoint_now:
            self._save_metadata(t)

    def _chain_streams(self, out):
        """Every stream reachable from ONE output stream (the failing
        chain) — fallback surgery must not touch sibling chains that
        already emitted their batch."""
        seen, chain, frontier = set(), [], [out]
        while frontier:
            s = frontier.pop()
            if id(s) in seen:
                continue
            seen.add(id(s))
            chain.append(s)
            frontier.extend(s.parents)
        return chain

    def _disable_numeric_rewrites(self, t, exc, out):
        """Fallback on the FIRST _NumericRewriteError from the numeric
        rewrite: flip the failing chain's _numeric latches to False
        (the rewrite never re-applies for those streams) and drop the
        failed batch's derived RDDs so the retry recomputes them
        generically.  Input streams keep their generated batch — the
        data must not be consumed twice (queue) or lost (socket).
        Returns False when the error did not come from the checked op
        (an unrelated user TypeError must NOT disable working
        rewrites) or no rewrite was active; the caller re-raises."""
        if not isinstance(exc, _NumericRewriteError) \
                and "_NumericRewriteError" not in str(exc):
            return False                # an unrelated failure
        chain = self._chain_streams(out)
        hit = False
        for s in chain:
            if getattr(s, "_numeric", None):
                s._numeric = False
                hit = True
                logger.warning(
                    "%s at t=%s: numeric union-reduce rewrite hit a "
                    "TypeError (probe saw numbers, batch holds "
                    "non-numbers); falling back to the generic path "
                    "permanently", type(s).__name__, t)
        if not hit:
            return False
        for s in chain:
            if not isinstance(s, InputDStream):
                s.generated.pop(t, None)
        return True

    def awaitTermination(self, timeout=None):
        if self._thread:
            self._thread.join(timeout)

    def stop(self, stop_context=False):
        self._stopped.set()
        if self._thread:
            self._thread.join(self.batch_duration * 2 + 1)
            self._thread = None
        for ins in self.input_streams:
            ins.stop()
        # drop this context's pane streams from the live-stats
        # registry (bounded /metrics cardinality across restarts)
        from dpark_tpu import panes as panes_mod
        for s in self._all_streams():
            sid = getattr(s, "_sid", None)
            if sid is not None:
                panes_mod.unregister_stream(sid)
        if stop_context:
            self.ctx.stop()


class DStream:
    def __init__(self, ssc):
        self.ssc = ssc
        self.generated = {}            # time -> rdd (or None)
        self.must_checkpoint = False

    @property
    def slide_duration(self):
        return self.ssc.batch_duration

    @property
    def parents(self):
        return []

    @property
    def window_duration(self):
        """How long this stream's own RDDs must be remembered by parents."""
        return self.slide_duration

    def compute(self, t):
        raise NotImplementedError

    def getOrCompute(self, t):
        t = round(t, 6)
        zero = self.ssc.zero_time
        if zero is not None and t <= zero + 1e-9:
            return None                 # before the stream started
        if t in self.generated:
            return self.generated[t]
        sd = self.slide_duration
        if zero is not None and sd:
            # slide cadence (reference parity): a stream only emits at
            # multiples of its OWN slide duration.  Off-cadence ticks
            # (a windowed stream with slide > batch) produce nothing —
            # the pane plane depends on this: pane boundaries ARE the
            # emit boundaries.
            k = (t - zero) / sd
            if abs(k - round(k)) > 1e-4:
                return None
        rdd = self.compute(t)
        self.generated[t] = rdd
        if rdd is not None and self.must_checkpoint \
                and self.ssc.ctx.checkpoint_dir \
                and getattr(self.ssc, "_checkpoint_now", False):
            rdd.checkpoint()
        return rdd

    def __getstate__(self):
        d = dict(self.__dict__)
        # only checkpointed RDDs survive serialization (their lineage is
        # truncated to on-disk partitions); everything else recomputes.
        # checkpoint() is LAZY: an RDD whose parts were all written by
        # the batch jobs may not have promoted on the driver yet —
        # promote here, or the metadata snapshot would silently drop
        # the stream state (review finding)
        for r in self.generated.values():
            if r is not None:
                r._maybe_promote_checkpoint()
        d["generated"] = {
            t: r for t, r in self.generated.items()
            if r is not None and r._checkpoint_rdd is not None}
        return d

    def forget_old(self, t, keep=None):
        keep = keep if keep is not None else self._remember_duration()
        for ts in list(self.generated):
            if ts < t - keep:
                rdd = self.generated.pop(ts)
                if rdd is not None and rdd.should_cache:
                    rdd.unpersist()     # free cached partitions, not just
                                        # the reference (long-running jobs)
        for p in self.parents:
            p.forget_old(t, keep=max(keep, self.window_duration))

    def _remember_duration(self):
        return max(self.slide_duration * 4, self.window_duration * 2)

    def _on_rebase(self):
        """Hook: the recovery timeline rebase re-keys `generated` to
        the new clock; streams holding OTHER time-keyed state (pane
        stores, per-batch reduce caches) clear it here — the carried
        predecessor window stays, stale-clock partials never mix in."""

    # -- transformations -------------------------------------------------
    def map(self, f):
        return MappedDStream(self, f)

    def flatMap(self, f):
        return TransformedDStream(self, _rdd_op("flatMap", f))

    def filter(self, f):
        return TransformedDStream(self, _rdd_op("filter", f))

    def glom(self):
        return TransformedDStream(self, _rdd_op("glom"))

    def mapPartitions(self, f):
        return TransformedDStream(self, _rdd_op("mapPartitions", f))

    def mapValue(self, f):
        return TransformedDStream(self, _rdd_op("mapValue", f))

    mapValues = mapValue

    def transform(self, func):
        """func(rdd) or func(rdd, time) -> rdd"""
        return TransformedDStream(self, func)

    def groupByKey(self, numSplits=None):
        return TransformedDStream(
            self, _rdd_op("groupByKey", numSplits))

    def reduceByKey(self, func, numSplits=None):
        return TransformedDStream(
            self, _rdd_op("reduceByKey", func, numSplits))

    def combineByKey(self, createCombiner, mergeValue, mergeCombiners,
                     numSplits=None):
        return TransformedDStream(
            self, _rdd_op("combineByKey", createCombiner, mergeValue,
                          mergeCombiners, numSplits))

    def countByValue(self):
        return TransformedDStream(
            self, lambda r: r.map(_pair_one_ds).reduceByKey(_add_ds))

    def union(self, other):
        return UnionDStream([self, other])

    def join(self, other, numSplits=None):
        return CoGroupedDStream([self, other], "join", numSplits)

    def cogroup(self, other, numSplits=None):
        return CoGroupedDStream([self, other], "cogroup", numSplits)

    # -- windows ---------------------------------------------------------
    def window(self, windowDuration, slideDuration=None):
        return WindowedDStream(self, windowDuration, slideDuration)

    def reduceByWindow(self, reduceFunc, windowDuration, slideDuration=None,
                       invReduceFunc=None):
        """Whole-window reduce; with invReduceFunc it rides the incremental
        keyed path (constant key) instead of recomputing the window."""
        if invReduceFunc is not None:
            keyed = self.map(_const_key)
            red = keyed.reduceByKeyAndWindow(
                reduceFunc, windowDuration, slideDuration,
                invFunc=invReduceFunc)
            return TransformedDStream(red, _rdd_op("map", _drop_key))
        w = self.window(windowDuration, slideDuration)
        return TransformedDStream(w, _reduce_to_rdd(reduceFunc))

    def countByWindow(self, windowDuration, slideDuration=None):
        return (self.window(windowDuration, slideDuration)
                .transform(_count_to_rdd))

    def reduceByKeyAndWindow(self, func, windowDuration, slideDuration=None,
                             numSplits=None, invFunc=None,
                             eventTime=None, lateness=None):
        """Windowed per-key reduce; with invFunc the window updates
        incrementally (prev - leaving + entering).

        PANE PLANE (ISSUE 10): when window %% slide == 0 and slide %%
        batch == 0 (and conf.STREAM_PANES is on), the window is sliced
        into slide-sized panes whose partial aggregates persist across
        ticks.  With invFunc the slide is O(1) panes (prev + new pane
        - expired pane); without invFunc a provably mergeable func (a
        classified monoid, or ``func.__dpark_window_merge__ = True``
        asserting associativity over partial aggregates) merges
        O(log w) cached dyadic tree nodes per slide instead of
        re-reducing all w panes.  A non-invertible func with NO
        registered merge keeps the whole-window O(w) recompute and the
        `window-noninv-no-merge` plan-lint rule says so.

        EVENT TIME: `eventTime` (record -> timestamp) assigns records
        to panes by event time instead of arrival batch; the watermark
        trails the max observed timestamp by `lateness` seconds
        (default conf.STREAM_ALLOWED_LATENESS).  Late records inside
        the bound patch ONLY their pane; older ones drop, counted per
        stream.  Requires the pane plane.

        PROBE CONTRACT: when (func, invFunc) prove to be plain (+, -),
        the incremental update is rewritten to one union-reduce per
        tick — but only after a one-time probe of up to 5 records from
        the first non-empty partition shows plain numeric values
        (numbers form a group under (+, -); e.g. collections.Counter
        supports both operators but is NOT invertible).  The rewrite
        then re-verifies numeric-ness on every folded pair: the first
        non-numeric value raises TypeError inside the batch, the
        rewrite is permanently disabled for this stream, and the batch
        regenerates through the generic leftOuterJoin+invFunc path —
        the probe accelerates, it never decides correctness."""
        if invFunc is None:
            from dpark_tpu import conf
            slide = float(slideDuration or self.slide_duration)
            aligned = (_grid_multiple(float(windowDuration), slide)
                       and _grid_multiple(slide, self.slide_duration))
            merge_ok = _window_merge_registered(func)
            if conf.STREAM_PANES and aligned and merge_ok:
                return PanedWindowReduceDStream(
                    self, func, windowDuration, slideDuration, numSplits,
                    eventTime=eventTime, lateness=lateness)
            if eventTime is not None:
                raise ValueError(
                    "eventTime windows need the pane plane: aligned "
                    "window/slide/batch durations, DPARK_STREAM_PANES "
                    "on, and (for non-invertible ops) a registered "
                    "merge")
            why = ("no registered merge for %r"
                   % getattr(func, "__name__", func)) if not merge_ok \
                else ("window/slide/batch durations not grid-aligned"
                      if not aligned else "DPARK_STREAM_PANES off")
            w = self.window(windowDuration, slideDuration)
            return TransformedDStream(
                w, _MarkedWindowReduce(func, numSplits, why))
        return ReducedWindowedDStream(self, func, invFunc, windowDuration,
                                      slideDuration, numSplits,
                                      eventTime=eventTime,
                                      lateness=lateness)

    # -- state -----------------------------------------------------------
    def updateStateByKey(self, updateFunc, numSplits=None):
        """updateFunc(new_values_list, prev_state_or_None) -> state|None

        PROBE CONTRACT: an updateFunc that provably is the running-sum
        idiom ``(prev or 0) + sum(vs)`` (or carries a
        __dpark_state_monoid__ hint) is rewritten to a flat
        union-reduce per batch — but only after a one-time probe of up
        to 5 records from the first non-empty partition shows plain
        numeric values (pairwise a+b == sum()-from-0 for numbers
        only).  The rewrite then re-verifies numeric-ness on every
        folded pair: the first non-numeric value raises TypeError
        inside the batch, the rewrite is permanently disabled for this
        stream, and the batch regenerates through the generic cogroup
        path — the probe accelerates, it never decides correctness."""
        return StateDStream(self, updateFunc, numSplits)

    # -- outputs ---------------------------------------------------------
    def foreachRDD(self, func):
        out = ForEachDStream(self, func)
        self.ssc.output_streams.append(out)
        return out

    def pprint(self, num=10):
        def show(rdd, t):
            items = rdd.take(num)
            print("--- time %s ---" % t)
            for it in items:
                print(it)
        return self.foreachRDD(show)

    def collect_batches(self, sink):
        """Test/utility output: append (time, list) per non-empty batch."""
        return self.foreachRDD(
            lambda rdd, t: sink.append((t, rdd.collect())))


def _fix_rdd_ctx(rdd, ctx):
    """Re-attach the live context to a recovered RDD graph (RDD pickling
    drops ctx)."""
    seen = set()
    frontier = [rdd]
    while frontier:
        r = frontier.pop()
        if id(r) in seen or r is None:
            continue
        seen.add(id(r))
        if getattr(r, "ctx", None) is None:
            r.ctx = ctx
        for attr in ("prev", "parent", "_checkpoint_rdd", "rdd1", "rdd2"):
            nxt = getattr(r, attr, None)
            if nxt is not None and hasattr(nxt, "dependencies"):
                frontier.append(nxt)
        for attr in ("rdds",):
            for nxt in getattr(r, attr, []) or []:
                if hasattr(nxt, "dependencies"):
                    frontier.append(nxt)
        for dep in getattr(r, "dependencies", []) or []:
            nxt = getattr(dep, "rdd", None)
            if nxt is not None:
                frontier.append(nxt)


def _rdd_op(name, *args):
    def op(rdd):
        f = getattr(rdd, name)
        return f(*[a for a in args if a is not None])
    return op


def _pair_one_ds(x):
    return (x, 1)


def _const_key(x):
    return (0, x)


def _drop_key(kv):
    return kv[1]


def _add_ds(a, b):
    return a + b


def _reduce_to_rdd(func):
    def op(rdd):
        vals = rdd.mapPartitions(lambda it: _safe_reduce(it, func)) \
                  .collect()
        out = None
        have = False
        for v in vals:
            out = v if not have else func(out, v)
            have = True
        return rdd.ctx.parallelize([out] if have else [], 1)
    return op


def _safe_reduce(it, func):
    out = None
    have = False
    for x in it:
        out = x if not have else func(out, x)
        have = True
    return [out] if have else []


def _count_to_rdd(rdd):
    return rdd.ctx.parallelize([rdd.count()], 1)


def _grid_multiple(a, b):
    """round(a/b) when a is an (approximate) integer multiple >= 1 of
    b, else 0 — the pane-grid alignment test."""
    if not b:
        return 0
    k = a / b
    n = int(round(k))
    return n if n >= 1 and abs(k - n) < 1e-6 else 0


def _window_merge_registered(func):
    """A non-invertible windowed reduce may merge PARTIAL aggregates
    (pane tree) only when merging partials with `func` provably equals
    folding the raw records: a classified monoid (exact bytecode /
    identity match), or the user's explicit
    ``func.__dpark_window_merge__`` assertion (truthy = func itself is
    associative over partials).  Anything else keeps the whole-window
    recompute — reduceByKey's contract nominally promises
    associativity, but the pane tree RE-ASSOCIATES across ticks, so
    only provable or asserted merges ride."""
    if getattr(func, "__dpark_window_merge__", None):
        return True
    from dpark_tpu.utils.monoid import classify_merge
    try:
        return classify_merge(func) is not None
    except Exception:
        return False


class _MarkedWindowReduce:
    """The O(w) whole-window reduce fallback, marking every emitted
    plan so the `window-noninv-no-merge` lint rule can explain the
    per-tick recompute cost (ISSUE 10 satellite)."""

    def __init__(self, func, numSplits, reason):
        self.func = func
        self.numSplits = numSplits
        self.reason = reason

    def __call__(self, rdd):
        out = rdd.reduceByKey(self.func, self.numSplits)
        out._window_noninv = {
            "reason": self.reason,
            "op": getattr(self.func, "__name__", str(self.func))}
        return out


class DerivedDStream(DStream):
    def __init__(self, parent):
        super().__init__(parent.ssc)
        self.parent = parent

    @property
    def parents(self):
        return [self.parent]

    @property
    def slide_duration(self):
        return self.parent.slide_duration


class MappedDStream(DerivedDStream):
    def __init__(self, parent, f):
        super().__init__(parent)
        self.f = f

    def compute(self, t):
        rdd = self.parent.getOrCompute(t)
        return rdd.map(self.f) if rdd is not None else None


class TransformedDStream(DerivedDStream):
    def __init__(self, parent, func):
        super().__init__(parent)
        self.func = func
        import inspect
        try:
            self._two_args = len(inspect.signature(func).parameters) >= 2
        except (TypeError, ValueError):
            self._two_args = False

    def compute(self, t):
        rdd = self.parent.getOrCompute(t)
        if rdd is None:
            return None
        return self.func(rdd, t) if self._two_args else self.func(rdd)


class UnionDStream(DStream):
    def __init__(self, streams):
        super().__init__(streams[0].ssc)
        self.streams = streams

    @property
    def parents(self):
        return list(self.streams)

    @property
    def slide_duration(self):
        return self.streams[0].slide_duration

    def compute(self, t):
        rdds = [s.getOrCompute(t) for s in self.streams]
        rdds = [r for r in rdds if r is not None]
        if not rdds:
            return None
        return self.ssc.ctx.union(rdds)


class CoGroupedDStream(DStream):
    def __init__(self, streams, how, numSplits=None):
        super().__init__(streams[0].ssc)
        self.streams = streams
        self.how = how
        self.numSplits = numSplits

    @property
    def parents(self):
        return list(self.streams)

    @property
    def slide_duration(self):
        return self.streams[0].slide_duration

    def compute(self, t):
        rdds = [s.getOrCompute(t) for s in self.streams]
        if any(r is None for r in rdds):
            empty = self.ssc.ctx.parallelize([], 1)
            rdds = [r if r is not None else empty for r in rdds]
        a, b = rdds
        if self.how == "join":
            return a.join(b, self.numSplits)
        return a.cogroup(b, numSplits=self.numSplits)


class WindowedDStream(DerivedDStream):
    def __init__(self, parent, windowDuration, slideDuration=None):
        super().__init__(parent)
        self._window = float(windowDuration)
        self._slide = float(slideDuration or parent.slide_duration)

    @property
    def slide_duration(self):
        return self._slide

    @property
    def window_duration(self):
        return self._window

    def compute(self, t):
        rdds = []
        step = self.parent.slide_duration
        # window covers (t - window, t]
        k = t
        while k > t - self._window + 1e-9:
            rdd = self.parent.getOrCompute(round(k, 6))
            if rdd is not None:
                rdds.append(rdd)
            k -= step
        if not rdds:
            return None
        return self.ssc.ctx.union(rdds)


class _PaneWindowBase(DerivedDStream):
    """Shared pane-plane machinery for the windowed streams (ISSUE 10
    tentpole; see dpark_tpu/panes.py for the decomposition): the
    window is sliced into slide-sized PANES whose partial aggregates
    live as cached reduced RDDs keyed by pane end time — on the tpu
    master their shuffle outputs stay HBM-resident between ticks, so
    sliding the window costs merge work over a constant (invertible)
    or logarithmic (merge-tree) number of panes, never a whole-window
    recompute.  Event-time classification, the bounded-lateness
    watermark, single-pane late patches, per-stream live stats
    (panes.stream_stats -> web UI + /metrics), trace events, and the
    adapt-store cost sampling all live here."""

    _kind = "win"

    def __init__(self, parent, func, windowDuration, slideDuration,
                 numSplits, eventTime=None, lateness=None):
        super().__init__(parent)
        self.func = func
        self._window = float(windowDuration)
        self._slide = float(slideDuration or parent.slide_duration)
        self.numSplits = numSplits
        self.must_checkpoint = True
        from dpark_tpu import conf
        # pane-grid admission: the window must be a whole number of
        # slides and the slide a whole number of parent batches
        self._np = _grid_multiple(self._window, self._slide)
        self._bpp = _grid_multiple(self._slide, parent.slide_duration)
        self._pane_mode = bool(conf.STREAM_PANES and self._np
                               and self._bpp)
        self.eventTime = eventTime
        if eventTime is not None and not self._pane_mode:
            raise ValueError(
                "eventTime windows need the pane plane: aligned "
                "window/slide/batch durations and DPARK_STREAM_PANES")
        if lateness is None:
            lateness = conf.STREAM_ALLOWED_LATENESS
        from dpark_tpu import panes as panes_mod
        self._wm = (panes_mod.Watermark(lateness)
                    if eventTime is not None else None)
        self._panes = {}        # pane END time -> reduced rdd or None
        self._tick_deltas = {}  # tick -> in-window late-delta rdds
        self._retired = []      # (due_time, replaced-pane rdd)
        self._anchor = None     # first emit time == pane index 0
        self._sid = None
        self._stats = None
        self._adapt_site = None
        self._tick_samples = []

    @property
    def slide_duration(self):
        return self._slide

    @property
    def window_duration(self):
        return self._window

    # -- identity / registration ----------------------------------------
    def _mode_name(self):
        return "pane"

    def _ensure_registered(self):
        from dpark_tpu import panes as panes_mod
        if self._sid is None:
            self._sid = panes_mod.new_stream_id(self._kind)
            self._stats = {
                "type": type(self).__name__, "mode": self._mode_name(),
                "window": self._window, "slide": self._slide,
                "panes": 0, "nodes": 0, "node_builds": 0, "ticks": 0,
                "watermark": None, "watermark_lag_s": None,
                "late_dropped": 0, "late_patched_rows": 0,
                "late_patches": 0}
            panes_mod.register_stream(self._sid, self._stats)
        if self._adapt_site is None:
            from dpark_tpu import adapt
            try:
                self._adapt_site = adapt.stable_key(
                    ("pane", type(self).__name__,
                     getattr(self.func, "__code__", repr(self.func)),
                     self._np))
            except Exception:
                self._adapt_site = False

    def _tag(self, rdd, role, pane=None):
        """Stage attribution (schedule.py reads `_stream_tag` into
        stage_info): which stream and which pane-plane role a stage's
        RDD serves."""
        if rdd is not None and self._sid is not None:
            tag = {"stream": self._sid, "role": role}
            if pane is not None:
                tag["pane"] = pane
            rdd._stream_tag = tag
        return rdd

    # -- pane store ------------------------------------------------------
    def _idx(self, t):
        return int(round((t - self._anchor) / self._slide))

    def _pane_time(self, idx):
        return round(self._anchor + idx * self._slide, 6)

    def _pane_by_idx(self, idx):
        return self._panes.get(self._pane_time(idx))

    def _new_data(self, t):
        """Union of the parent batches in (t - slide, t], generated in
        ASCENDING time order (queue inputs pop in arrival order)."""
        step = self.parent.slide_duration
        rdds = []
        for j in range(self._bpp - 1, -1, -1):
            r = self.parent.getOrCompute(round(t - j * step, 6))
            if r is not None:
                rdds.append(r)
        if not rdds:
            return None
        return rdds[0] if len(rdds) == 1 else self.ssc.ctx.union(rdds)

    def _reduce(self, rdd):
        return rdd.reduceByKey(self.func, self.numSplits)

    def _on_pane_patched(self, pane_time):
        """Hook: the merge tree invalidates the nodes covering a
        patched pane."""

    def _ingest_pane(self, t):
        """Build pane(t) from the tick's new data, event-time-split
        when configured: on-time records form the new pane, admissible
        late records patch ONLY their pane (bounded by the watermark,
        the window horizon, and conf.STREAM_LATE_BUFFER_ROWS), the
        rest drop (counted).  Returns the tick's in-window late-delta
        RDDs so incremental window updates can fold the patches in;
        idempotent per tick (the numeric-rewrite fallback replays a
        batch through compute())."""
        from dpark_tpu import conf, panes as panes_mod, trace
        t = round(t, 6)
        self._tick_emitted = True       # adapt sampling: a REAL emit
                                        # tick (run_batch also observes
                                        # off-cadence no-op ticks)
        if t in self._panes:
            return self._tick_deltas.get(t, [])
        self._ensure_registered()
        if self._anchor is None:
            self._anchor = t
        new = self._new_data(t)
        deltas = []
        if new is None:
            self._panes[t] = None
            self._note_tick(t)
            return deltas
        if self.eventTime is None:
            pane = self._tag(self._reduce(new).cache(), "pane-build",
                             pane=self._idx(t))
            self._panes[t] = pane
            trace.event("stream.pane.build", "stream", stream=self._sid,
                        pane=self._idx(t))
            self._note_tick(t)
            return deltas
        new = new.cache()
        # the raw tick union materializes for the scan job and feeds
        # the pane/delta filters; retire its cache at the horizon like
        # a replaced pane (its lineage stays recomputable)
        self._retired.append(
            (t + self._window + self._wm.lateness, new))
        # classify the tick's records under the PREVIOUS watermark
        # (one small job; the filters below share the same rule)
        max_back = min(self._np - 1, self._idx(t))
        floor = self._wm.floor()
        mx, on_time, late, dropped = panes_mod.event_scan(
            new, self.eventTime, t, self._slide, max_back, floor)
        pane = None
        if on_time:
            pane = new.filter(panes_mod._PaneFilter(
                self.eventTime, t, self._slide, 0, floor))
            pane = self._tag(self._reduce(pane).cache(), "pane-build",
                             pane=self._idx(t))
            trace.event("stream.pane.build", "stream", stream=self._sid,
                        pane=self._idx(t))
        self._panes[t] = pane
        cap = conf.STREAM_LATE_BUFFER_ROWS
        for back in sorted(late):
            rows = late[back]
            if cap and rows > cap:
                # bounded late buffer: an oversized patch drops WHOLE
                # (deterministic — a first-N admission would depend on
                # partition scan order)
                dropped += rows
                continue
            pt = round(t - back * self._slide, 6)
            delta = new.filter(panes_mod._PaneFilter(
                self.eventTime, t, self._slide, back, floor))
            delta = self._tag(self._reduce(delta).cache(), "late-patch",
                              pane=self._idx(pt))
            old = self._panes.get(pt)
            if old is None:
                patched = delta
            else:
                patched = self._tag(
                    self._reduce(old.union(delta)).cache(),
                    "pane-build", pane=self._idx(pt))
                # the replaced pane may still back cached lineage of
                # already-emitted windows: retire it at the horizon
                self._retired.append(
                    (pt + self._window + self._wm.lateness, old))
            self._panes[pt] = patched
            self._on_pane_patched(pt)
            deltas.append(delta)
            self._stats["late_patches"] += 1
            self._stats["late_patched_rows"] += rows
            trace.event("stream.late.patch", "stream", stream=self._sid,
                        pane=self._idx(pt), rows=rows)
        self._wm.update(mx)
        self._stats["late_dropped"] += dropped
        if deltas:
            self._tick_deltas[t] = deltas
        self._note_tick(t)
        return deltas

    def _window_pane_rdds(self, t):
        """The window's existing pane partials (cold start / flat
        emit)."""
        out = []
        k = t
        while k > t - self._window + 1e-9:
            p = self._panes.get(round(k, 6))
            if p is not None:
                out.append(p)
            k -= self._slide
        return out

    # -- bookkeeping -----------------------------------------------------
    def _note_tick(self, t):
        st = self._stats
        st["ticks"] += 1
        st["panes"] = sum(1 for r in self._panes.values()
                          if r is not None)
        if self._wm is not None:
            st["watermark"] = self._wm.value()
            lag = self._wm.lag(t)
            st["watermark_lag_s"] = (None if lag is None
                                     else round(lag, 6))

    def _observe_tick_ms(self, ms):
        """Sample the per-tick wall into the adapt store (split-point
        pricing: the planner compares tree vs flat emit costs for this
        stream signature across runs).  One append per stream — the
        median of the post-warmup ticks."""
        if not self._pane_mode or not self._adapt_site:
            return
        # only REAL emit ticks count (with slide > batch, run_batch
        # also times off-cadence no-op ticks — ~0 ms walls that would
        # poison the median), and the list stops growing once sampled
        if not getattr(self, "_tick_emitted", False) \
                or len(self._tick_samples) >= 8:
            return
        self._tick_emitted = False
        self._tick_samples.append(float(ms))
        if len(self._tick_samples) == 8:
            from dpark_tpu import adapt
            tail = sorted(self._tick_samples[4:])
            adapt.record_pane_cost(self._adapt_site, self._mode_name(),
                                   tail[len(tail) // 2], self._np)

    def forget_old(self, t, keep=None):
        super().forget_old(t, keep)
        horizon = self._window + self._slide * 2 \
            + (self._wm.lateness if self._wm is not None else 0.0)
        for ts in list(self._panes):
            if ts < t - horizon:
                rdd = self._panes.pop(ts)
                if rdd is not None and rdd.should_cache:
                    rdd.unpersist()
        for ts in list(self._tick_deltas):
            if ts < t - horizon:
                for rdd in self._tick_deltas.pop(ts):
                    if rdd.should_cache:
                        rdd.unpersist()
        keep_retired = []
        for due, rdd in self._retired:
            if due < t:
                if rdd.should_cache:
                    rdd.unpersist()
            else:
                keep_retired.append((due, rdd))
        self._retired = keep_retired
        if self._stats is not None:
            self._stats["panes"] = sum(
                1 for r in self._panes.values() if r is not None)

    def _on_rebase(self):
        # pane stores are keyed by the OLD clock: clear them (the
        # carried predecessor window survives via `generated`; panes
        # refill from the new anchor, exactly like the pre-pane
        # per-batch reduce cache)
        self._panes = {}
        self._tick_deltas = {}
        self._retired = []
        self._anchor = None

    def __getstate__(self):
        d = super().__getstate__()
        # only checkpointed panes survive the metadata snapshot (same
        # contract as `generated`); live stats/registry re-create on
        # the first tick after recovery
        for r in self._panes.values():
            if r is not None:
                r._maybe_promote_checkpoint()
        d["_panes"] = {
            ts: r for ts, r in self._panes.items()
            if r is not None and r._checkpoint_rdd is not None}
        d["_tick_deltas"] = {}
        d["_retired"] = []
        d["_sid"] = None
        d["_stats"] = None
        d["_tick_samples"] = []
        return d


class ReducedWindowedDStream(_PaneWindowBase):
    """Incremental windowed reduce: new_window = inv(prev_window - old
    slice) + new slice (reference: ReducedWindowedDStream).

    PANE PLANE (ISSUE 10): on the aligned grid the slide is O(1) PANES
    regardless of the window/slide ratio — prev + new pane - expired
    pane — where the pre-pane path paid one join/reduce per BATCH
    leaving and entering (O(slide/batch) per tick, O(window/batch) on
    cold start).  Pane partials are cached reduced RDDs; the expired
    pane was built when it entered, so no recompute.  Misaligned
    windows (or DPARK_STREAM_PANES=0) keep the per-batch path."""

    _kind = "rwin"

    def __init__(self, parent, func, invFunc, windowDuration,
                 slideDuration=None, numSplits=None, eventTime=None,
                 lateness=None):
        super().__init__(parent, func, windowDuration, slideDuration,
                         numSplits, eventTime=eventTime,
                         lateness=lateness)
        self.invFunc = invFunc
        self._reduced = {}      # time -> per-batch reduced rdd
                                # (pre-pane path only)
        # provably (add, sub): the incremental update rewrites to
        # prev + new - old as ONE union-reduce — every branch is a
        # reduced shuffle, so the whole window update rides the device
        # union path instead of leftOuterJoin + per-pair Python inv.
        # The operators alone don't prove the VALUES form a group
        # under them (collections.Counter supports + and - but its -
        # saturates at zero and its negation drops positives), so the
        # rewrite additionally needs the one-time numeric value probe
        # below (_numeric) before it applies.
        self._linear_ops = _is_plain_add(func) and _is_plain_sub(invFunc)
        self._numeric = None            # undecided until data shows up
        # ONE checked-op instance for the stream's lifetime: the tpu
        # backend keys compiled programs by merge-callable identity, so
        # a fresh wrapper per batch would defeat the program cache
        # (and leak one compiled entry per tick — review finding)
        self._checked_op = (_CheckedNumericOp(func, "add")
                            if self._linear_ops else None)

    def _mode_name(self):
        return "inv"

    def _batch_reduced(self, t):
        if t not in self._reduced:
            rdd = self.parent.getOrCompute(t)
            self._reduced[t] = (rdd.reduceByKey(self.func, self.numSplits)
                                if rdd is not None else None)
        return self._reduced[t]

    def _probe_numeric(self, prev):
        if self._linear_ops and self._numeric is None:
            # one-time value probe (a one-partition job on the cached
            # window): plain numbers form a group under (+, -); other
            # +/- types (Counter saturates) must keep the join path.
            # Probe SEVERAL records, not one (ADVICE r4): a stream whose
            # first reduced value is a number but whose later ones are
            # not would otherwise silently take the union-negate
            # rewrite and diverge from the leftOuterJoin+invFunc path.
            # The verdict caches per (op, value type) process-wide —
            # sibling streams folding the same op over the same record
            # type skip the re-derivation (ISSUE 10 satellite)
            probe = _probe_values(prev)
            if probe:
                self._numeric = _numeric_verdict(
                    "add", [rec[1] for rec in probe])

    def compute(self, t):
        if not self._pane_mode:
            return self._compute_batchwise(t)
        from dpark_tpu import trace
        t = round(t, 6)
        prev = self.generated.get(round(t - self._slide, 6))
        deltas = self._ingest_pane(t)
        pane_new = self._panes.get(t)
        if prev is None:
            # cold start: flat union-reduce over the window's panes
            # (each pane already reduced; deltas are folded into the
            # patched panes, so they must NOT be added again here)
            rdds = self._window_pane_rdds(t)
            if not rdds:
                return None
            if len(rdds) == 1:
                return rdds[0]
            out = rdds[0].union(*rdds[1:]) \
                         .reduceByKey(self.func, self.numSplits).cache()
            trace.event("stream.window.emit", "stream",
                        stream=self._sid, branches=len(rdds))
            return self._tag(out, "window-emit")
        pane_old = self._panes.get(round(t - self._window, 6))
        self._probe_numeric(prev)
        if self._linear_ops and self._numeric:
            # prev + new pane - expired pane (+ late patch deltas), ONE
            # union-reduce over a CONSTANT number of branches.  Key-set
            # parity with the join formulation: every key in the
            # expired pane also appears in prev (prev's window
            # contained that pane), so negated orphan keys cannot
            # materialize; keys at the zero element stay present,
            # exactly like leftOuterJoin + sub
            branches = [prev]
            if pane_new is not None:
                branches.append(pane_new)
            branches.extend(deltas)
            if pane_old is not None:
                branches.append(pane_old.mapValue(_neg_value))
            if len(branches) == 1:
                return prev             # quiet tick: window unchanged
            # checked op: a non-numeric tail raises TypeError and
            # run_batch falls back to the join+invFunc path
            out = branches[0].union(*branches[1:]) \
                .reduceByKey(self._checked_op, self.numSplits).cache()
            trace.event("stream.window.emit", "stream",
                        stream=self._sid, branches=len(branches))
            return self._tag(out, "window-emit")
        # generic invFunc path, pane granularity: ONE inverse join for
        # the expired pane (invFunc sees the pane's AGGREGATE — the
        # reference contract: old values are reduced first, then
        # inverse-reduced once) + one union-reduce for the new pane
        # and any late patches
        out = prev
        if pane_old is not None:
            out = out.leftOuterJoin(pane_old, self.numSplits) \
                     .mapValue(_InvApply(self.invFunc))
        entering = ([pane_new] if pane_new is not None else []) + deltas
        if entering:
            out = out.union(*entering) \
                     .reduceByKey(self.func, self.numSplits)
        if out is prev:
            return prev
        # drop keys whose count reached the zero element is left to the
        # user's invFunc semantics (parity with reference)
        trace.event("stream.window.emit", "stream", stream=self._sid,
                    branches=1 + len(entering))
        return self._tag(out.cache(), "window-emit")

    def _compute_batchwise(self, t):
        """The pre-pane per-batch path (misaligned windows or
        DPARK_STREAM_PANES=0 — also the parity suite's reference
        side)."""
        prev = self.generated.get(round(t - self._slide, 6))
        step = self.parent.slide_duration
        if prev is None:
            # cold start: plain window reduce
            rdds = []
            k = t
            while k > t - self._window + 1e-9:
                r = self._batch_reduced(round(k, 6))
                if r is not None:
                    rdds.append(r)
                k -= step
            if not rdds:
                return None
            out = rdds[0]
            for r in rdds[1:]:
                out = out.union(r)
            return out.reduceByKey(self.func, self.numSplits).cache()
        # incremental: subtract slices leaving the window, add new ones
        leaving, entering = [], []
        k = t - self._window
        while k > t - self._window - self._slide + 1e-9:
            r = self._batch_reduced(round(k, 6))
            if r is not None:
                leaving.append(r)
            k -= step
        k = t
        while k > t - self._slide + 1e-9:
            r = self._batch_reduced(round(k, 6))
            if r is not None:
                entering.append(r)
            k -= step
        self._probe_numeric(prev)
        if self._linear_ops and self._numeric:
            branches = ([prev] + entering
                        + [r.mapValue(_neg_value) for r in leaving])
            out = branches[0]
            if len(branches) > 1:
                # checked op: a non-numeric tail raises TypeError and
                # run_batch falls back to the join+invFunc path
                out = out.union(*branches[1:]) \
                         .reduceByKey(self._checked_op, self.numSplits)
            return out.cache()
        out = prev
        for r in leaving:
            joined = out.leftOuterJoin(r, self.numSplits)
            out = joined.mapValue(_InvApply(self.invFunc))
        for r in entering:
            out = out.union(r).reduceByKey(self.func, self.numSplits)
        return out.cache()

    def forget_old(self, t, keep=None):
        super().forget_old(t, keep)
        for ts in list(self._reduced):
            if ts < t - (self._window + self._slide * 2):
                rdd = self._reduced.pop(ts)
                if rdd is not None and rdd.should_cache:
                    rdd.unpersist()

    def _on_rebase(self):
        super()._on_rebase()
        self._reduced = {}


class PanedWindowReduceDStream(_PaneWindowBase):
    """Non-invertible windowed reduce over the pane plane: each tick
    merges the window's pane range through a cache of ALIGNED dyadic
    merge nodes (panes.MergeTree) — at most ~2*log2(w) branches per
    emit and amortized O(1) node builds per pane, vs. re-reducing all
    w panes (let alone all raw batches) every slide.  Below
    conf.STREAM_PANE_TREE_MIN panes the tree's extra cached
    intermediate shuffles don't pay and the panes union FLAT; with
    DPARK_ADAPT=on the split-point choice comes from OBSERVED per-tick
    costs instead (adapt.steer_pane_mode).

    Admission (checked by reduceByKeyAndWindow before constructing
    this class): merging PARTIAL aggregates with `func` must provably
    equal folding raw records — a classified monoid or an explicit
    ``func.__dpark_window_merge__`` assertion.  Float caveat: the tree
    re-associates the fold, so float low-order bits can differ from
    the whole-window recompute (the GROUP_AGG_REWRITE caveat); integer
    and min/max aggregates are exact."""

    _kind = "pwin"

    def __init__(self, parent, func, windowDuration, slideDuration=None,
                 numSplits=None, eventTime=None, lateness=None):
        super().__init__(parent, func, windowDuration, slideDuration,
                         numSplits, eventTime=eventTime,
                         lateness=lateness)
        assert self._pane_mode, "constructed without pane admission"
        self._tree = None
        self._use_tree = None           # decided at first emit
        # a node wider than half the window is covered at most once
        # per window length — not worth caching
        half = max(1, self._np // 2)
        self._max_node = 1 << (half.bit_length() - 1)

    def _mode_name(self):
        if self._use_tree is None:
            return "pane"
        return "tree" if self._use_tree else "flat"

    def _get_tree(self):
        if self._tree is None:
            from dpark_tpu import panes as panes_mod
            self._tree = panes_mod.MergeTree(self._pane_by_idx,
                                             self._merge_node)
        return self._tree

    def _merge_node(self, kids, size, start):
        from dpark_tpu import trace
        out = kids[0].union(*kids[1:]) \
            .reduceByKey(self.func, self.numSplits).cache()
        self._tag(out, "tree-merge", pane=start)
        trace.event("stream.tree.merge", "stream", stream=self._sid,
                    start=start, size=size)
        return out

    def _on_pane_patched(self, pane_time):
        if self._tree is not None:
            # a late patch dirties exactly the O(log w) nodes covering
            # its pane; the next emit rebuilds only those
            self._tree.invalidate(self._idx(pane_time))

    def _decide_mode(self):
        from dpark_tpu import adapt, conf
        static = self._np >= max(2, conf.STREAM_PANE_TREE_MIN)
        self._use_tree = adapt.steer_pane_mode(
            self._adapt_site, self._np, static)
        if self._stats is not None:
            self._stats["mode"] = self._mode_name()

    def compute(self, t):
        from dpark_tpu import trace
        t = round(t, 6)
        self._ingest_pane(t)    # deltas fold via the patched panes
        if self._use_tree is None:
            self._decide_mode()
        hi = self._idx(t)
        lo = max(0, hi - self._np + 1)
        if self._use_tree:
            tree = self._get_tree()
            rdds = tree.cover(lo, hi, max_size=self._max_node)
            if self._stats is not None:
                self._stats["nodes"] = len(tree.nodes)
                self._stats["node_builds"] = tree.builds
        else:
            rdds = self._window_pane_rdds(t)
        if not rdds:
            return None
        trace.event("stream.window.emit", "stream", stream=self._sid,
                    branches=len(rdds))
        if len(rdds) == 1:
            return rdds[0]
        out = rdds[0].union(*rdds[1:]) \
            .reduceByKey(self.func, self.numSplits).cache()
        return self._tag(out, "window-emit")

    def forget_old(self, t, keep=None):
        super().forget_old(t, keep)
        if self._tree is not None and self._anchor is not None:
            horizon = self._window + self._slide * 2 + (
                self._wm.lateness if self._wm is not None else 0.0)
            self._tree.forget(self._idx(t - horizon))

    def _on_rebase(self):
        super()._on_rebase()
        self._tree = None

    def __getstate__(self):
        d = super().__getstate__()
        d["_tree"] = None               # rebuilt from panes on demand
        return d


class _InvApply:
    def __init__(self, invFunc):
        self.invFunc = invFunc

    def __call__(self, pair):
        cur, old = pair
        return self.invFunc(cur, old) if old is not None else cur


def _code_is_2arg(f, template):
    """f is a closure-free 2-arg function with the template's bytecode
    (the classify_merge idiom — exact identification, never probing)."""
    code = getattr(f, "__code__", None)
    if code is None or getattr(f, "__closure__", None):
        return False
    t = template.__code__
    return (code.co_code == t.co_code
            and code.co_consts == t.co_consts
            and code.co_names == t.co_names
            and code.co_argcount == 2)


def _is_plain_add(f):
    import operator
    return (f is operator.add
            or _code_is_2arg(f, lambda a, b: a + b)
            or _code_is_2arg(f, lambda a, b: b + a))


def _is_plain_sub(f):
    import operator
    return f is operator.sub or _code_is_2arg(f, lambda a, b: a - b)


def _neg_value(v):
    return -v


def _arraylike(x):
    """NUMERIC array-likes only: jax tracers during the merge-fn trace,
    numpy numeric scalars/arrays on ingested columns.  dtype.kind is
    checked so np.str_ (which carries dtype+shape) cannot slip a
    string concatenation past the numeric rewrite."""
    dt = getattr(x, "dtype", None)
    # sentinel default: a dtype WITHOUT .kind must default-deny ("" is
    # a substring of every string — review finding)
    return (dt is not None and hasattr(x, "shape")
            and getattr(dt, "kind", "?") in "biufc")


class _NumericRewriteError(TypeError):
    """Raised by _CheckedNumericOp when a rewritten union-reduce folds
    a non-numeric pair.  A DEDICATED type (with a distinctive name that
    survives traceback stringification across task retries) so
    run_batch never attributes an unrelated user TypeError to the
    rewrite and never disables healthy rewrites for it."""


class _CheckedNumericOp:
    """The binary op a numeric union-reduce rewrite folds with,
    re-verifying PER PAIR what the 5-record probe asserted: both
    operands are plain numbers.  A mixed batch (numeric head,
    non-numeric tail) raises TypeError instead of silently
    concatenating/diverging; StreamingContext.run_batch catches it,
    latches the stream's _numeric off, and regenerates the batch
    through the generic path.

    Carries the __dpark_monoid__ hint so the tpu master still
    classifies the merge: the device path only ever runs over ingested
    NUMERIC columns (non-numeric rows can't ingest and fall back to
    the host object path, where this check executes), so the hint is
    sound.

    The per-operand verdict caches per (class, dtype kind) in a table
    SHARED across streams (ISSUE 10 satellite): the isinstance probe
    runs once per value type seen in the process, and every later fold
    over that type is one dict hit — not one isinstance chain per pair
    per batch.  The dtype kind is part of the key because np.ndarray
    is one class over many dtypes (an int array must not pre-approve a
    string array); the verdict itself is op-independent (the op was
    vetted at rewrite admission), so the table is shared by add/min/
    max/mul checked ops alike."""

    __slots__ = ("op", "__dpark_monoid__")

    _HINTS = {"add": "add", "min": "min", "max": "max", "mul": "mul"}

    # (operand class, dtype kind or None) -> bool, process-global
    _TYPE_VERDICTS = {}

    def __init__(self, op, hint=None):
        self.op = op
        if hint in self._HINTS:
            self.__dpark_monoid__ = hint

    @classmethod
    def _operand_ok(cls, x):
        dt = getattr(x, "dtype", None)
        key = (x.__class__, getattr(dt, "kind", None))
        ok = cls._TYPE_VERDICTS.get(key)
        if ok is None:
            # array-likes (jax tracers during the merge-fn trace,
            # numpy scalars/arrays on ingested columns) are numeric by
            # construction — the check targets arbitrary Python
            # objects on the host object path (str concatenation was
            # the r5 finding)
            ok = isinstance(x, numbers.Number) or _arraylike(x)
            cls._TYPE_VERDICTS[key] = ok
        return ok

    def __call__(self, a, b):
        if self._operand_ok(a) and self._operand_ok(b):
            return self.op(a, b)
        raise _NumericRewriteError(
            "numeric union-reduce rewrite saw a non-numeric pair "
            "(%s, %s): the probe-based rewrite does not apply to "
            "this stream" % (type(a).__name__, type(b).__name__))


# probe-verdict cache (ISSUE 10 satellite): (op kind, value type) ->
# bool, so sibling streams folding the same op over the same record
# type skip re-deriving the numeric verdict from their own probe rows
_PROBE_VERDICTS = {}


def _numeric_verdict(op_kind, values):
    """Are these probed values plain numbers (the union-reduce rewrite
    admission)?  Cached per (op kind, value type) when the sample is
    type-homogeneous; a mixed sample never caches (its verdict is not
    a property of one type)."""
    vt = values[0].__class__
    if all(v.__class__ is vt for v in values):
        key = (op_kind, vt)
        v = _PROBE_VERDICTS.get(key)
        if v is None:
            v = all(isinstance(x, numbers.Number) for x in values)
            _PROBE_VERDICTS[key] = v
        return v
    return all(isinstance(x, numbers.Number) for x in values)


def _probe_values(rdd, k=5):
    """Up to k records from the first non-empty partition.  Every scan
    is a parts==1 job — the array path skips single-task jobs by
    design, so the rewrite probes never pollute steady-state
    stage-kind accounting (take(k)'s expanding multi-partition scans
    did, r5 test fallout).  Scans EVERY partition like take(k) would
    (review finding: stopping early would leave _numeric undecided
    forever on streams whose leading partitions are empty); empty
    partitions cost one trivial job each, and a non-empty stream
    resolves the probe once."""
    from itertools import islice

    def head(it):
        return list(islice(it, k))
    for p in range(len(rdd.splits)):
        rows = list(rdd.ctx.runJob(rdd, head, partitions=[p]))[0]
        if rows:
            return rows
    return []


def _classify_state_update(f):
    """EXACT identification of the running-sum updateFunc — the
    streaming counter idiom ``(prev or 0) + sum(vs)`` and its spelling
    variants — as a binary monoid op for the union-reduce rewrite
    (VERDICT r4 #5: monoid state rides the mesh per batch).  Such an
    updateFunc never evicts (returns None) and treats absent prev as
    the identity, so ``prev UNION reduce(batch) -> reduceByKey(op)``
    is observationally identical.  A user function equivalent to a
    monoid fold but written differently opts in via
    ``f.__dpark_state_monoid__ = "add"|"min"|"max"|"mul"`` (contract:
    state' = op(op-reduce(new_values), prev-if-present), no eviction).
    Everything else returns None and keeps the cogroup path."""
    import operator
    hint = getattr(f, "__dpark_state_monoid__", None)
    if hint in ("add", "min", "max", "mul"):
        return {"add": operator.add, "min": min, "max": max,
                "mul": operator.mul}[hint]
    for tmpl in (lambda vs, prev: (prev or 0) + sum(vs),
                 lambda vs, prev: sum(vs) + (prev or 0),
                 lambda vs, prev: (prev if prev is not None else 0)
                 + sum(vs)):
        from dpark_tpu.utils import builtin_globals_ok
        if _code_is_2arg(f, tmpl) and builtin_globals_ok(f):
            return operator.add
    return None


class _TagState:
    """Record-level tag map for the seg-state rewrite: value -> (value
    cast to the state dtype, flag).  `+ zero` is the cast that means
    the same thing on the host (numpy promotion) and under the tracer
    (jax promotion) — flag 1 marks the carried state row.  ONE instance
    per (stream, role) so the tpu program cache stays warm across
    ticks."""

    def __init__(self, zero, flag):
        self.zero = zero
        self.flag = flag

    def __call__(self, v):
        return (v + self.zero, self.flag)


class _SegStateApply:
    """Per-group consumer of the general-updateStateByKey rewrite:
    the group's items are (value, flag) pairs — flag 1 is the carried
    state (at most one per key), flag 0 the batch's new values.  On the
    host paths this callable executes directly over the list; on the
    tpu master fuse.py recognizes `__dpark_seg_state__` and runs the
    user's update as a state-mode SegMapOp (vmapped over padded value
    segments, prev/no-prev dual trace).  Admitted updates return a
    numeric scalar in BOTH traces, so they never evict (return None) —
    the rewrite therefore skips the cogroup path's None filter."""

    def __init__(self, update):
        self.update = update
        self.__dpark_seg_state__ = update

    def __call__(self, items):
        prev = None
        vs = []
        for v, fl in items:
            if fl:
                prev = v
            else:
                vs.append(v)
        return self.update(vs, prev)


class StateDStream(DerivedDStream):
    def __init__(self, parent, updateFunc, numSplits=None):
        super().__init__(parent)
        self.updateFunc = updateFunc
        self.numSplits = numSplits
        self.must_checkpoint = True
        self._monoid_op = _classify_state_update(updateFunc)
        self._numeric = None            # undecided until data shows up
        # general TRACEABLE updateFunc (beyond the provable monoid
        # fold): rewrite to flag-union + groupByKey + _SegStateApply so
        # the tpu master's state-mode SegMapOp keeps the whole per-tick
        # update on device (state as HBM-resident columns, padded value
        # segments, vmapped update(prev, values)).  None = undecided
        # (needs a data probe), False = declined, else (zero_new,
        # zero_old, applyer) — built once, stable identities
        self._seg_state = None
        # one instance for the stream's lifetime — stable identity
        # keeps the tpu backend's compiled-program cache warm across
        # batches (review finding)
        self._checked_op = None
        if self._monoid_op is not None:
            # hint name from the SHARED classifier (utils/monoid) — no
            # fourth copy of the op->name table (review finding)
            from dpark_tpu.utils.monoid import classify_merge
            self._checked_op = _CheckedNumericOp(
                self._monoid_op,
                getattr(updateFunc, "__dpark_state_monoid__", None)
                or classify_merge(self._monoid_op))

    def compute(self, t):
        prev = self.generated.get(round(t - self.slide_duration, 6))
        if prev is None:
            # a failed/dropped batch leaves a hole in `generated`; carry
            # the most recent state forward instead of silently
            # resetting to empty (the hole batch's data is lost either
            # way, the accumulated state must not be)
            earlier = [ts for ts, rdd in self.generated.items()
                       if ts < t - 1e-9 and rdd is not None]
            if earlier:
                prev = self.generated[max(earlier)]
        batch = self.parent.getOrCompute(t)
        ctx = self.ssc.ctx
        if self._monoid_op is not None and self._numeric is None \
                and batch is not None:
            # one-time value probe (same idiom as the window rewrite,
            # ADVICE r4: several records, all must be numbers): the
            # union-reduce rewrite folds values PAIRWISE where the
            # updateFunc summed a list from 0 — identical for numbers,
            # different for e.g. strings (sum() raises, a + b doesn't).
            # The verdict caches per (op, value type) process-wide
            # (ISSUE 10 satellite)
            probe = _probe_values(batch)
            if probe:
                self._numeric = _numeric_verdict(
                    getattr(self._checked_op, "__dpark_monoid__", "add"),
                    [rec[1] for rec in probe])
        if self._monoid_op is not None and self._numeric:
            # monoid state: state' = prev U reduce(batch), one flat
            # union-reduce per batch — every stage rides the array path
            # in steady state (HBM-resident prev shuffle + new batch),
            # exactly like the (add, sub) window rewrite above.  The
            # checked op re-verifies numeric-ness PER PAIR: a batch
            # that defeats the probe (numeric head, string tail) raises
            # TypeError and run_batch falls back to the generic path
            if batch is None and prev is not None:
                return prev              # state unchanged this tick
            if batch is not None:
                op = self._checked_op
                reduced = batch.reduceByKey(op, self.numSplits)
                if prev is None:
                    return reduced.cache()
                return prev.union(reduced) \
                    .reduceByKey(op, self.numSplits).cache()
        from dpark_tpu import conf
        if self._monoid_op is None and conf.SEG_STATE \
                and self._seg_state is None and batch is not None:
            self._seg_state = self._classify_seg_state(batch)
        if self._monoid_op is None and self._seg_state:
            tag_new, tag_old, applyer = self._seg_state
            if batch is None and prev is not None:
                b = ctx.parallelize([], 1).mapValue(tag_new)
            elif batch is None:
                return None
            else:
                b = batch.mapValue(tag_new)
            u = b if prev is None else b.union(prev.mapValue(tag_old))
            return u.groupByKey(self.numSplits) \
                    .mapValues(applyer).cache()
        if batch is None:
            batch = ctx.parallelize([], 1)
        if prev is None:
            prev = ctx.parallelize([], 1)
        grouped = batch.cogroup(prev, numSplits=self.numSplits)
        updated = grouped.mapValue(_StateUpdate(self.updateFunc)) \
                         .filter(_state_not_none)
        return updated.mapValue(_unwrap_state).cache()

    def _classify_seg_state(self, batch):
        """(tag_new, tag_old, applyer) when the updateFunc is a
        traceable, padding-invariant update(values, prev) over numeric
        scalar values — the admission the state-mode SegMapOp needs —
        else False (cogroup path).  The state DTYPE is discovered by a
        fixed-point trace (int values whose update decays to float
        carry float state; both tag maps cast to it so host and device
        agree on every column)."""
        import numbers
        f = self.updateFunc
        code = getattr(f, "__code__", None)
        if code is not None and code.co_argcount != 2:
            return False
        probe = _probe_values(batch)
        if not probe:
            return None                  # stay undecided: no data yet
        vals = [rec[1] for rec in probe
                if isinstance(rec, tuple) and len(rec) == 2]
        if len(vals) != len(probe) or not all(
                isinstance(v, numbers.Number)
                and not isinstance(v, bool) for v in vals):
            return False
        try:
            import numpy as np
            import jax
            from dpark_tpu.backend.tpu import fuse
        except Exception:
            return False
        # device value dtype per layout.record_spec conventions
        vdt = np.result_type(*[np.asarray(v).dtype for v in vals])
        vdt = np.dtype(np.int64) if vdt.kind in "iu" else \
            np.dtype(np.float32)
        ds = vdt
        try:
            for _ in range(3):
                fn_p, _fn_n = fuse._seg_state_row_fns(f)
                outs = jax.eval_shape(
                    fn_p, jax.ShapeDtypeStruct((4,), ds),
                    jax.ShapeDtypeStruct((), ds))
                if len(outs) != 1 or outs[0].shape != ():
                    return False
                nxt = np.result_type(ds, outs[0].dtype)
                if nxt == ds:
                    break
                ds = np.dtype(nxt)
            else:
                return False             # state dtype does not settle
        except Exception:
            return False
        pad, reason, _ = fuse.classify_seg_map(f, ds, state=True)
        if pad is None:
            logger.debug("updateStateByKey stays on the cogroup path: "
                         "%s", reason)
            return False
        zero = ds.type(0)
        return (_TagState(zero, 0), _TagState(zero, 1),
                _SegStateApply(f))


class _StateUpdate:
    def __init__(self, updateFunc):
        self.updateFunc = updateFunc

    def __call__(self, groups):
        new_values, old_states = groups
        prev = old_states[0] if old_states else None
        return (self.updateFunc(new_values, prev),)


def _state_not_none(kv):
    return kv[1][0] is not None


def _unwrap_state(wrapped):
    return wrapped[0]


class ForEachDStream(DerivedDStream):
    def __init__(self, parent, func):
        super().__init__(parent)
        self.func = func
        import inspect
        try:
            self._two_args = len(inspect.signature(func).parameters) >= 2
        except (TypeError, ValueError):
            self._two_args = False

    def compute(self, t):
        return self.parent.getOrCompute(t)

    def generate_job(self, t):
        rdd = self.getOrCompute(t)
        if rdd is None:
            return
        if self._two_args:
            self.func(rdd, t)
        else:
            self.func(rdd)


# --------------------------------------------------------------------------
# input streams
# --------------------------------------------------------------------------

class InputDStream(DStream):
    def __init__(self, ssc):
        super().__init__(ssc)
        ssc.input_streams.append(self)

    def start(self):
        pass

    def stop(self):
        pass


class ConstantInputDStream(InputDStream):
    def __init__(self, ssc, rdd):
        super().__init__(ssc)
        self.rdd = rdd

    def compute(self, t):
        return self.rdd


class QueueInputDStream(InputDStream):
    def __init__(self, ssc, queue, oneAtATime=True, defaultRDD=None):
        super().__init__(ssc)
        self.queue = queue
        self.oneAtATime = oneAtATime
        self.defaultRDD = defaultRDD

    def put(self, item):
        self.queue.append(item)

    def _to_rdd(self, item):
        from dpark_tpu.rdd import RDD
        if isinstance(item, RDD):
            return item
        # default parallelism (== the device mesh on the tpu master):
        # a hardcoded slice count forfeited the array path for every
        # queue batch
        return self.ssc.ctx.parallelize(item)

    def compute(self, t):
        if self.queue:
            if self.oneAtATime:
                return self._to_rdd(self.queue.pop(0))
            items = list(self.queue)
            del self.queue[:len(items)]
            rdds = [self._to_rdd(i) for i in items]
            return rdds[0] if len(rdds) == 1 else self.ssc.ctx.union(rdds)
        return self.defaultRDD


class _ArrivalStamp:
    """record -> (arrival_ts, record); one picklable instance per scan
    so every record a scan picked up carries the same timestamp."""

    def __init__(self, ts):
        self.ts = ts

    def __call__(self, rec):
        return (self.ts, rec)


class FileInputDStream(InputDStream):
    """Scan a directory each batch; per-file byte offsets are tracked so a
    batch picks up both new files AND data appended to known files
    (tail -f semantics; reference FileInputDStream scans by mtime).

    CLOCK CONTRACT (ISSUE 10 satellite): with ``stamp_arrival=True``
    every record is emitted as ``(arrival_ts, line)``.  The arrival
    time is the DRIVER's wall clock at the directory scan that first
    observed the bytes — one timestamp per scan, so all lines a batch
    picked up share it, and the stamp is monotonically non-decreasing
    across batches of one stream.  That makes it a consistent
    event-time source for the watermark plane (e.g.
    ``eventTime=lambda kv: kv[1][0]`` after keying) when records carry
    no domain timestamp; file mtimes are deliberately NOT used (they
    follow the writer's clock, which may jump)."""

    def __init__(self, ssc, directory, filter_fn=None, newFilesOnly=True,
                 stamp_arrival=False):
        super().__init__(ssc)
        self.directory = directory
        self.filter_fn = filter_fn or (lambda n: not n.startswith("."))
        self.offsets = {}               # path -> bytes already consumed
        self.new_files_only = newFilesOnly
        self.stamp_arrival = stamp_arrival

    def start(self):
        if self.new_files_only:
            for name in os.listdir(self.directory):
                p = os.path.join(self.directory, name)
                if os.path.isfile(p):
                    self.offsets[p] = os.path.getsize(p)

    def compute(self, t):
        rdds = []
        scan_ts = _time.time()
        for name in sorted(os.listdir(self.directory)):
            if not self.filter_fn(name):
                continue
            p = os.path.join(self.directory, name)
            if not os.path.isfile(p):
                continue
            size = os.path.getsize(p)
            off = self.offsets.get(p, 0)
            if size > off:
                rdds.append(self.ssc.ctx.partialTextFile(p, off, size))
                self.offsets[p] = size
        if not rdds:
            return None
        out = rdds[0] if len(rdds) == 1 else self.ssc.ctx.union(rdds)
        if self.stamp_arrival:
            out = out.map(_ArrivalStamp(scan_ts))
        return out


class SocketInputDStream(InputDStream):
    """TCP line reader: a background thread accumulates lines; each batch
    drains the buffer (reference: socketTextStream).

    CLOCK CONTRACT (ISSUE 10 satellite): with ``stamp_arrival=True``
    every record is emitted as ``(arrival_ts, line)``.  The arrival
    time is the RECEIVER thread's wall clock at the moment the line
    was parsed off the socket — assigned BEFORE batching, so two lines
    that arrive around a batch boundary keep their true arrival order
    in their stamps even when the boundary splits them into different
    batches; stamps are monotonically non-decreasing per stream.  Use
    it as the watermark plane's event-time source when the wire
    carries no domain timestamp."""

    def __init__(self, ssc, hostname, port, stamp_arrival=False):
        super().__init__(ssc)
        self.hostname = hostname
        self.port = port
        self.stamp_arrival = stamp_arrival
        self.buffer = []
        self.lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        self._stop.clear()
        self._thread = threading.Thread(target=self._read, daemon=True)
        self._thread.start()

    def _read(self):
        while not self._stop.is_set():
            try:
                sock = _socket.create_connection(
                    (self.hostname, self.port), timeout=2)
                f = sock.makefile("rb")
                for line in f:
                    if self._stop.is_set():
                        break
                    rec = line.rstrip(b"\r\n").decode("utf-8", "replace")
                    if self.stamp_arrival:
                        rec = (_time.time(), rec)
                    with self.lock:
                        self.buffer.append(rec)
                sock.close()
            except OSError:
                if self._stop.wait(0.5):
                    return

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(3)
            self._thread = None

    def __getstate__(self):
        d = dict(self.__dict__)
        for k in ("lock", "_stop", "_thread"):
            d[k] = None
        d["buffer"] = []
        d["generated"] = {}
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self.lock = threading.Lock()
        self._stop = threading.Event()

    def compute(self, t):
        with self.lock:
            lines, self.buffer = self.buffer, []
        if not lines:
            return None
        return self.ssc.ctx.parallelize(lines, 2)
