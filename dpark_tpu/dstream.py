"""DStream: micro-batch stream processing over RDDs.

Reference parity: dpark/dstream.py (SURVEY.md sections 2.3 and 3.3) — a
DStream is a time-indexed sequence of RDDs; a recurring timer turns each
batch tick into ordinary RDD jobs generated from the output streams.
Windowing unions the parent's RDDs over the window; updateStateByKey
cogroups the previous state RDD with the new batch; reduceByKeyAndWindow
supports the incremental inverse-reduce optimization.

On the tpu master every batch reuses the structurally-keyed compiled stage
programs (backend/tpu/fuse.py), so the per-tick cost is execution, not
compilation — the DStream-specific recompile hazard of SURVEY.md 7.2.5.
"""

import numbers
import os
import socket as _socket
import threading
import time as _time

from dpark_tpu.utils.log import get_logger

logger = get_logger("dstream")


class StreamingContext:
    def __init__(self, ctx, batchDuration):
        from dpark_tpu.context import DparkContext
        if isinstance(ctx, str):
            ctx = DparkContext(ctx)
        self.ctx = ctx
        self._master = ctx.master
        self.batch_duration = float(batchDuration)
        self.zero_time = None
        self.output_streams = []
        self.input_streams = []
        self._timer = None
        self._stopped = threading.Event()
        self._thread = None
        self.checkpoint_interval = 10     # batches
        self.checkpoint_path = None
        self._batches_done = 0
        self._checkpoint_now = False
        self.last_checkpoint_t = None

    # -- checkpoint / recovery (reference: StreamingContext recovery from
    #    a checkpoint dir, SURVEY.md 5.4) --------------------------------
    def checkpoint(self, directory):
        os.makedirs(directory, exist_ok=True)
        self.checkpoint_path = directory
        self.ctx.setCheckpointDir(directory)
        return self

    def __getstate__(self):
        d = dict(self.__dict__)
        for k in ("ctx", "_thread", "_timer", "_stopped"):
            d[k] = None
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self._stopped = threading.Event()

    def _save_metadata(self, t):
        from dpark_tpu import serialize
        from dpark_tpu.context import DparkContext
        from dpark_tpu.utils import atomic_file
        self.last_checkpoint_t = t
        # persist the rdd-id high-water mark: checkpoint dirs are keyed
        # rdd-<id> in a persistent dir, so a recovered process must not
        # re-mint lower ids
        self._rdd_id_hwm = DparkContext._rdd_id_counter[0]
        path = os.path.join(self.checkpoint_path, "metadata")
        with atomic_file(path) as f:
            f.write(serialize.dumps(self))

    @classmethod
    def getOrCreate(cls, directory, create_fn):
        """Recover the stream graph + state from `directory`, or build a
        fresh context via create_fn() and enable checkpointing into it.
        Recovery resumes state streams from their last checkpointed batch;
        queue/socket input consumed after that checkpoint is not replayed
        (at-most-once, as in the reference's data-loss caveats)."""
        import os as _os
        from dpark_tpu import serialize
        path = _os.path.join(directory, "metadata")
        if _os.path.exists(path):
            with open(path, "rb") as f:
                ssc = serialize.loads(f.read())
            ssc._restore(directory)
            return ssc
        ssc = create_fn()
        ssc.checkpoint(directory)
        return ssc

    def _restore(self, directory):
        from dpark_tpu.context import DparkContext
        self.ctx = DparkContext(self._master)
        self.ctx.setCheckpointDir(directory)
        self.checkpoint_path = directory
        DparkContext.advance_rdd_ids(getattr(self, "_rdd_id_hwm", 0))
        self._recovered = True
        for stream in self._all_streams():
            stream.ssc = self
            for rdd in self._stream_rdds(stream):
                _fix_rdd_ctx(rdd, self.ctx)

    @staticmethod
    def _stream_rdds(stream):
        """Every RDD a stream holds: generated batches plus RDDs embedded
        in input streams (constant rdd, queued items, defaults)."""
        out = [r for r in stream.generated.values() if r is not None]
        for attr in ("rdd", "defaultRDD"):
            r = getattr(stream, attr, None)
            if hasattr(r, "dependencies"):
                out.append(r)
        for item in getattr(stream, "queue", []) or []:
            if hasattr(item, "dependencies"):
                out.append(item)
        return out

    def _rebase_timeline(self, new_zero):
        """After recovery, restart the clock at `new_zero`: each stream's
        latest checkpointed batch becomes the batch at new_zero so the
        first new batch (new_zero + batch) finds its predecessor state."""
        for stream in self._all_streams():
            if stream.generated:
                last_t = max(stream.generated)
                last_rdd = stream.generated[last_t]
                stream.generated = {round(new_zero, 6): last_rdd}
        self.zero_time = new_zero
        self._recovered = False

    def _all_streams(self):
        out = []
        seen = set()
        frontier = list(self.output_streams) + list(self.input_streams)
        while frontier:
            s = frontier.pop()
            if id(s) in seen:
                continue
            seen.add(id(s))
            out.append(s)
            frontier.extend(s.parents)
        return out

    batchDuration = property(lambda self: self.batch_duration)

    # -- input stream constructors --------------------------------------
    def queueStream(self, queue, oneAtATime=True, defaultRDD=None):
        """queue: list/deque of RDDs or of plain lists (auto-parallelized)."""
        return QueueInputDStream(self, list(queue), oneAtATime, defaultRDD)

    def textFileStream(self, directory, filter_fn=None):
        return FileInputDStream(self, directory, filter_fn)

    fileStream = textFileStream

    def socketTextStream(self, hostname, port):
        return SocketInputDStream(self, hostname, port)

    def makeStream(self, rdd):
        return ConstantInputDStream(self, rdd)

    def union(self, *streams):
        return UnionDStream(list(streams))

    # -- lifecycle -------------------------------------------------------
    def start(self, t0=None):
        if not self.output_streams:
            raise ValueError("no output streams registered "
                             "(call foreachRDD / pprint)")
        self.ctx.start()
        for ins in self.input_streams:
            ins.start()
        bd = self.batch_duration
        if getattr(self, "_recovered", False):
            # recovered context: restart the clock NOW, carrying each
            # state stream's checkpointed batch over as the predecessor
            # (no replay storm over the downtime gap)
            now = t0 if t0 is not None else _time.time()
            self._rebase_timeline(now - (now % bd))
        elif self.zero_time is None or t0 is not None:
            now = t0 if t0 is not None else _time.time()
            self.zero_time = now - (now % bd)
        self._stopped.clear()
        self._thread = threading.Thread(target=self._run_loop, daemon=True)
        self._thread.start()

    def _run_loop(self):
        bd = self.batch_duration
        t = self.zero_time + bd
        while not self._stopped.is_set():
            now = _time.time()
            if now < t:
                self._stopped.wait(min(t - now, 0.05))
                continue
            try:
                self.run_batch(t)
            except Exception:
                logger.exception("batch at %s failed", t)
            t += bd

    def run_batch(self, t):
        """Generate and run one batch's jobs (called by the timer loop; in
        tests it can be driven manually for determinism).

        A TypeError escaping a batch whose state/window streams took
        the probe-based numeric union-reduce rewrite permanently
        disables that rewrite (the probe saw a numeric head; the tail
        proved it wrong) and regenerates the batch through the generic
        updateFunc/invFunc path — the 5-record probe is an accelerator
        heuristic, never the arbiter of correctness."""
        t = round(t, 6)
        self._batches_done += 1
        self._checkpoint_now = (
            self.checkpoint_path is not None
            and self._batches_done % self.checkpoint_interval == 0)
        for out in self.output_streams:
            try:
                out.generate_job(t)
            except (TypeError, RuntimeError) as e:
                if not self._disable_numeric_rewrites(t, e, out):
                    raise
                try:
                    out.generate_job(t)  # regenerate via the generic path
                except Exception:
                    # the generic path rejects this batch too (the
                    # user's own function raises on the data): drop the
                    # poisoned derived RDDs so LATER batches carry the
                    # last good state forward instead of replaying the
                    # failure forever.  Scope to THIS output's chain —
                    # sibling chains already emitted their batch
                    for s in self._chain_streams(out):
                        if not isinstance(s, InputDStream):
                            s.generated.pop(t, None)
                    raise
        for out in self.output_streams:
            out.forget_old(t)
        if self._checkpoint_now:
            self._save_metadata(t)

    def _chain_streams(self, out):
        """Every stream reachable from ONE output stream (the failing
        chain) — fallback surgery must not touch sibling chains that
        already emitted their batch."""
        seen, chain, frontier = set(), [], [out]
        while frontier:
            s = frontier.pop()
            if id(s) in seen:
                continue
            seen.add(id(s))
            chain.append(s)
            frontier.extend(s.parents)
        return chain

    def _disable_numeric_rewrites(self, t, exc, out):
        """Fallback on the FIRST _NumericRewriteError from the numeric
        rewrite: flip the failing chain's _numeric latches to False
        (the rewrite never re-applies for those streams) and drop the
        failed batch's derived RDDs so the retry recomputes them
        generically.  Input streams keep their generated batch — the
        data must not be consumed twice (queue) or lost (socket).
        Returns False when the error did not come from the checked op
        (an unrelated user TypeError must NOT disable working
        rewrites) or no rewrite was active; the caller re-raises."""
        if not isinstance(exc, _NumericRewriteError) \
                and "_NumericRewriteError" not in str(exc):
            return False                # an unrelated failure
        chain = self._chain_streams(out)
        hit = False
        for s in chain:
            if getattr(s, "_numeric", None):
                s._numeric = False
                hit = True
                logger.warning(
                    "%s at t=%s: numeric union-reduce rewrite hit a "
                    "TypeError (probe saw numbers, batch holds "
                    "non-numbers); falling back to the generic path "
                    "permanently", type(s).__name__, t)
        if not hit:
            return False
        for s in chain:
            if not isinstance(s, InputDStream):
                s.generated.pop(t, None)
        return True

    def awaitTermination(self, timeout=None):
        if self._thread:
            self._thread.join(timeout)

    def stop(self, stop_context=False):
        self._stopped.set()
        if self._thread:
            self._thread.join(self.batch_duration * 2 + 1)
            self._thread = None
        for ins in self.input_streams:
            ins.stop()
        if stop_context:
            self.ctx.stop()


class DStream:
    def __init__(self, ssc):
        self.ssc = ssc
        self.generated = {}            # time -> rdd (or None)
        self.must_checkpoint = False

    @property
    def slide_duration(self):
        return self.ssc.batch_duration

    @property
    def parents(self):
        return []

    @property
    def window_duration(self):
        """How long this stream's own RDDs must be remembered by parents."""
        return self.slide_duration

    def compute(self, t):
        raise NotImplementedError

    def getOrCompute(self, t):
        t = round(t, 6)
        zero = self.ssc.zero_time
        if zero is not None and t <= zero + 1e-9:
            return None                 # before the stream started
        if t in self.generated:
            return self.generated[t]
        rdd = self.compute(t)
        self.generated[t] = rdd
        if rdd is not None and self.must_checkpoint \
                and self.ssc.ctx.checkpoint_dir \
                and getattr(self.ssc, "_checkpoint_now", False):
            rdd.checkpoint()
        return rdd

    def __getstate__(self):
        d = dict(self.__dict__)
        # only checkpointed RDDs survive serialization (their lineage is
        # truncated to on-disk partitions); everything else recomputes.
        # checkpoint() is LAZY: an RDD whose parts were all written by
        # the batch jobs may not have promoted on the driver yet —
        # promote here, or the metadata snapshot would silently drop
        # the stream state (review finding)
        for r in self.generated.values():
            if r is not None:
                r._maybe_promote_checkpoint()
        d["generated"] = {
            t: r for t, r in self.generated.items()
            if r is not None and r._checkpoint_rdd is not None}
        return d

    def forget_old(self, t, keep=None):
        keep = keep if keep is not None else self._remember_duration()
        for ts in list(self.generated):
            if ts < t - keep:
                rdd = self.generated.pop(ts)
                if rdd is not None and rdd.should_cache:
                    rdd.unpersist()     # free cached partitions, not just
                                        # the reference (long-running jobs)
        for p in self.parents:
            p.forget_old(t, keep=max(keep, self.window_duration))

    def _remember_duration(self):
        return max(self.slide_duration * 4, self.window_duration * 2)

    # -- transformations -------------------------------------------------
    def map(self, f):
        return MappedDStream(self, f)

    def flatMap(self, f):
        return TransformedDStream(self, _rdd_op("flatMap", f))

    def filter(self, f):
        return TransformedDStream(self, _rdd_op("filter", f))

    def glom(self):
        return TransformedDStream(self, _rdd_op("glom"))

    def mapPartitions(self, f):
        return TransformedDStream(self, _rdd_op("mapPartitions", f))

    def mapValue(self, f):
        return TransformedDStream(self, _rdd_op("mapValue", f))

    mapValues = mapValue

    def transform(self, func):
        """func(rdd) or func(rdd, time) -> rdd"""
        return TransformedDStream(self, func)

    def groupByKey(self, numSplits=None):
        return TransformedDStream(
            self, _rdd_op("groupByKey", numSplits))

    def reduceByKey(self, func, numSplits=None):
        return TransformedDStream(
            self, _rdd_op("reduceByKey", func, numSplits))

    def combineByKey(self, createCombiner, mergeValue, mergeCombiners,
                     numSplits=None):
        return TransformedDStream(
            self, _rdd_op("combineByKey", createCombiner, mergeValue,
                          mergeCombiners, numSplits))

    def countByValue(self):
        return TransformedDStream(
            self, lambda r: r.map(_pair_one_ds).reduceByKey(_add_ds))

    def union(self, other):
        return UnionDStream([self, other])

    def join(self, other, numSplits=None):
        return CoGroupedDStream([self, other], "join", numSplits)

    def cogroup(self, other, numSplits=None):
        return CoGroupedDStream([self, other], "cogroup", numSplits)

    # -- windows ---------------------------------------------------------
    def window(self, windowDuration, slideDuration=None):
        return WindowedDStream(self, windowDuration, slideDuration)

    def reduceByWindow(self, reduceFunc, windowDuration, slideDuration=None,
                       invReduceFunc=None):
        """Whole-window reduce; with invReduceFunc it rides the incremental
        keyed path (constant key) instead of recomputing the window."""
        if invReduceFunc is not None:
            keyed = self.map(_const_key)
            red = keyed.reduceByKeyAndWindow(
                reduceFunc, windowDuration, slideDuration,
                invFunc=invReduceFunc)
            return TransformedDStream(red, _rdd_op("map", _drop_key))
        w = self.window(windowDuration, slideDuration)
        return TransformedDStream(w, _reduce_to_rdd(reduceFunc))

    def countByWindow(self, windowDuration, slideDuration=None):
        return (self.window(windowDuration, slideDuration)
                .transform(_count_to_rdd))

    def reduceByKeyAndWindow(self, func, windowDuration, slideDuration=None,
                             numSplits=None, invFunc=None):
        """Windowed per-key reduce; with invFunc the window updates
        incrementally (prev - leaving + entering).

        PROBE CONTRACT: when (func, invFunc) prove to be plain (+, -),
        the incremental update is rewritten to one union-reduce per
        tick — but only after a one-time probe of up to 5 records from
        the first non-empty partition shows plain numeric values
        (numbers form a group under (+, -); e.g. collections.Counter
        supports both operators but is NOT invertible).  The rewrite
        then re-verifies numeric-ness on every folded pair: the first
        non-numeric value raises TypeError inside the batch, the
        rewrite is permanently disabled for this stream, and the batch
        regenerates through the generic leftOuterJoin+invFunc path —
        the probe accelerates, it never decides correctness."""
        if invFunc is None:
            w = self.window(windowDuration, slideDuration)
            return TransformedDStream(
                w, _rdd_op("reduceByKey", func, numSplits))
        return ReducedWindowedDStream(self, func, invFunc, windowDuration,
                                      slideDuration, numSplits)

    # -- state -----------------------------------------------------------
    def updateStateByKey(self, updateFunc, numSplits=None):
        """updateFunc(new_values_list, prev_state_or_None) -> state|None

        PROBE CONTRACT: an updateFunc that provably is the running-sum
        idiom ``(prev or 0) + sum(vs)`` (or carries a
        __dpark_state_monoid__ hint) is rewritten to a flat
        union-reduce per batch — but only after a one-time probe of up
        to 5 records from the first non-empty partition shows plain
        numeric values (pairwise a+b == sum()-from-0 for numbers
        only).  The rewrite then re-verifies numeric-ness on every
        folded pair: the first non-numeric value raises TypeError
        inside the batch, the rewrite is permanently disabled for this
        stream, and the batch regenerates through the generic cogroup
        path — the probe accelerates, it never decides correctness."""
        return StateDStream(self, updateFunc, numSplits)

    # -- outputs ---------------------------------------------------------
    def foreachRDD(self, func):
        out = ForEachDStream(self, func)
        self.ssc.output_streams.append(out)
        return out

    def pprint(self, num=10):
        def show(rdd, t):
            items = rdd.take(num)
            print("--- time %s ---" % t)
            for it in items:
                print(it)
        return self.foreachRDD(show)

    def collect_batches(self, sink):
        """Test/utility output: append (time, list) per non-empty batch."""
        return self.foreachRDD(
            lambda rdd, t: sink.append((t, rdd.collect())))


def _fix_rdd_ctx(rdd, ctx):
    """Re-attach the live context to a recovered RDD graph (RDD pickling
    drops ctx)."""
    seen = set()
    frontier = [rdd]
    while frontier:
        r = frontier.pop()
        if id(r) in seen or r is None:
            continue
        seen.add(id(r))
        if getattr(r, "ctx", None) is None:
            r.ctx = ctx
        for attr in ("prev", "parent", "_checkpoint_rdd", "rdd1", "rdd2"):
            nxt = getattr(r, attr, None)
            if nxt is not None and hasattr(nxt, "dependencies"):
                frontier.append(nxt)
        for attr in ("rdds",):
            for nxt in getattr(r, attr, []) or []:
                if hasattr(nxt, "dependencies"):
                    frontier.append(nxt)
        for dep in getattr(r, "dependencies", []) or []:
            nxt = getattr(dep, "rdd", None)
            if nxt is not None:
                frontier.append(nxt)


def _rdd_op(name, *args):
    def op(rdd):
        f = getattr(rdd, name)
        return f(*[a for a in args if a is not None])
    return op


def _pair_one_ds(x):
    return (x, 1)


def _const_key(x):
    return (0, x)


def _drop_key(kv):
    return kv[1]


def _add_ds(a, b):
    return a + b


def _reduce_to_rdd(func):
    def op(rdd):
        vals = rdd.mapPartitions(lambda it: _safe_reduce(it, func)) \
                  .collect()
        out = None
        have = False
        for v in vals:
            out = v if not have else func(out, v)
            have = True
        return rdd.ctx.parallelize([out] if have else [], 1)
    return op


def _safe_reduce(it, func):
    out = None
    have = False
    for x in it:
        out = x if not have else func(out, x)
        have = True
    return [out] if have else []


def _count_to_rdd(rdd):
    return rdd.ctx.parallelize([rdd.count()], 1)


class DerivedDStream(DStream):
    def __init__(self, parent):
        super().__init__(parent.ssc)
        self.parent = parent

    @property
    def parents(self):
        return [self.parent]

    @property
    def slide_duration(self):
        return self.parent.slide_duration


class MappedDStream(DerivedDStream):
    def __init__(self, parent, f):
        super().__init__(parent)
        self.f = f

    def compute(self, t):
        rdd = self.parent.getOrCompute(t)
        return rdd.map(self.f) if rdd is not None else None


class TransformedDStream(DerivedDStream):
    def __init__(self, parent, func):
        super().__init__(parent)
        self.func = func
        import inspect
        try:
            self._two_args = len(inspect.signature(func).parameters) >= 2
        except (TypeError, ValueError):
            self._two_args = False

    def compute(self, t):
        rdd = self.parent.getOrCompute(t)
        if rdd is None:
            return None
        return self.func(rdd, t) if self._two_args else self.func(rdd)


class UnionDStream(DStream):
    def __init__(self, streams):
        super().__init__(streams[0].ssc)
        self.streams = streams

    @property
    def parents(self):
        return list(self.streams)

    @property
    def slide_duration(self):
        return self.streams[0].slide_duration

    def compute(self, t):
        rdds = [s.getOrCompute(t) for s in self.streams]
        rdds = [r for r in rdds if r is not None]
        if not rdds:
            return None
        return self.ssc.ctx.union(rdds)


class CoGroupedDStream(DStream):
    def __init__(self, streams, how, numSplits=None):
        super().__init__(streams[0].ssc)
        self.streams = streams
        self.how = how
        self.numSplits = numSplits

    @property
    def parents(self):
        return list(self.streams)

    @property
    def slide_duration(self):
        return self.streams[0].slide_duration

    def compute(self, t):
        rdds = [s.getOrCompute(t) for s in self.streams]
        if any(r is None for r in rdds):
            empty = self.ssc.ctx.parallelize([], 1)
            rdds = [r if r is not None else empty for r in rdds]
        a, b = rdds
        if self.how == "join":
            return a.join(b, self.numSplits)
        return a.cogroup(b, numSplits=self.numSplits)


class WindowedDStream(DerivedDStream):
    def __init__(self, parent, windowDuration, slideDuration=None):
        super().__init__(parent)
        self._window = float(windowDuration)
        self._slide = float(slideDuration or parent.slide_duration)

    @property
    def slide_duration(self):
        return self._slide

    @property
    def window_duration(self):
        return self._window

    def compute(self, t):
        rdds = []
        step = self.parent.slide_duration
        # window covers (t - window, t]
        k = t
        while k > t - self._window + 1e-9:
            rdd = self.parent.getOrCompute(round(k, 6))
            if rdd is not None:
                rdds.append(rdd)
            k -= step
        if not rdds:
            return None
        return self.ssc.ctx.union(rdds)


class ReducedWindowedDStream(DerivedDStream):
    """Incremental windowed reduce: new_window = inv(prev_window - old
    slice) + new slice (reference: ReducedWindowedDStream)."""

    def __init__(self, parent, func, invFunc, windowDuration,
                 slideDuration=None, numSplits=None):
        super().__init__(parent)
        self.func = func
        self.invFunc = invFunc
        self._window = float(windowDuration)
        self._slide = float(slideDuration or parent.slide_duration)
        self.numSplits = numSplits
        self.must_checkpoint = True
        self._reduced = {}      # time -> per-batch reduced rdd
        # provably (add, sub): the incremental update rewrites to
        # prev + new - old as ONE union-reduce — every branch is a
        # reduced shuffle, so the whole window update rides the device
        # union path instead of leftOuterJoin + per-pair Python inv.
        # The operators alone don't prove the VALUES form a group
        # under them (collections.Counter supports + and - but its -
        # saturates at zero and its negation drops positives), so the
        # rewrite additionally needs the one-time numeric value probe
        # below (_numeric) before it applies.
        self._linear_ops = _is_plain_add(func) and _is_plain_sub(invFunc)
        self._numeric = None            # undecided until data shows up
        # ONE checked-op instance for the stream's lifetime: the tpu
        # backend keys compiled programs by merge-callable identity, so
        # a fresh wrapper per batch would defeat the program cache
        # (and leak one compiled entry per tick — review finding)
        self._checked_op = (_CheckedNumericOp(func, "add")
                            if self._linear_ops else None)

    @property
    def slide_duration(self):
        return self._slide

    @property
    def window_duration(self):
        return self._window

    def _batch_reduced(self, t):
        if t not in self._reduced:
            rdd = self.parent.getOrCompute(t)
            self._reduced[t] = (rdd.reduceByKey(self.func, self.numSplits)
                                if rdd is not None else None)
        return self._reduced[t]

    def compute(self, t):
        prev = self.generated.get(round(t - self._slide, 6))
        step = self.parent.slide_duration
        if prev is None:
            # cold start: plain window reduce
            rdds = []
            k = t
            while k > t - self._window + 1e-9:
                r = self._batch_reduced(round(k, 6))
                if r is not None:
                    rdds.append(r)
                k -= step
            if not rdds:
                return None
            out = rdds[0]
            for r in rdds[1:]:
                out = out.union(r)
            return out.reduceByKey(self.func, self.numSplits).cache()
        # incremental: subtract slices leaving the window, add new ones
        leaving, entering = [], []
        k = t - self._window
        while k > t - self._window - self._slide + 1e-9:
            r = self._batch_reduced(round(k, 6))
            if r is not None:
                leaving.append(r)
            k -= step
        k = t
        while k > t - self._slide + 1e-9:
            r = self._batch_reduced(round(k, 6))
            if r is not None:
                entering.append(r)
            k -= step
        if self._linear_ops and self._numeric is None:
            # one-time value probe (a one-partition job on the cached
            # window): plain numbers form a group under (+, -); other
            # +/- types (Counter saturates) must keep the join path.
            # Probe SEVERAL records, not one (ADVICE r4): a stream whose
            # first reduced value is a number but whose later ones are
            # not would otherwise silently take the union-negate
            # rewrite and diverge from the leftOuterJoin+invFunc path
            import numbers
            probe = _probe_values(prev)
            if probe:
                self._numeric = all(
                    isinstance(rec[1], numbers.Number) for rec in probe)
        if self._linear_ops and self._numeric:
            # prev + new - old, one union-reduce.  Key-set parity with
            # the join formulation: every key in a leaving slice also
            # appears in prev (prev's window contains that slice), so
            # negated orphan keys cannot materialize; keys at the zero
            # element stay present, exactly like leftOuterJoin + sub.
            branches = ([prev] + entering
                        + [r.mapValue(_neg_value) for r in leaving])
            out = branches[0]
            if len(branches) > 1:
                # checked op: a non-numeric tail raises TypeError and
                # run_batch falls back to the join+invFunc path
                out = out.union(*branches[1:]) \
                         .reduceByKey(self._checked_op, self.numSplits)
            return out.cache()
        out = prev
        for r in leaving:
            joined = out.leftOuterJoin(r, self.numSplits)
            out = joined.mapValue(_InvApply(self.invFunc))
        for r in entering:
            out = out.union(r).reduceByKey(self.func, self.numSplits)
        # drop keys whose count reached the zero element is left to the
        # user's invFunc semantics (parity with reference)
        return out.cache()

    def forget_old(self, t, keep=None):
        super().forget_old(t, keep)
        for ts in list(self._reduced):
            if ts < t - (self._window + self._slide * 2):
                rdd = self._reduced.pop(ts)
                if rdd is not None and rdd.should_cache:
                    rdd.unpersist()


class _InvApply:
    def __init__(self, invFunc):
        self.invFunc = invFunc

    def __call__(self, pair):
        cur, old = pair
        return self.invFunc(cur, old) if old is not None else cur


def _code_is_2arg(f, template):
    """f is a closure-free 2-arg function with the template's bytecode
    (the classify_merge idiom — exact identification, never probing)."""
    code = getattr(f, "__code__", None)
    if code is None or getattr(f, "__closure__", None):
        return False
    t = template.__code__
    return (code.co_code == t.co_code
            and code.co_consts == t.co_consts
            and code.co_names == t.co_names
            and code.co_argcount == 2)


def _is_plain_add(f):
    import operator
    return (f is operator.add
            or _code_is_2arg(f, lambda a, b: a + b)
            or _code_is_2arg(f, lambda a, b: b + a))


def _is_plain_sub(f):
    import operator
    return f is operator.sub or _code_is_2arg(f, lambda a, b: a - b)


def _neg_value(v):
    return -v


def _arraylike(x):
    """NUMERIC array-likes only: jax tracers during the merge-fn trace,
    numpy numeric scalars/arrays on ingested columns.  dtype.kind is
    checked so np.str_ (which carries dtype+shape) cannot slip a
    string concatenation past the numeric rewrite."""
    dt = getattr(x, "dtype", None)
    # sentinel default: a dtype WITHOUT .kind must default-deny ("" is
    # a substring of every string — review finding)
    return (dt is not None and hasattr(x, "shape")
            and getattr(dt, "kind", "?") in "biufc")


class _NumericRewriteError(TypeError):
    """Raised by _CheckedNumericOp when a rewritten union-reduce folds
    a non-numeric pair.  A DEDICATED type (with a distinctive name that
    survives traceback stringification across task retries) so
    run_batch never attributes an unrelated user TypeError to the
    rewrite and never disables healthy rewrites for it."""


class _CheckedNumericOp:
    """The binary op a numeric union-reduce rewrite folds with,
    re-verifying PER PAIR what the 5-record probe asserted: both
    operands are plain numbers.  A mixed batch (numeric head,
    non-numeric tail) raises TypeError instead of silently
    concatenating/diverging; StreamingContext.run_batch catches it,
    latches the stream's _numeric off, and regenerates the batch
    through the generic path.

    Carries the __dpark_monoid__ hint so the tpu master still
    classifies the merge: the device path only ever runs over ingested
    NUMERIC columns (non-numeric rows can't ingest and fall back to
    the host object path, where this check executes), so the hint is
    sound."""

    __slots__ = ("op", "__dpark_monoid__")

    _HINTS = {"add": "add", "min": "min", "max": "max", "mul": "mul"}

    def __init__(self, op, hint=None):
        self.op = op
        if hint in self._HINTS:
            self.__dpark_monoid__ = hint

    def __call__(self, a, b):
        # array-likes (jax tracers during the merge-fn trace, numpy
        # scalars/arrays on ingested columns) are numeric by
        # construction — the check targets arbitrary Python objects on
        # the host object path (str concatenation was the r5 finding)
        if (isinstance(a, numbers.Number) or _arraylike(a)) \
                and (isinstance(b, numbers.Number) or _arraylike(b)):
            return self.op(a, b)
        raise _NumericRewriteError(
            "numeric union-reduce rewrite saw a non-numeric pair "
            "(%s, %s): the probe-based rewrite does not apply to "
            "this stream" % (type(a).__name__, type(b).__name__))


def _probe_values(rdd, k=5):
    """Up to k records from the first non-empty partition.  Every scan
    is a parts==1 job — the array path skips single-task jobs by
    design, so the rewrite probes never pollute steady-state
    stage-kind accounting (take(k)'s expanding multi-partition scans
    did, r5 test fallout).  Scans EVERY partition like take(k) would
    (review finding: stopping early would leave _numeric undecided
    forever on streams whose leading partitions are empty); empty
    partitions cost one trivial job each, and a non-empty stream
    resolves the probe once."""
    from itertools import islice

    def head(it):
        return list(islice(it, k))
    for p in range(len(rdd.splits)):
        rows = list(rdd.ctx.runJob(rdd, head, partitions=[p]))[0]
        if rows:
            return rows
    return []


def _classify_state_update(f):
    """EXACT identification of the running-sum updateFunc — the
    streaming counter idiom ``(prev or 0) + sum(vs)`` and its spelling
    variants — as a binary monoid op for the union-reduce rewrite
    (VERDICT r4 #5: monoid state rides the mesh per batch).  Such an
    updateFunc never evicts (returns None) and treats absent prev as
    the identity, so ``prev UNION reduce(batch) -> reduceByKey(op)``
    is observationally identical.  A user function equivalent to a
    monoid fold but written differently opts in via
    ``f.__dpark_state_monoid__ = "add"|"min"|"max"|"mul"`` (contract:
    state' = op(op-reduce(new_values), prev-if-present), no eviction).
    Everything else returns None and keeps the cogroup path."""
    import operator
    hint = getattr(f, "__dpark_state_monoid__", None)
    if hint in ("add", "min", "max", "mul"):
        return {"add": operator.add, "min": min, "max": max,
                "mul": operator.mul}[hint]
    for tmpl in (lambda vs, prev: (prev or 0) + sum(vs),
                 lambda vs, prev: sum(vs) + (prev or 0),
                 lambda vs, prev: (prev if prev is not None else 0)
                 + sum(vs)):
        from dpark_tpu.utils import builtin_globals_ok
        if _code_is_2arg(f, tmpl) and builtin_globals_ok(f):
            return operator.add
    return None


class _TagState:
    """Record-level tag map for the seg-state rewrite: value -> (value
    cast to the state dtype, flag).  `+ zero` is the cast that means
    the same thing on the host (numpy promotion) and under the tracer
    (jax promotion) — flag 1 marks the carried state row.  ONE instance
    per (stream, role) so the tpu program cache stays warm across
    ticks."""

    def __init__(self, zero, flag):
        self.zero = zero
        self.flag = flag

    def __call__(self, v):
        return (v + self.zero, self.flag)


class _SegStateApply:
    """Per-group consumer of the general-updateStateByKey rewrite:
    the group's items are (value, flag) pairs — flag 1 is the carried
    state (at most one per key), flag 0 the batch's new values.  On the
    host paths this callable executes directly over the list; on the
    tpu master fuse.py recognizes `__dpark_seg_state__` and runs the
    user's update as a state-mode SegMapOp (vmapped over padded value
    segments, prev/no-prev dual trace).  Admitted updates return a
    numeric scalar in BOTH traces, so they never evict (return None) —
    the rewrite therefore skips the cogroup path's None filter."""

    def __init__(self, update):
        self.update = update
        self.__dpark_seg_state__ = update

    def __call__(self, items):
        prev = None
        vs = []
        for v, fl in items:
            if fl:
                prev = v
            else:
                vs.append(v)
        return self.update(vs, prev)


class StateDStream(DerivedDStream):
    def __init__(self, parent, updateFunc, numSplits=None):
        super().__init__(parent)
        self.updateFunc = updateFunc
        self.numSplits = numSplits
        self.must_checkpoint = True
        self._monoid_op = _classify_state_update(updateFunc)
        self._numeric = None            # undecided until data shows up
        # general TRACEABLE updateFunc (beyond the provable monoid
        # fold): rewrite to flag-union + groupByKey + _SegStateApply so
        # the tpu master's state-mode SegMapOp keeps the whole per-tick
        # update on device (state as HBM-resident columns, padded value
        # segments, vmapped update(prev, values)).  None = undecided
        # (needs a data probe), False = declined, else (zero_new,
        # zero_old, applyer) — built once, stable identities
        self._seg_state = None
        # one instance for the stream's lifetime — stable identity
        # keeps the tpu backend's compiled-program cache warm across
        # batches (review finding)
        self._checked_op = None
        if self._monoid_op is not None:
            # hint name from the SHARED classifier (utils/monoid) — no
            # fourth copy of the op->name table (review finding)
            from dpark_tpu.utils.monoid import classify_merge
            self._checked_op = _CheckedNumericOp(
                self._monoid_op,
                getattr(updateFunc, "__dpark_state_monoid__", None)
                or classify_merge(self._monoid_op))

    def compute(self, t):
        prev = self.generated.get(round(t - self.slide_duration, 6))
        if prev is None:
            # a failed/dropped batch leaves a hole in `generated`; carry
            # the most recent state forward instead of silently
            # resetting to empty (the hole batch's data is lost either
            # way, the accumulated state must not be)
            earlier = [ts for ts, rdd in self.generated.items()
                       if ts < t - 1e-9 and rdd is not None]
            if earlier:
                prev = self.generated[max(earlier)]
        batch = self.parent.getOrCompute(t)
        ctx = self.ssc.ctx
        if self._monoid_op is not None and self._numeric is None \
                and batch is not None:
            # one-time value probe (same idiom as the window rewrite,
            # ADVICE r4: several records, all must be numbers): the
            # union-reduce rewrite folds values PAIRWISE where the
            # updateFunc summed a list from 0 — identical for numbers,
            # different for e.g. strings (sum() raises, a + b doesn't)
            import numbers
            probe = _probe_values(batch)
            if probe:
                self._numeric = all(
                    isinstance(rec[1], numbers.Number) for rec in probe)
        if self._monoid_op is not None and self._numeric:
            # monoid state: state' = prev U reduce(batch), one flat
            # union-reduce per batch — every stage rides the array path
            # in steady state (HBM-resident prev shuffle + new batch),
            # exactly like the (add, sub) window rewrite above.  The
            # checked op re-verifies numeric-ness PER PAIR: a batch
            # that defeats the probe (numeric head, string tail) raises
            # TypeError and run_batch falls back to the generic path
            if batch is None and prev is not None:
                return prev              # state unchanged this tick
            if batch is not None:
                op = self._checked_op
                reduced = batch.reduceByKey(op, self.numSplits)
                if prev is None:
                    return reduced.cache()
                return prev.union(reduced) \
                    .reduceByKey(op, self.numSplits).cache()
        from dpark_tpu import conf
        if self._monoid_op is None and conf.SEG_STATE \
                and self._seg_state is None and batch is not None:
            self._seg_state = self._classify_seg_state(batch)
        if self._monoid_op is None and self._seg_state:
            tag_new, tag_old, applyer = self._seg_state
            if batch is None and prev is not None:
                b = ctx.parallelize([], 1).mapValue(tag_new)
            elif batch is None:
                return None
            else:
                b = batch.mapValue(tag_new)
            u = b if prev is None else b.union(prev.mapValue(tag_old))
            return u.groupByKey(self.numSplits) \
                    .mapValues(applyer).cache()
        if batch is None:
            batch = ctx.parallelize([], 1)
        if prev is None:
            prev = ctx.parallelize([], 1)
        grouped = batch.cogroup(prev, numSplits=self.numSplits)
        updated = grouped.mapValue(_StateUpdate(self.updateFunc)) \
                         .filter(_state_not_none)
        return updated.mapValue(_unwrap_state).cache()

    def _classify_seg_state(self, batch):
        """(tag_new, tag_old, applyer) when the updateFunc is a
        traceable, padding-invariant update(values, prev) over numeric
        scalar values — the admission the state-mode SegMapOp needs —
        else False (cogroup path).  The state DTYPE is discovered by a
        fixed-point trace (int values whose update decays to float
        carry float state; both tag maps cast to it so host and device
        agree on every column)."""
        import numbers
        f = self.updateFunc
        code = getattr(f, "__code__", None)
        if code is not None and code.co_argcount != 2:
            return False
        probe = _probe_values(batch)
        if not probe:
            return None                  # stay undecided: no data yet
        vals = [rec[1] for rec in probe
                if isinstance(rec, tuple) and len(rec) == 2]
        if len(vals) != len(probe) or not all(
                isinstance(v, numbers.Number)
                and not isinstance(v, bool) for v in vals):
            return False
        try:
            import numpy as np
            import jax
            from dpark_tpu.backend.tpu import fuse
        except Exception:
            return False
        # device value dtype per layout.record_spec conventions
        vdt = np.result_type(*[np.asarray(v).dtype for v in vals])
        vdt = np.dtype(np.int64) if vdt.kind in "iu" else \
            np.dtype(np.float32)
        ds = vdt
        try:
            for _ in range(3):
                fn_p, _fn_n = fuse._seg_state_row_fns(f)
                outs = jax.eval_shape(
                    fn_p, jax.ShapeDtypeStruct((4,), ds),
                    jax.ShapeDtypeStruct((), ds))
                if len(outs) != 1 or outs[0].shape != ():
                    return False
                nxt = np.result_type(ds, outs[0].dtype)
                if nxt == ds:
                    break
                ds = np.dtype(nxt)
            else:
                return False             # state dtype does not settle
        except Exception:
            return False
        pad, reason, _ = fuse.classify_seg_map(f, ds, state=True)
        if pad is None:
            logger.debug("updateStateByKey stays on the cogroup path: "
                         "%s", reason)
            return False
        zero = ds.type(0)
        return (_TagState(zero, 0), _TagState(zero, 1),
                _SegStateApply(f))


class _StateUpdate:
    def __init__(self, updateFunc):
        self.updateFunc = updateFunc

    def __call__(self, groups):
        new_values, old_states = groups
        prev = old_states[0] if old_states else None
        return (self.updateFunc(new_values, prev),)


def _state_not_none(kv):
    return kv[1][0] is not None


def _unwrap_state(wrapped):
    return wrapped[0]


class ForEachDStream(DerivedDStream):
    def __init__(self, parent, func):
        super().__init__(parent)
        self.func = func
        import inspect
        try:
            self._two_args = len(inspect.signature(func).parameters) >= 2
        except (TypeError, ValueError):
            self._two_args = False

    def compute(self, t):
        return self.parent.getOrCompute(t)

    def generate_job(self, t):
        rdd = self.getOrCompute(t)
        if rdd is None:
            return
        if self._two_args:
            self.func(rdd, t)
        else:
            self.func(rdd)


# --------------------------------------------------------------------------
# input streams
# --------------------------------------------------------------------------

class InputDStream(DStream):
    def __init__(self, ssc):
        super().__init__(ssc)
        ssc.input_streams.append(self)

    def start(self):
        pass

    def stop(self):
        pass


class ConstantInputDStream(InputDStream):
    def __init__(self, ssc, rdd):
        super().__init__(ssc)
        self.rdd = rdd

    def compute(self, t):
        return self.rdd


class QueueInputDStream(InputDStream):
    def __init__(self, ssc, queue, oneAtATime=True, defaultRDD=None):
        super().__init__(ssc)
        self.queue = queue
        self.oneAtATime = oneAtATime
        self.defaultRDD = defaultRDD

    def put(self, item):
        self.queue.append(item)

    def _to_rdd(self, item):
        from dpark_tpu.rdd import RDD
        if isinstance(item, RDD):
            return item
        # default parallelism (== the device mesh on the tpu master):
        # a hardcoded slice count forfeited the array path for every
        # queue batch
        return self.ssc.ctx.parallelize(item)

    def compute(self, t):
        if self.queue:
            if self.oneAtATime:
                return self._to_rdd(self.queue.pop(0))
            items = list(self.queue)
            del self.queue[:len(items)]
            rdds = [self._to_rdd(i) for i in items]
            return rdds[0] if len(rdds) == 1 else self.ssc.ctx.union(rdds)
        return self.defaultRDD


class FileInputDStream(InputDStream):
    """Scan a directory each batch; per-file byte offsets are tracked so a
    batch picks up both new files AND data appended to known files
    (tail -f semantics; reference FileInputDStream scans by mtime)."""

    def __init__(self, ssc, directory, filter_fn=None, newFilesOnly=True):
        super().__init__(ssc)
        self.directory = directory
        self.filter_fn = filter_fn or (lambda n: not n.startswith("."))
        self.offsets = {}               # path -> bytes already consumed
        self.new_files_only = newFilesOnly

    def start(self):
        if self.new_files_only:
            for name in os.listdir(self.directory):
                p = os.path.join(self.directory, name)
                if os.path.isfile(p):
                    self.offsets[p] = os.path.getsize(p)

    def compute(self, t):
        rdds = []
        for name in sorted(os.listdir(self.directory)):
            if not self.filter_fn(name):
                continue
            p = os.path.join(self.directory, name)
            if not os.path.isfile(p):
                continue
            size = os.path.getsize(p)
            off = self.offsets.get(p, 0)
            if size > off:
                rdds.append(self.ssc.ctx.partialTextFile(p, off, size))
                self.offsets[p] = size
        if not rdds:
            return None
        return rdds[0] if len(rdds) == 1 else self.ssc.ctx.union(rdds)


class SocketInputDStream(InputDStream):
    """TCP line reader: a background thread accumulates lines; each batch
    drains the buffer (reference: socketTextStream)."""

    def __init__(self, ssc, hostname, port):
        super().__init__(ssc)
        self.hostname = hostname
        self.port = port
        self.buffer = []
        self.lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        self._stop.clear()
        self._thread = threading.Thread(target=self._read, daemon=True)
        self._thread.start()

    def _read(self):
        while not self._stop.is_set():
            try:
                sock = _socket.create_connection(
                    (self.hostname, self.port), timeout=2)
                f = sock.makefile("rb")
                for line in f:
                    if self._stop.is_set():
                        break
                    with self.lock:
                        self.buffer.append(
                            line.rstrip(b"\r\n").decode("utf-8", "replace"))
                sock.close()
            except OSError:
                if self._stop.wait(0.5):
                    return

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(3)
            self._thread = None

    def __getstate__(self):
        d = dict(self.__dict__)
        for k in ("lock", "_stop", "_thread"):
            d[k] = None
        d["buffer"] = []
        d["generated"] = {}
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self.lock = threading.Lock()
        self._stop = threading.Event()

    def compute(self, t):
        with self.lock:
            lines, self.buffer = self.buffer, []
        if not lines:
            return None
        return self.ssc.ctx.parallelize(lines, 2)
