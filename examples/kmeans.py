"""k-means (reference: examples/kmeans.py), written jnp-first: the
assignment map is jnp-traceable, so on the tpu master each iteration's
assign+partial-sum runs as one fused device program over the mesh.

Usage: python examples/kmeans.py [-m local|process|tpu] [-k K]
"""

import random
import sys

from dpark_tpu import DparkContext, optParser


def make_assign(centers):
    import jax
    import jax.numpy as jnp
    cx = jnp.asarray([c[0] for c in centers])
    cy = jnp.asarray([c[1] for c in centers])

    def assign(p):
        x, y = p
        d = (x - cx) ** 2 + (y - cy) ** 2
        k = jnp.argmin(d)
        if not isinstance(k, jax.core.Tracer):
            # host masters bucket by hash(key): a concrete jnp scalar
            # is unhashable — the device trace keeps it traced
            k = int(k)
        return (k, (x, y, 1))
    return assign


def merge(a, b):
    return (a[0] + b[0], a[1] + b[1], a[2] + b[2])


def main():
    optParser.add_argument("-k", "--clusters", type=int, default=4)
    options, _ = optParser.parse_known_args()
    ctx = DparkContext(options.master)
    k = options.clusters

    rng = random.Random(7)
    true_centers = [(0.0, 0.0), (10.0, 0.0), (0.0, 10.0), (10.0, 10.0)]
    points = [(tc[0] + rng.gauss(0, 1.0), tc[1] + rng.gauss(0, 1.0))
              for _ in range(5000) for tc in true_centers[:k]]
    rdd = ctx.parallelize(points).cache()

    centers = points[:k]
    for it in range(8):
        stats = dict(rdd.map(make_assign(centers))
                     .reduceByKey(merge, k).collect())
        centers = [
            (float(sx) / n, float(sy) / n)
            for ki, (sx, sy, n) in sorted(
                (int(kk), vv) for kk, vv in stats.items())]
        print("iter %d: %s" % (it, [(round(x, 2), round(y, 2))
                                    for x, y in centers]))
    ctx.stop()


if __name__ == "__main__":
    main()
