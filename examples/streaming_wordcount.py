"""Windowed streaming wordcount over a queue stream
(reference: Spark-Streaming-style dpark example).

Usage: python examples/streaming_wordcount.py [-m local|process|tpu]
"""

import operator
import time

from dpark_tpu import DparkContext, parse_options
from dpark_tpu.dstream import StreamingContext


def main():
    options = parse_options()
    ctx = DparkContext(options.master)
    ssc = StreamingContext(ctx, 0.25)
    batches = [
        ["the quick brown fox", "the lazy dog"],
        ["the fox jumps", "over the dog"],
        ["brown fox red fox"],
    ]
    q = ssc.queueStream(batches)
    counts = (q.flatMap(lambda line: line.split())
               .map(lambda w: (w, 1))
               .reduceByKeyAndWindow(operator.add, 0.75))
    out = []
    counts.collect_batches(out)
    ssc.start()
    deadline = time.time() + 10
    while len(out) < 3 and time.time() < deadline:
        time.sleep(0.05)
    ssc.stop()
    for t, batch in out[:3]:
        print(sorted(batch, key=lambda kv: (-kv[1], kv[0]))[:4])
    ctx.stop()


if __name__ == "__main__":
    main()
