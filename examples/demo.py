"""API tour (reference: examples/demo.py) — one small example per major
capability, runnable on any master.

Usage: python examples/demo.py [-m local|process|tpu]
"""

import operator
import os
import tempfile

from dpark_tpu import DparkContext, optParser


def main():
    options, _ = optParser.parse_known_args()
    ctx = DparkContext(options.master)

    # transformations + actions
    nums = ctx.parallelize(range(100), 4)
    print("sum:", nums.reduce(operator.add))
    print("evens:", nums.filter(lambda x: x % 2 == 0).count())
    print("squares:", nums.map(lambda x: x * x).take(5))

    # key/value: shuffle, join, sort
    pairs = ctx.parallelize([(i % 5, i) for i in range(50)], 4)
    print("reduceByKey:", sorted(pairs.reduceByKey(operator.add)
                                 .collect()))
    names = ctx.parallelize([(k, "g%d" % k) for k in range(5)], 2)
    print("join sample:", sorted(pairs.join(names).collect())[:3])
    print("sorted keys:", [k for k, _ in
                           pairs.sortByKey(numSplits=3).collect()][:10])

    # accumulators + broadcast
    acc = ctx.accumulator(0)
    lookup = ctx.broadcast({i: i * 10 for i in range(5)})
    out = pairs.map(lambda kv: (acc.add(1), lookup.value[kv[0]])[1]) \
               .collect()
    print("accumulated %d tasks-worth of records; first mapped: %s"
          % (acc.value, out[:3]))

    # caching + checkpoint
    cached = nums.map(lambda x: x + 1).cache()
    cached.count()
    print("cached re-count:", cached.count())

    # text IO round-trip
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "out")
        ctx.parallelize(["line %d" % i for i in range(10)], 2) \
           .saveAsTextFile(path)
        print("text round-trip:", ctx.textFile(path).count())

    # table DSL
    t = ctx.parallelize([("north", 3, 1.5), ("south", 5, 1.4),
                         ("north", 2, 2.0)], 2) \
           .asTable("region qty price", name="sales")
    for row in t.groupBy("region", "sum(qty) as total").collect():
        print("table:", row.region, row.total)

    ctx.stop()


if __name__ == "__main__":
    main()
