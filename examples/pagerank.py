"""PageRank via Pregel supersteps (reference: examples/pagerank.py).

Uses the TPU-native vectorized Pregel contract (bagel.run_pregel): on
`-m tpu` every superstep is fused shard_map programs over the device
mesh; on local/process masters the identical math runs as the
vectorized host loop.  The object-vertex formulation of the same
algorithm lives in examples/pagerank_objects.py.

Usage: python examples/pagerank.py [-m local|process|tpu]
"""

import numpy as np

from dpark_tpu import DparkContext, parse_options
from dpark_tpu.bagel import run_pregel

N = 64
DAMPING = 0.85
STEPS = 20


def compute(value, msg, has_msg, active, agg, superstep):
    # superstep 0 keeps the initial rank (no mail has arrived yet);
    # vectorized contract: arithmetic, not Python branches
    is0 = superstep == 0
    new = is0 * value + (1 - is0) * ((1 - DAMPING) / N + DAMPING * msg)
    return new, superstep < STEPS


def send(src_value, edge_value, src_degree):
    return src_value / src_degree


def main():
    options = parse_options()
    ctx = DparkContext(options.master)
    # a small ring-with-chords graph
    ids = np.arange(N, dtype=np.int64)
    src = np.repeat(ids, 2)
    dst = np.stack([(ids + 1) % N, (ids * 7 + 3) % N], 1).reshape(-1)
    values = np.full(N, 1.0 / N)
    out_ids, ranks, _ = run_pregel(
        ctx, ids, values, (src, dst), compute, send, combine="add")
    top = sorted(zip(ranks, out_ids), reverse=True)
    print("total rank: %.4f" % float(np.sum(ranks)))
    for r, vid in top[:5]:
        print("  %3d: %.5f" % (vid, r))
    ctx.stop()


if __name__ == "__main__":
    main()
