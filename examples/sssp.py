"""Single-source shortest paths via the device-native Pregel
(bagel.run_pregel with the MIN message monoid): distances relax along
weighted edges until no vertex improves.

Usage: python examples/sssp.py [-m local|process|tpu]
"""

import numpy as np

from dpark_tpu import DparkContext, parse_options
from dpark_tpu.bagel import run_pregel


def compute(dist, msg, has_msg, active, agg, superstep):
    import jax.numpy as jnp
    new = jnp.minimum(dist, msg)      # msg identity for "min" is +inf
    return new, new < dist            # active only while improving


def send(dist, weight, deg):
    return dist + weight


def main():
    options = parse_options()
    ctx = DparkContext(options.master)
    rng = np.random.RandomState(42)
    n, ne = 1000, 6000
    ids = np.arange(n, dtype=np.int64)
    src = rng.randint(0, n, ne).astype(np.int64)
    dst = rng.randint(0, n, ne).astype(np.int64)
    w = rng.randint(1, 100, ne).astype(np.float64)
    out_ids, dist, _ = run_pregel(
        ctx, ids, np.full(n, np.inf), (src, dst), compute, send,
        combine="min", edge_values=w,
        initial_messages=(np.array([0]), np.array([0.0])))
    reachable = np.isfinite(dist)
    print("reachable: %d/%d  mean dist: %.1f  max: %.0f"
          % (reachable.sum(), n, dist[reachable].mean(),
             dist[reachable].max()))
    ctx.stop()


if __name__ == "__main__":
    main()
