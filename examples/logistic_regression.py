"""Logistic regression by gradient descent (reference: examples/lr.py),
jnp-first so each iteration's gradient map+sum fuses on the tpu master.

Usage: python examples/logistic_regression.py [-m local|process|tpu]
"""

import random

from dpark_tpu import DparkContext, optParser


def make_grad(w0, w1, b):
    import jax.numpy as jnp

    def grad(row):
        x0, x1, label = row
        z = w0 * x0 + w1 * x1 + b
        p = 1.0 / (1.0 + jnp.exp(-z))
        err = p - label
        # key 0: single global reduce of the gradient triple
        return (0, (err * x0, err * x1, err))
    return grad


def add3(a, b):
    return (a[0] + b[0], a[1] + b[1], a[2] + b[2])


def main():
    options, _ = optParser.parse_known_args()
    ctx = DparkContext(options.master)

    rng = random.Random(3)
    data = []
    for _ in range(20000):
        x0, x1 = rng.gauss(0, 1), rng.gauss(0, 1)
        label = 1.0 if 2 * x0 - x1 + 0.5 + rng.gauss(0, 0.3) > 0 else 0.0
        data.append((x0, x1, label))
    rdd = ctx.parallelize(data).cache()
    n = float(len(data))

    w0 = w1 = b = 0.0
    lr = 2.0
    for it in range(15):
        (_, (g0, g1, gb)), = rdd.map(make_grad(w0, w1, b)) \
                                .reduceByKey(add3, 1).collect()
        w0 -= lr * float(g0) / n
        w1 -= lr * float(g1) / n
        b -= lr * float(gb) / n
    print("weights: w0=%.3f w1=%.3f b=%.3f (true direction 2,-1,0.5)"
          % (w0, w1, b))
    correct = rdd.filter(
        lambda row: (2 * row[0] - row[1] + 0.5 > 0) == (row[2] > 0.5)
    ).count()
    print("consistency with true boundary: %.1f%%" % (100 * correct / n))
    ctx.stop()


if __name__ == "__main__":
    main()
