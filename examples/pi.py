"""Monte-Carlo pi (reference: examples/pi.py)."""

import random
import sys

from dpark_tpu import DparkContext, parse_options


def inside(_):
    x, y = random.random(), random.random()
    return x * x + y * y < 1


def main():
    options = parse_options()
    ctx = DparkContext(options.master)
    n = 100000
    count = ctx.parallelize(range(n), 10).filter(inside).count()
    print("Pi is roughly %f" % (4.0 * count / n))
    ctx.stop()


if __name__ == "__main__":
    main()
