"""PageRank via the reference's OBJECT Bagel contract.  On the tpu
master, numeric object programs like this one are AUTO-COLUMNARIZED
onto the device Pregel (Bagel._run_columnar): compute is vmapped per
degree class and supersteps run as fused mesh programs — see
examples/pagerank.py for the explicitly device-native formulation.

Usage: python examples/pagerank_objects.py [-m local|process|tpu]
"""

import operator

from dpark_tpu import DparkContext, parse_options
from dpark_tpu.bagel import Bagel, BasicCombiner, Edge, Message, Vertex


class PageRank:
    def __init__(self, n, damping=0.85, steps=20):
        self.n = n
        self.damping = damping
        self.steps = steps

    def __call__(self, vert, msg_sum, agg, superstep):
        if superstep == 0:
            value = vert.value
        else:
            # `msg_sum if ... is not None else 0.0` (not `msg_sum or
            # 0.0`): equivalent on the host paths, and the device
            # columnarizer can trace it (no truthiness on array values)
            value = ((1 - self.damping) / self.n
                     + self.damping
                     * (msg_sum if msg_sum is not None else 0.0))
        active = superstep < self.steps
        v = Vertex(vert.id, value, vert.outEdges, active)
        if active and vert.outEdges:
            share = value / len(vert.outEdges)
            return (v, [Message(e.target_id, share) for e in vert.outEdges])
        return (v, [])


def main():
    options = parse_options()
    ctx = DparkContext(options.master)
    # a power-law-ish graph: most vertices have a few edges, a handful
    # have dozens (max degree 48 — far past the r4 adapter's degree-8
    # cap; the class-sliced r5 adapter columnarizes it whole)
    import random
    rng = random.Random(7)
    n = 64
    ladder = [1, 2, 2, 3, 4, 6, 9, 14, 22, 48]
    links = {i: [rng.randrange(n)
                 for _ in range(ladder[min(int(rng.paretovariate(1.2)),
                                           len(ladder)) - 1])]
             for i in range(n)}
    verts = ctx.parallelize(
        [(i, Vertex(i, 1.0 / n, [Edge(t) for t in targets]))
         for i, targets in links.items()], 4)
    msgs = ctx.parallelize([], 4)
    final = Bagel.run(ctx, verts, msgs, PageRank(n),
                      combiner=BasicCombiner(operator.add))
    ranks = sorted(((v.value, vid) for vid, v in final.collect()),
                   reverse=True)
    print("total rank: %.4f" % sum(r for r, _ in ranks))
    for r, vid in ranks[:5]:
        print("  %3d: %.5f" % (vid, r))
    ctx.stop()


if __name__ == "__main__":
    main()
