"""Wordcount — the canonical dpark example (reference: examples/wordcount).

Usage: python examples/wordcount.py <path> [-m local|process|tpu]
"""

import sys

from dpark_tpu import DparkContext, parse_options


def main():
    args = [a for a in sys.argv[1:] if not a.startswith("-")]
    path = args[0] if args else __file__
    options = parse_options()
    ctx = DparkContext(options.master)
    counts = (ctx.textFile(path)
              .flatMap(lambda line: line.split())
              .map(lambda w: (w, 1))
              .reduceByKey(lambda a, b: a + b))
    top = counts.top(10, key=lambda kv: kv[1])
    for word, n in top:
        print("%8d  %s" % (n, word))
    ctx.stop()


if __name__ == "__main__":
    main()
