"""Wordcount — the canonical dpark example (reference: examples/wordcount).

Usage: python examples/wordcount.py <path> [-m local|process|tpu]
"""


from dpark_tpu import DparkContext


def main():
    from dpark_tpu import optParser
    options, rest = optParser.parse_known_args()
    path = rest[0] if rest else __file__
    ctx = DparkContext(options.master)
    counts = (ctx.textFile(path)
              .flatMap(lambda line: line.split())
              .map(lambda w: (w, 1))
              .reduceByKey(lambda a, b: a + b))
    top = counts.top(10, key=lambda kv: kv[1])
    for word, n in top:
        print("%8d  %s" % (n, word))
    ctx.stop()


if __name__ == "__main__":
    main()
