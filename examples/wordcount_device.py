"""Wordcount with string keys on the device (SURVEY.md 7.2 item 3).

Strings cannot ride the TPU shuffle directly, so the host dictionary-
encodes tokens to dense int64 ids with the C++ TokenDict
(dpark_tpu/native), the device reduces ids columnar-ly, and the top
results decode back to words.  Contrast with examples/wordcount.py,
whose string path runs on the host object path.

Usage: python examples/wordcount_device.py <path> [-m tpu]
"""

import sys
import time

import numpy as np

from dpark_tpu import Columns, DparkContext
from dpark_tpu.native import TokenDict


def main():
    from dpark_tpu import optParser
    options, rest = optParser.parse_known_args()
    path = rest[0] if rest else __file__
    ctx = DparkContext(options.master or "tpu")

    t0 = time.perf_counter()
    d = TokenDict()
    with open(path, "rb") as f:
        ids = d.encode(f.read())
    t_encode = time.perf_counter() - t0

    t0 = time.perf_counter()
    ones = np.ones(len(ids), dtype=np.int64)
    counts = (ctx.parallelize(Columns(ids, ones))
              .reduceByKey(lambda a, b: a + b))
    top = counts.top(10, key=lambda kv: kv[1])
    t_count = time.perf_counter() - t0

    for tid, n in top:
        print("%10d  %s" % (n, d.decode(int(tid))))
    print("# %d tokens, %d distinct; encode %.3fs, count %.3fs"
          % (len(ids), len(d), t_encode, t_count), file=sys.stderr)
    ctx.stop()


if __name__ == "__main__":
    main()
