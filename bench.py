"""Benchmark: reduceByKey shuffle throughput, tpu master vs process master.

Prints ONE JSON line:
  {"metric": "reduceByKey_GBps_per_chip", "value": N, "unit": "GB/s/chip",
   "vs_baseline": N}
vs_baseline is the tpu-master speedup over the reference-semantics
`-m process` CPU baseline on the same workload (BASELINE.md: the reference
publishes no numbers; the process master IS the baseline).

The process run executes FIRST, before jax is imported, so its fork pool is
jax-free (fork after jax import can deadlock).
"""

import json
import os
import sys
import time

N_PAIRS = int(os.environ.get("BENCH_PAIRS", 16_000_000))
N_KEYS = int(os.environ.get("BENCH_KEYS", 65_536))
BYTES = N_PAIRS * 8            # two int32 columns


def make_data():
    # scrambled int keys, deterministic; columnar (numpy) input — the
    # ingestion analog of the reference's file sources.  Both masters get
    # the same columns: the process master iterates them as Python rows
    # (its real execution model), the tpu master ingests them into HBM.
    import numpy as np
    from dpark_tpu import Columns
    i = np.arange(N_PAIRS, dtype=np.int64)
    keys = (i * 2654435761) % N_KEYS
    vals = i & 0xFFFF
    return Columns(keys, vals)


def run_once(ctx, data, n_parts, expect_keys=None):
    t0 = time.perf_counter()
    r = (ctx.parallelize(data, n_parts)
         .reduceByKey(lambda a, b: a + b, n_parts))
    n = r.count()
    dt = time.perf_counter() - t0
    if expect_keys is not None:
        assert n == expect_keys, (n, expect_keys)
    return dt


def bench_process(data):
    from dpark_tpu import DparkContext
    nproc = min(8, os.cpu_count() or 4)
    ctx = DparkContext("process:%d" % nproc)
    ctx.start()
    dt = run_once(ctx, data, nproc, min(N_KEYS, N_PAIRS))
    ctx.stop()
    return dt


def bench_tpu(data):
    import jax
    if os.environ.get("BENCH_PLATFORM"):     # e.g. cpu mesh for CI
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    from dpark_tpu import DparkContext
    ctx = DparkContext("tpu")
    ctx.start()
    ndev = ctx.scheduler.executor.ndev
    # warm-up: compile the stage programs at the same size class
    run_once(ctx, data, ndev)
    best = min(run_once(ctx, data, ndev, min(N_KEYS, N_PAIRS))
               for _ in range(3))
    ctx.stop()
    return best, ndev


def main():
    data = make_data()
    t_proc = bench_process(data)
    t_tpu, ndev = bench_tpu(data)
    gbps_chip = BYTES / t_tpu / 1e9 / ndev
    gbps_proc = BYTES / t_proc / 1e9
    out = {
        "metric": "reduceByKey_GBps_per_chip",
        "value": round(gbps_chip, 4),
        "unit": "GB/s/chip",
        "vs_baseline": round(t_proc / t_tpu, 2),
    }
    print(json.dumps(out))
    print("# pairs=%d keys=%d chips=%d tpu=%.3fs process=%.3fs "
          "(process=%.4f GB/s)"
          % (N_PAIRS, N_KEYS, ndev, t_tpu, t_proc, gbps_proc),
          file=sys.stderr)


if __name__ == "__main__":
    main()
