"""Benchmark: tpu master vs process master on the BASELINE.md configs.

Headline JSON line:
  {"metric": "reduceByKey_GBps_per_chip", "value": N, "unit": "GB/s/chip",
   "vs_baseline": N, "pct_of_sort_roofline": N}
vs_baseline is the tpu-master speedup over the reference-semantics
`-m process` CPU baseline on the same workload (BASELINE.md: the reference
publishes no numbers; the process master IS the baseline).
pct_of_sort_roofline is value / the chip's OWN single-operand `jnp.sort`
throughput measured in the same session — distance to "actually fast",
not just distance to the CPU baseline (VERDICT r3 #5).

Additional lines: out-of-core reduceByKey, join/cogroup (BASELINE config
#2), DStream reduceByKeyAndWindow (config #4), file wordcount (config
#0), sortByKey+groupByKey (config #1) — every row of BASELINE.md's
configs table emits a JSON line.

The process runs execute FIRST, before jax is imported, so their fork
pools are jax-free (fork after jax import can deadlock).
"""

import json
import os
import sys
import time

N_PAIRS = int(os.environ.get("BENCH_PAIRS", 16_000_000))
# with a REAL device reachable the default rises to a non-toy size
# (main() sets this after the probe; BENCH_PAIRS always wins)
N_PAIRS_DEVICE_DEFAULT = 64_000_000
N_KEYS = int(os.environ.get("BENCH_KEYS", 65_536))
# two int64 columns (16 bytes/pair) — computed from the real dtypes in
# make_data below, kept in sync by an assert there
BYTES = N_PAIRS * 16


def make_data():
    # scrambled int keys, deterministic; columnar (numpy) input — the
    # ingestion analog of the reference's file sources.  Both masters get
    # the same columns: the process master iterates them as Python rows
    # (its real execution model), the tpu master ingests them into HBM.
    import numpy as np
    from dpark_tpu import Columns
    i = np.arange(N_PAIRS, dtype=np.int64)
    keys = (i * 2654435761) % N_KEYS
    vals = i & 0xFFFF
    assert keys.nbytes + vals.nbytes == BYTES, "BYTES out of sync"
    return Columns(keys, vals)


def run_once(ctx, data, n_parts, expect_keys=None):
    t0 = time.perf_counter()
    r = (ctx.parallelize(data, n_parts)
         .reduceByKey(lambda a, b: a + b, n_parts))
    n = r.count()
    dt = time.perf_counter() - t0
    if expect_keys is not None:
        assert n == expect_keys, (n, expect_keys)
    return dt


def bench_process(data):
    from dpark_tpu import DparkContext
    nproc = min(8, os.cpu_count() or 4)
    ctx = DparkContext("process:%d" % nproc)
    ctx.start()
    dt = run_once(ctx, data, nproc, min(N_KEYS, N_PAIRS))
    ctx.stop()
    return dt


def _pad_stats(ex):
    """Pad efficiency with an honest label: wire padding when an
    exchange actually moved bytes, ingest padding on a single-chip
    identity exchange (advisor r3: never present one as the other)."""
    real = ex.exchange_real_rows
    if ex.exchange_slot_rows:
        return {"pad_efficiency": round(
                    real / max(1, ex.exchange_slot_rows), 4),
                "pad_kind": "wire"}
    return {"pad_efficiency": round(
                real / max(1, ex.ingest_slot_rows), 4),
            "pad_kind": "ingest"}


def _pipeline_stats(ctx):
    """The streamed map stage's overlapped-wave pipeline aggregates
    (scheduler.pipeline_summary), or None off the streamed paths."""
    summary = getattr(ctx.scheduler, "pipeline_summary", None)
    return summary() if summary is not None else None


def _sort_roofline_gbps():
    """The chip's own single-operand `jnp.sort` throughput (GB/s) at the
    benchmark size — the per-session roofline every headline metric is
    reported against.  Returns 0.0 on failure (field then omitted)."""
    try:
        import numpy as np
        import jax
        import jax.numpy as jnp
        n = min(N_PAIRS, 64_000_000)
        x = jax.device_put(np.arange(n, dtype=np.int32)[::-1].copy())
        jnp.sort(x).block_until_ready()          # compile
        t0 = time.perf_counter()
        jnp.sort(x).block_until_ready()
        dt = time.perf_counter() - t0
        return round(x.nbytes / dt / 1e9, 3)
    except Exception:
        return 0.0


def bench_tpu(data):
    import jax
    if os.environ.get("BENCH_PLATFORM"):     # e.g. cpu mesh for CI
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    from dpark_tpu import DparkContext
    ctx = DparkContext("tpu")
    ctx.start()
    ex = ctx.scheduler.executor
    ndev = ex.ndev
    # warm-up: compile the stage programs at the same size class
    run_once(ctx, data, ndev)
    best = min(run_once(ctx, data, ndev, min(N_KEYS, N_PAIRS))
               for _ in range(3))
    stats = dict({"wire_bytes": ex.exchange_wire_bytes,
                  "sort_roofline_gbps": _sort_roofline_gbps()},
                 **_pad_stats(ex))
    ctx.stop()
    return best, ndev, stats


def _tpu_phase():
    """Child-process entry: run the tpu benchmark and print its result
    as one line (isolated so a wedged TPU tunnel cannot hang the whole
    benchmark — the parent times out and still reports)."""
    data = make_data()
    t_tpu, ndev, stats = bench_tpu(data)
    print("TPU_RESULT %s" % json.dumps(
        dict(stats, t=t_tpu, ndev=ndev)), flush=True)


# out-of-core config: sized by env knob, routed through the wave-stream
# path (ingest -> exchange -> merge waves with HBM holding one chunk),
# reporting bounded RSS/HBM next to throughput (VERDICT r2 ask #3: the
# flagship capability must be visible in the driver-captured artifact)
OOC_GB = float(os.environ.get("BENCH_OOC_GB", "0.25"))
OOC_KEYS = 1_000_000


def _ooc_phase():
    """Child-process entry: streamed out-of-core reduceByKey."""
    import resource

    import numpy as np
    import jax
    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    from dpark_tpu import Columns, DparkContext, conf
    n = int(OOC_GB * (1 << 30)) // 16
    i = np.arange(n, dtype=np.int64)
    data = Columns((i * 2654435761) % OOC_KEYS, i & 0xFFFF)
    ctx = DparkContext("tpu")
    ctx.start()
    ndev = ctx.scheduler.executor.ndev
    # exactly >=2 waves per device so the wave-stream machinery carries
    # the run even at sub-HBM benchmark sizes (a real >HBM run hits the
    # same code path with the auto HBM-sized chunk); an explicit number
    # here overrides "auto" — the streamed path MUST run for this metric
    conf.STREAM_CHUNK_ROWS = max(1, n // (ndev * 2))
    t0 = time.perf_counter()
    cnt = (ctx.parallelize(data, ndev)
           .reduceByKey(lambda a, b: a + b, ndev).count())
    dt = time.perf_counter() - t0
    assert cnt == min(OOC_KEYS, n), (cnt, OOC_KEYS)
    ex = ctx.scheduler.executor
    rss_gb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss \
        / (1 << 20)
    payload = {
        "data_gb": round(OOC_GB, 3),
        "seconds": round(dt, 3),
        "gbps_per_chip": round(OOC_GB / dt / ndev, 4),
        "max_rss_gb": round(rss_gb, 3),
        "hbm_store_gb": round(ex._store_bytes / (1 << 30), 4),
        "exchange_wire_gb": round(ex.exchange_wire_bytes / (1 << 30),
                                  4),
        "chips": ndev,
    }
    payload.update(_pad_stats(ex))
    pipe = _pipeline_stats(ctx)
    if pipe is not None:
        payload["pipeline"] = pipe
    # per-phase table + fallback reasons: the bench-smoke schema gate
    # (tools/bench_smoke_check.py) asserts both fields are present
    phases = getattr(ctx.scheduler, "phase_table", lambda: None)()
    if phases is not None:
        payload["phases"] = phases
    payload["fallback_reasons"] = getattr(
        ctx.scheduler, "fallback_reasons", lambda: [])()
    # chaos/recovery accounting (ISSUE 5 satellite): per-site injected
    # fault counters and the degrade/resubmit/retry summary — gated by
    # tools/bench_smoke_check.py so a refactor cannot silently drop
    # the recovery observability
    recovery = getattr(ctx.scheduler, "recovery_summary",
                       lambda: {})() or {}
    payload["faults"] = recovery.pop("faults", {})
    # coded-shuffle decode counters (ISSUE 6): repair/straggler_win/
    # decode_failures + the active mode, schema-gated like faults
    payload["decodes"] = recovery.pop("decodes", {})
    payload["degrades"] = recovery
    # adaptive-execution accounting (ISSUE 7): mode, store hit/steer
    # counters, and the decisions taken (predicted-vs-observed ms) —
    # schema-gated like faults/decodes
    from dpark_tpu import adapt
    payload["adapt"] = adapt.summary()
    # trace plane (ISSUE 8): mode + span counts + the critical-path
    # summary of the longest traced job (which stage/phase chain bound
    # wall time) — so the perf trajectory records WHERE time went, not
    # just how much.  {"mode": "off", "spans": 0, ...} when untraced;
    # schema-gated like faults/decodes/adapt.
    from dpark_tpu import trace
    payload["trace"] = trace.summary()
    # health plane (ISSUE 14): per-site latency-tail summaries + event
    # rates — {"mode": "on", "sites": {}} when nothing was traced
    # (sketches fold off the trace plane); schema-gated like trace
    from dpark_tpu import health
    payload["health"] = health.summary()
    # resource attribution (ISSUE 15): per-tenant account rollup +
    # conservation — {"mode": "off", "tenants": {}} when off;
    # schema-gated like health
    from dpark_tpu import ledger
    payload["ledger"] = ledger.summary()
    ctx.stop()
    print("OOC_RESULT %s" % json.dumps(payload), flush=True)


def _tuple_phase():
    """Child-process entry: composite-key A/B (ISSUE 3 acceptance) —
    the SAME reduceByKey workload keyed by one int column vs by a
    2-int-tuple key, both on the tpu master.  Before tuple keys rode
    the device, the B side silently ran the object path (orders of
    magnitude slower); the ratio is the regression gate."""
    import numpy as np
    import jax
    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    from dpark_tpu import Columns, DparkContext
    n = min(N_PAIRS, int(os.environ.get("BENCH_TUPLE_PAIRS",
                                        N_PAIRS)))
    i = np.arange(n, dtype=np.int64)
    k = (i * 2654435761) % N_KEYS
    data = Columns(k, i & 0xFFFF)
    ctx = DparkContext("tpu")
    ctx.start()
    ndev = ctx.scheduler.executor.ndev

    def scalar_run():
        # the map mirrors the tuple side's key-split op, so the A/B
        # isolates KEY WIDTH (one extra sort/exchange column), not an
        # extra fused map
        t0 = time.perf_counter()
        cnt = (ctx.parallelize(data, ndev)
               .map(lambda kv: (kv[0] // 64 * 64 + kv[0] % 64, kv[1]))
               .reduceByKey(lambda a, b: a + b, ndev).count())
        assert cnt == min(N_KEYS, n), cnt
        return time.perf_counter() - t0

    def tuple_run():
        # same rows, key split into a 2-int tuple (k // 64, k % 64) —
        # same distinct-key count, same combine volume
        t0 = time.perf_counter()
        cnt = (ctx.parallelize(data, ndev)
               .map(lambda kv: ((kv[0] // 64, kv[0] % 64), kv[1]))
               .reduceByKey(lambda a, b: a + b, ndev).count())
        assert cnt == min(N_KEYS, n), cnt
        return time.perf_counter() - t0

    scalar_run(); tuple_run()            # warm-up compiles
    t_scalar = min(scalar_run() for _ in range(2))
    t_tuple = min(tuple_run() for _ in range(2))
    # the tuple job must have ridden the array path, or the ratio is
    # measuring the very fallback this PR removes
    kinds = set()
    for rec in ctx.scheduler.history:
        for st in rec.get("stage_info", ()):
            kinds.add(st.get("kind"))
    ctx.stop()
    print("TUPLE_RESULT %s" % json.dumps(
        {"t_scalar": t_scalar, "t_tuple": t_tuple, "ndev": ndev,
         "pairs": n, "array_path": "array" in kinds}), flush=True)


def _groupmap_fn(vs):
    """The A/B's per-group consumer: a second-moment accumulator —
    traceable + zero-pad-invariant but NOT one of the five provable
    aggregates, so only the ISSUE 4 segmented apply keeps it on
    device.  Natural host code too — the same callable folds Python
    lists on the object path."""
    return sum(3 * v * v + 2 * v for v in vs)


def _groupmap_phase():
    """Child-process entry: device segmented apply A/B (ISSUE 4
    acceptance) — the SAME groupByKey().mapValues(traceable per-group
    fn) job with conf.SEG_MAP on (SegMapOp: all-array, no host bridge)
    vs off (the pre-PR host object path through the export bridge).
    The ratio is the regression gate: >= 5x on the 2-dev CPU mesh."""
    import numpy as np
    import jax
    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    from dpark_tpu import Columns, DparkContext, conf
    n = min(N_PAIRS, int(os.environ.get("BENCH_GROUPMAP_PAIRS",
                                        2_000_000)))
    nkeys = min(N_KEYS, max(16, n // 64))
    i = np.arange(n, dtype=np.int64)
    k = (i * 2654435761) % nkeys
    data = Columns(k, i & 0xFFFF)
    ctx = DparkContext("tpu")
    ctx.start()
    ndev = ctx.scheduler.executor.ndev

    def run():
        t0 = time.perf_counter()
        cnt = (ctx.parallelize(data, ndev).groupByKey(ndev)
               .mapValues(_groupmap_fn).count())
        assert cnt == min(nkeys, n), cnt
        wall = time.perf_counter() - t0
        # the CONSUME stage's own seconds: both sides share the same
        # device no-combine shuffle write, so the whole-job wall would
        # dilute the quantity under test (segmented apply vs the
        # object path's export-bridge + per-group Python fold)
        rec = ctx.scheduler.history[-1]
        consume = sum((st.get("seconds") or 0.0)
                      for st in rec.get("stage_info", ())
                      if not st.get("shuffle"))
        return wall, consume

    conf.SEG_MAP = True
    run()                               # warm-up compiles
    t_dev, c_dev = min(run() for _ in range(2))
    # EVERY stage of the device-side job must be array-kind — a
    # "kind contains array anywhere" check is vacuously true (the
    # shuffle write always rides) and would let a consume-stage
    # fallback measure host-vs-host unnoticed
    rec = ctx.scheduler.history[-1]
    array_path = bool(rec.get("stage_info")) and all(
        str(st.get("kind", "")).startswith("array")
        for st in rec["stage_info"])
    conf.SEG_MAP = False
    try:
        t_host, c_host = min(run() for _ in range(2))
    finally:
        conf.SEG_MAP = True
    ctx.stop()
    print("GROUPMAP_RESULT %s" % json.dumps(
        {"t_device": c_dev, "t_host": c_host,
         "wall_device": t_dev, "wall_host": t_host, "ndev": ndev,
         "pairs": n, "keys": nkeys,
         "device_array_path": array_path}), flush=True)


def _table_phase():
    """Child-process entry: columnar query plane A/B (ISSUE 13
    acceptance) — the SAME select+filter+group-by SQL-shaped query
    over the SAME tabular part files, once through the device query
    plan (column-pruned vectorized scan + device group exchange,
    DPARK_QUERY on) and once through the pre-plan host row path
    (per-row Python eval + host dict aggregation, DPARK_QUERY=0).
    Both sides pay the full query wall including the scan."""
    import tempfile

    import numpy as np
    import jax
    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    from dpark_tpu import DparkContext, conf
    from dpark_tpu.tabular import write_tabular
    n = int(os.environ.get("BENCH_TABLE_ROWS", 2_000_000))
    d = tempfile.mkdtemp(prefix="bench_table_")
    i = np.arange(n, dtype=np.int64)
    cols = [((i * 2654435761) % 1000).tolist(),      # k: group key
            (i % 100).tolist(),                      # a: filter col
            (i % 7).tolist(),                        # b: sum arg
            ((i % 13) * 0.5).tolist(),               # f: avg arg
            ["s%d" % (x % 5) for x in range(n)]]     # s: never read
    write_tabular(os.path.join(d, "part-00000.tab"),
                  ["k", "a", "b", "f", "s"], zip(*cols),
                  chunk_rows=1 << 16)
    del cols
    ctx = DparkContext("tpu")
    ctx.start()
    ndev = ctx.scheduler.executor.ndev

    def run():
        t = ctx.tabular(d).asTable("t")
        q = t.where("a >= 10").groupBy(
            "k", "sum(b) as sb", "count(*) as c", "avg(f) as af")
        t0 = time.perf_counter()
        rows = sorted(q.collect())
        return time.perf_counter() - t0, rows, q

    conf.QUERY_PLAN = True
    run()                               # warm-up compiles
    n0 = len(ctx.scheduler.history)
    best = None
    for _ in range(2):
        dt, rows_dev, q = run()
        if best is None or dt < best[0]:
            best = (dt, rows_dev, q)
    t_dev, rows_dev, q = best
    pq = q._planned()
    recs = ctx.scheduler.history[n0:]
    all_array = bool(recs) and all(
        str(st.get("kind", "")).startswith("array")
        and not st.get("fallback_reason")
        for rec in recs for st in rec.get("stage_info", []))
    scan = {k: (sorted(v) if isinstance(v, set) else v)
            for k, v in (pq.scan_stats if pq else {}).items()}
    conf.QUERY_PLAN = False
    try:
        t_host, rows_host, _ = run()
    finally:
        conf.QUERY_PLAN = True
    ctx.stop()
    print("TABLE_RESULT %s" % json.dumps(
        {"t_device": t_dev, "t_host": t_host, "rows": n,
         "ndev": ndev, "parity": rows_dev == rows_host,
         "device_all_array": all_array, "scan": scan,
         "columns_total": 5}), flush=True)


# BASELINE config #2: join/cogroup of two keyed RDDs (TPC-H
# lineitem⋈orders subset shape: big fact table, smaller key table,
# every fact key hits).  Sizes are row counts; device default rises.
JOIN_FACT = int(os.environ.get("BENCH_JOIN_FACT", 2_000_000))
JOIN_DIM = int(os.environ.get("BENCH_JOIN_DIM", 500_000))
JOIN_FACT_DEVICE_DEFAULT = 16_000_000


def make_join_data():
    import numpy as np
    from dpark_tpu import Columns
    i = np.arange(JOIN_FACT, dtype=np.int64)
    fact = Columns((i * 2654435761) % JOIN_DIM, i & 0xFFFF)   # lineitem
    j = np.arange(JOIN_DIM, dtype=np.int64)
    dim = Columns(j, (j * 31) & 0xFF)                          # orders
    return fact, dim


def run_join_once(ctx, fact, dim, n_parts):
    t0 = time.perf_counter()
    a = ctx.parallelize(fact, n_parts)
    b = ctx.parallelize(dim, n_parts)
    n = a.join(b, n_parts).count()
    dt = time.perf_counter() - t0
    assert n == JOIN_FACT, (n, JOIN_FACT)
    return dt


def bench_join_process():
    from dpark_tpu import DparkContext
    fact, dim = make_join_data()
    nproc = min(8, os.cpu_count() or 4)
    ctx = DparkContext("process:%d" % nproc)
    ctx.start()
    dt = run_join_once(ctx, fact, dim, nproc)
    ctx.stop()
    return dt


def _join_phase():
    """Child-process entry: tpu join/cogroup (BASELINE config #2)."""
    import jax
    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    from dpark_tpu import DparkContext
    fact, dim = make_join_data()
    ctx = DparkContext("tpu")
    ctx.start()
    ndev = ctx.scheduler.executor.ndev
    run_join_once(ctx, fact, dim, ndev)           # warm-up compile
    best = min(run_join_once(ctx, fact, dim, ndev) for _ in range(2))
    ctx.stop()
    print("JOIN_RESULT %s" % json.dumps({"t": best, "ndev": ndev}),
          flush=True)


# BASELINE config #0: wordcount over a REAL text file
# (textFile -> flatMap -> map -> reduceByKey), deterministic corpus.
WC_MB = float(os.environ.get("BENCH_WC_MB", 64))
WC_MB_DEVICE_DEFAULT = 512.0
WC_WORDS = ["alpha", "beta", "gamma", "delta", "epsilon", "zeta",
            "eta", "theta", "iota", "kappa", "lam", "mu", "nu", "xi"]


def _wc_corpus():
    import hashlib
    tag = hashlib.md5(("wc-%s" % WC_MB).encode()).hexdigest()[:8]
    path = "/tmp/dpark_bench_wc_%s.txt" % tag
    if not os.path.exists(path):
        import random as _random
        rng = _random.Random(11)
        target = int(WC_MB * (1 << 20))
        with open(path + ".tmp", "w") as f:
            written = 0
            while written < target:
                line = " ".join(rng.choices(WC_WORDS, k=10)) + "\n"
                f.write(line)
                written += len(line)
        os.replace(path + ".tmp", path)
    return path


def _wc_run(ctx, path):
    t0 = time.perf_counter()
    n = (ctx.textFile(path)
         .flatMap(lambda line: line.split())
         .map(lambda w: (w, 1))
         .reduceByKey(lambda a, b: a + b, 8).count())
    dt = time.perf_counter() - t0
    assert n == len(WC_WORDS), (n, len(WC_WORDS))
    return dt


def bench_wc_process(path):
    from dpark_tpu import DparkContext
    nproc = min(8, os.cpu_count() or 4)
    ctx = DparkContext("process:%d" % nproc)
    ctx.start()
    dt = _wc_run(ctx, path)
    ctx.stop()
    return dt


def _wc_phase():
    """Child-process entry: tpu wordcount (BASELINE config #0)."""
    import jax
    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    from dpark_tpu import DparkContext
    path = _wc_corpus()
    ctx = DparkContext("tpu")
    ctx.start()
    _wc_run(ctx, path)                            # warm-up compile
    dt = _wc_run(ctx, path)
    ctx.stop()
    print("WC_RESULT %s" % json.dumps({"t": dt}), flush=True)


# BASELINE config #1: sortByKey + groupByKey over synthetic (int, int)
# pairs — the no-combine exchange paths (range + hash).
SG_PAIRS = int(os.environ.get("BENCH_SG_PAIRS", 2_000_000))
SG_PAIRS_DEVICE_DEFAULT = 10_000_000
SG_KEYS = 100_000


def make_sg_data():
    import numpy as np
    from dpark_tpu import Columns
    i = np.arange(SG_PAIRS, dtype=np.int64)
    return Columns((i * 2654435761) % SG_KEYS, i & 0xFFFF)


def _sg_run(ctx, data, n_parts):
    t0 = time.perf_counter()
    r = ctx.parallelize(data, n_parts)
    ns = r.sortByKey(numSplits=n_parts).count()
    ng = r.groupByKey(n_parts).count()
    dt = time.perf_counter() - t0
    assert ns == SG_PAIRS and ng == min(SG_KEYS, SG_PAIRS), (ns, ng)
    return dt


def bench_sg_process():
    from dpark_tpu import DparkContext
    data = make_sg_data()
    nproc = min(8, os.cpu_count() or 4)
    ctx = DparkContext("process:%d" % nproc)
    ctx.start()
    dt = _sg_run(ctx, data, nproc)
    ctx.stop()
    return dt


def _sg_phase():
    """Child-process entry: tpu sortByKey+groupByKey (config #1)."""
    import jax
    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    from dpark_tpu import DparkContext
    data = make_sg_data()
    ctx = DparkContext("tpu")
    ctx.start()
    ndev = ctx.scheduler.executor.ndev
    _sg_run(ctx, data, ndev)                      # warm-up compile
    dt = _sg_run(ctx, data, ndev)
    out = {"t": dt, "ndev": ndev}
    pipe = _pipeline_stats(ctx)
    if pipe is not None:        # only when the input streamed in waves
        out["pipeline"] = pipe
    ctx.stop()
    print("SG_RESULT %s" % json.dumps(out), flush=True)


# BASELINE config #4: DStream reduceByKeyAndWindow micro-batches.
# records per batch x batches, 2-batch window with inverse-reduce.
STREAM_RECS = int(os.environ.get("BENCH_STREAM_RECS", 200_000))
STREAM_BATCHES = int(os.environ.get("BENCH_STREAM_BATCHES", 8))
STREAM_KEYS = 4_096


def _stream_run(ctx):
    """Drive reduceByKeyAndWindow over a deterministic queueStream with
    the manual clock (the timer would measure sleep, not work); returns
    wall seconds over all batches."""
    import operator

    import numpy as np
    from dpark_tpu.dstream import StreamingContext
    rng = np.random.RandomState(7)
    batches = []
    for _ in range(STREAM_BATCHES):
        ks = rng.randint(0, STREAM_KEYS, STREAM_RECS)
        vs = rng.randint(0, 100, STREAM_RECS)
        batches.append(list(zip(ks.tolist(), vs.tolist())))
    ssc = StreamingContext(ctx, 1.0)
    out = []
    q = ssc.queueStream(batches)
    q.reduceByKeyAndWindow(operator.add, 2.0,
                           invFunc=operator.sub).collect_batches(out)
    ctx.start()
    for ins in ssc.input_streams:
        ins.start()
    ssc.zero_time = 1000.0
    t0 = time.perf_counter()
    for k in range(1, STREAM_BATCHES + 1):
        ssc.run_batch(1000.0 + k * ssc.batch_duration)
    dt = time.perf_counter() - t0
    assert len(out) == STREAM_BATCHES and len(out[-1][1]) == STREAM_KEYS
    return dt


def bench_stream_process():
    from dpark_tpu import DparkContext
    nproc = min(8, os.cpu_count() or 4)
    ctx = DparkContext("process:%d" % nproc)
    dt = _stream_run(ctx)
    ctx.stop()
    return dt


def _stream_phase():
    """Child-process entry: tpu DStream window (BASELINE config #4)."""
    import jax
    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    from dpark_tpu import DparkContext, panes
    ctx = DparkContext("tpu")
    _stream_run(ctx)                              # warm-up compile
    dt = _stream_run(ctx)
    # pane-plane accounting (ISSUE 10): the window above rides the
    # pane path — report the last driven stream's live stats so the
    # bench artifact records pane mode/counts next to the throughput
    stats = panes.stream_stats()
    pane_info = list(stats.values())[-1] if stats else {}
    ctx.stop()
    print("STREAM_RESULT %s" % json.dumps(
        {"t": dt, "panes": pane_info}), flush=True)


def _coded_phase():
    """Child-process entry: coded-shuffle overhead A/B (ISSUE 6
    acceptance) — the SAME shuffle-heavy host-path reduceByKey job
    with the code off vs rs(4,2), NO faults injected.  The coded side
    pays encode at map time plus the k-of-n framed shard reads at
    reduce time; the acceptance bound is <= 15% wall overhead.  Runs
    on the local master: the host bucket exchange is the path the
    parity shards ride (the in-device all_to_all never touches
    them)."""
    from dpark_tpu import DparkContext, coding
    n = int(os.environ.get("BENCH_CODED_PAIRS", "400000"))
    parts = 8
    ctx = DparkContext("local")

    def run():
        t0 = time.perf_counter()
        cnt = (ctx.parallelize(range(n), parts)
               .map(lambda i: (i % 10007, i))
               .reduceByKey(lambda a, b: a + b, parts).count())
        assert cnt == min(10007, n), cnt
        return time.perf_counter() - t0

    coding.configure(None)
    run()                               # warm imports / page cache
    t_off = min(run() for _ in range(2))
    coding.configure("rs(4,2)")
    coding.reset_counters()
    try:
        t_on = min(run() for _ in range(2))
        stats = coding.stats()
    finally:
        coding.configure(None)
    ctx.stop()
    print("CODED_RESULT %s" % json.dumps(
        {"t_off": t_off, "t_on": t_on, "decodes": stats, "pairs": n}),
        flush=True)


_BULK_PEER_SCRIPT = r'''
import os, sys, time
import numpy as np
n, wd = int(sys.argv[1]), sys.argv[2]
from dpark_tpu import shuffle as sm
from dpark_tpu.dcn import BucketServer
i = np.arange(n, dtype=np.int64)
keys = (i * 2654435761) % 100003
vals = i & 0xFFFF
# rows are materialized ONCE (conservative: the real bridge rebuilds
# them from device slices per fetch) — the bridge still pays
# pickle+compress per request, which is its real per-byte cost
rows = list(zip(keys.tolist(), vals.tolist()))
sm.HBM_EXPORTERS["bench"] = lambda sid, m, r, shard=None: rows
sm.HBM_COL_EXPORTERS["bench"] = \
    lambda sid, m, r: ({"no_combine": False}, [keys, vals])
srv = BucketServer(wd, host="127.0.0.1").start()
print("ADDR %s" % srv.addr, flush=True)
time.sleep(600)
'''


def _bulk_phase():
    """Child-process entry: bulk-channel vs pickled-bridge A/B
    (ISSUE 12 acceptance).  A PEER PROCESS serves the same
    HBM-shaped bucket both ways over same-box loopback: the bridge
    path (single-frame ``("bucket", ...)`` — server pickles rows,
    client unpickles then re-columnarizes) vs the bulk path (chunked
    ``bulk_bucket`` stream of RAW COLUMN BYTES assembled zero-copy).
    Both sides end at numpy columns on the receiving controller;
    bytes/s is logical column bytes over the median fetch, p99 over
    the rep distribution.  Acceptance: bulk >= 2x the bridge's
    bytes/s."""
    import pickle
    import statistics
    import subprocess
    import tempfile

    import numpy as np
    from dpark_tpu import bulkplane, dcn
    from dpark_tpu.utils import decompress
    n = int(os.environ.get("BENCH_BULK_ROWS", "2000000"))
    reps = max(3, int(os.environ.get("BENCH_BULK_REPS", "9")))
    tmp = tempfile.mkdtemp(prefix="dpark-bulk-ab-")
    script = os.path.join(tmp, "peer.py")
    with open(script, "w") as f:
        f.write(_BULK_PEER_SCRIPT)
    here = os.path.dirname(os.path.abspath(__file__))
    child_env = dict(os.environ)
    child_env["PYTHONPATH"] = here + os.pathsep + \
        child_env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, script, str(n), tmp],
        stdout=subprocess.PIPE, text=True, env=child_env)
    try:
        addr = proc.stdout.readline().split()[1]
        logical = n * 16                      # two int64 columns

        def bridge_fetch():
            payload = dcn.fetch(addr, ("bucket", 0, 0, 0))
            items = pickle.loads(decompress(payload))
            ks = np.fromiter((kv[0] for kv in items), dtype=np.int64,
                             count=len(items))
            vs = np.fromiter((kv[1] for kv in items), dtype=np.int64,
                             count=len(items))
            return ks, vs, items

        def bulk_fetch():
            meta, view = bulkplane.fetch(addr,
                                         ("bulk_bucket", 0, 0, 0))
            return bulkplane.cols_from_buf(meta, view)

        # warm both paths (connects, page cache, the peer's pickle of
        # rows is per-request by design), then verify BIT-PARITY
        bks, bvs, items = bridge_fetch()
        cols = bulk_fetch()
        parity = (list(zip(cols[0].tolist(), cols[1].tolist()))
                  == items
                  and bks.tolist() == cols[0].tolist()
                  and bvs.tolist() == cols[1].tolist())
        t_bridge, t_bulk = [], []
        for _ in range(reps):
            t0 = time.perf_counter()
            bridge_fetch()
            t_bridge.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            bulk_fetch()
            t_bulk.append(time.perf_counter() - t0)

        def p99(ts):
            s = sorted(ts)
            return s[min(len(s) - 1, int(0.99 * len(s)))]

        bridge_bps = logical / statistics.median(t_bridge)
        bulk_bps = logical / statistics.median(t_bulk)
        out = {"rows": n, "reps": reps,
               "logical_mb": round(logical / 1e6, 1),
               "bridge_MBps": round(bridge_bps / 1e6, 1),
               "bulk_MBps": round(bulk_bps / 1e6, 1),
               "ratio": round(bulk_bps / max(bridge_bps, 1e-9), 2),
               "p50_bridge_ms": round(
                   statistics.median(t_bridge) * 1e3, 1),
               "p50_bulk_ms": round(
                   statistics.median(t_bulk) * 1e3, 1),
               "p99_bridge_ms": round(p99(t_bridge) * 1e3, 1),
               "p99_bulk_ms": round(p99(t_bulk) * 1e3, 1),
               "parity": bool(parity),
               "bulk_streams": bulkplane.stats()["streams"]}
        print("BULKPLANE_RESULT %s" % json.dumps(out), flush=True)
    finally:
        proc.terminate()
        proc.wait(timeout=30)
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)


def _adapt_phase():
    """Child-process entry: adaptive-execution warm-vs-cold A/B
    (ISSUE 7 acceptance) — the streamed sortgroup config run twice
    with DPARK_ADAPT=on against a deterministic emulated HBM ceiling
    (conf.EMULATED_WAVE_OOM_ROWS).  The COLD run's auto wave budget
    exceeds the ceiling, so it walks the real OOM degradation ladder
    (fail, halve, retry) and persists the outcome; the WARM run seeds
    its budget from the store and streams first try.  The JSON reports
    wall seconds, OOM-ladder retries, and store hits per run — warm
    must show fewer ladder retries (and typically less wall).  A
    pre-warmed DPARK_ADAPT_DIR (the CI two-pass smoke) makes even the
    "cold" run seed from the store: cold ladder_retries == 0 with
    store_hits >= 1 is the cross-process persistence proof."""
    import tempfile

    import numpy as np
    import jax
    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    from dpark_tpu import Columns, DparkContext, adapt, conf
    store = os.environ.get("DPARK_ADAPT_DIR") \
        or tempfile.mkdtemp(prefix="dpark-adapt-ab-")
    adapt.configure(mode="on", store_dir=store)
    # the A/B grades the ladder+store loop, not real HBM sizing: pin
    # the auto derivation to a known base (no device memory limit) so
    # base > ceiling > base/2 holds on every backend, and the ladder's
    # single halving lands under the ceiling deterministically
    base = int(os.environ.get("BENCH_ADAPT_BASE_ROWS", 1 << 18))
    conf._hbm_bytes_limit = lambda: 0
    conf._STREAM_CHUNK_ROWS_FALLBACK = base
    conf.EMULATED_WAVE_OOM_ROWS = int(os.environ.get(
        "BENCH_ADAPT_CEIL_ROWS", base * 3 // 4))
    conf.STREAM_CHUNK_ROWS = "auto"
    ctx = DparkContext("tpu")
    ctx.start()
    ndev = ctx.scheduler.executor.ndev
    # each device's slice must exceed the base wave budget or the
    # in-core path runs and nothing streams (no ladder to grade)
    n = int(os.environ.get("BENCH_ADAPT_PAIRS",
                           str(base * 3 // 2 * ndev)))
    i = np.arange(n, dtype=np.int64)
    data = Columns((i * 2654435761) % 100_000, i & 0xFFFF)

    def run():
        hits0 = adapt.summary()["store_hits"]
        # count ladder walks from the per-stage job records, NOT from
        # degrade_reasons() — that helper de-duplicates identical
        # reason strings across the whole history, so a warm run
        # re-walking the ladder with the same budgets would be
        # invisible and the A/B could false-pass
        jobs0 = len(ctx.scheduler.history)
        t0 = time.perf_counter()
        r = ctx.parallelize(data, ndev)
        ns = r.sortByKey(numSplits=ndev).count()
        ng = r.groupByKey(ndev).count()
        wall = time.perf_counter() - t0
        assert ns == n and ng == min(100_000, n), (ns, ng)
        s = adapt.summary()
        ladder = sum(
            1 for rec in ctx.scheduler.history[jobs0:]
            for st in rec.get("stage_info", ())
            if "wave budget" in (st.get("degrade_reason") or ""))
        return {"wall_s": round(wall, 3),
                "ladder_retries": ladder,
                "store_hits": s["store_hits"] - hits0}

    cold = run()
    warm = run()
    out = {"cold": cold, "warm": warm, "pairs": n, "ndev": ndev,
           "adapt": adapt.summary()}
    ctx.stop()
    print("ADAPT_RESULT %s" % json.dumps(out), flush=True)


def _code_adapt_phase():
    """Child-process entry: straggler-adaptive coding + skew re-plan
    A/B (ISSUE 19 acceptance).

    adaptive_code: two shuffle exchanges on one local master — a HOT
    site whose learn-pass fetches consume parity under seeded shard
    failures, and a COLD site with tight recorded tails.  The static
    leg codes BOTH exchanges rs(4,2); the adaptive leg
    (DPARK_CODE_ADAPT over the same global code) re-prices per
    exchange — hot stays escalated (it demonstrably decoded), cold
    PINS UNCODED and sheds its parity bytes.  Both legs time the same
    graded pass under the same injected per-peer fetch delay, so the
    acceptance reads directly off the JSON: adaptive wall <= 1.1x
    static at LOWER total parity bytes.

    skew_replan: a dominant-bucket reduceByKey on the multiprocess
    master — with DPARK_REPLAN off, one reduce task drags ~the whole
    exchange; on, the mid-job salted re-split spreads it across the
    worker pool with zero map recomputes, and the SECOND run
    pre-salts at plan time (same stage count as the off leg, only
    the salt differs — the steady-state improvement)."""
    import operator
    import tempfile

    from dpark_tpu import DparkContext, adapt, coding, conf, faults
    from dpark_tpu.health import Sketch
    from dpark_tpu.utils.phash import portable_hash

    n = int(os.environ.get("BENCH_CODE_ADAPT_PAIRS", "200000"))
    reps = max(2, int(os.environ.get("BENCH_CODE_ADAPT_REPS", "3")))
    delay_spec = os.environ.get(
        "BENCH_CODE_ADAPT_DELAY",
        "shuffle.fetch:p=0.4,seed=9,kind=delay,ms=15")
    fail_spec = "shuffle.fetch:p=0.2,seed=7"

    def hot(c):
        return (c.parallelize(range(n), 4)
                .map(lambda i: (i % 5003, i))
                .reduceByKey(operator.add, 4).count())

    def cold(c):
        return (c.parallelize(range(n), 4)
                .map(lambda i: (i % 5003, i))
                .reduceByKey(operator.add, 4).count())

    def graded_pass(ctx):
        """Time cold FIRST (its code choice must not see the delayed
        fetches), then hot under the injected per-peer delay; parity
        is the delta over exactly this window."""
        p0 = coding.parity_bytes()
        t_cold = 1e9
        for _ in range(reps):
            t0 = time.perf_counter()
            assert cold(ctx) == min(5003, n)
            t_cold = min(t_cold, time.perf_counter() - t0)
        faults.configure(delay_spec)
        try:
            t_hot = 1e9
            for _ in range(reps):
                t0 = time.perf_counter()
                assert hot(ctx) == min(5003, n)
                t_hot = min(t_hot, time.perf_counter() - t0)
        finally:
            faults.configure(None)
        return t_hot, t_cold, coding.parity_bytes() - p0

    # --- static leg: one global rs(4,2) codes every exchange --------
    adapt.configure(mode="off")
    conf.CODE_ADAPT = False
    coding.configure("rs(4,2)")
    coding.clear_shuffle_codes()
    ctx = DparkContext("local")
    ctx.start()
    hot(ctx)
    cold(ctx)                           # warm imports / page cache
    t_hot_s, t_cold_s, parity_static = graded_pass(ctx)
    ctx.stop()

    # --- adaptive leg: same global, per-exchange re-pricing ---------
    adapt.configure(mode="on", store_dir=tempfile.mkdtemp(
        prefix="dpark-code-adapt-"))
    conf.CODE_ADAPT = True
    coding.configure("rs(4,2)")
    coding.clear_shuffle_codes()
    ctx = DparkContext("local")
    ctx.start()
    faults.configure(fail_spec)         # learn pass: hot decodes
    try:
        hot(ctx)
    finally:
        faults.configure(None)
    cold(ctx)                           # learn pass: cold stays clean
    # the serving peer's fetch-tail record (PR 14's input): tight —
    # only OBSERVED decode consumption may escalate an exchange
    sk = Sketch()
    for _ in range(35):
        sk.add(0.005)
    adapt.record_site_tail("fetch.bucket:local", sk.to_dict())
    t_hot_a, t_cold_a, parity_adapt = graded_pass(ctx)
    hist = coding.code_history()
    hot_escalated = any(c["applied"] and c["code"] != "off"
                        for c in hist)
    cold_pinned = any(c["applied"] and c["code"] == "off"
                      for c in hist)
    ctx.stop()
    coding.configure(None)
    coding.clear_shuffle_codes()
    conf.CODE_ADAPT = False

    # --- skew re-plan A/B on the multiprocess master ----------------
    # every key collides into ONE hash bucket; incompressible ~50-byte
    # values make the dominant bucket's fetch+merge the reduce-side
    # cost the re-split spreads across the worker pool
    nk = int(os.environ.get("BENCH_REPLAN_KEYS", "300000"))
    width = 4
    skew_keys = [k for k in range(nk * 5)
                 if portable_hash(k) % width == 0][:nk]
    skew_data = [(k, ("%d" % (k * 2654435761)) * 5)
                 for k in skew_keys] * 2
    expect = len(skew_keys)

    def skew(c):
        return (c.parallelize(skew_data, 4)
                .reduceByKey(operator.add, width).count())

    def reduce_wall(rec):
        # the RESULT stage's wall — the reduce side the re-plan grades
        return [st.get("seconds") for st in rec.get("stage_info", ())
                if not st.get("shuffle")][-1]

    adapt.configure(mode="on", store_dir=tempfile.mkdtemp(
        prefix="dpark-replan-"))
    old_replan = (conf.REPLAN, conf.REPLAN_MIN_BYTES)
    conf.REPLAN = False
    ctxp = DparkContext("process:2")
    ctxp.start()
    try:
        assert skew(ctxp) == expect     # warm the forkserver pool
        t_off = red_off = 1e9
        for _ in range(reps):
            t0 = time.perf_counter()
            assert skew(ctxp) == expect
            t_off = min(t_off, time.perf_counter() - t0)
            red_off = min(red_off,
                          reduce_wall(ctxp.scheduler.history[-1]))
        conf.REPLAN = True
        conf.REPLAN_MIN_BYTES = 64
        t0 = time.perf_counter()
        assert skew(ctxp) == expect     # re-plans mid-job
        t_replan = time.perf_counter() - t0
        rec = ctxp.scheduler.history[-1]
        t_presalt = red_presalt = 1e9   # steady state: salted at plan
        for _ in range(reps):
            t0 = time.perf_counter()
            assert skew(ctxp) == expect
            t_presalt = min(t_presalt, time.perf_counter() - t0)
            red_presalt = min(red_presalt,
                              reduce_wall(ctxp.scheduler.history[-1]))
        rec2 = ctxp.scheduler.history[-1]
        replan = {
            "t_off_s": round(t_off, 3),
            "t_replan_s": round(t_replan, 3),
            "t_presalt_s": round(t_presalt, 3),
            "reduce_off_s": round(red_off, 3),
            "reduce_presalt_s": round(red_presalt, 3),
            "replans": int(rec.get("replans") or 0),
            "resubmits": int(rec.get("resubmits") or 0),
            "recomputes": int(rec.get("recomputes") or 0),
            "replan_reason": next(
                (st.get("replan_reason")
                 for st in rec.get("stage_info", ())
                 if st.get("replan_reason")), None),
            "presalt_replans": int(rec2.get("replans") or 0),
            "keys": nk, "width": width}
    finally:
        ctxp.stop()
        (conf.REPLAN, conf.REPLAN_MIN_BYTES) = old_replan
        adapt.configure(mode="observe")

    print("CODE_ADAPT_RESULT %s" % json.dumps(
        {"static": {"t_hot_s": round(t_hot_s, 3),
                    "t_cold_s": round(t_cold_s, 3),
                    "parity_bytes": parity_static},
         "adaptive": {"t_hot_s": round(t_hot_a, 3),
                      "t_cold_s": round(t_cold_a, 3),
                      "parity_bytes": parity_adapt},
         "hot_escalated": hot_escalated,
         "cold_pinned_uncoded": cold_pinned,
         "pairs": n, "reps": reps,
         "replan": replan}), flush=True)


def _svc_add(a, b):
    # module-level on purpose: the warm-submit A/B re-builds the DAG,
    # and a stable function identity is what lets the program cache
    # prove "0 re-compiles" on the second submission
    return a + b


def _svc_distinct(vs):
    # set() forces the host object path — the concurrent A/B wants one
    # device-bound job and one host-bound job so the service's slot
    # threads can genuinely overlap them
    return len(set(vs))


def _service_phase():
    """Child-process entry: resident-service A/B (ISSUE 9 acceptance).

    warm-submit: the same DAG submitted twice to one resident server —
    the second submission must hit the compiled-program cache for
    every stage (0 compiles, asserted from the cache counters) and
    show a far lower submit-to-first-wave latency.

    concurrent: one device-bound job and one host-bound job, solo then
    concurrently — the combined wall vs the slower solo wall measures
    how much of the mesh the fair dispatcher keeps busy."""
    import threading

    import numpy as np
    import jax
    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    from dpark_tpu import Columns, DparkContext
    from dpark_tpu import conf as _conf
    n = int(os.environ.get("BENCH_SERVICE_PAIRS",
                           os.environ.get("BENCH_PAIRS", "500000")))
    # per-tenant SLO accounting (ISSUE 14): declare a generous default
    # target so the A/B records attainment for the service cell (the
    # smoke gate asserts the tenants section is present and graded)
    _conf.SERVICE_SLO_MS = float(os.environ.get(
        "BENCH_SERVICE_SLO_MS", "60000"))
    ctx = DparkContext("service:tpu")
    ctx.start()
    sched = ctx.scheduler
    ndev = sched.executor.ndev
    i = np.arange(n, dtype=np.int64)
    data = Columns((i * 2654435761) % 4096, np.ones(n, np.int64))

    def submit():
        t0 = time.perf_counter()
        out = dict(ctx.parallelize(data, ndev)
                   .reduceByKey(_svc_add, ndev).collect())
        return time.perf_counter() - t0, out

    ex = sched.executor
    pc0 = ex.program_cache_stats()
    t_cold, out_cold = submit()
    rec_cold = dict(sched.history[-1])
    pc1 = ex.program_cache_stats()
    t_warm, out_warm = submit()
    rec_warm = dict(sched.history[-1])
    pc2 = ex.program_cache_stats()
    assert out_cold == out_warm, "warm submission changed the answer"
    cold = {"wall_s": round(t_cold, 3),
            "first_wave_ms": rec_cold.get("first_wave_ms"),
            "compiles": pc1["misses"] - pc0["misses"],
            "cache_hits": pc1["hits"] - pc0["hits"]}
    warm = {"wall_s": round(t_warm, 3),
            "first_wave_ms": rec_warm.get("first_wave_ms"),
            "compiles": pc2["misses"] - pc1["misses"],
            "cache_hits": pc2["hits"] - pc1["hits"]}

    datb = [(int(k), int(v))
            for k, v in zip(i[:n // 4] % 257, i[:n // 4])]

    # the concurrent cell runs as TWO named tenants (ISSUE 15): the
    # ledger must attribute each one's mesh consumption separately,
    # and their device-seconds must reconcile with mesh busy time.
    # Tracing starts HERE, not around the warm/cold submits above —
    # service_warm_submit must keep measuring what it always did
    # (PR 9's acceptance record is untraced), and conservation grades
    # over the meter delta of the traced window only.
    from dpark_tpu import ledger, trace
    trace.configure("ring")
    ledger.configure("on")
    meter0 = ledger.mesh_meter(sched)
    from dpark_tpu.service import ClientScheduler
    ten_a = ClientScheduler(sched.server, client="tenant-a")
    ten_b = ClientScheduler(sched.server, client="tenant-b")

    def _collect(tenant, rdd):
        return dict(x for part in tenant.run_job(
            rdd, lambda it: list(it)) for x in part)

    def job_a():
        return _collect(ten_a, ctx.parallelize(data, ndev)
                        .reduceByKey(_svc_add, ndev))

    def job_b():
        return _collect(ten_b, ctx.parallelize(datb, 4).groupByKey(4)
                        .mapValue(_svc_distinct))

    t0 = time.perf_counter()
    ref_a = job_a()
    t_a = time.perf_counter() - t0
    t0 = time.perf_counter()
    ref_b = job_b()
    t_b = time.perf_counter() - t0
    got = {}
    th = threading.Thread(target=lambda: got.update(a=job_a()))
    t0 = time.perf_counter()
    th.start()
    got["b"] = job_b()
    th.join()
    t_conc = time.perf_counter() - t0
    parity = got["a"] == ref_a and got["b"] == ref_b
    conc = {"t_a_solo_s": round(t_a, 3), "t_b_solo_s": round(t_b, 3),
            "t_concurrent_s": round(t_conc, 3),
            "ratio_vs_slower_solo": round(
                t_conc / max(t_a, t_b, 1e-9), 3),
            "parity": bool(parity)}
    jobs = [{"id": r["id"], "client": r.get("client"),
             "queue_wait_ms": r.get("queue_wait_ms")}
            for r in sched.history if r.get("service")]
    stats = sched.service_stats()
    meter_delta = ledger.meter_delta(meter0,
                                     ledger.mesh_meter(sched))
    out = {"cold": cold, "warm": warm, "concurrent": conc,
           "pairs": n, "ndev": ndev,
           "service": stats, "jobs": jobs,
           # per-tenant SLO attainment (ISSUE 14)
           "slo": stats.get("tenants", {}),
           # per-tenant resource attribution + the conservation check
           # (ISSUE 15 acceptance: attributed device-seconds within
           # 10% of measured mesh busy time across the two tenants)
           "ledger": {"tenants": ledger.tenant_totals(),
                      "conservation": ledger.conservation(
                          meter=meter_delta)}}
    trace.configure("off")
    from dpark_tpu import service as service_mod
    service_mod.shutdown()
    print("SERVICE_RESULT %s" % json.dumps(out), flush=True)


def _aot_step_phase():
    """Grandchild entry for the AOT restart A/B: ONE fresh process
    submitting the module-level reduceByKey DAG once against whatever
    DPARK_AOT_CACHE_DIR already holds.  Reports the first-submission
    wall, the number of BACKEND compiles (via jax.monitoring — a fresh
    process always misses the in-memory program-cache tier, so those
    counters cannot distinguish a disk hit from a recompile), and the
    AOT plane's own counters."""
    import numpy as np
    import jax
    compiles = [0]
    try:
        from jax import monitoring
        monitoring.register_event_duration_secs_listener(
            lambda event, duration, **kw: compiles.__setitem__(
                0, compiles[0] + 1)
            if "backend_compile" in event else None)
    except Exception:
        compiles[0] = -1        # listener unavailable: mark unknown
    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    from dpark_tpu import Columns, DparkContext, aotcache
    n = int(os.environ.get("BENCH_AOT_PAIRS",
                           os.environ.get("BENCH_PAIRS", "200000")))
    i = np.arange(n, dtype=np.int64)
    data = Columns((i * 2654435761) % 4096, np.ones(n, np.int64))
    ctx = DparkContext("tpu")
    ctx.start()
    ndev = ctx.scheduler.executor.ndev
    t0 = time.perf_counter()
    out = dict(ctx.parallelize(data, ndev)
               .reduceByKey(_svc_add, ndev).collect())
    wall = time.perf_counter() - t0
    # order-independent checksum: the cold and warm PROCESS must agree
    # on the answer, and neither side can ship the whole dict up
    csum = sum((int(k) * 1000003 + int(v)) % ((1 << 61) - 1)
               for k, v in out.items()) % ((1 << 61) - 1)
    payload = {"wall_s": round(wall, 4),
               "backend_compiles": compiles[0],
               "keys": len(out), "checksum": csum,
               "aot": aotcache.stats(), "ndev": ndev}
    ctx.stop()
    print("AOT_STEP %s" % json.dumps(payload), flush=True)


def _aot_phase():
    """Child entry: AOT restart A/B (ISSUE 17 acceptance).  Two FRESH
    processes submit the identical DAG sharing one on-disk AOT cache
    dir: the cold one populates it (backend compiles > 0, stores > 0),
    the warm one must deserialize every executable back off disk —
    0 backend compiles — and agree bit-for-bit on the answer."""
    import shutil
    import tempfile
    root = tempfile.mkdtemp(prefix="dpark-aot-bench-")
    step_env = {"DPARK_AOT_CACHE": "on",
                "DPARK_AOT_CACHE_DIR": os.path.join(root, "cache"),
                "DPARK_ADAPT_DIR": os.path.join(root, "adapt"),
                "DPARK_WORK_DIR": os.path.join(root, "work")}
    timeout = int(os.environ.get("BENCH_AOT_STEP_TIMEOUT", "300"))
    try:
        cold = _run_child("--aot-step", timeout, env=step_env,
                          ok_prefix="AOT_STEP ")
        warm = _run_child("--aot-step", timeout, env=step_env,
                          ok_prefix="AOT_STEP ")
        if cold is None or warm is None:
            raise SystemExit("aot restart step child failed")
        c, w = json.loads(cold), json.loads(warm)
        out = {"cold": c, "warm": w,
               "parity": bool(c["checksum"] == w["checksum"]
                              and c["keys"] == w["keys"])}
        print("AOT_RESULT %s" % json.dumps(out), flush=True)
    finally:
        shutil.rmtree(root, ignore_errors=True)


def _recovery_step_phase():
    """Grandchild entry for the crash-recovery certification (ISSUE
    20): ONE controller process running a 2-stage reduceByKey on the
    pure-Python local master under whatever DPARK_JOURNAL /
    DPARK_FAULTS the parent armed.  When the chaos spec kills it at
    the first reduce fetch it dies with os._exit(137) AFTER the map
    stage journaled; the next invocation over the same journal dir
    must replay that stage (resumed_stages >= 1, 0 recomputes) and
    agree on the order-independent checksum."""
    from dpark_tpu import DparkContext, journal, trace
    trace.configure("ring")
    n = int(os.environ.get("BENCH_RECOVERY_PAIRS", "100000"))
    ctx = DparkContext("local")
    ctx.start()
    t0 = time.perf_counter()
    out = dict(ctx.parallelize([(i % 4096, i) for i in range(n)], 8)
               .reduceByKey(_svc_add, 8).collect())
    wall = time.perf_counter() - t0
    csum = sum((int(k) * 1000003 + int(v)) % ((1 << 61) - 1)
               for k, v in out.items()) % ((1 << 61) - 1)
    rec = ctx.scheduler.history[-1]
    replay_traced = any(ev.get("name") == "journal.replay"
                        for ev in trace.snapshot())
    payload = {"wall_s": round(wall, 4), "keys": len(out),
               "checksum": csum,
               "resumed_stages": rec.get("resumed_stages") or 0,
               "seeded_partitions": rec.get("seeded_partitions") or 0,
               "recomputes": rec.get("recomputes", 0),
               "resubmits": rec.get("resubmits", 0),
               "replay_traced": replay_traced,
               "journal": journal.stats()}
    ctx.stop()
    print("RECOVERY_STEP %s" % json.dumps(payload), flush=True)


def _recovery_phase():
    """Child entry: kill -9 chaos certification + journal overhead A/B
    (ISSUE 20 acceptance).  Four fresh controller processes: journal
    OFF baseline, journal ON (the <=1.02x overhead pair), a VICTIM
    that the chaos plane os._exit(137)s at its first reduce fetch (no
    ok-line — the kill is the expected outcome), and a RESUME run over
    the victim's journal + work dirs that must complete bit-identically
    with resumed_stages >= 1 and 0 recomputes."""
    import shutil
    import tempfile
    root = tempfile.mkdtemp(prefix="dpark-recovery-bench-")
    timeout = int(os.environ.get("BENCH_RECOVERY_STEP_TIMEOUT", "180"))

    def env_for(tag, journal="on", faults=""):
        return {"DPARK_JOURNAL": journal,
                "DPARK_JOURNAL_DIR": os.path.join(root, tag, "jnl"),
                "DPARK_WORK_DIR": os.path.join(root, tag, "work"),
                "DPARK_FAULTS": faults,
                "JAX_PLATFORMS": "cpu"}

    try:
        off = _run_child("--recovery-step", timeout,
                         env=env_for("off", journal="off"),
                         ok_prefix="RECOVERY_STEP ")
        on = _run_child("--recovery-step", timeout, env=env_for("on"),
                        ok_prefix="RECOVERY_STEP ")
        chaos_env = env_for("chaos")
        victim = _run_child(
            "--recovery-step", timeout,
            env=dict(chaos_env,
                     DPARK_FAULTS="shuffle.fetch:nth=1,kind=kill"),
            ok_prefix="RECOVERY_STEP ")
        resume = _run_child("--recovery-step", timeout, env=chaos_env,
                            ok_prefix="RECOVERY_STEP ")
        if off is None or on is None or resume is None:
            raise SystemExit("recovery step child failed")
        o, j, r = json.loads(off), json.loads(on), json.loads(resume)
        out = {"off": o, "on": j, "resume": r,
               "victim_killed": victim is None,
               "overhead": round(j["wall_s"] / max(o["wall_s"], 1e-9),
                                 3),
               "parity": bool(o["checksum"] == j["checksum"]
                              == r["checksum"]),
               "resumed_stages": r.get("resumed_stages", 0),
               "recomputes": r.get("recomputes", 0),
               "replay_traced": bool(r.get("replay_traced"))}
        print("RECOVERY_RESULT %s" % json.dumps(out), flush=True)
    finally:
        shutil.rmtree(root, ignore_errors=True)


def _reuse_data(d, n):
    """Deterministic tabular part file for the reuse cells (written
    once per dir — the restart step's two processes must fingerprint
    identically)."""
    import numpy as np
    from dpark_tpu.tabular import write_tabular
    part = os.path.join(d, "part-00000.tab")
    if os.path.exists(part):
        return part
    os.makedirs(d, exist_ok=True)
    i = np.arange(n, dtype=np.int64)
    write_tabular(part, ["t", "k", "f"],
                  zip(i.tolist(), ((i * 2654435761) % 997).tolist(),
                      ((i % 1000) * 0.25).tolist()),
                  chunk_rows=1 << 14)
    return part


def _reuse_checksum(rows):
    import zlib
    return zlib.crc32(repr(rows).encode("utf-8")) & 0xFFFFFFFF


def _reuse_scan(pq):
    """JSON-safe scan_stats (columns_read is a set)."""
    return {k: (sorted(v) if isinstance(v, set) else v)
            for k, v in (pq.scan_stats if pq is not None else {})
            .items()}


def _reuse_phase():
    """Child entry: shared-computation plane A/B (ISSUE 18
    acceptance).  Cell 1 — two named tenants run the IDENTICAL
    ctx.sql group-by: tenant-a pays the scan + device exchange and
    populates the cache; tenant-b's run must plan into a full cache
    hit (zero scan chunks, ledger-proven: no device-seconds, a
    resultcache hit billed to tenant-b).  Cell 2 — partial-aggregate
    reuse: a cached aggregate over 95% of the rows serves a wider
    query through a residual scan of the remaining 5%, beating the
    cold run while staying bit-identical to the plane-off answer."""
    import tempfile

    import jax
    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    from dpark_tpu import ledger, resultcache, trace
    from dpark_tpu import service as service_mod
    n = int(os.environ.get("BENCH_REUSE_ROWS", "200000"))
    mode = os.environ.get("BENCH_REUSE_CACHE", "mem")
    d = tempfile.mkdtemp(prefix="bench_reuse_")
    _reuse_data(d, n)
    trace.configure("ring")
    ledger.configure("on")
    resultcache.configure(mode)
    server = service_mod.get_server("local")
    server.start()
    ctx_a = service_mod._context_for(server, "tenant-a")
    ctx_b = service_mod._context_for(server, "tenant-b")
    sql = ("select k, sum(f) as s, count(t) as c from events "
           "where t >= 1000 group by k")

    def run_sql(ctx):
        t = ctx.tabular(d, ["t", "k", "f"]).asTable("events")
        q = ctx.sql(sql, events=t)
        t0 = time.perf_counter()
        rows = sorted(q.collect())
        return time.perf_counter() - t0, rows, q

    t_cold, rows_a, qa = run_sql(ctx_a)
    t_warm, rows_b, qb = run_sql(ctx_b)
    st = resultcache.stats() or {}
    pq_b = qb._planned()
    scan_warm = _reuse_scan(pq_b)
    pq_a = qa._planned()
    scan_cold = _reuse_scan(pq_a)
    # ledger proof BEFORE the partial cell muddies tenant-b: the
    # served tenant must show a resultcache hit and NO device time
    tenants = ledger.tenant_totals()
    reuse_cell = {
        "t_cold_s": round(t_cold, 4), "t_warm_s": round(t_warm, 4),
        "speedup": round(t_cold / max(t_warm, 1e-9), 2),
        "parity": bool(rows_a == rows_b),
        "scan_cold": scan_cold, "scan_warm": scan_warm,
        "hits": st.get("hits", 0), "stores": st.get("stores", 0),
        "tenant_b": tenants.get("tenant-b", {}),
        "tenant_a_device_s": tenants.get("tenant-a", {})
        .get("device_seconds", 0.0)}

    # cell 2: partial-aggregate reuse.  Fresh plane so the cell
    # stands alone; the cached entry covers t >= n/20 (95% of rows),
    # the reuse query wants everything — the probe merges the cached
    # aggregate with a residual scan of t <= n/20-1 (chunk-skipped
    # to ~5% of the file).
    resultcache.configure(mode)
    lo = n // 20

    def run_where(ctx, where):
        q = ctx.tabular(d, ["t", "k", "f"]).asTable("events") \
            .where(where).groupBy("k", "sum(f) as s", "count(t) as c")
        t0 = time.perf_counter()
        rows = sorted(q.collect())
        return time.perf_counter() - t0, rows, q

    t_pcold, _, _ = run_where(ctx_a, "t >= %d" % lo)
    t_preuse, rows_part, qp = run_where(ctx_b, "t >= 0")
    stp = resultcache.stats() or {}
    pq_p = qp._planned()
    scan_part = _reuse_scan(pq_p)
    resultcache.configure("off")
    _, rows_off, _ = run_where(ctx_b, "t >= 0")
    partial_cell = {
        "t_cold_s": round(t_pcold, 4),
        "t_reuse_s": round(t_preuse, 4),
        "speedup": round(t_pcold / max(t_preuse, 1e-9), 2),
        "parity": bool(rows_part == rows_off),
        "partial_hits": stp.get("partial_hits", 0),
        "scan_reuse": scan_part}

    out = {"mode": mode, "rows": n, "reuse": reuse_cell,
           "partial": partial_cell,
           "conservation": ledger.conservation()}
    trace.configure("off")
    service_mod.shutdown()
    print("REUSE_RESULT %s" % json.dumps(out), flush=True)


def _reuse_step_phase():
    """Grandchild entry for the disk-tier restart smoke: ONE fresh
    process running the reuse query against whatever
    DPARK_RESULT_CACHE_DIR already holds (DPARK_RESULT_CACHE=disk in
    the env).  The first run scans and stores; a second process must
    boot the entry back and serve it with zero scan chunks and a
    bit-identical checksum."""
    import jax
    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    from dpark_tpu import resultcache
    from dpark_tpu import service as service_mod
    n = int(os.environ.get("BENCH_REUSE_ROWS", "200000"))
    d = os.environ["DPARK_REUSE_DATA"]
    _reuse_data(d, n)
    server = service_mod.get_server("local")
    server.start()          # disk mode: boots hot entries to memory
    ctx = service_mod._context_for(server, "tenant-restart")
    t0 = time.perf_counter()
    q = ctx.tabular(d, ["t", "k", "f"]).asTable("events") \
        .where("t >= 1000").groupBy("k", "sum(f) as s",
                                    "count(t) as c")
    rows = sorted(q.collect())
    wall = time.perf_counter() - t0
    pq = q._planned()
    st = resultcache.stats() or {}
    out = {"wall_s": round(wall, 4), "groups": len(rows),
           "checksum": _reuse_checksum(rows),
           "scan": _reuse_scan(pq),
           "hits": st.get("hits", 0), "stores": st.get("stores", 0),
           "preloaded": st.get("preloaded", 0),
           "boot": getattr(server, "_rc_boot", None)}
    service_mod.shutdown()
    print("REUSE_STEP %s" % json.dumps(out), flush=True)


def _health_phase():
    """Child-process entry: health-plane overhead A/B (ISSUE 14
    acceptance).  The same ring-traced device reduceByKey with the
    streaming health sink OFF vs ON — folding every span into the
    sketches must cost <= 3% wall.  Also reports the nonzero site
    count the CI smoke gates (the sink actually observed the run)."""
    import numpy as np
    import jax
    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    from dpark_tpu import Columns, DparkContext, health, trace
    n = int(os.environ.get("BENCH_HEALTH_PAIRS",
                           os.environ.get("BENCH_PAIRS", "500000")))
    i = np.arange(n, dtype=np.int64)
    data = Columns((i * 2654435761) % 4096, i & 0xFFFF)
    ctx = DparkContext("tpu")
    ctx.start()
    ndev = ctx.scheduler.executor.ndev
    trace.configure("ring")

    def run():
        t0 = time.perf_counter()
        cnt = (ctx.parallelize(data, ndev)
               .reduceByKey(_svc_add, ndev).count())
        assert cnt == min(4096, n), cnt
        return time.perf_counter() - t0

    reps = int(os.environ.get("BENCH_HEALTH_REPS", "3"))
    health.configure("off")
    run()                                      # warm-up compile
    t_off = min(run() for _ in range(reps))
    health.configure("on")
    run()                                      # fold path warm
    t_on = min(run() for _ in range(reps))
    sites = len(health.summary()["sites"])
    trace.configure("off")
    payload = {"t_off": round(t_off, 4), "t_on": round(t_on, 4),
               "sites": sites, "pairs": n, "ndev": ndev}
    ctx.stop()
    print("HEALTH_RESULT %s" % json.dumps(payload), flush=True)


def _ledger_phase():
    """Child-process entry: ledger-plane overhead A/B (ISSUE 15
    acceptance, riding the health_plane_overhead pattern).  The same
    ring-traced device reduceByKey with the attribution sink OFF vs
    ON — folding every span into the per-(tenant, job, stage,
    program) accounts must cost <= 3% wall.  Also reports the nonzero
    account count and the conservation check (attributed
    device-seconds vs measured mesh-lock busy time) the CI smoke
    gates."""
    import numpy as np
    import jax
    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    from dpark_tpu import Columns, DparkContext, ledger, trace
    n = int(os.environ.get("BENCH_LEDGER_PAIRS",
                           os.environ.get("BENCH_PAIRS", "500000")))
    i = np.arange(n, dtype=np.int64)
    data = Columns((i * 2654435761) % 4096, i & 0xFFFF)
    ctx = DparkContext("tpu")
    ctx.start()
    ndev = ctx.scheduler.executor.ndev
    trace.configure("ring")

    def run():
        t0 = time.perf_counter()
        cnt = (ctx.parallelize(data, ndev)
               .reduceByKey(_svc_add, ndev).count())
        assert cnt == min(4096, n), cnt
        return time.perf_counter() - t0

    reps = int(os.environ.get("BENCH_LEDGER_REPS", "3"))
    ledger.configure("off")
    run()                                      # warm-up compile
    t_off = min(run() for _ in range(reps))
    # conservation is graded over the ON window only: the sink starts
    # empty here, so the meter baseline must too (the off leg's mesh
    # time was deliberately unobserved)
    meter0 = ledger.mesh_meter(ctx.scheduler)
    ledger.configure("on")
    run()                                      # fold path warm
    t_on = min(run() for _ in range(reps))
    summ = ledger.summary()
    cons = ledger.conservation(meter=ledger.meter_delta(
        meter0, ledger.mesh_meter(ctx.scheduler)))
    trace.configure("off")
    payload = {"t_off": round(t_off, 4), "t_on": round(t_on, 4),
               "accounts": summ["accounts"],
               "tenants": summ["tenants"],
               "conservation": cons, "pairs": n, "ndev": ndev}
    ctx.stop()
    print("LEDGER_RESULT %s" % json.dumps(payload), flush=True)


def _lockcheck_phase():
    """Child-process entry: lock-sanitizer overhead A/B (ISSUE 16
    acceptance).  The same ring-traced device reduceByKey with the
    named-lock registry OFF (one `is None` check per acquisition, the
    plane contract) vs RECORD (per-thread order stacks + process-wide
    edge merge) — arming the sanitizer must cost <= 3% wall.  Also
    reports the acquisition/edge counts and that the observed graph
    stayed acyclic (a cycle here is a real ordering bug, not an
    overhead artifact)."""
    import numpy as np
    import jax
    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    from dpark_tpu import Columns, DparkContext, locks, trace
    n = int(os.environ.get("BENCH_LOCKCHECK_PAIRS",
                           os.environ.get("BENCH_PAIRS", "500000")))
    i = np.arange(n, dtype=np.int64)
    data = Columns((i * 2654435761) % 4096, i & 0xFFFF)
    ctx = DparkContext("tpu")
    ctx.start()
    ndev = ctx.scheduler.executor.ndev
    trace.configure("ring")

    def run():
        t0 = time.perf_counter()
        cnt = (ctx.parallelize(data, ndev)
               .reduceByKey(_svc_add, ndev).count())
        assert cnt == min(4096, n), cnt
        return time.perf_counter() - t0

    reps = int(os.environ.get("BENCH_LOCKCHECK_REPS", "7"))
    locks.configure("off")
    run()                                      # warm-up compile
    locks.configure("record")
    run()                                      # record path warm
    offs, ons = [], []
    rep = None
    for _ in range(reps):          # interleaved A/B: clock drift and
        locks.configure("off")     # cache effects hit both sides
        offs.append(run())
        locks.configure("record")  # fresh sanitizer per pass; `rep`
        ons.append(run())          # keeps the final pass's graph
        rep = locks.report()
    # the headline ratio is the MEDIAN of per-pass paired ratios:
    # adjacent off/on passes share whatever the box was doing, so the
    # pair cancels drift that min-of-walls across the whole block
    # does not (observed 1.09x "overhead" from pure scheduler noise)
    paired = sorted(b / max(a, 1e-9) for a, b in zip(offs, ons))
    ratio = paired[len(paired) // 2]
    t_off, t_on = min(offs), min(ons)
    locks.configure("off")
    trace.configure("off")
    payload = {"t_off": round(t_off, 4), "t_on": round(t_on, 4),
               "ratio": round(ratio, 3),
               "acquisitions": rep["acquisitions"],
               "locks": len(rep["locks"]), "edges": len(rep["edges"]),
               "cycles": len(rep["cycles"]),
               "order_violations": len(rep["order_violations"]),
               "pairs": n, "ndev": ndev}
    ctx.stop()
    print("LOCKCHECK_RESULT %s" % json.dumps(payload), flush=True)


def _probe_phase():
    """Child-process entry: just initialize the device backend.  Fast on
    a healthy platform; hangs forever on a wedged axon tunnel — which is
    exactly what the parent's short timeout detects."""
    import jax
    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    devs = jax.devices()
    import jax.numpy as jnp
    jnp.ones((8,)).block_until_ready()       # end-to-end: compile + run
    print("PROBE_OK %d %s" % (len(devs), devs[0].platform), flush=True)


def _run_child(arg, timeout, env=None, ok_prefix="TPU_RESULT "):
    """Run `python bench.py <arg>` in its own process group with a hard
    timeout; return the payload line or None.  File-backed output + the
    process group SIGKILL mean a wedged TPU tunnel cannot hang the parent
    or leak grandchildren."""
    import signal
    import subprocess
    import tempfile
    child_env = dict(os.environ, **(env or {}))
    with tempfile.TemporaryFile("w+") as so, \
            tempfile.TemporaryFile("w+") as se:
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), arg],
            stdout=so, stderr=se, text=True, start_new_session=True,
            env=child_env)
        try:
            proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except OSError:
                pass
            proc.wait()
            print("# %s timed out after %ss" % (arg, timeout),
                  file=sys.stderr)
            return None
        so.seek(0)
        for line in so.read().splitlines():
            if line.startswith(ok_prefix):
                return line[len(ok_prefix):]
        se.seek(0)
        print("# %s failed:\n%s" % (arg, se.read()[-1500:]),
              file=sys.stderr)
        return None


def _device_reachable():
    """Probe device init in a short-timeout child, retrying at
    intervals (VERDICT r3 #1: the chip demonstrably answers
    mid-session; a give-up-after-60s cadence forfeits real numbers a
    patient one captures).  Worst case with defaults: 5 x 45s timeouts
    + 4 x 45s sleeps = ~7 min before the emulated fallback."""
    timeout = int(os.environ.get("BENCH_PROBE_TIMEOUT", 45))
    attempts = int(os.environ.get("BENCH_PROBE_ATTEMPTS", 5))
    sleep_s = int(os.environ.get("BENCH_PROBE_SLEEP", 45))
    want = os.environ.get("BENCH_PLATFORM")
    for attempt in range(1, attempts + 1):
        if attempt > 1:
            time.sleep(sleep_s)
        got = _run_child("--probe", timeout, ok_prefix="PROBE_OK ")
        if got is not None:
            n, platform = got.split()
            # jax silently falls back to CPU when the device backend is
            # absent; a cpu probe result is NOT a reachable device unless
            # cpu was explicitly requested via BENCH_PLATFORM
            if want is None and platform == "cpu":
                print("# device probe got cpu fallback, not a device",
                      file=sys.stderr)
                return False
            if want is not None and platform != want:
                print("# device probe got %s, wanted %s"
                      % (platform, want), file=sys.stderr)
                return False
            print("# device probe ok: %s x%s" % (platform, n),
                  file=sys.stderr)
            return True
        print("# device probe attempt %d failed" % attempt,
              file=sys.stderr)
    return False


def _run_tpu_with_timeout(timeout, env=None):
    got = _run_child("--tpu-only", timeout, env=env)
    if got is None:
        return None
    stats = json.loads(got)
    return stats.pop("t"), stats.pop("ndev"), stats


def main():
    global N_PAIRS, BYTES
    if "--tpu-only" in sys.argv:
        _tpu_phase()
        return
    if "--ooc-only" in sys.argv:
        _ooc_phase()
        return
    if "--join-only" in sys.argv:
        _join_phase()
        return
    if "--tuple-only" in sys.argv:
        _tuple_phase()
        return
    if "--groupmap-only" in sys.argv:
        _groupmap_phase()
        return
    if "--stream-only" in sys.argv:
        _stream_phase()
        return
    if "--wc-only" in sys.argv:
        _wc_phase()
        return
    if "--sg-only" in sys.argv:
        _sg_phase()
        return
    if "--coded-only" in sys.argv:
        _coded_phase()
        return
    if "--bulk-only" in sys.argv:
        _bulk_phase()
        return
    if "--adapt-only" in sys.argv:
        _adapt_phase()
        return
    if "--code-adapt-only" in sys.argv:
        _code_adapt_phase()
        return
    if "--service-only" in sys.argv:
        _service_phase()
        return
    if "--aot-only" in sys.argv:
        _aot_phase()
        return
    if "--aot-step" in sys.argv:
        _aot_step_phase()
        return
    if "--reuse-only" in sys.argv:
        _reuse_phase()
        return
    if "--recovery-only" in sys.argv:
        _recovery_phase()
        return
    if "--recovery-step" in sys.argv:
        _recovery_step_phase()
        return
    if "--reuse-step" in sys.argv:
        _reuse_step_phase()
        return
    if "--health-only" in sys.argv:
        _health_phase()
        return
    if "--ledger-only" in sys.argv:
        _ledger_phase()
        return
    if "--lockcheck-only" in sys.argv:
        _lockcheck_phase()
        return
    if "--table-only" in sys.argv:
        _table_phase()
        return
    if "--probe" in sys.argv:
        _probe_phase()
        return
    # probe FIRST: a real chip raises the default workload out of toy
    # range; a wedged tunnel costs the retry cadence (~7 min default —
    # see _device_reachable) before the emulated fallback.
    # An explicitly requested platform (BENCH_PLATFORM=cpu in CI) keeps
    # the toy size — only an actual device earns the big run.
    global JOIN_FACT, WC_MB, SG_PAIRS
    reachable = _device_reachable()
    if reachable and os.environ.get("BENCH_PLATFORM") is None:
        if "BENCH_PAIRS" not in os.environ:
            N_PAIRS = N_PAIRS_DEVICE_DEFAULT
            BYTES = N_PAIRS * 16
            os.environ["BENCH_PAIRS"] = str(N_PAIRS)   # child agrees
        if "BENCH_JOIN_FACT" not in os.environ:
            JOIN_FACT = JOIN_FACT_DEVICE_DEFAULT
            os.environ["BENCH_JOIN_FACT"] = str(JOIN_FACT)
        if "BENCH_WC_MB" not in os.environ:
            WC_MB = WC_MB_DEVICE_DEFAULT
            os.environ["BENCH_WC_MB"] = str(WC_MB)
        if "BENCH_SG_PAIRS" not in os.environ:
            SG_PAIRS = SG_PAIRS_DEVICE_DEFAULT
            os.environ["BENCH_SG_PAIRS"] = str(SG_PAIRS)
    data = make_data()
    t_proc = bench_process(data)
    del data                 # the child regenerates its own copy
    extras = os.environ.get("BENCH_EXTRAS", "1") != "0"
    t_join_proc = bench_join_process() if extras else None
    t_stream_proc = bench_stream_process() if extras else None
    t_wc_proc = bench_wc_process(_wc_corpus()) if extras else None
    t_sg_proc = bench_sg_process() if extras else None
    emulated = False
    tpu = None
    if reachable:
        tpu = _run_tpu_with_timeout(
            int(os.environ.get("BENCH_TPU_TIMEOUT", 900)))
    if tpu is None and not os.environ.get("BENCH_PLATFORM"):
        # real device unreachable (wedged tunnel): fall back to the
        # 8-virtual-CPU mesh so the run still produces a nonzero,
        # clearly-labeled diagnostic number instead of a bare 0.0
        print("# real device unreachable; falling back to emulated "
              "8-virtual-CPU mesh", file=sys.stderr)
        emulated = True
        tpu = _run_tpu_with_timeout(
            int(os.environ.get("BENCH_TPU_TIMEOUT", 900)),
            env={"BENCH_PLATFORM": "cpu",
                 "XLA_FLAGS": (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8"
                               ).strip()})
    if tpu is None:
        print(json.dumps({
            "metric": "reduceByKey_GBps_per_chip", "value": 0.0,
            "unit": "GB/s/chip", "vs_baseline": 0.0}))
        print("# process baseline: %.3fs (%.4f GB/s); tpu unavailable"
              % (t_proc, BYTES / t_proc / 1e9), file=sys.stderr)
        return
    t_tpu, ndev, stats = tpu
    gbps_chip = BYTES / t_tpu / 1e9 / ndev
    gbps_proc = BYTES / t_proc / 1e9
    sort_roof = stats.get("sort_roofline_gbps", 0.0)
    out = {
        # a distinct metric name for the emulated fallback: a consumer
        # keying on the real metric never ingests a CPU-emulation number
        "metric": ("reduceByKey_GBps_per_chip_EMULATED_CPU" if emulated
                   else "reduceByKey_GBps_per_chip"),
        "value": round(gbps_chip, 4),
        "unit": "GB/s/chip",
        "vs_baseline": round(t_proc / t_tpu, 2),
    }
    if sort_roof:
        # distance to the chip's own jnp.sort bound, same session
        out["pct_of_sort_roofline"] = round(
            100.0 * gbps_chip / sort_roof, 2)
        out["sort_roofline_gbps"] = sort_roof
    out["pad_efficiency"] = stats.get("pad_efficiency")
    out["pad_kind"] = stats.get("pad_kind")
    if emulated:
        # diagnostic only: CPU-emulated mesh, not TPU throughput
        out["emulated_cpu_mesh"] = True
    print(json.dumps(out))
    print("# pairs=%d keys=%d chips=%d tpu=%.3fs process=%.3fs "
          "(process=%.4f GB/s) exchange_wire_bytes=%d "
          "pad_efficiency=%s (%s)%s"
          % (N_PAIRS, N_KEYS, ndev, t_tpu, t_proc, gbps_proc,
             stats.get("wire_bytes", 0), stats.get("pad_efficiency"),
             stats.get("pad_kind"),
             " [EMULATED cpu mesh]" if emulated else ""),
          file=sys.stderr)
    # further lines run on the same platform that just answered
    extra_env = {}
    if emulated:
        extra_env = {"BENCH_PLATFORM": "cpu",
                     "XLA_FLAGS": (os.environ.get("XLA_FLAGS", "") +
                                   " --xla_force_host_platform_device_"
                                   "count=8").strip()}
    child_timeout = int(os.environ.get("BENCH_TPU_TIMEOUT", 900))

    def _suffix(name):
        return name + ("_EMULATED_CPU" if emulated else "")

    # second line: the out-of-core wave-stream config
    if os.environ.get("BENCH_OOC_GB") != "0":
        got = _run_child("--ooc-only", child_timeout,
                         env=extra_env, ok_prefix="OOC_RESULT ")
        if got is not None:
            ooc = json.loads(got)
            ooc = dict({"metric": _suffix("ooc_reduceByKey_GBps_per_chip"),
                        "value": ooc.pop("gbps_per_chip"),
                        "unit": "GB/s/chip"}, **ooc)
            if sort_roof:
                ooc["pct_of_sort_roofline"] = round(
                    100.0 * ooc["value"] / sort_roof, 2)
            if emulated:
                ooc["emulated_cpu_mesh"] = True
            print(json.dumps(ooc))
    # composite-key A/B (ISSUE 3 acceptance): tuple-key reduceByKey
    # wall vs the equivalent scalar-key job — must be within 1.3x now
    # that tuple keys ride the device (the object path was 10x+ off)
    if os.environ.get("BENCH_TUPLE", "1") != "0":
        got = _run_child("--tuple-only", child_timeout,
                         env=extra_env, ok_prefix="TUPLE_RESULT ")
        if got is not None:
            tp = json.loads(got)
            tout = {"metric": _suffix("tuple_key_reduce_vs_scalar"),
                    "value": round(tp["t_tuple"]
                                   / max(tp["t_scalar"], 1e-9), 3),
                    "unit": "x (lower is better; <=1.3 passes)",
                    "t_scalar_s": round(tp["t_scalar"], 3),
                    "t_tuple_s": round(tp["t_tuple"], 3),
                    "pairs": tp["pairs"], "chips": tp["ndev"],
                    "tuple_rode_array_path": tp["array_path"]}
            if emulated:
                tout["emulated_cpu_mesh"] = True
            print(json.dumps(tout))
    # segmented-apply A/B (ISSUE 4 acceptance): the same grouped
    # consumer on the device SegMapOp path vs the host object path —
    # the widest remaining host pocket after tuple keys went device
    if os.environ.get("BENCH_GROUPMAP", "1") != "0":
        got = _run_child("--groupmap-only", child_timeout,
                         env=extra_env, ok_prefix="GROUPMAP_RESULT ")
        if got is not None:
            gm = json.loads(got)
            gout = {"metric": _suffix("group_mapvalues_device_vs_host"),
                    # consume-stage seconds: both sides share the same
                    # device shuffle write, so the ratio isolates the
                    # segmented apply vs the object path
                    "value": round(gm["t_host"]
                                   / max(gm["t_device"], 1e-9), 3),
                    "unit": "x (higher is better; >=5 passes)",
                    "t_device_s": round(gm["t_device"], 3),
                    "t_host_s": round(gm["t_host"], 3),
                    "wall_device_s": round(gm["wall_device"], 3),
                    "wall_host_s": round(gm["wall_host"], 3),
                    "pairs": gm["pairs"], "chips": gm["ndev"],
                    "device_rode_array_path": gm["device_array_path"]}
            if emulated:
                gout["emulated_cpu_mesh"] = True
            print(json.dumps(gout))
    # coded-shuffle overhead A/B (ISSUE 6 acceptance): the same
    # shuffle-heavy host-path job with the erasure code off vs
    # rs(4,2), no faults — the premium paid for decode-not-recompute
    # recovery must stay <= 15% wall
    if os.environ.get("BENCH_CODED", "1") != "0":
        got = _run_child("--coded-only", child_timeout,
                         ok_prefix="CODED_RESULT ")
        if got is not None:
            c = json.loads(got)
            cout = {"metric": "coded_shuffle_overhead",
                    "value": round(c["t_on"]
                                   / max(c["t_off"], 1e-9), 3),
                    "unit": "x (lower is better; <=1.15 passes)",
                    "t_off_s": round(c["t_off"], 3),
                    "t_on_s": round(c["t_on"], 3),
                    "pairs": c["pairs"],
                    "coding": c["decodes"]}
            print(json.dumps(cout))
    # columnar query plane A/B (ISSUE 13 acceptance): the same
    # select+filter+group-by query over the same tabular input — the
    # scan-pruned device plan (vectorized column scan + device group
    # exchange) vs the pre-plan host row path (per-row Python eval).
    # >= 3x at 2M rows on the 2-dev CPU mesh with bit-identical rows.
    if os.environ.get("BENCH_TABLE", "1") != "0":
        got = _run_child("--table-only", child_timeout,
                         env=extra_env, ok_prefix="TABLE_RESULT ")
        if got is not None:
            tb = json.loads(got)
            tbo = {"metric": _suffix("table_query_device_vs_host"),
                   "value": round(tb["t_host"]
                                  / max(tb["t_device"], 1e-9), 3),
                   "unit": "x (higher is better; >=3 passes)",
                   "t_device_s": round(tb["t_device"], 3),
                   "t_host_s": round(tb["t_host"], 3),
                   "rows": tb["rows"], "chips": tb["ndev"],
                   "parity": tb["parity"],
                   "device_all_array": tb["device_all_array"],
                   "columns_total": tb["columns_total"],
                   "scan": tb["scan"]}
            if emulated:
                tbo["emulated_cpu_mesh"] = True
            print(json.dumps(tbo))
    # bulk-channel vs pickled-bridge A/B (ISSUE 12 acceptance): the
    # same HBM-shaped bucket fetched cross-process over loopback both
    # ways — the chunked raw-column bulk stream must move >= 2x the
    # bytes/s of the single-frame pickled host bridge, with fetch p99
    # for both recorded
    if os.environ.get("BENCH_BULK", "1") != "0":
        got = _run_child("--bulk-only", child_timeout,
                         ok_prefix="BULKPLANE_RESULT ")
        if got is not None:
            b = json.loads(got)
            bout = {"metric": "bulk_channel_vs_bridge",
                    "value": b["ratio"],
                    "unit": "x bytes/s (higher is better; >=2 passes)",
                    "bridge_MBps": b["bridge_MBps"],
                    "bulk_MBps": b["bulk_MBps"],
                    "p99_bridge_ms": b["p99_bridge_ms"],
                    "p99_bulk_ms": b["p99_bulk_ms"],
                    "p50_bridge_ms": b["p50_bridge_ms"],
                    "p50_bulk_ms": b["p50_bulk_ms"],
                    "rows": b["rows"], "reps": b["reps"],
                    "parity": b["parity"],
                    "bulk_streams": b["bulk_streams"]}
            print(json.dumps(bout))
    # adaptive-execution warm-vs-cold A/B (ISSUE 7 acceptance): the
    # streamed sortgroup/groupmap config run twice with DPARK_ADAPT=on
    # against a deterministic emulated HBM ceiling — the warm run must
    # seed its wave budget from the store (fewer OOM-ladder retries,
    # typically less wall) instead of re-walking the halving ladder
    if os.environ.get("BENCH_ADAPT", "1") != "0":
        got = _run_child("--adapt-only", child_timeout,
                         env=extra_env, ok_prefix="ADAPT_RESULT ")
        if got is not None:
            a = json.loads(got)
            aout = {"metric": _suffix("adapt_warm_vs_cold"),
                    "value": round(a["warm"]["wall_s"]
                                   / max(a["cold"]["wall_s"], 1e-9), 3),
                    "unit": ("x wall (lower is better; warm must also "
                             "drop ladder retries)"),
                    "cold": a["cold"], "warm": a["warm"],
                    "pairs": a["pairs"], "chips": a["ndev"],
                    "adapt": a["adapt"]}
            if emulated:
                aout["emulated_cpu_mesh"] = True
            print(json.dumps(aout))
    # straggler-adaptive coding + skew re-plan A/B (ISSUE 19
    # acceptance): per-exchange (k,m) re-pricing must hold wall within
    # 1.1x of a global static rs(4,2) under the same injected fetch
    # delay while shedding the tight-tailed exchange's parity bytes;
    # the skew re-plan leg reports the dominant-bucket reduce wall
    # off-vs-presalted with zero resubmits/recomputes
    if os.environ.get("BENCH_CODE_ADAPT", "1") != "0":
        got = _run_child("--code-adapt-only", child_timeout,
                         ok_prefix="CODE_ADAPT_RESULT ")
        if got is not None:
            ca = json.loads(got)
            st, ad = ca["static"], ca["adaptive"]
            wall_s = st["t_hot_s"] + st["t_cold_s"]
            wall_a = ad["t_hot_s"] + ad["t_cold_s"]
            caout = {"metric": "adaptive_code",
                     "value": round(wall_a / max(wall_s, 1e-9), 3),
                     "unit": ("x wall vs static rs(4,2) (lower is "
                              "better; <=1.1 at lower parity passes)"),
                     "static": st, "adaptive": ad,
                     "parity_ratio": round(
                         ad["parity_bytes"]
                         / max(st["parity_bytes"], 1), 3),
                     "hot_escalated": ca["hot_escalated"],
                     "cold_pinned_uncoded": ca["cold_pinned_uncoded"],
                     "pairs": ca["pairs"], "reps": ca["reps"]}
            print(json.dumps(caout))
            rp = ca["replan"]
            rpout = {"metric": "skew_replan",
                     "value": round(rp["reduce_off_s"]
                                    / max(rp["reduce_presalt_s"],
                                          1e-9), 3),
                     "unit": ("x reduce-stage wall, skewed vs "
                              "pre-salted (higher is better)"),
                     **rp}
            print(json.dumps(rpout))
    # resident-service A/B (ISSUE 9 acceptance): a warm re-submission
    # of an identical DAG to the resident server must perform 0 stage
    # re-compiles (cache counters) and cut submit-to-first-wave
    # latency >= 3x vs the cold submission; the concurrent section
    # reports two jobs' combined wall vs the slower solo wall
    if os.environ.get("BENCH_SERVICE", "1") != "0":
        got = _run_child("--service-only", child_timeout,
                         env=extra_env, ok_prefix="SERVICE_RESULT ")
        if got is not None:
            s = json.loads(got)
            warm_fw = (s["warm"].get("first_wave_ms") or 1e9)
            cold_fw = (s["cold"].get("first_wave_ms") or 0)
            svout = {"metric": _suffix("service_warm_submit"),
                     "value": round(cold_fw / max(warm_fw, 1e-9), 2),
                     "unit": ("x submit-to-first-wave latency "
                              "(higher is better; >=3 passes, with 0 "
                              "warm compiles)"),
                     "cold": s["cold"], "warm": s["warm"],
                     "concurrent": s["concurrent"],
                     "service": s["service"], "jobs": s["jobs"],
                     "slo": s.get("slo", {}),
                     "ledger": s.get("ledger", {}),
                     "pairs": s["pairs"], "chips": s["ndev"]}
            if emulated:
                svout["emulated_cpu_mesh"] = True
            print(json.dumps(svout))
    # instant-on restart A/B (ISSUE 17 acceptance): a fresh process
    # whose on-disk AOT executable cache was populated by a prior
    # process must submit its first DAG with ZERO backend compiles —
    # every executable deserializes straight off disk — and match the
    # cold process's answer bit-for-bit
    if os.environ.get("BENCH_AOT", "1") != "0":
        got = _run_child("--aot-only", child_timeout,
                         env=extra_env, ok_prefix="AOT_RESULT ")
        if got is not None:
            ab = json.loads(got)
            rst = {"metric": _suffix("aot_restart"),
                   "value": round(ab["cold"]["wall_s"]
                                  / max(ab["warm"]["wall_s"], 1e-9),
                                  3),
                   "unit": ("x first-submission wall (higher is "
                            "better; warm process must report 0 "
                            "backend compiles)"),
                   "cold": ab["cold"], "warm": ab["warm"],
                   "parity": ab["parity"]}
            if emulated:
                rst["emulated_cpu_mesh"] = True
            print(json.dumps(rst))
    # shared-computation reuse A/B (ISSUE 18 acceptance): tenant-b's
    # identical ctx.sql query must plan into a full result-cache hit
    # (zero scan chunks, ledger-proven: no device-seconds, the hit
    # billed to tenant-b), and the partial-aggregate cell must beat
    # its cold run while staying bit-identical to the uncached plan
    if os.environ.get("BENCH_REUSE", "1") != "0":
        got = _run_child("--reuse-only", child_timeout,
                         env=extra_env, ok_prefix="REUSE_RESULT ")
        if got is not None:
            ru = json.loads(got)
            rout = {"metric": _suffix("result_reuse"),
                    "value": round(ru["reuse"]["speedup"], 2),
                    "unit": ("x repeated-query wall (higher is "
                             "better; >=5 passes, zero scan chunks "
                             "on the hit)"),
                    "reuse": ru["reuse"], "partial": ru["partial"],
                    "mode": ru["mode"], "rows": ru["rows"]}
            if emulated:
                rout["emulated_cpu_mesh"] = True
            print(json.dumps(rout))
    # health-plane overhead A/B (ISSUE 14 acceptance): the same
    # ring-traced job with the streaming sketch sink off vs on —
    # folding every span must cost <= 3% wall, with nonzero site
    # sketches proving the sink observed the run
    if os.environ.get("BENCH_HEALTH", "1") != "0":
        got = _run_child("--health-only", child_timeout,
                         env=extra_env, ok_prefix="HEALTH_RESULT ")
        if got is not None:
            h = json.loads(got)
            hout = {"metric": _suffix("health_plane_overhead"),
                    "value": round(h["t_on"]
                                   / max(h["t_off"], 1e-9), 3),
                    "unit": "x wall (lower is better; <=1.03 passes)",
                    "t_off_s": h["t_off"], "t_on_s": h["t_on"],
                    "sites": h["sites"], "pairs": h["pairs"],
                    "chips": h["ndev"]}
            if emulated:
                hout["emulated_cpu_mesh"] = True
            print(json.dumps(hout))
    # ledger-plane overhead A/B (ISSUE 15 acceptance): the same
    # ring-traced job with the attribution sink off vs on — folding
    # every span into the per-tenant accounts must cost <= 3% wall,
    # with nonzero accounts and the conservation check attached
    if os.environ.get("BENCH_LEDGER", "1") != "0":
        got = _run_child("--ledger-only", child_timeout,
                         env=extra_env, ok_prefix="LEDGER_RESULT ")
        if got is not None:
            led = json.loads(got)
            lout = {"metric": _suffix("ledger_plane_overhead"),
                    "value": round(led["t_on"]
                                   / max(led["t_off"], 1e-9), 3),
                    "unit": "x wall (lower is better; <=1.03 passes)",
                    "t_off_s": led["t_off"], "t_on_s": led["t_on"],
                    "accounts": led["accounts"],
                    "tenants": led["tenants"],
                    "conservation": led["conservation"],
                    "pairs": led["pairs"], "chips": led["ndev"]}
            if emulated:
                lout["emulated_cpu_mesh"] = True
            print(json.dumps(lout))
    # lock-sanitizer overhead A/B (ISSUE 16 acceptance): the same
    # ring-traced job with the named-lock registry off vs record —
    # arming the order recorder must cost <= 1.03x wall, and the
    # observed graph must stay acyclic
    if os.environ.get("BENCH_LOCKCHECK", "1") != "0":
        got = _run_child("--lockcheck-only", child_timeout,
                         env=extra_env, ok_prefix="LOCKCHECK_RESULT ")
        if got is not None:
            lk = json.loads(got)
            kout = {"metric": _suffix("lockcheck_overhead"),
                    "value": lk.get("ratio",
                                    round(lk["t_on"]
                                          / max(lk["t_off"], 1e-9),
                                          3)),
                    "unit": "x wall (lower is better; <=1.03 passes)",
                    "t_off_s": lk["t_off"], "t_on_s": lk["t_on"],
                    "acquisitions": lk["acquisitions"],
                    "locks": lk["locks"], "edges": lk["edges"],
                    "cycles": lk["cycles"],
                    "order_violations": lk["order_violations"],
                    "pairs": lk["pairs"], "chips": lk["ndev"]}
            if emulated:
                kout["emulated_cpu_mesh"] = True
            print(json.dumps(kout))
    # crash-recovery chaos certification (ISSUE 20 acceptance): a
    # controller kill -9ed at its first reduce fetch — after the map
    # stage journaled — restarts and completes the SAME job
    # bit-identically, replaying the completed stage from the journal
    # (0 recomputes), with journal-on overhead <= 1.02x
    if os.environ.get("BENCH_RECOVERY", "1") != "0":
        got = _run_child("--recovery-only", child_timeout,
                         env=extra_env, ok_prefix="RECOVERY_RESULT ")
        if got is not None:
            rv = json.loads(got)
            rout = {"metric": _suffix("journal_recovery"),
                    "value": rv["overhead"],
                    "unit": ("x journal-on wall (lower is better; "
                             "<=1.02 passes; the resume run must "
                             "replay >=1 stage with 0 recomputes)"),
                    "parity": rv["parity"],
                    "victim_killed": rv["victim_killed"],
                    "resumed_stages": rv["resumed_stages"],
                    "recomputes": rv["recomputes"],
                    "replay_traced": rv["replay_traced"],
                    "off": rv["off"], "on": rv["on"],
                    "resume": rv["resume"]}
            if emulated:
                rout["emulated_cpu_mesh"] = True
            print(json.dumps(rout))
    if not extras:
        return
    # third line: join/cogroup, BASELINE config #2
    got = _run_child("--join-only", child_timeout,
                     env=extra_env, ok_prefix="JOIN_RESULT ")
    if got is not None:
        j = json.loads(got)
        jbytes = (JOIN_FACT + JOIN_DIM) * 16
        jout = {"metric": _suffix("join_GBps_per_chip"),
                "value": round(jbytes / j["t"] / 1e9 / j["ndev"], 4),
                "unit": "GB/s/chip",
                "vs_baseline": round(t_join_proc / j["t"], 2),
                "fact_rows": JOIN_FACT, "dim_rows": JOIN_DIM,
                "chips": j["ndev"]}
        if sort_roof:
            jout["pct_of_sort_roofline"] = round(
                100.0 * jout["value"] / sort_roof, 2)
        if emulated:
            jout["emulated_cpu_mesh"] = True
        print(json.dumps(jout))
    # fourth line: DStream reduceByKeyAndWindow, BASELINE config #4
    got = _run_child("--stream-only", child_timeout,
                     env=extra_env, ok_prefix="STREAM_RESULT ")
    if got is not None:
        s = json.loads(got)
        total = STREAM_RECS * STREAM_BATCHES
        sout = {"metric": _suffix("dstream_window_Mrecords_per_s"),
                "value": round(total / s["t"] / 1e6, 4),
                "unit": "Mrecords/s",
                "vs_baseline": round(t_stream_proc / s["t"], 2),
                "recs_per_batch": STREAM_RECS,
                "batches": STREAM_BATCHES,
                "panes": s.get("panes", {})}
        if emulated:
            sout["emulated_cpu_mesh"] = True
        print(json.dumps(sout))
    # fifth line: file wordcount, BASELINE config #0
    got = _run_child("--wc-only", child_timeout,
                     env=extra_env, ok_prefix="WC_RESULT ")
    if got is not None:
        w = json.loads(got)
        wout = {"metric": _suffix("wordcount_MBps"),
                "value": round(WC_MB / w["t"], 2),
                "unit": "MB/s",
                "vs_baseline": round(t_wc_proc / w["t"], 2),
                "corpus_mb": WC_MB}
        if emulated:
            wout["emulated_cpu_mesh"] = True
        print(json.dumps(wout))
    # sixth line: sortByKey + groupByKey, BASELINE config #1
    got = _run_child("--sg-only", child_timeout,
                     env=extra_env, ok_prefix="SG_RESULT ")
    if got is not None:
        g = json.loads(got)
        gout = {"metric": _suffix("sortgroup_Mpairs_per_s"),
                "value": round(SG_PAIRS / g["t"] / 1e6, 4),
                "unit": "Mpairs/s",
                "vs_baseline": round(t_sg_proc / g["t"], 2),
                "pairs": SG_PAIRS, "chips": g.get("ndev")}
        if g.get("pipeline"):
            gout["pipeline"] = g["pipeline"]
        if emulated:
            gout["emulated_cpu_mesh"] = True
        print(json.dumps(gout))


if __name__ == "__main__":
    main()
