"""Benchmark: reduceByKey shuffle throughput, tpu master vs process master.

Prints ONE JSON line:
  {"metric": "reduceByKey_GBps_per_chip", "value": N, "unit": "GB/s/chip",
   "vs_baseline": N}
vs_baseline is the tpu-master speedup over the reference-semantics
`-m process` CPU baseline on the same workload (BASELINE.md: the reference
publishes no numbers; the process master IS the baseline).

The process run executes FIRST, before jax is imported, so its fork pool is
jax-free (fork after jax import can deadlock).
"""

import json
import os
import sys
import time

N_PAIRS = int(os.environ.get("BENCH_PAIRS", 16_000_000))
N_KEYS = int(os.environ.get("BENCH_KEYS", 65_536))
BYTES = N_PAIRS * 8            # two int32 columns


def make_data():
    # scrambled int keys, deterministic; columnar (numpy) input — the
    # ingestion analog of the reference's file sources.  Both masters get
    # the same columns: the process master iterates them as Python rows
    # (its real execution model), the tpu master ingests them into HBM.
    import numpy as np
    from dpark_tpu import Columns
    i = np.arange(N_PAIRS, dtype=np.int64)
    keys = (i * 2654435761) % N_KEYS
    vals = i & 0xFFFF
    return Columns(keys, vals)


def run_once(ctx, data, n_parts, expect_keys=None):
    t0 = time.perf_counter()
    r = (ctx.parallelize(data, n_parts)
         .reduceByKey(lambda a, b: a + b, n_parts))
    n = r.count()
    dt = time.perf_counter() - t0
    if expect_keys is not None:
        assert n == expect_keys, (n, expect_keys)
    return dt


def bench_process(data):
    from dpark_tpu import DparkContext
    nproc = min(8, os.cpu_count() or 4)
    ctx = DparkContext("process:%d" % nproc)
    ctx.start()
    dt = run_once(ctx, data, nproc, min(N_KEYS, N_PAIRS))
    ctx.stop()
    return dt


def bench_tpu(data):
    import jax
    if os.environ.get("BENCH_PLATFORM"):     # e.g. cpu mesh for CI
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    from dpark_tpu import DparkContext
    ctx = DparkContext("tpu")
    ctx.start()
    ndev = ctx.scheduler.executor.ndev
    # warm-up: compile the stage programs at the same size class
    run_once(ctx, data, ndev)
    best = min(run_once(ctx, data, ndev, min(N_KEYS, N_PAIRS))
               for _ in range(3))
    ctx.stop()
    return best, ndev


def _tpu_phase():
    """Child-process entry: run the tpu benchmark and print its result
    as one line (isolated so a wedged TPU tunnel cannot hang the whole
    benchmark — the parent times out and still reports)."""
    data = make_data()
    t_tpu, ndev = bench_tpu(data)
    print("TPU_RESULT %r %d" % (t_tpu, ndev), flush=True)


def _run_tpu_with_timeout(timeout):
    import signal
    import subprocess
    import tempfile
    # file-backed output + its own process group: a SIGKILL on timeout
    # takes any grandchildren too, and no inherited pipe can keep the
    # parent blocked after the kill
    with tempfile.TemporaryFile("w+") as so, \
            tempfile.TemporaryFile("w+") as se:
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--tpu-only"],
            stdout=so, stderr=se, text=True, start_new_session=True)
        try:
            proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except OSError:
                pass
            proc.wait()
            print("# tpu phase timed out after %ss (wedged TPU tunnel?)"
                  % timeout, file=sys.stderr)
            return None
        so.seek(0)
        for line in so.read().splitlines():
            if line.startswith("TPU_RESULT "):
                _, t, ndev = line.split()
                return float(t), int(ndev)
        se.seek(0)
        print("# tpu phase failed:\n%s" % se.read()[-1500:],
              file=sys.stderr)
        return None


def main():
    if "--tpu-only" in sys.argv:
        _tpu_phase()
        return
    data = make_data()
    t_proc = bench_process(data)
    del data                 # the child regenerates its own copy
    tpu = _run_tpu_with_timeout(
        int(os.environ.get("BENCH_TPU_TIMEOUT", 900)))
    if tpu is None:
        # device unreachable: report a zero so the failure is visible
        # rather than hanging the harness
        print(json.dumps({
            "metric": "reduceByKey_GBps_per_chip", "value": 0.0,
            "unit": "GB/s/chip", "vs_baseline": 0.0}))
        print("# process baseline: %.3fs (%.4f GB/s); tpu unavailable"
              % (t_proc, BYTES / t_proc / 1e9), file=sys.stderr)
        return
    t_tpu, ndev = tpu
    gbps_chip = BYTES / t_tpu / 1e9 / ndev
    gbps_proc = BYTES / t_proc / 1e9
    out = {
        "metric": "reduceByKey_GBps_per_chip",
        "value": round(gbps_chip, 4),
        "unit": "GB/s/chip",
        "vs_baseline": round(t_proc / t_tpu, 2),
    }
    print(json.dumps(out))
    print("# pairs=%d keys=%d chips=%d tpu=%.3fs process=%.3fs "
          "(process=%.4f GB/s)"
          % (N_PAIRS, N_KEYS, ndev, t_tpu, t_proc, gbps_proc),
          file=sys.stderr)


if __name__ == "__main__":
    main()
