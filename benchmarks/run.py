"""Run the BASELINE.json benchmark configs against one or more masters.

Usage:
  python benchmarks/run.py [config ...] [-m master] [--compare]

--compare runs each config on `process` then `tpu` and prints the
speedup; checksums must agree between masters.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from benchmarks import configs


def run_master(master, names, scale=1.0):
    from dpark_tpu import DparkContext
    results = {}
    for name in names:
        ctx = DparkContext(master)
        ctx.start()
        try:
            fn = configs.ALL[name]
            nbytes, dt, checksum = fn(ctx)
            results[name] = {
                "bytes": nbytes, "seconds": round(dt, 3),
                "MBps": round(nbytes / dt / 1e6, 2),
                "checksum": checksum,
            }
            print("  %-16s %-8s %8.3fs  %9.2f MB/s  (checksum %s)"
                  % (name, master, dt, nbytes / dt / 1e6, checksum),
                  file=sys.stderr)
        finally:
            ctx.stop()
    return results


def main():
    p = argparse.ArgumentParser()
    p.add_argument("names", nargs="*", default=None)
    p.add_argument("-m", "--master", default="process")
    p.add_argument("--compare", action="store_true",
                   help="run process then tpu, print speedups")
    args = p.parse_args()
    names = args.names or list(configs.ALL)

    if not args.compare:
        out = run_master(args.master, names)
        print(json.dumps({args.master: out}))
        return

    base = run_master("process", names)
    tpu = run_master("tpu", names)
    report = {}
    for name in names:
        b, t = base[name], tpu[name]
        if b["checksum"] != t["checksum"]:
            print("CHECKSUM MISMATCH %s: %s vs %s"
                  % (name, b["checksum"], t["checksum"]), file=sys.stderr)
        report[name] = {
            "process_s": b["seconds"], "tpu_s": t["seconds"],
            "speedup": round(b["seconds"] / t["seconds"], 2),
            "checksum_ok": b["checksum"] == t["checksum"],
        }
    print(json.dumps(report, indent=2))


if __name__ == "__main__":
    main()
