"""Sustained-ingest streaming benchmark (ISSUE 10): records/s at a
fixed p99 batch latency, plus the window-scaling A/B that proves the
pane plane's complexity claims.

Two JSON lines (schema-gated by tools/bench_smoke_check.py and the CI
`stream` job):

  stream_rate             ramp the per-batch record count over a
                          reduceByKeyAndWindow pipeline driven by the
                          MANUAL clock (the timer would measure sleep)
                          and report the highest rate whose p99
                          per-tick wall stays within the batch budget
                          — the serving-adjacent "how much can this
                          pipeline sustain" number.
  stream_window_scaling   median steady-state per-tick wall as the
                          window/slide ratio grows 4 -> 32, three
                          series: the pre-pane whole-window recompute
                          (linear in w), the non-invertible pane tree
                          (O(log w) merged branches), and the
                          invertible pane path (O(1) panes per slide).
                          `value` is the pane-tree growth factor
                          w=32 vs w=4; `old_growth` the recompute
                          path's.

Sizes shrink under --smoke (CI boxes grade schema, not throughput;
BENCH_*.json records honest numbers from quiet machines).  The tick
walls recorded here also seed the adapt store's pane-cost entries
(adapt.record_pane_cost), so a DPARK_ADAPT=on run after this bench
picks tree-vs-flat split points from these observations.
"""

import json
import operator
import os
import sys
import time


def _master():
    return os.environ.get("BENCH_STREAM_MASTER", "local")


def _mk_batches(nbatches, recs, keys, seed=7):
    import numpy as np
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(nbatches):
        ks = rng.randint(0, keys, recs)
        vs = rng.randint(0, 100, recs)
        out.append(list(zip(ks.tolist(), vs.tolist())))
    return out


def _drive(ctx, batches, window, invFunc, panes_on):
    """Run the windowed pipeline over a deterministic queueStream with
    the manual clock; returns per-tick wall seconds."""
    from dpark_tpu import conf
    from dpark_tpu.dstream import StreamingContext
    was = conf.STREAM_PANES
    conf.STREAM_PANES = panes_on
    try:
        ssc = StreamingContext(ctx, 1.0)
        out = []
        q = ssc.queueStream(batches)
        q.reduceByKeyAndWindow(operator.add, float(window),
                               invFunc=invFunc).collect_batches(out)
        ctx.start()
        for ins in ssc.input_streams:
            ins.start()
        ssc.zero_time = 1000.0
        walls = []
        for k in range(1, len(batches) + 1):
            t0 = time.perf_counter()
            ssc.run_batch(1000.0 + k * ssc.batch_duration)
            walls.append(time.perf_counter() - t0)
        assert out, "stream produced no batches"
        return walls
    finally:
        conf.STREAM_PANES = was


def _median(xs):
    xs = sorted(xs)
    return xs[len(xs) // 2]


def _p99(xs):
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(len(xs) * 0.99))]


def _steady(walls, window):
    """Ticks after the window filled (cold start + compile warmup)."""
    return walls[min(len(walls) - 1, int(window) + 2):] or walls


def bench_window_scaling(smoke):
    """Per-tick wall vs window/slide ratio, slide = 1 batch."""
    from dpark_tpu import DparkContext
    ratios = [4, 8, 16, 32]
    recs = 2_000 if smoke else 50_000
    keys = 97 if smoke else 4_096
    series = {"old_ms": [], "pane_ms": [], "inv_ms": []}
    for w in ratios:
        nb = w + (8 if smoke else 16)
        batches = _mk_batches(nb, recs, keys)
        for name, inv, panes_on in (("old_ms", None, False),
                                    ("pane_ms", None, True),
                                    ("inv_ms", operator.sub, True)):
            ctx = DparkContext(_master())
            walls = _drive(ctx, [list(b) for b in batches], w, inv,
                           panes_on)
            ctx.stop()
            series[name].append(
                round(_median(_steady(walls, w)) * 1000.0, 2))
    growth = {k: round(v[-1] / max(v[0], 1e-9), 2)
              for k, v in series.items()}
    return {"metric": "stream_window_scaling",
            "value": growth["pane_ms"], "unit": "x",
            "ratios": ratios, "recs_per_batch": recs,
            "pane_ms": series["pane_ms"], "inv_ms": series["inv_ms"],
            "old_ms": series["old_ms"],
            "pane_growth": growth["pane_ms"],
            "inv_growth": growth["inv_ms"],
            "old_growth": growth["old_ms"]}


def bench_stream_rate(smoke):
    """Highest sustainable ingest rate: ramp recs/batch geometrically
    while the p99 per-tick wall fits the batch budget."""
    from dpark_tpu import DparkContext, panes
    batch_s = float(os.environ.get("BENCH_STREAM_BATCH_S",
                                   "0.25" if smoke else "1.0"))
    window = 8.0 * batch_s
    nb = 16 if smoke else 40
    keys = 97 if smoke else 4_096
    start = 2_000 if smoke else 25_000
    cap = 16_000 if smoke else 1_600_000
    best = None
    tried = []
    last_panes = {}
    recs = start
    while recs <= cap:
        batches = _mk_batches(nb, recs, keys)
        ctx = DparkContext(_master())
        walls = _drive(ctx, batches, window / batch_s, operator.sub,
                       True)
        stats = panes.stream_stats()
        last_panes = list(stats.values())[-1] if stats else last_panes
        ctx.stop()
        steady = _steady(walls, window / batch_s)
        p99_ms = round(_p99(steady) * 1000.0, 2)
        point = {"recs_per_batch": recs, "p99_batch_ms": p99_ms,
                 "rate_records_per_s": round(recs / batch_s, 1)}
        tried.append(point)
        if p99_ms <= batch_s * 1000.0:
            best = dict(point, panes=last_panes)
            recs *= 2
        else:
            break
    if best is None:
        # even the floor rate overran the budget: report it honestly
        # (sustained=false) WITH its pane stats — the schema gates
        # check pane-mode indicators, never wall ratios
        best = dict(tried[0], panes=last_panes)
    return {"metric": "stream_rate",
            "value": best["rate_records_per_s"],
            "unit": "records/s",
            "p99_batch_ms": best["p99_batch_ms"],
            "batch_s": batch_s,
            "target_p99_ms": batch_s * 1000.0,
            "sustained": best["p99_batch_ms"] <= batch_s * 1000.0,
            "recs_per_batch": best["recs_per_batch"],
            "rates_tried": tried,
            "panes": best.get("panes", {})}


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    smoke = "--smoke" in argv
    if os.environ.get("BENCH_PLATFORM"):
        try:
            import jax
            jax.config.update("jax_platforms",
                              os.environ["BENCH_PLATFORM"])
        except Exception:
            pass
    print(json.dumps(bench_window_scaling(smoke)), flush=True)
    print(json.dumps(bench_stream_rate(smoke)), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
