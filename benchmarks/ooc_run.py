"""Out-of-core demonstration runs (SURVEY.md 7.2 item 4 / round-2 plan):
a >=10GB synthetic dataset through the north-star configs with bounded
RSS and HBM, on either master.

  python benchmarks/ooc_run.py --config wordcount --master tpu --gb 10
  python benchmarks/ooc_run.py --config sortgroup --master tpu --gb 10

Prints one JSON line: wall seconds, max RSS, HBM budget, spool bytes.
The text corpus is generated once under --data-dir and reused.
"""

import argparse
import json
import os
import resource
import sys
import time


def gen_corpus(path, gb):
    """~gb GB of whitespace text, written in repeated 8MB blocks."""
    import random
    if os.path.exists(path) and os.path.getsize(path) >= gb * (1 << 30):
        return
    rng = random.Random(1234)
    words = ["%s%d" % (w, i) for i in range(2000)
             for w in ("tok", "key", "val")][:5000]
    lines = []
    size = 0
    while size < (8 << 20):
        line = " ".join(rng.choices(words, k=10)) + "\n"
        lines.append(line)
        size += len(line)
    block = "".join(lines).encode()
    with open(path, "wb") as f:
        written = 0
        target = gb * (1 << 30)
        while written < target:
            f.write(block)
            written += len(block)


def run_wordcount(ctx, path, n_parts):
    r = (ctx.textFile(path)
         .flatMap(lambda line: line.split())
         .map(lambda w: (w, 1))
         .reduceByKey(lambda a, b: a + b, n_parts))
    top = r.top(5, key=lambda kv: kv[1])
    return {"top": top[0][1], "distinct": r.count()}


def run_sortgroup(ctx, gb, n_parts, reduce_parts=64):
    """Config #1 over columnar input: sortByKey + groupByKey with the
    spilled-run streaming path (HBM + spool bounded; input in RAM).
    reduce_parts > mesh keeps each reduce partition small — the rid
    column rides the exchange."""
    import numpy as np
    from dpark_tpu import Columns, conf
    if os.environ.get("DPARK_TPU_PLATFORM") == "cpu":
        # smaller waves: on the CPU-emulated mesh every device buffer
        # lives in host RSS, so the wave working-set multiplier (~10x
        # across the program pipeline) must stay a fraction of the
        # input; a real chip keeps full waves
        conf.STREAM_CHUNK_ROWS = 1 << 20
    n = int(gb * (1 << 30)) // 16         # two int64 columns
    keys = (np.arange(n, dtype=np.int64) * 2654435761) % (10 ** 9)
    vals = np.arange(n, dtype=np.int64) & 0xFFFF
    data = Columns(keys, vals)
    s = ctx.parallelize(data, n_parts).sortByKey(
        numSplits=reduce_parts)
    first_keys = [k for k, _ in s.take(3)]
    g = (ctx.parallelize(data, n_parts)
         .map(lambda kv: (kv[0] % 1000, kv[1]))
         .reduceByKey(lambda a, b: a + b, n_parts))
    return {"sort_head": first_keys, "groups": g.count()}


def _ooc_group_fn(vs):
    """Traceable, zero-pad-invariant, NOT a provable aggregate: only
    the ISSUE 4 segmented apply keeps this grouped consumer on device."""
    return sum(v * v for v in vs)


def run_groupmap(ctx, gb, n_parts, reduce_parts=None):
    """Streamed variant of the bench.py group_mapvalues A/B: the
    no-combine groupByKey write runs through the spilled-run wave
    stream (chunked waves, key-sorted runs on disk), then the SAME
    mapValues(traceable fn) consumer runs once with conf.SEG_MAP on
    (the premerged runs load back as a device batch and the segmented
    apply answers all-array) and once with it off (the pre-PR host
    export-bridge path)."""
    import numpy as np
    from dpark_tpu import Columns, conf
    if os.environ.get("DPARK_TPU_PLATFORM") == "cpu":
        conf.STREAM_CHUNK_ROWS = 1 << 20
    ctx.start()
    ex = getattr(ctx.scheduler, "executor", None)
    if reduce_parts is None:
        # the seg consume only rides with r <= mesh size; defaulting
        # past the mesh would silently measure host-vs-host
        reduce_parts = ex.ndev if ex is not None else 8
    n = int(gb * (1 << 30)) // 16         # two int64 columns
    keys = (np.arange(n, dtype=np.int64) * 2654435761) % 100_000
    vals = np.arange(n, dtype=np.int64) & 0xFFFF
    data = Columns(keys, vals)

    def once():
        t0 = time.time()
        cnt = (ctx.parallelize(data, n_parts)
               .groupByKey(reduce_parts)
               .mapValues(_ooc_group_fn).count())
        return time.time() - t0, cnt

    conf.SEG_MAP = True
    t_dev, groups = once()
    # every stage of the device-side job must be array-kind (a
    # contains-"array" check over all stages is vacuously true)
    rec = ctx.scheduler.history[-1]
    dev_array = bool(rec.get("stage_info")) and all(
        str(st.get("kind", "")).startswith("array")
        for st in rec["stage_info"])
    conf.SEG_MAP = False
    try:
        t_host, groups_host = once()
    finally:
        conf.SEG_MAP = True
    assert groups == groups_host, (groups, groups_host)
    return {"groups": groups,
            "groupmap_device_s": round(t_dev, 1),
            "groupmap_host_s": round(t_host, 1),
            "groupmap_device_array_path": dev_array,
            "groupmap_device_vs_host": round(t_host
                                             / max(t_dev, 1e-9), 2)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", choices=["wordcount", "sortgroup",
                                         "groupmap"],
                    default="wordcount")
    ap.add_argument("--master", default="tpu")
    ap.add_argument("--gb", type=float, default=10.0)
    ap.add_argument("--parts", type=int, default=8)
    ap.add_argument("--data-dir", default="/tmp/dpark_ooc")
    args = ap.parse_args()

    if args.master == "tpu" and os.environ.get("DPARK_TPU_PLATFORM",
                                               "cpu") == "cpu":
        # default to the virtual CPU mesh unless a real device is asked
        os.environ.setdefault("DPARK_TPU_PLATFORM", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()

    from dpark_tpu import DparkContext, conf
    ctx = DparkContext(args.master)

    os.makedirs(args.data_dir, exist_ok=True)
    t0 = time.time()
    out = {"config": args.config, "master": args.master, "gb": args.gb}
    if args.config == "wordcount":
        path = os.path.join(args.data_dir,
                            "corpus_%dg.txt" % int(args.gb))
        gen_corpus(path, args.gb)
        out["gen_s"] = round(time.time() - t0, 1)
        t0 = time.time()
        out.update(run_wordcount(ctx, path, args.parts))
    elif args.config == "groupmap":
        out.update(run_groupmap(ctx, args.gb, args.parts))
    else:
        out.update(run_sortgroup(ctx, args.gb, args.parts))
    out["wall_s"] = round(time.time() - t0, 1)
    out["max_rss_gb"] = round(
        resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / (1 << 20),
        2)
    out["hbm_budget_gb"] = round(conf.SHUFFLE_HBM_BUDGET / (1 << 30), 2)
    ex = getattr(ctx.scheduler, "executor", None)
    if ex is not None:
        out["hbm_used_gb"] = round(
            (ex._store_bytes + ex._result_bytes) / (1 << 30), 3)
    # overlapped wave pipeline: aggregate ingest/compute/exchange/spill
    # ms + device-idle fraction of the deepest streamed stage
    pipe = getattr(ctx.scheduler, "pipeline_summary", lambda: None)()
    if pipe is not None:
        out["pipeline"] = pipe
    # per-phase wall-time table (ingest/tokenize, narrow, exchange,
    # spill, export) + every recorded why-the-array-path-was-left
    # reason: the bench-smoke CI job gates both schema fields
    phases = getattr(ctx.scheduler, "phase_table", lambda: None)()
    if phases is not None:
        out["phases"] = phases
    out["fallback_reasons"] = getattr(
        ctx.scheduler, "fallback_reasons", lambda: [])()
    # chaos/recovery accounting (ISSUE 5): per-site injected fault
    # counters + degrade/resubmit/retry summary, same shape as the
    # bench.py OOC line
    recovery = getattr(ctx.scheduler, "recovery_summary",
                       lambda: {})() or {}
    out["faults"] = recovery.pop("faults", {})
    # coded-shuffle decode counters (ISSUE 6), same shape as bench.py
    out["decodes"] = recovery.pop("decodes", {})
    out["degrades"] = recovery
    # adaptive-execution accounting (ISSUE 7): mode, store hit/steer
    # counters, and the decisions taken — same shape as the bench.py
    # OOC line, schema-gated by tools/bench_smoke_check.py
    from dpark_tpu import adapt
    out["adapt"] = adapt.summary()
    # trace plane (ISSUE 8): span counts + critical-path summary of
    # the longest traced job, same shape as the bench.py OOC line
    from dpark_tpu import trace
    out["trace"] = trace.summary()
    # health plane (ISSUE 14): per-site latency-tail summaries + event
    # rates, same shape as the bench.py OOC line (empty sites when
    # nothing was traced — the sketches fold off the trace plane)
    from dpark_tpu import health
    out["health"] = health.summary()
    ctx.stop()
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
