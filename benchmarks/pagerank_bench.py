"""North-star config #4: Bagel PageRank superstep wall-clock.

Compares the device-native vectorized Pregel (bagel.run_pregel on the
tpu master) against the reference-shaped OBJECT Bagel on the process
master, on the same random graph.  Prints one JSON line per run.

  python benchmarks/pagerank_bench.py --vertices 200000 --degree 8
"""

import argparse
import json
import os
import subprocess
import sys
import time


def gen_graph(n, degree, seed=7):
    import numpy as np
    rng = np.random.RandomState(seed)
    ids = np.arange(n, dtype=np.int64)
    src = np.repeat(ids, degree)
    dst = rng.randint(0, n, n * degree).astype(np.int64)
    return ids, src, dst


def run_device(n, degree, steps):
    import jax
    import numpy as np
    from dpark_tpu import DparkContext
    from dpark_tpu.bagel import run_pregel
    ctx = DparkContext("tpu")
    ctx.start()
    platform = ctx.scheduler.executor.mesh.devices.flat[0].platform
    ids, src, dst = gen_graph(n, degree)

    def compute(value, msg, has_msg, active, agg, superstep):
        is0 = superstep == 0
        new = is0 * value + (1 - is0) * (0.15 / n + 0.85 * msg)
        return new, superstep < steps

    def send(v, e, deg):
        return v / deg

    t0 = time.perf_counter()
    _, ranks, _ = run_pregel(ctx, ids, np.full(n, 1.0 / n), (src, dst),
                             compute, send, combine="add",
                             max_superstep=steps + 1)
    wall = time.perf_counter() - t0
    used = ctx.scheduler._pregel_device_used
    ctx.stop()
    return wall, float(ranks.sum()), used, platform


class ObjectPR:
    """Reference-shaped object compute (module-level: fork workers must
    unpickle it)."""

    def __init__(self, n, steps):
        self.n = n
        self.steps = steps

    def __call__(self, vert, msg_sum, agg, superstep):
        from dpark_tpu.bagel import Message, Vertex
        if superstep == 0:
            value = vert.value
        else:
            value = 0.15 / self.n + 0.85 * (msg_sum or 0.0)
        active = superstep < self.steps
        v = Vertex(vert.id, value, vert.outEdges, active)
        if active and vert.outEdges:
            share = value / len(vert.outEdges)
            return (v, [Message(e.target_id, share)
                        for e in vert.outEdges])
        return (v, [])


def run_object(n, degree, steps):
    import operator
    from dpark_tpu import DparkContext
    from dpark_tpu.bagel import Bagel, BasicCombiner, Edge, Vertex
    ctx = DparkContext("process:8")
    ids, src, dst = gen_graph(n, degree)
    outs = {}
    for s, d in zip(src.tolist(), dst.tolist()):
        outs.setdefault(s, []).append(d)
    PR = lambda: ObjectPR(n, steps)         # noqa: E731

    verts = ctx.parallelize(
        [(int(i), Vertex(int(i), 1.0 / n,
                         [Edge(t) for t in outs.get(int(i), [])]))
         for i in ids], 8)
    msgs = ctx.parallelize([], 8)
    t0 = time.perf_counter()
    final = Bagel.run(ctx, verts, msgs, PR(),
                      combiner=BasicCombiner(operator.add),
                      max_superstep=steps + 1)
    total = sum(v.value for _, v in final.collect())
    wall = time.perf_counter() - t0
    ctx.stop()
    return wall, total


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--vertices", type=int, default=200_000)
    ap.add_argument("--degree", type=int, default=8)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--mode", choices=["both", "device", "object"],
                    default="both")
    args = ap.parse_args()

    if args.mode in ("both", "object"):
        # object path FIRST and in this process only if device is not
        # also requested (fork pools must stay jax-free)
        if args.mode == "both":
            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--mode", "object",
                 "--vertices", str(args.vertices),
                 "--degree", str(args.degree),
                 "--steps", str(args.steps)],
                capture_output=True, text=True)
            sys.stderr.write(out.stderr[-2000:])
            print(out.stdout, end="")
            if out.returncode != 0 or not out.stdout.strip():
                sys.exit("object-mode child failed (rc=%d)"
                         % out.returncode)
            obj = json.loads(out.stdout.splitlines()[-1])
        else:
            wall, total = run_object(args.vertices, args.degree,
                                     args.steps)
            print(json.dumps({
                "metric": "bagel_pagerank_s", "mode": "object_process",
                "vertices": args.vertices, "degree": args.degree,
                "steps": args.steps, "value": round(wall, 3),
                "rank_mass": round(total, 6)}))
            return
    if args.mode in ("both", "device"):
        if os.environ.get("BENCH_PLATFORM") \
                and not os.environ.get("DPARK_TPU_PLATFORM"):
            # an explicitly requested platform must ALSO govern the
            # in-process run_device jax init: the probe child honors
            # BENCH_PLATFORM and answers "reachable", but without the
            # override this process would still dial the real device
            # backend — and hang on a wedged tunnel
            os.environ["DPARK_TPU_PLATFORM"] = \
                os.environ["BENCH_PLATFORM"]
        if not os.environ.get("DPARK_TPU_PLATFORM"):
            # probe for a real device first (a wedged tunnel must not
            # hang the benchmark); fall back to the labeled CPU mesh
            sys.path.insert(0, os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))
            import bench
            if not bench._device_reachable():
                print("# no real device; emulated 8-virtual-CPU mesh",
                      file=sys.stderr)
                os.environ["DPARK_TPU_PLATFORM"] = "cpu"
                flags = os.environ.get("XLA_FLAGS", "")
                if "host_platform_device_count" not in flags:
                    os.environ["XLA_FLAGS"] = (
                        flags +
                        " --xla_force_host_platform_device_count=8"
                    ).strip()
        wall, total, used, platform = run_device(
            args.vertices, args.degree, args.steps)
        rec = {"metric": "bagel_pagerank_s", "mode": "device_pregel",
               "vertices": args.vertices, "degree": args.degree,
               "steps": args.steps, "value": round(wall, 3),
               "rank_mass": round(total, 6), "device_used": used,
               "platform": platform}
        if platform == "cpu":
            rec["emulated_cpu_mesh"] = True    # not TPU throughput
        if args.mode == "both":
            rec["vs_object"] = round(obj["value"] / wall, 2)
        print(json.dumps(rec))


if __name__ == "__main__":
    main()
