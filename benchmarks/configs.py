"""The five BASELINE.json benchmark configs, runnable against any master.

Reference baseline: the reference publishes no numbers (BASELINE.md); the
`-m process` master measured here IS the baseline the tpu master is
compared against.

Each config returns (bytes_processed, wall_seconds, checksum) so runs are
verifiable across masters.
"""

import operator
import os
import random
import time


def _timed(fn):
    t0 = time.perf_counter()
    out = fn()
    return time.perf_counter() - t0, out


# --------------------------------------------------------------------------
def wordcount(ctx, path=None, n_lines=200_000):
    """configs[0]: textFile -> flatMap -> map -> reduceByKey."""
    if path is None:
        path = "/tmp/dpark_bench_text.txt"
        if not os.path.exists(path):
            rng = random.Random(1)
            words = ["w%d" % i for i in range(10_000)]
            with open(path, "w") as f:
                for _ in range(n_lines):
                    f.write(" ".join(rng.choices(words, k=10)) + "\n")
    nbytes = os.path.getsize(path)
    dt, counts = _timed(lambda: dict(
        ctx.textFile(path)
        .flatMap(lambda line: line.split())
        .map(lambda w: (w, 1))
        .reduceByKey(operator.add).collect()))
    return nbytes, dt, sum(counts.values())


def sort_and_group(ctx, n=10_000_000, nparts=None):
    """configs[1]: sortByKey + groupByKey over synthetic (int,int) pairs."""
    nparts = nparts or ctx.default_parallelism
    mult = 2654435761
    pairs = [((i * mult) & 0x3FFFFFFF, i & 0xFFFF) for i in range(n)]
    nbytes = n * 8

    def run():
        r = ctx.parallelize(pairs, nparts)
        s = r.sortByKey(numSplits=nparts)
        sorted_count = s.count()        # forces every partition's sort
        g = r.map(lambda kv: (kv[0] & 0xFFFF, kv[1])) \
             .groupByKey(nparts)
        total_groups = g.count()
        return sorted_count, total_groups

    dt, (scount, ngroups) = _timed(run)
    return nbytes, dt, (scount, ngroups)


def join_cogroup(ctx, n_orders=1_000_000, n_items=2_000_000, nparts=None):
    """configs[2]: join/cogroup of two keyed RDDs (TPC-H-subset shape:
    orders(orderkey, custkey) joined with lineitem(orderkey, qty))."""
    nparts = nparts or ctx.default_parallelism
    orders = [(i, i % 1000) for i in range(n_orders)]
    items = [(i % n_orders, (i * 7) % 50 + 1) for i in range(n_items)]
    nbytes = (n_orders + n_items) * 8

    def run():
        o = ctx.parallelize(orders, nparts)
        l = ctx.parallelize(items, nparts)
        joined = o.join(l, nparts)
        return joined.count()

    dt, count = _timed(run)
    return nbytes, dt, count


def pagerank(ctx, n_vertices=20_000, steps=10, nparts=None):
    """configs[3]: PageRank via the Bagel Pregel superstep loop."""
    import dpark_tpu.bagel as bagel
    nparts = nparts or ctx.default_parallelism
    links = {i: [(i + 1) % n_vertices, (i * 13 + 7) % n_vertices]
             for i in range(n_vertices)}
    verts = ctx.parallelize(
        [(i, bagel.Vertex(i, 1.0 / n_vertices,
                          [bagel.Edge(t) for t in targets]))
         for i, targets in links.items()], nparts)
    msgs = ctx.parallelize([], nparts)

    nbytes = n_vertices * 3 * 8 * steps
    dt, final = _timed(lambda: bagel.Bagel.run(
        ctx, verts, msgs, _PRCompute(n_vertices, steps),
        combiner=bagel.BasicCombiner(operator.add),
        max_superstep=steps + 1, numSplits=nparts))
    total = final.map(lambda kv: kv[1].value).sum()
    return nbytes, dt, round(total, 3)


class _PRCompute:
    def __init__(self, n_vertices, steps):
        self.n = n_vertices
        self.steps = steps

    def __call__(self, vert, msg_sum, agg, superstep):
        import dpark_tpu.bagel as bagel
        if superstep == 0:
            value = vert.value
        else:
            value = 0.15 / self.n + 0.85 * (msg_sum or 0.0)
        active = superstep < self.steps
        v = bagel.Vertex(vert.id, value, vert.outEdges, active)
        out = [bagel.Message(e.target_id, value / len(vert.outEdges))
               for e in vert.outEdges] if active else []
        return (v, out)


def dstream_window(ctx, n_batches=20, batch_items=50_000):
    """configs[4]: DStream reduceByKeyAndWindow micro-batches (manual
    clock: measures per-batch job cost, not wall-clock waits)."""
    from dpark_tpu.dstream import StreamingContext
    ssc = StreamingContext(ctx, 1.0)
    batches = [[(i % 100, 1) for i in range(batch_items)]
               for _ in range(n_batches)]
    q = ssc.queueStream(batches)
    out = []
    q.reduceByKeyAndWindow(operator.add, 4.0,
                           invFunc=operator.sub).collect_batches(out)
    ctx.start()
    ssc.zero_time = 1000.0

    def run():
        for k in range(1, n_batches + 1):
            ssc.run_batch(1000.0 + k)
        return len(out)

    nbytes = n_batches * batch_items * 8
    dt, nb = _timed(run)
    checksum = sum(v for _, batch in out[-1:] for _, v in batch)
    return nbytes, dt, checksum


ALL = {
    "wordcount": wordcount,
    "sort_group": sort_and_group,
    "join": join_cogroup,
    "pagerank": pagerank,
    "dstream_window": dstream_window,
}
