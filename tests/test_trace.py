"""Trace plane (ISSUE 8): span/event parity, spool robustness,
cross-process merge, critical-path analysis, /metrics scrape.

The non-negotiable contract: tracing OBSERVES, it never perturbs —
off/ring/spool runs are bit-identical, including across the chaos
matrix (injected fetch faults, spill corruption, device OOM).  Device
tests run on a 2-device sliced mesh ("tpu:2") so the suite fits small
containers."""

import json
import os
import urllib.request

import pytest

from dpark_tpu import conf, faults, trace


@pytest.fixture(autouse=True)
def _clean_planes(tmp_path):
    """Every test starts and ends without trace or chaos planes."""
    trace.configure("off")
    faults.configure(None)
    yield
    trace.configure("off")
    faults.configure(None)


@pytest.fixture()
def tctx2():
    from dpark_tpu import DparkContext
    c = DparkContext("tpu:2")
    c.start()
    yield c
    c.stop()


@pytest.fixture()
def tiny_waves():
    old = conf.STREAM_CHUNK_ROWS
    conf.STREAM_CHUNK_ROWS = 500
    yield
    conf.STREAM_CHUNK_ROWS = old


def _reduce_job(c, n=200, parts=4, reduce_parts=3):
    return dict(c.parallelize([(i % 5, 1) for i in range(n)], parts)
                .reduceByKey(lambda a, b: a + b,
                             reduce_parts).collect())


# ---------------------------------------------------------------------------
# the plane itself
# ---------------------------------------------------------------------------

def test_off_mode_is_one_predicate():
    assert trace._PLANE is None
    assert trace.mode() == "off"
    # span()/ctx() return the shared no-op singleton: no allocation
    assert trace.span("x", "y", a=1) is trace._NOOP
    assert trace.ctx(job=1) is trace._NOOP
    trace.event("x", "y", a=1)          # swallowed
    trace.emit("x", "y", 0.0, 1.0)      # swallowed
    assert trace.counts() == (0, 0)
    assert trace.snapshot() == []
    assert trace.collected() == []


def test_configure_validates_mode():
    with pytest.raises(ValueError):
        trace.configure("loud")


def test_ring_mode_bounded_and_ordered(tmp_path):
    trace.configure("ring")
    for i in range(10):
        trace.emit("e%d" % i, "t", float(i), 0.5)
    recs = trace.snapshot()
    assert [r["name"] for r in recs] == ["e%d" % i for i in range(10)]
    assert trace._PLANE.ring.maxlen == conf.TRACE_RING_SPANS
    assert trace.counts()[0] == 10


def test_span_context_and_error_capture(tmp_path):
    trace.configure("ring")
    with trace.ctx(job=7, stage=3):
        with trace.span("work", "test", detail="x"):
            pass
        with pytest.raises(RuntimeError):
            with trace.span("boom", "test"):
                raise RuntimeError("no")
    ok, bad = trace.snapshot()
    assert ok["job"] == 7 and ok["stage"] == 3
    assert ok["args"] == {"detail": "x"}
    assert bad["args"]["error"] == "RuntimeError"
    assert bad["dur"] >= 0


# ---------------------------------------------------------------------------
# parity: tracing observes, never perturbs (chaos matrix included)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("spec", [
    None,
    "shuffle.fetch:p=0.3,seed=11,times=3",
    "shuffle.spill_write:nth=1,kind=corrupt",
])
def test_mode_parity_local_chaos_matrix(ctx, tmp_path, spec):
    pairs = [(i % 11, i) for i in range(500)]

    def run():
        faults.configure(spec)
        try:
            return dict(ctx.parallelize(pairs, 4)
                        .groupByKey(3)
                        .mapValues(sorted).collect())
        finally:
            faults.configure(None)

    expected = run()                     # trace off
    for mode in ("ring", "spool"):
        trace.configure(mode, str(tmp_path / mode))
        try:
            assert run() == expected, (mode, spec)
            assert trace.counts()[0] > 0
        finally:
            trace.configure("off")


def test_mode_parity_device_oom_ladder(tctx2, tiny_waves, tmp_path):
    import numpy as np
    from dpark_tpu import Columns
    i = np.arange(4000, dtype=np.int64)
    data = Columns(i % 37, i & 0xFF)

    def run():
        faults.configure("executor.dispatch:nth=1,kind=oom")
        try:
            return dict(tctx2.parallelize(data, 2)
                        .reduceByKey(lambda a, b: a + b, 2).collect())
        finally:
            faults.configure(None)

    expected = run()
    trace.configure("spool", str(tmp_path / "dev"))
    try:
        assert run() == expected
        names = {r["name"] for r in trace.collected()}
        assert "wave" in names and "stage.exec" in names, names
    finally:
        trace.configure("off")


# ---------------------------------------------------------------------------
# spool robustness
# ---------------------------------------------------------------------------

def test_spool_corruption_and_torn_lines_skip(tmp_path):
    d = str(tmp_path / "sp")
    trace.configure("spool", d)
    for i in range(8):
        trace.emit("e%d" % i, "t", float(i), 1.0)
    trace.configure("off")
    (path,) = [os.path.join(d, f) for f in os.listdir(d)]
    raw = bytearray(open(path, "rb").read())
    lines = raw.split(b"\n")
    # flip a byte inside line 2's payload and tear the final line
    lines[2] = bytes(lines[2][:-3]) + b"zzz"
    torn = lines[:-1] + [lines[-2][: len(lines[-2]) // 2]]
    with open(path, "wb") as f:
        f.write(b"\n".join(torn))
    recs = trace.read_spool(d)
    names = {r["name"] for r in recs}
    assert "e2" not in names            # corrupt line skipped
    assert "e0" in names and "e5" in names
    assert 5 <= len(recs) <= 7          # never raises, never garbage


def test_spool_cap_drops_spans_keeps_counters(tmp_path, monkeypatch):
    monkeypatch.setattr(conf, "TRACE_SPOOL_MAX_BYTES", 600)
    d = str(tmp_path / "cap")
    trace.configure("spool", d)
    for i in range(50):
        trace.emit("e%d" % i, "t", float(i), 1.0)
    assert trace.counts()[1] > 0        # spans dropped past the cap
    trace.emit_process_counters()       # counter events always land
    recs = trace.read_spool(d)
    assert any(r["cat"] == "counters" for r in recs)
    assert len(recs) < 50


def test_merged_worker_counters_latest_per_pid(tmp_path):
    """Counter events are CUMULATIVE per process: the merge takes the
    newest per (host, pid) and sums across processes."""
    d = str(tmp_path / "ct")
    os.makedirs(d)

    def write(pid, ts, fired, repair):
        rec = {"name": "process.counters", "cat": "counters",
               "ts": ts, "dur": 0.0, "pid": pid, "host": "w",
               "tid": 1,
               "args": {"faults": {"shuffle.fetch":
                                   {"hits": fired + 2,
                                    "fired": fired, "kind": "raise"}},
                        "decodes": {"repair": repair,
                                    "straggler_win": 0,
                                    "decode_failures": 0},
                        "decodes_per_shuffle":
                            {"3": {"repair": repair}}}}
        payload = json.dumps(rec, sort_keys=True,
                             separators=(",", ":")).encode()
        line = b"%08x %s\n" % (trace._crc(payload), payload)
        with open(os.path.join(d, "counters-w-%d.jsonl" % pid),
                  "ab") as f:
            f.write(line)

    write(100, 1.0, fired=1, repair=1)
    write(100, 2.0, fired=3, repair=2)   # newer cumulative snapshot
    write(200, 1.5, fired=2, repair=0)
    got = trace.merged_worker_counters(d)
    assert got["processes"] == 2
    assert got["faults"]["shuffle.fetch"]["fired"] == 5      # 3 + 2
    assert got["decodes"]["repair"] == 2                     # 2 + 0
    assert got["decodes_per_shuffle"][3]["repair"] == 2


def test_cross_run_spool_isolation(tmp_path):
    """Job ids restart at 1 per scheduler, so a spool dir surviving
    across runs (the default /tmp location) must not merge two runs'
    "job 1" spans: every record carries a run id, collected() and the
    counter merge restrict to the current run, and dtrace analyzes
    per run."""
    d = str(tmp_path / "runs")
    trace.configure("spool", d)
    run1 = trace.run_id()
    trace.emit("job", "sched", 1.0, 5.0, job=1)
    trace.emit_process_counters()
    trace.configure("spool", d)          # same dir, NEW run
    run2 = trace.run_id()
    assert run2 != run1
    trace.emit("job", "sched", 10.0, 2.0, job=1)
    try:
        recs = trace.collected()
        assert len(recs) == 1 and recs[0]["run"] == run2
        # the dead prior run's counters don't contribute phantoms
        merged = trace.merged_worker_counters(d, include_self=True)
        assert merged["processes"] == 0   # run1's event filtered out
        assert trace.merged_worker_counters(
            d, include_self=True, run=False)["processes"] == 1
        # dtrace: one critical path PER RUN, never a merged DAG
        all_recs = trace.read_spool(d)
        runs = {r.get("run") for r in all_recs
                if r.get("name") == "job"}
        assert runs == {run1, run2}
        cp1 = trace.critical_path(
            [r for r in all_recs if r.get("run") == run1], 1)
        cp2 = trace.critical_path(
            [r for r in all_recs if r.get("run") == run2], 1)
        assert cp1["wall_s"] == 5.0 and cp2["wall_s"] == 2.0
    finally:
        trace.configure("off")


def test_metrics_running_jobs_gauge_not_counter(ctx):
    """A still-running record must not fold into counter-typed series
    (its state flips and its totals grow between scrapes — Prometheus
    reads a decrease as a counter reset); it surfaces only in the
    dpark_jobs_running gauge."""
    from dpark_tpu.web import render_metrics
    _reduce_job(ctx)
    body = render_metrics(ctx.scheduler)
    assert 'dpark_jobs_total{state="done"} 1' in body
    assert "dpark_jobs_running 0" in body
    ctx.scheduler.history.append(
        {"id": 98, "state": "running", "retries": 7,
         "stage_info": [{"id": 1, "kind": "array",
                         "tasks": [{"ok": True}]}]})
    try:
        body = render_metrics(ctx.scheduler)
    finally:
        ctx.scheduler.history.pop()
    assert "dpark_jobs_running 1" in body
    assert 'state="running"' not in body
    assert "dpark_retries_total 0" in body     # running job excluded


@pytest.fixture()
def fresh_forkserver():
    """The forkserver is a process-wide singleton that inherits
    os.environ when it FIRST starts — an earlier process-master test
    pins a faults-free environment for every later pool.  Restart it
    on both sides so this test's DPARK_FAULTS reaches the workers and
    later tests get a clean environment again."""
    from multiprocessing import forkserver

    def stop():
        try:
            forkserver._forkserver._stop()
        except Exception:
            pass

    stop()
    yield
    stop()


def test_cross_process_spool_merge(fresh_forkserver, pctx, tmp_path,
                                   monkeypatch):
    # fixture order matters: fresh_forkserver FIRST so its teardown
    # runs LAST — stopping the forkserver while pctx's pool is alive
    # wedges pool.terminate()
    """The multiprocess blindspot closes: worker task.run spans land
    in the merged spool under their own pids, and worker-observed
    fault counters surface in recovery_summary() — the driver's own
    faults.stats() stays empty because only the workers (which
    inherit DPARK_FAULTS through the forkserver environment) carry a
    chaos plane."""
    monkeypatch.setenv("DPARK_FAULTS", "shuffle.fetch:nth=1")
    trace.configure("spool", str(tmp_path / "mp"))
    try:
        assert _reduce_job(pctx, n=400, parts=4, reduce_parts=3) \
            == {k: 80 for k in range(5)}
        recs = trace.collected()
        me = os.getpid()
        worker_pids = {r["pid"] for r in recs
                       if r["name"] == "task.run" and r["pid"] != me}
        assert worker_pids, "no worker-process spans in the spool"
        # worker spans carry the job/stage parentage shipped with the
        # task, so the merged timeline parents across processes
        wspan = next(r for r in recs if r["name"] == "task.run"
                     and r["pid"] != me)
        assert wspan.get("stage") is not None
        assert wspan.get("job") is not None
        assert faults.stats() == {}          # driver saw nothing...
        summary = pctx.scheduler.recovery_summary()
        assert summary["worker_processes"] >= 1
        assert summary["faults"]["shuffle.fetch"]["fired"] >= 1
        # acceptance: the Chrome export of a multiprocess run carries
        # worker spans under their own process rows
        chrome = trace.to_chrome(recs)
        assert json.dumps(chrome)
        ev_pids = {e["pid"] for e in chrome["traceEvents"]
                   if e.get("ph") == "X"}
        assert len(ev_pids) >= 2, ev_pids    # driver + >=1 worker
    finally:
        trace.configure("off")


# ---------------------------------------------------------------------------
# critical path
# ---------------------------------------------------------------------------

def _rec(name, cat, ts, dur, **kw):
    out = {"name": name, "cat": cat, "ts": ts, "dur": dur,
           "pid": 1, "host": "h", "tid": 1}
    args = kw.pop("args", None)
    out.update(kw)
    if args:
        out["args"] = args
    return out


def test_critical_path_synthetic_dag():
    # stage 1 (2s) and stage 2 (5s) both feed stage 3 (1s): the chain
    # must route through stage 2; stage 2's phases say exchange-bound
    recs = [
        _rec("job", "sched", 0.0, 7.0, job=1),
        _rec("stage", "sched", 0.0, 2.0, job=1, stage=1,
             args={"parents": []}),
        _rec("stage", "sched", 0.0, 5.0, job=1, stage=2,
             args={"parents": []}),
        _rec("stage", "sched", 5.0, 1.0, job=1, stage=3,
             args={"parents": [1, 2]}),
        _rec("phase.narrow", "phase", 0.0, 1.0, job=1, stage=2),
        _rec("phase.exchange", "phase", 1.0, 3.5, job=1, stage=2),
        _rec("fetch.bucket", "shuffle", 5.0, 0.5, job=1, stage=3),
    ]
    cp = trace.critical_path(recs, 1)
    assert cp["chain"] == [2, 3]
    assert cp["wall_s"] == 7.0
    assert cp["phases_s"]["exchange"] == 3.5
    assert cp["phases_s"]["fetch"] == 0.5
    assert cp["bound"] == "exchange"
    # unattributed stage time lands in `other`, totals cover the chain
    assert abs(sum(cp["phases_s"].values())
               - cp["chain_wall_s"]) < 1e-6


def test_critical_path_none_without_job():
    assert trace.critical_path([], 1) is None
    assert trace.critical_path([]) is None


def test_critical_path_reconciles_with_phase_table(tctx2, tiny_waves):
    """Acceptance: the analyzer's streamed-phase totals match the
    scheduler's phase_table() within 5% — both read the same
    _StreamStats snapshot by construction."""
    import numpy as np
    from dpark_tpu import Columns
    trace.configure("ring")
    i = np.arange(6000, dtype=np.int64)
    data = Columns(i % 53, i & 0xFF)
    got = dict(tctx2.parallelize(data, 2)
               .reduceByKey(lambda a, b: a + b, 2).collect())
    assert len(got) == 53
    cp = trace.critical_path(trace.snapshot())
    pt = tctx2.scheduler.phase_table()
    assert pt is not None, "streamed path did not run"
    for phase, key in (("ingest_tokenize", "ingest_tokenize_ms"),
                       ("narrow", "narrow_ms"),
                       ("exchange", "exchange_ms"),
                       ("spill", "spill_ms")):
        a = cp["phases_s"].get(phase, 0.0) * 1e3
        b = pt[key]
        assert abs(a - b) <= 0.05 * max(a, b, 1e-3) + 0.5, \
            (phase, a, b)


# ---------------------------------------------------------------------------
# chrome export + dtrace CLI
# ---------------------------------------------------------------------------

def _load_dtrace():
    from tests.conftest import load_tool
    return load_tool("dtrace")


def test_chrome_export_shape(ctx, tmp_path):
    trace.configure("ring")
    _reduce_job(ctx)
    chrome = trace.to_chrome(trace.snapshot())
    evs = chrome["traceEvents"]
    assert evs and json.dumps(chrome)
    complete = [e for e in evs if e.get("ph") == "X"]
    assert complete, "no complete spans in the export"
    for e in complete:
        assert {"name", "cat", "pid", "tid", "ts", "dur"} <= set(e)
    assert any(e.get("ph") == "M" and e["name"] == "process_name"
               for e in evs)
    # counter events are merge substrate, not timeline rows
    assert not any(e.get("cat") == "counters" for e in evs)


def test_dtrace_self_check_and_export(ctx, tmp_path, capsys):
    d = str(tmp_path / "cli")
    trace.configure("spool", d)
    _reduce_job(ctx)
    trace.configure("off")
    dtrace = _load_dtrace()
    assert dtrace.main(["--self-check", "--dir", d]) == 0
    out = str(tmp_path / "trace.json")
    assert dtrace.main(["--out", out, "--dir", d]) == 0
    chrome = json.load(open(out))
    assert chrome["traceEvents"]
    assert dtrace.main(["--critical-path", "--dir", d]) == 0
    body = capsys.readouterr().out
    assert '"chain"' in body
    # an empty spool fails the self-check (the CI gate's contract)
    assert dtrace.main(["--self-check", "--dir",
                        str(tmp_path / "empty")]) == 1


# ---------------------------------------------------------------------------
# /metrics + /api/trace
# ---------------------------------------------------------------------------

def test_metrics_scrape_and_api_trace(ctx, tmp_path):
    from dpark_tpu.web import start_ui
    trace.configure("ring")
    _reduce_job(ctx)
    server, url = start_ui(ctx.scheduler)
    try:
        with urllib.request.urlopen(url + "metrics") as r:
            assert r.status == 200
            ctype = r.headers.get("Content-Type", "")
            assert ctype.startswith("text/plain; version=0.0.4")
            body = r.read().decode()
        assert 'dpark_jobs_total{state="done"} 1' in body
        assert "dpark_stages_total" in body
        assert 'dpark_tasks_total{ok="true"}' in body
        assert "dpark_faults_injected_total" in body
        assert "dpark_decodes_total" in body
        assert "dpark_adapt_decisions_total" in body
        assert 'dpark_trace_spans_total{mode="ring"}' in body
        assert "dpark_phase_seconds" in body
        job = ctx.scheduler.history[-1]["id"]
        with urllib.request.urlopen(
                url + "api/trace?job=%d" % job) as r:
            payload = json.loads(r.read().decode())
        assert payload["mode"] == "ring"
        assert payload["job"] == job
        assert any(s["name"] == "job" for s in payload["spans"])
        assert all(s.get("job") == job for s in payload["spans"])
    finally:
        server.shutdown()


def test_metrics_never_throws_mid_mutation(ctx):
    """A job record mutating mid-scrape must yield valid text, not an
    error (ISSUE 8 satellite): poison the history with a record shaped
    like a half-written mutation and render."""
    from dpark_tpu.web import render_metrics
    _reduce_job(ctx)
    ctx.scheduler.history.append(
        {"id": 99, "state": None, "stage_info": [
            {"id": 1, "kind": None, "tasks": None,
             "pipeline": {"ingest_ms": "not-a-number"}},
            "not-a-dict"]})
    try:
        body = render_metrics(ctx.scheduler)
    finally:
        ctx.scheduler.history.pop()
    assert "dpark_jobs_total" in body


def test_stage_rows_link_to_trace_api():
    from dpark_tpu import web
    assert "/api/trace?job=" in web._PAGE


# ---------------------------------------------------------------------------
# span parentage + phase spans ride the job record path
# ---------------------------------------------------------------------------

def test_task_spans_carry_job_and_stage(ctx):
    trace.configure("ring")
    _reduce_job(ctx)
    recs = trace.snapshot()
    tasks = [r for r in recs if r["name"] == "task"]
    stages = [r for r in recs if r["name"] == "stage"]
    (job,) = [r for r in recs if r["name"] == "job"]
    assert tasks and stages
    for t in tasks:
        assert t["job"] == job["job"]
        assert "stage" in t and "task" in t
        assert t["args"]["status"] == "success"
    # stage spans carry the parent edges the critical path walks
    kinds = {s["stage"]: s["args"].get("parents") for s in stages}
    assert any(kinds.values()), "no stage recorded its parents"


def test_worker_span_inherits_ctx_inline(ctx):
    """On inline masters the task.run span inherits job/stage from the
    submit-time context (same mechanism workers use via the stamped
    task attribute)."""
    trace.configure("ring")
    _reduce_job(ctx)
    runs = [r for r in trace.snapshot() if r["name"] == "task.run"]
    assert runs
    assert all("stage" in r and "job" in r for r in runs)


# ---------------------------------------------------------------------------
# plan-lint rule
# ---------------------------------------------------------------------------

def test_trace_overhead_hint_rule(ctx, tmp_path, monkeypatch):
    from dpark_tpu.analysis.plan_rules import lint_plan
    wide = ctx.parallelize([(i % 5, 1) for i in range(64)], 16) \
        .reduceByKey(lambda a, b: a + b, 2)
    monkeypatch.setattr(conf, "TRACE_SPAN_WRITES_PER_TASK", 8)
    # quiet with tracing off / ring — no spool writes to warn about
    assert not [f for f in lint_plan(wide).findings
                if f.rule == "trace-overhead-hint"]
    trace.configure("ring")
    assert not [f for f in lint_plan(wide).findings
                if f.rule == "trace-overhead-hint"]
    trace.configure("spool", str(tmp_path / "lint"))
    hits = [f for f in lint_plan(wide).findings
            if f.rule == "trace-overhead-hint"]
    assert hits and "16 parent map buckets" in hits[0].message
    # a reduce over few map buckets stays under the threshold
    narrow = ctx.parallelize([(i % 5, 1) for i in range(64)], 4) \
        .reduceByKey(lambda a, b: a + b, 2)
    assert not [f for f in lint_plan(narrow).findings
                if f.rule == "trace-overhead-hint"]
