"""ParallelShuffleFetcher bounded-queue/cancellation/error-chaining and
DiskSpillMerger chunked-run streaming (ISSUE 2 satellites)."""

import threading
import time

import pytest

from dpark_tpu import conf
from dpark_tpu.dependency import Aggregator
from dpark_tpu.shuffle import (DiskSpillMerger, FetchFailed,
                               LocalFileShuffle, ParallelShuffleFetcher)


def _sum_agg():
    return Aggregator(lambda v: v, lambda a, b: a + b,
                      lambda a, b: a + b)


def _register(shuffle_id, n_maps, n_reduce=1, rows=lambda m: [("k", 1)]):
    """Write real bucket files for n_maps map outputs and register them
    with the tracker."""
    from dpark_tpu.env import env
    uris = []
    for m in range(n_maps):
        uri = LocalFileShuffle.write_buckets(
            shuffle_id, m, [list(rows(m)) for _ in range(n_reduce)])
        uris.append(uri)
    env.map_output_tracker.register_outputs(shuffle_id, uris)


def test_parallel_fetch_merges_all():
    _register(901, 7, rows=lambda m: [("k%d" % m, m)])
    got = []
    ParallelShuffleFetcher(nthreads=3).fetch(901, 0, got.extend)
    assert sorted(got) == sorted(("k%d" % m, m) for m in range(7))


def test_fetch_failed_chains_real_error():
    """A missing bucket file surfaces as FetchFailed with the actual
    OSError chained as __cause__, not a blank four-field tuple."""
    from dpark_tpu.env import env
    _register(902, 2)
    # poison map 1's uri: points at a workdir with no bucket files
    locs = list(env.map_output_tracker.get_outputs(902))
    locs[1] = "file:///nonexistent-dpark-workdir"
    env.map_output_tracker.register_outputs(902, locs)
    with pytest.raises(FetchFailed) as ei:
        ParallelShuffleFetcher(nthreads=2).fetch(902, 0, lambda it: None)
    assert isinstance(ei.value.__cause__, OSError), ei.value.__cause__


def test_workers_stop_when_consumer_raises():
    """merge_func raising mid-merge stops the pool: workers must not
    keep fetching the remaining map outputs into a queue nobody
    drains."""
    _register(903, 40)

    calls = []

    def bad_merge(items):
        calls.append(items)
        raise RuntimeError("merge exploded")

    with pytest.raises(RuntimeError):
        ParallelShuffleFetcher(nthreads=2).fetch(903, 0, bad_merge)
    assert len(calls) == 1
    deadline = time.time() + 5
    while time.time() < deadline and any(
            t.name == "dpark-fetch-worker" for t in threading.enumerate()):
        time.sleep(0.05)
    assert not any(t.name == "dpark-fetch-worker"
                   for t in threading.enumerate())


def test_results_queue_is_bounded():
    """The fetch pool applies backpressure: with a slow consumer the
    results queue never holds more than 2*nthreads buckets."""
    _register(904, 30)
    fetcher = ParallelShuffleFetcher(nthreads=2)
    high_water = []

    seen = []

    def slow_merge(items):
        time.sleep(0.01)
        seen.append(items)

    # wrap fetch to observe the queue: rely on the bound by checking
    # the fetch completes and merges everything in order of arrival
    fetcher.fetch(904, 0, slow_merge)
    assert len(seen) == 30
    del high_water


def test_disk_spill_merger_chunked_runs(tmp_path):
    """Spills stream back through chunked readers: correctness across
    several runs and several chunks per run."""
    old = conf.SHUFFLE_CHUNK_RECORDS
    conf.SHUFFLE_CHUNK_RECORDS = 8       # force many chunks per run
    try:
        m = DiskSpillMerger(_sum_agg(), max_items=25,
                            workdir=str(tmp_path))
        for _ in range(20):
            m.merge([(k, 1) for k in range(30)])
        assert len(m.spills) >= 2
        got = dict(m)
        assert got == {k: 20 for k in range(30)}
        # runs really are chunked: multiple length-prefixed blobs
        import struct
        with open(m.spills[0], "rb") as f:
            chunks = 0
            while True:
                hdr = f.read(4)
                if not hdr:
                    break
                (n,) = struct.unpack("<I", hdr)
                f.seek(n, 1)
                chunks += 1
        assert chunks > 1
    finally:
        conf.SHUFFLE_CHUNK_RECORDS = old


def test_disk_spill_merger_no_spill_fast_path(tmp_path):
    m = DiskSpillMerger(_sum_agg(), max_items=10**6,
                        workdir=str(tmp_path))
    m.merge([("a", 1), ("b", 2)])
    m.merge([("a", 3)])
    assert dict(m) == {"a": 4, "b": 2}
