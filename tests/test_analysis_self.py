"""CI gate: the closure linter over dpark_tpu/ and examples/ must stay
clean against the committed baseline (tools/dlint_baseline.json).

This is the in-suite twin of the CI lint job (.github/workflows): any
NEW anti-pattern in the package or the shipped examples fails tier-1.
To accept a deliberate new finding, refresh the baseline with
`tools/dlint --self --write-baseline` and commit it."""

import os

from dpark_tpu.analysis.__main__ import main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_self_lint_is_clean_against_baseline(capsys):
    rc = main(["--self"])
    out = capsys.readouterr()
    assert rc == 0, "new lint findings:\n%s%s" % (out.out, out.err)


def test_baseline_file_is_committed_and_sorted():
    import json
    path = os.path.join(REPO, "tools", "dlint_baseline.json")
    assert os.path.exists(path), "tools/dlint_baseline.json missing"
    with open(path) as f:
        data = json.load(f)
    # ISSUE 16 format: {key: justification}; the legacy bare list is
    # still accepted by load_baseline but the committed file carries
    # a non-empty justification for every accepted finding
    assert isinstance(data, dict)
    keys = list(data)
    assert keys == sorted(keys)
    assert all("::" in k for k in keys)
    assert all(isinstance(v, str) and v.strip() for v in data.values())


def test_shipped_examples_have_no_errors(capsys):
    # acceptance: zero ERROR findings across every shipped example
    # (warnings like pi.py's unseeded random are baselined, not errors)
    from dpark_tpu.analysis.closure_rules import lint_source
    from dpark_tpu.analysis.report import Report
    report = Report()
    exdir = os.path.join(REPO, "examples")
    for name in sorted(os.listdir(exdir)):
        if name.endswith(".py"):
            lint_source(os.path.join(exdir, name), report=report)
    errors = [f.render() for f in report.errors()]
    assert not errors, "\n".join(errors)
