"""Test harness: force an 8-virtual-device CPU platform BEFORE jax import so
TPU-backend tests exercise real Mesh sharding without TPU hardware
(SURVEY.md section 4: the local master is the golden model; every backend
test asserts backend output == local output)."""

import os
import shutil

os.environ["JAX_PLATFORMS"] = "cpu"      # override e.g. axon tunnel
os.environ["JAX_PLATFORM_NAME"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("DPARK_PROGRESS", "0")

# mesh-marked tests (full 8-virtual-device collectives) need roughly
# one host CPU per mesh device: an 8-device all_to_all on a 2-CPU
# container wedges in the XLA:CPU intra-process rendezvous and the
# whole tier-1 run dies in the suite timeout instead of finishing with
# skips.  conf.MESH_TEST_DEVICES is the knob (DPARK_MESH_TEST_DEVICES;
# 0 forces the tests to run regardless of CPU count).  Tests on small
# sliced meshes ("tpu:2") stay unmarked — they fit tiny containers.

# the environment may pre-load a TPU tunnel plugin that ignores the env
# var; force the platform through the config API as well.  jax is optional
# for the pure-host tests.
try:
    import jax
    jax.config.update("jax_platforms", "cpu")
except ImportError:
    pass

import pytest


def pytest_collection_modifyitems(config, items):
    from dpark_tpu import conf
    want = conf.MESH_TEST_DEVICES
    have = os.cpu_count() or 1
    if not want or have >= want:
        return
    skip = pytest.mark.skip(
        reason="mesh test needs >= %d CPUs for the %d-device virtual "
               "mesh (host has %d); set DPARK_MESH_TEST_DEVICES=0 to "
               "force" % (want, want, have))
    for item in items:
        if "mesh" in item.keywords:
            item.add_marker(skip)


def load_tool(name):
    """Import one of the extensionless tools/ CLIs (dtrace, ...) or a
    tools/*.py script as a module — shared by every tool-driving
    test."""
    import importlib.machinery
    import importlib.util
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", name)
    modname = "_tool_%s" % name.replace(".", "_")
    loader = importlib.machinery.SourceFileLoader(modname, path)
    spec = importlib.util.spec_from_loader(modname, loader)
    mod = importlib.util.module_from_spec(spec)
    loader.exec_module(mod)
    return mod


@pytest.fixture()
def ctx():
    from dpark_tpu import DparkContext
    c = DparkContext("local")
    yield c
    c.stop()


@pytest.fixture()
def pctx():
    from dpark_tpu import DparkContext
    c = DparkContext("process:4")
    yield c
    c.stop()


@pytest.fixture(scope="session", autouse=True)
def _lockcheck_grade():
    """With DPARK_LOCKCHECK=record armed over the whole suite (the CI
    lockcheck job), fail the RUN if the merged acquisition-order graph
    drew any cycle — even one whose threads got lucky and never
    wedged.  Off (the default) this is a no-op."""
    yield
    from dpark_tpu import locks
    san = locks.sanitizer()
    if san is None:
        return
    rep = san.report()
    if rep["cycles"] or rep["findings"]:
        raise AssertionError(
            "lock sanitizer observed ordering hazards across the "
            "suite:\n%s" % locks.render_report(rep))


@pytest.fixture(autouse=True)
def _fresh_env(tmp_path_factory):
    """Each test gets its own workdir; the env singleton is reset."""
    from dpark_tpu.env import env
    import dpark_tpu.context as context_mod
    was = env.started
    env.stop()
    env.__init__()
    env.start(is_master=True,
              environ={"DPARK_WORKDIR":
                       str(tmp_path_factory.mktemp("dpark-work"))})
    yield
    env.stop()
    env.__init__()
